"""Recursive-descent SQL parser (Pratt-style expression parsing).

Covers the surface the reference's benchmark/test suites exercise
(TPC-H-complete plus the CLI's DDL): SELECT with CTEs, derived tables,
explicit and comma joins, WHERE/GROUP BY/HAVING/ORDER BY/LIMIT/OFFSET,
UNION [ALL], scalar/IN/EXISTS subqueries, CASE, CAST, EXTRACT, SUBSTRING,
date/interval literals, EXPLAIN [ANALYZE], CREATE EXTERNAL TABLE, DROP
TABLE, SHOW TABLES, SET.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any

import pyarrow as pa

from ballista_tpu.errors import SqlParseError
from ballista_tpu.plan.expressions import (
    AggregateFunction,
    Alias,
    Between,
    BinaryExpr,
    Case,
    Cast,
    Column,
    Exists,
    Expr,
    InList,
    InSubquery,
    IsNotNull,
    IsNull,
    Like,
    Literal,
    Negative,
    Not,
    ScalarFunction,
    ScalarSubquery,
    SortKey,
    WINDOW_FUNCS,
    WindowFunction,
)
from ballista_tpu.sql.ast import (
    CreateExternalTable,
    DerivedTable,
    DropTable,
    ShowColumns,
    ValuesClause,
    ExplainStmt,
    JoinClause,
    SelectStmt,
    SetVariable,
    ShowTables,
    TableName,
)
from ballista_tpu.sql.tokenizer import Token, tokenize

AGGREGATES = {"SUM", "AVG", "MIN", "MAX", "COUNT",
              "STDDEV", "STDDEV_SAMP", "STDDEV_POP",
              "VARIANCE", "VAR_SAMP", "VAR_POP"}

# SQL surface names → canonical aggregate names (SQL-standard sample forms)
_AGG_CANONICAL = {"stddev": "stddev_samp", "variance": "var_samp"}

SCALAR_FUNCS = {
    # canonical-name mapping; evaluation lives in the engines
    "SUBSTR": "substr", "SUBSTRING": "substr", "STRPOS": "strpos",
    "POSITION": "strpos", "LENGTH": "length", "CHAR_LENGTH": "length",
    "UPPER": "upper", "LOWER": "lower", "TRIM": "trim", "BTRIM": "trim",
    "CONCAT": "concat", "ABS": "abs", "ROUND": "round", "CEIL": "ceil",
    "CEILING": "ceil", "FLOOR": "floor", "COALESCE": "coalesce",
    "DATE_TRUNC": "date_trunc", "DATE_PART": "date_part", "YEAR": "extract_year",
    "SQRT": "sqrt",
}

_TYPE_NAMES = {
    "INT": pa.int64(), "INTEGER": pa.int64(), "BIGINT": pa.int64(),
    "SMALLINT": pa.int64(), "TINYINT": pa.int64(),
    "FLOAT": pa.float64(), "DOUBLE": pa.float64(), "REAL": pa.float64(),
    "DECIMAL": pa.float64(), "NUMERIC": pa.float64(),  # engine decimal policy
    "VARCHAR": pa.string(), "CHAR": pa.string(), "TEXT": pa.string(),
    "STRING": pa.string(), "DATE": pa.date32(), "BOOLEAN": pa.bool_(),
    "BOOL": pa.bool_(), "TIMESTAMP": pa.timestamp("us"),
}


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def accept_kw(self, *kws: str) -> bool:
        if self.peek().is_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        t = self.next()
        if not (t.kind == "kw" and t.value == kw):
            raise SqlParseError(f"expected {kw}, got {t.kind} {t.value!r} at {t.pos}")

    def accept_punct(self, p: str) -> bool:
        if self.peek().kind == "punct" and self.peek().value == p:
            self.next()
            return True
        return False

    def expect_punct(self, p: str) -> None:
        t = self.next()
        if not (t.kind == "punct" and t.value == p):
            raise SqlParseError(f"expected {p!r}, got {t.value!r} at {t.pos}")

    def expect_ident(self) -> str:
        t = self.next()
        if t.kind == "ident":
            return t.value
        # allow non-reserved keywords as identifiers in a few positions
        if t.kind == "kw" and t.value in (
            "DATE", "YEAR", "FIRST", "LAST", "ALL", "TABLES",
            "ROLLUP", "CUBE", "GROUPING", "SETS",
        ):
            return t.value.lower()
        raise SqlParseError(f"expected identifier, got {t.kind} {t.value!r} at {t.pos}")

    # -- statements ---------------------------------------------------------

    def parse_statement(self) -> Any:
        t = self.peek()
        if t.is_kw("EXPLAIN"):
            self.next()
            analyze = self.accept_kw("ANALYZE")
            verbose = self.accept_kw("VERBOSE")
            return ExplainStmt(self.parse_statement(), analyze, verbose)
        if t.is_kw("CREATE"):
            return self._parse_create()
        if t.is_kw("DROP"):
            self.next()
            self.expect_kw("TABLE")
            if_exists = False
            if self.peek().kind == "kw" and self.peek().value == "IS":  # unreachable, keep simple
                pass
            if self.peek().kind == "ident" and self.peek().value.upper() == "IF":
                self.next()
                ex = self.next()
                if not (ex.kind == "kw" and ex.value == "EXISTS"):
                    raise SqlParseError("expected EXISTS after IF")
                if_exists = True
            return DropTable(self.expect_ident(), if_exists)
        if t.is_kw("SHOW"):
            self.next()
            if self.accept_kw("COLUMNS"):
                # SHOW COLUMNS FROM t
                if not self.accept_kw("FROM"):
                    self.expect_kw("IN")
                return ShowColumns(self.expect_ident())
            self.expect_kw("TABLES")
            return ShowTables()
        if t.kind == "ident" and t.value.upper() == "DESCRIBE":
            self.next()
            return ShowColumns(self.expect_ident())
        if t.is_kw("SET"):
            self.next()
            key = self._parse_dotted_name()
            op = self.next()
            if not (op.kind == "op" and op.value == "="):
                raise SqlParseError("expected = in SET")
            val = self.next()
            return SetVariable(key, val.value)
        return self.parse_query()

    def _parse_create(self) -> CreateExternalTable:
        self.expect_kw("CREATE")
        self.accept_kw("EXTERNAL")
        self.expect_kw("TABLE")
        name = self.expect_ident()
        fmt = "parquet"
        if self.accept_kw("STORED"):
            self.expect_kw("AS")
            fmt = self.expect_ident().lower()
        self.expect_kw("LOCATION")
        loc = self.next()
        if loc.kind != "string":
            raise SqlParseError("expected string LOCATION")
        return CreateExternalTable(name, loc.value, fmt)

    def _parse_dotted_name(self) -> str:
        parts = [self.expect_ident()]
        while self.accept_punct("."):
            parts.append(self.expect_ident())
        return ".".join(parts)

    # -- queries ------------------------------------------------------------

    def parse_query(self) -> SelectStmt:
        ctes: list[tuple[str, SelectStmt]] = []
        if self.accept_kw("WITH"):
            while True:
                name = self.expect_ident()
                self.expect_kw("AS")
                self.expect_punct("(")
                sub = self.parse_query()
                self.expect_punct(")")
                ctes.append((name.lower(), sub))
                if not self.accept_punct(","):
                    break
        stmt = self._parse_query_term()
        # a parenthesized term may carry its own WITH clause — outer CTEs
        # prepend (inner names shadow outer per SQL scoping)
        stmt.ctes = ctes + list(stmt.ctes or [])
        # set operations: chain via nested set_op fields on the RHS so
        # a UNION ALL b UNION ALL c keeps all three branches (homogeneous
        # chains are associative; planner flattens them)
        cur = stmt
        while self.peek().is_kw("UNION", "EXCEPT", "INTERSECT"):
            kw = self.next().value
            all_ = self.accept_kw("ALL")
            # standard SQL: set-op branches take no bare ORDER BY/LIMIT —
            # trailing clauses bind to the whole chain
            rhs = self._parse_query_term(allow_order=False)
            op = {"UNION": "union_all" if all_ else "union",
                  "EXCEPT": "except_all" if all_ else "except",
                  "INTERSECT": "intersect_all" if all_ else "intersect"}[kw]
            cur.set_op = (op, rhs)
            cur = rhs
        # trailing ORDER BY / LIMIT of a set operation
        if self.peek().is_kw("ORDER") and not stmt.order_by:
            stmt.order_by = self._parse_order_by()
        if self.peek().is_kw("LIMIT") and stmt.limit is None:
            stmt.limit, stmt.offset = self._parse_limit()
        return stmt

    def _parse_query_term(self, allow_order: bool = True) -> SelectStmt:
        """One operand of a set-operation chain: a SELECT body or a
        parenthesized query expression `( query )` (q38/q87 shape).

        A parenthesized operand that carries its own set-op chain, ORDER
        BY/LIMIT, or WITH clause wraps into `SELECT * FROM (query)` — the
        outer chain's left-associative splicing would otherwise regroup
        non-associative EXCEPT/INTERSECT or misattach the inner clauses."""
        if self.peek().kind == "punct" and self.peek().value == "(":
            self.next()
            sub = self.parse_query()
            self.expect_punct(")")
            if sub.set_op or sub.order_by or sub.limit is not None or sub.ctes:
                self._wrap_counter = getattr(self, "_wrap_counter", 0) + 1
                wrapped = SelectStmt()
                wrapped.projections = [Column("*")]
                wrapped.from_tables = [
                    DerivedTable(sub, f"__setwrap{self._wrap_counter}")]
                return wrapped
            return sub
        return self._parse_select_body(allow_order=allow_order)

    def _parse_select_body(self, allow_order: bool = True) -> SelectStmt:
        self.expect_kw("SELECT")
        stmt = SelectStmt()
        stmt.distinct = self.accept_kw("DISTINCT")
        self.accept_kw("ALL")
        # projections
        while True:
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                stmt.projections.append(Column("*"))
            else:
                e = self.parse_expr()
                if self.accept_kw("AS"):
                    e = Alias(e, self.expect_ident().lower())
                elif self.peek().kind == "ident":
                    e = Alias(e, self.next().value.lower())
                stmt.projections.append(e)
            if not self.accept_punct(","):
                break
        if self.accept_kw("FROM"):
            stmt.from_tables.append(self._parse_table_ref())
            while self.accept_punct(","):
                stmt.from_tables.append(self._parse_table_ref())
        if self.accept_kw("WHERE"):
            stmt.where = self.parse_expr()
        if self.peek().is_kw("GROUP"):
            self.next()
            self.expect_kw("BY")
            if self.accept_kw("ROLLUP"):
                self.expect_punct("(")
                stmt.group_by = self._parse_group_exprs()
                self.expect_punct(")")
                k = len(stmt.group_by)
                stmt.grouping_sets = [list(range(i)) for i in range(k, -1, -1)]
            elif self.accept_kw("CUBE"):
                self.expect_punct("(")
                stmt.group_by = self._parse_group_exprs()
                self.expect_punct(")")
                k = len(stmt.group_by)
                if k > 5:
                    raise SqlParseError("CUBE over more than 5 keys")
                stmt.grouping_sets = [
                    [i for i in range(k) if m & (1 << i)] for m in range(2**k - 1, -1, -1)
                ]
            elif self.accept_kw("GROUPING"):
                self.expect_kw("SETS")
                self.expect_punct("(")
                sets: list[list[int]] = []
                order: list = []
                while True:
                    self.expect_punct("(")
                    one: list[int] = []
                    if not (self.peek().kind == "punct" and self.peek().value == ")"):
                        while True:
                            e = self.parse_expr()
                            if e not in order:
                                order.append(e)
                            one.append(order.index(e))
                            if not self.accept_punct(","):
                                break
                    self.expect_punct(")")
                    sets.append(one)
                    if not self.accept_punct(","):
                        break
                self.expect_punct(")")
                stmt.group_by = order
                stmt.grouping_sets = sets
            else:
                while True:
                    if self.peek().kind == "number":
                        stmt.group_by.append(int(self.next().value))
                    else:
                        stmt.group_by.append(self.parse_expr())
                    if not self.accept_punct(","):
                        break
        if self.accept_kw("HAVING"):
            stmt.having = self.parse_expr()
        if allow_order and self.peek().is_kw("ORDER"):
            stmt.order_by = self._parse_order_by()
        if allow_order and self.peek().is_kw("LIMIT"):
            stmt.limit, stmt.offset = self._parse_limit()
        return stmt

    def _parse_group_exprs(self) -> list:
        out = [self.parse_expr()]
        while self.accept_punct(","):
            out.append(self.parse_expr())
        return out

    def _parse_order_by(self) -> list[SortKey]:
        self.expect_kw("ORDER")
        self.expect_kw("BY")
        keys = []
        while True:
            if self.peek().kind == "number":
                e: Expr = Literal(int(self.next().value))  # ordinal, resolved by planner
            else:
                e = self.parse_expr()
            asc = True
            if self.accept_kw("DESC"):
                asc = False
            else:
                self.accept_kw("ASC")
            nulls_first = not asc
            if self.accept_kw("NULLS"):
                t = self.next()
                nulls_first = t.is_kw("FIRST")
            keys.append(SortKey(e, asc, nulls_first))
            if not self.accept_punct(","):
                break
        return keys

    def _parse_limit(self) -> tuple[int | None, int]:
        self.expect_kw("LIMIT")
        t = self.next()
        if t.kind != "number":
            raise SqlParseError("expected number after LIMIT")
        fetch = int(t.value)
        offset = 0
        if self.accept_kw("OFFSET"):
            o = self.next()
            offset = int(o.value)
        return fetch, offset

    # -- table refs ---------------------------------------------------------

    def _parse_table_ref(self) -> Any:
        left = self._parse_table_factor()
        while True:
            jt = None
            if self.peek().is_kw("JOIN"):
                jt = "inner"
                self.next()
            elif self.peek().is_kw("INNER"):
                self.next()
                self.expect_kw("JOIN")
                jt = "inner"
            elif self.peek().is_kw("LEFT"):
                self.next()
                self.accept_kw("OUTER")
                if self.accept_kw("SEMI"):
                    jt = "left_semi"
                elif self.accept_kw("ANTI"):
                    jt = "left_anti"
                else:
                    jt = "left"
                self.expect_kw("JOIN")
            elif self.peek().is_kw("RIGHT"):
                self.next()
                self.accept_kw("OUTER")
                jt = "right"
                self.expect_kw("JOIN")
            elif self.peek().is_kw("FULL"):
                self.next()
                self.accept_kw("OUTER")
                jt = "full"
                self.expect_kw("JOIN")
            elif self.peek().is_kw("CROSS"):
                self.next()
                self.expect_kw("JOIN")
                right = self._parse_table_factor()
                left = JoinClause(left, right, "cross", None)
                continue
            if jt is None:
                return left
            right = self._parse_table_factor()
            on = None
            if self.accept_kw("ON"):
                on = self.parse_expr()
            elif self.accept_kw("USING"):
                self.expect_punct("(")
                cols = [self.expect_ident().lower()]
                while self.accept_punct(","):
                    cols.append(self.expect_ident().lower())
                self.expect_punct(")")
                on = None
                for c in cols:
                    eq = BinaryExpr(Column(c, _qual_of(left)), "=", Column(c, _qual_of_right(right)))
                    on = eq if on is None else BinaryExpr(on, "and", eq)
            left = JoinClause(left, right, jt, on)

    def _parse_table_factor(self) -> Any:
        if self.accept_punct("("):
            if self.peek().is_kw("VALUES"):
                vc = self._parse_values()
                self.expect_punct(")")
                alias = None
                cols = None
                if self.accept_kw("AS"):
                    alias = self.expect_ident().lower()
                elif self.peek().kind == "ident":
                    alias = self.next().value.lower()
                if alias and self.accept_punct("("):
                    cols = [self.expect_ident().lower()]
                    while self.accept_punct(","):
                        cols.append(self.expect_ident().lower())
                    self.expect_punct(")")
                vc.alias = alias or vc.alias
                vc.column_names = cols
                return vc
            sub = self.parse_query()
            self.expect_punct(")")
            alias = None
            if self.accept_kw("AS"):
                alias = self.expect_ident().lower()
            elif self.peek().kind == "ident":
                alias = self.next().value.lower()
            return DerivedTable(sub, alias or "__subquery__")
        name = self._parse_dotted_name().lower()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect_ident().lower()
        elif self.peek().kind == "ident":
            alias = self.next().value.lower()
        return TableName(name, alias)

    def _parse_values(self):
        self.expect_kw("VALUES")
        rows = []
        while True:
            self.expect_punct("(")
            row = [self._parse_literal_value()]
            while self.accept_punct(","):
                row.append(self._parse_literal_value())
            self.expect_punct(")")
            rows.append(row)
            if not self.accept_punct(","):
                break
        if any(len(r) != len(rows[0]) for r in rows):
            raise SqlParseError("VALUES rows have differing arities")
        return ValuesClause(rows)

    # -- expressions (Pratt) -------------------------------------------------

    def parse_expr(self) -> Expr:
        return self._parse_or()

    def _parse_or(self) -> Expr:
        left = self._parse_and()
        while self.peek().is_kw("OR"):
            self.next()
            left = BinaryExpr(left, "or", self._parse_and())
        return left

    def _parse_and(self) -> Expr:
        left = self._parse_not()
        while self.peek().is_kw("AND"):
            self.next()
            left = BinaryExpr(left, "and", self._parse_not())
        return left

    def _parse_not(self) -> Expr:
        if self.accept_kw("NOT"):
            return Not(self._parse_not())
        return self._parse_comparison()

    def _parse_comparison(self) -> Expr:
        left = self._parse_additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "<", "<=", ">", ">="):
            self.next()
            right = self._parse_additive()
            return BinaryExpr(left, t.value, right)
        negated = False
        if t.is_kw("NOT"):
            nxt = self.peek(1)
            if nxt.is_kw("IN", "LIKE", "BETWEEN"):
                self.next()
                negated = True
                t = self.peek()
        if t.is_kw("IN"):
            self.next()
            self.expect_punct("(")
            if self.peek().is_kw("SELECT", "WITH"):
                sub = self.parse_query()
                self.expect_punct(")")
                return InSubquery(left, sub, negated)
            vals = [self._parse_literal_value()]
            while self.accept_punct(","):
                vals.append(self._parse_literal_value())
            self.expect_punct(")")
            return InList(left, tuple(vals), negated)
        if t.is_kw("LIKE"):
            self.next()
            pat = self.next()
            if pat.kind != "string":
                raise SqlParseError("expected string pattern after LIKE")
            return Like(left, pat.value, negated)
        if t.is_kw("BETWEEN"):
            self.next()
            lo = self._parse_additive()
            self.expect_kw("AND")
            hi = self._parse_additive()
            return Between(left, lo, hi, negated)
        if t.is_kw("IS"):
            self.next()
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                return IsNotNull(left)
            self.expect_kw("NULL")
            return IsNull(left)
        return left

    def _parse_additive(self) -> Expr:
        left = self._parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                right = self._parse_multiplicative()
                left = BinaryExpr(left, t.value, right)
            elif t.kind == "op" and t.value == "||":
                self.next()
                right = self._parse_multiplicative()
                left = ScalarFunction("concat", (left, right))
            else:
                return left

    def _parse_multiplicative(self) -> Expr:
        left = self._parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = BinaryExpr(left, t.value, self._parse_unary())
            else:
                return left

    def _parse_unary(self) -> Expr:
        t = self.peek()
        if t.kind == "op" and t.value == "-":
            self.next()
            return Negative(self._parse_unary())
        if t.kind == "op" and t.value == "+":
            self.next()
            return self._parse_unary()
        return self._parse_primary()

    def _parse_literal_value(self) -> Any:
        t = self.next()
        if t.kind == "string":
            return t.value
        if t.kind == "number":
            return _num(t.value)
        if t.is_kw("TRUE"):
            return True
        if t.is_kw("FALSE"):
            return False
        if t.is_kw("NULL"):
            return None
        if t.is_kw("DATE"):
            s = self.next()
            return _dt.date.fromisoformat(s.value)
        if t.kind == "op" and t.value == "-":
            v = self._parse_literal_value()
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SqlParseError(f"cannot negate literal {v!r} at {t.pos}")
            return -v
        raise SqlParseError(f"expected literal, got {t.value!r} at {t.pos}")

    SOFT_KEYWORDS = ("ROLLUP", "CUBE", "GROUPING", "SETS")

    def _parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "kw" and t.value in self.SOFT_KEYWORDS:
            # contextual keywords: valid column/table names outside GROUP BY
            self.next()
            return self._parse_ident_expr_from(t.value.lower())
        if t.kind == "punct" and t.value == "(":
            self.next()
            if self.peek().is_kw("SELECT", "WITH"):
                sub = self.parse_query()
                self.expect_punct(")")
                return ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_punct(")")
            return e
        if t.is_kw("EXISTS"):
            self.next()
            self.expect_punct("(")
            sub = self.parse_query()
            self.expect_punct(")")
            return Exists(sub)
        if t.is_kw("NOT"):
            # NOT EXISTS handled at _parse_not; here only for safety
            self.next()
            return Not(self._parse_primary())
        if t.is_kw("CASE"):
            return self._parse_case()
        if t.is_kw("CAST"):
            self.next()
            self.expect_punct("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            ty = self._parse_type()
            self.expect_punct(")")
            return Cast(e, ty)
        if t.is_kw("EXTRACT"):
            self.next()
            self.expect_punct("(")
            part = self.expect_ident() if self.peek().kind == "ident" else self.next().value
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_punct(")")
            return ScalarFunction(f"extract_{part.lower()}", (e,))
        if t.is_kw("SUBSTRING"):
            self.next()
            self.expect_punct("(")
            e = self.parse_expr()
            if self.accept_kw("FROM"):
                start = self.parse_expr()
                length = None
                if self.accept_kw("FOR"):
                    length = self.parse_expr()
            else:
                self.expect_punct(",")
                start = self.parse_expr()
                length = None
                if self.accept_punct(","):
                    length = self.parse_expr()
            self.expect_punct(")")
            args = (e, start) if length is None else (e, start, length)
            return ScalarFunction("substr", args)
        if t.is_kw("DATE"):
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlParseError("expected string after DATE")
            return Literal(_dt.date.fromisoformat(s.value))
        if t.is_kw("INTERVAL"):
            self.next()
            s = self.next()
            # INTERVAL '3' MONTH  |  INTERVAL '1' YEAR  |  INTERVAL '90' DAY
            # also INTERVAL '3 month' (datafusion style)
            if s.kind != "string":
                raise SqlParseError("expected string after INTERVAL")
            text = s.value.strip()
            unit = None
            if self.peek().kind == "ident" and self.peek().value.upper() in (
                "DAY", "DAYS", "MONTH", "MONTHS", "YEAR", "YEARS",
            ):
                unit = self.next().value.upper()
            else:
                parts = text.split()
                if len(parts) == 2:
                    text, unit = parts[0], parts[1].upper()
            if unit is None:
                raise SqlParseError(f"cannot parse interval {s.value!r}")
            n = int(text)
            unit = unit.rstrip("S")
            return _IntervalLiteral(n, unit.lower())
        if t.is_kw("TRUE"):
            self.next()
            return Literal(True)
        if t.is_kw("FALSE"):
            self.next()
            return Literal(False)
        if t.is_kw("NULL"):
            self.next()
            return Literal(None)
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if t.kind == "number":
            self.next()
            return Literal(_num(t.value))
        if t.kind == "ident" or t.is_kw("LEFT", "RIGHT"):
            return self._parse_ident_expr()
        raise SqlParseError(f"unexpected token {t.value!r} at {t.pos}")

    def _parse_case(self) -> Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.peek().is_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("WHEN"):
            w = self.parse_expr()
            if operand is not None:
                w = BinaryExpr(operand, "=", w)
            self.expect_kw("THEN")
            th = self.parse_expr()
            branches.append((w, th))
        els = None
        if self.accept_kw("ELSE"):
            els = self.parse_expr()
        self.expect_kw("END")
        return Case(tuple(branches), els)

    def _parse_type(self) -> pa.DataType:
        t = self.next()
        name = t.value.upper()
        ty = _TYPE_NAMES.get(name)
        if ty is None:
            raise SqlParseError(f"unknown type {t.value!r}")
        # optional (p[,s]) — ignored (decimal policy / varchar length)
        if self.accept_punct("("):
            self.next()
            if self.accept_punct(","):
                self.next()
            self.expect_punct(")")
        return ty

    def _parse_ident_expr(self) -> Expr:
        return self._parse_ident_expr_from(self.next().value)

    def _parse_ident_expr_from(self, name: str) -> Expr:
        # function call?
        if self.peek().kind == "punct" and self.peek().value == "(":
            return self._parse_function(name)
        if self.accept_punct("."):
            col = self.expect_ident()
            return Column(col.lower(), name.lower())
        return Column(name.lower())

    def _parse_function(self, name: str) -> Expr:
        up = name.upper()
        self.expect_punct("(")
        if up == "COUNT":
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                self.expect_punct(")")
                return self._maybe_window(AggregateFunction("count", None))
            if self.accept_kw("DISTINCT"):
                arg = self.parse_expr()
                self.expect_punct(")")
                return self._maybe_window(AggregateFunction("count_distinct", arg, True))
            arg = self.parse_expr()
            self.expect_punct(")")
            return self._maybe_window(AggregateFunction("count", arg))
        if up in AGGREGATES:
            distinct = self.accept_kw("DISTINCT")
            arg = self.parse_expr()
            self.expect_punct(")")
            canonical = _AGG_CANONICAL.get(up.lower(), up.lower())
            return self._maybe_window(AggregateFunction(canonical, arg, distinct))
        args: list[Expr] = []
        if not (self.peek().kind == "punct" and self.peek().value == ")"):
            args.append(self.parse_expr())
            while self.accept_punct(","):
                args.append(self.parse_expr())
        self.expect_punct(")")
        canonical = SCALAR_FUNCS.get(up)
        if canonical is None:
            canonical = name.lower()
        if canonical == "strpos" and up == "POSITION":
            args = [args[1], args[0]] if len(args) == 2 else args
        return self._maybe_window(ScalarFunction(canonical, tuple(args)))

    def _maybe_window(self, fn: Expr) -> Expr:
        """fn(...) OVER (PARTITION BY ... ORDER BY ...) → WindowFunction."""
        if not self.accept_kw("OVER"):
            if isinstance(fn, ScalarFunction) and fn.name in (
                "row_number", "rank", "dense_rank", "lag", "lead"
            ):
                raise SqlParseError(f"{fn.name}() requires an OVER clause")
            return fn
        self.expect_punct("(")
        partition_by: list[Expr] = []
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self.parse_expr())
            while self.accept_punct(","):
                partition_by.append(self.parse_expr())
        order_by: list[SortKey] = []
        if self.peek().is_kw("ORDER"):
            order_by = self._parse_order_by()
        frame = self._maybe_frame()
        self.expect_punct(")")
        if isinstance(fn, AggregateFunction):
            if fn.distinct or fn.func == "count_distinct":
                raise SqlParseError("DISTINCT window aggregates are unsupported")
            if fn.func not in WINDOW_FUNCS:
                raise SqlParseError(f"{fn.func}() is not supported as a window function")
            func = fn.func
            args: tuple = (fn.arg,) if fn.arg is not None else ()
        elif isinstance(fn, ScalarFunction) and fn.name in WINDOW_FUNCS:
            func, args = fn.name, fn.args
        else:
            raise SqlParseError(f"{fn} is not a window function")
        if func in ("lag", "lead") and not (1 <= len(args) <= 3):
            raise SqlParseError(f"{func} takes 1-3 arguments, got {len(args)}")
        if func in ("row_number", "rank", "dense_rank") and args:
            raise SqlParseError(f"{func} takes no arguments")
        if frame is not None and func not in ("sum", "avg", "min", "max", "count"):
            raise SqlParseError(f"{func} does not take a frame clause")
        return WindowFunction(func, args, tuple(partition_by), tuple(order_by), frame)

    def _maybe_frame(self):
        """ROWS BETWEEN <bound> AND <bound> (contextual words — ROWS /
        UNBOUNDED / PRECEDING / FOLLOWING lex as identifiers, so columns
        with those names stay usable). RANGE frames with offsets are
        unsupported; the default RANGE UNBOUNDED..CURRENT is frame=None."""
        t = self.peek()
        word = t.value.upper() if t.kind == "ident" else ""
        if word not in ("ROWS", "RANGE"):
            return None
        self.next()
        if word == "RANGE":
            raise SqlParseError("explicit RANGE frames are unsupported (use ROWS)")

        def bound(is_start: bool) -> int | None:
            b = self.next()
            w = b.value.upper() if b.kind in ("ident", "number") else b.value
            if w == "UNBOUNDED":
                side = self.next().value.upper()
                # direction is positional: only UNBOUNDED PRECEDING can open
                # a frame, only UNBOUNDED FOLLOWING can close one
                want = "PRECEDING" if is_start else "FOLLOWING"
                if side != want:
                    raise SqlParseError(
                        f"UNBOUNDED {side} is invalid as a frame "
                        f"{'start' if is_start else 'end'} (expected {want})"
                    )
                return None
            if w == "CURRENT":
                nxt = self.next()
                if nxt.value.upper() != "ROW":
                    raise SqlParseError("expected ROW after CURRENT")
                return 0
            if b.kind == "number":
                side = self.next().value.upper()
                try:
                    off = int(b.value)
                except ValueError:
                    raise SqlParseError(f"frame offset must be an integer, got {b.value!r}") from None
                if side == "PRECEDING":
                    return -off
                if side == "FOLLOWING":
                    return off
                raise SqlParseError("expected PRECEDING/FOLLOWING after frame offset")
            raise SqlParseError(f"bad frame bound {b.value!r}")

        if self.accept_kw("BETWEEN"):
            start = bound(True)
            self.expect_kw("AND")
            end = bound(False)
        else:
            start = bound(True)
            end = 0  # single-bound form: <bound> .. CURRENT ROW
        return ("rows", start, end)


def _num(s: str):
    if "e" in s or "E" in s:
        return float(s)  # scientific notation: approximate by intent
    if "." in s:
        # exact decimal policy: plain decimal literals carry minimal
        # precision/scale (0.06 → decimal(2,2)) so money arithmetic stays
        # exact; Arrow promotes them transparently in float contexts
        import decimal

        return decimal.Decimal(s)
    return int(s)


class _IntervalLiteral(Literal):
    """Interval literal (days/months/years); arithmetic handled by engines."""

    def __init__(self, n: int, unit: str):
        super().__init__((n, unit))
        object.__setattr__(self, "n", n)
        object.__setattr__(self, "unit", unit)

    def data_type(self, schema):
        return pa.duration("s")  # placeholder; date arithmetic handled specially

    def __str__(self) -> str:
        return f"INTERVAL '{self.n}' {self.unit.upper()}"


def _qual_of(ref: Any) -> str | None:
    from ballista_tpu.sql.ast import DerivedTable, JoinClause, TableName

    if isinstance(ref, TableName):
        return ref.alias or ref.name
    if isinstance(ref, DerivedTable):
        return ref.alias
    return None


def _qual_of_right(ref: Any) -> str | None:
    return _qual_of(ref)


def parse_sql(sql: str) -> Any:
    p = Parser(sql)
    stmt = p.parse_statement()
    p.accept_punct(";")
    t = p.peek()
    if t.kind != "eof":
        raise SqlParseError(f"unexpected trailing input at {t.pos}: {t.value!r}")
    return stmt
