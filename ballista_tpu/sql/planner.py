"""SQL planner (binder): AST → LogicalPlan.

Responsibilities:
- resolve table names (catalog + CTE environment) and aliases,
- plan FROM (comma refs become CrossJoins; explicit JOIN ... ON splits into
  equi keys + residual filter),
- detect aggregates and rewrite post-aggregation expressions to reference
  aggregate outputs,
- plan subqueries recursively (correlated columns stay unresolved inside the
  subplan; the decorrelation optimizer turns them into joins),
- resolve ORDER BY aliases/ordinals against the projection output.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

from ballista_tpu.errors import PlanningError
from ballista_tpu.plan.expressions import (
    AggregateFunction,
    Alias,
    BinaryExpr,
    Column,
    Exists,
    Expr,
    InSubquery,
    Literal,
    ScalarSubquery,
    SortKey,
    WindowFunction,
    collect_columns,
    split_conjunction,
    transform_expr,
)
from ballista_tpu.plan.logical import (
    Aggregate,
    CrossJoin,
    Distinct,
    EmptyRelation,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Sort,
    SubqueryAlias,
    TableScan,
    Union,
    Window,
)
from ballista_tpu.sql.ast import DerivedTable, JoinClause, SelectStmt, TableName


class SqlPlanner:
    def __init__(self, catalog):
        self.catalog = catalog

    def plan_query(self, stmt: SelectStmt, cte_env: dict[str, LogicalPlan] | None = None) -> LogicalPlan:
        cte_env = dict(cte_env or {})
        for name, sub in stmt.ctes:
            cte_env[name] = self.plan_query(sub, cte_env)
        # a trailing ORDER BY / LIMIT of a set-operation chain orders the
        # WHOLE union, not the first branch — defer them past the Union
        defer = stmt.set_op is not None
        plan = self._plan_select(stmt, cte_env, defer_order=defer)
        if stmt.set_op is not None:
            # LEFT-associative chain walk: `a UNION ALL b UNION c` dedups
            # the whole accumulated left side, never just a branch
            # collect the chain, then apply SQL precedence: INTERSECT
            # binds tighter than UNION/EXCEPT
            chain = [(None, plan)]
            cur = stmt
            while cur.set_op is not None:
                op, rhs = cur.set_op
                chain.append((op, self._plan_select(rhs, cte_env, defer_order=True)))
                cur = rhs
            terms: list[tuple[str | None, LogicalPlan]] = []
            for op, p_ in chain:
                if op in ("intersect", "intersect_all") and terms:
                    lop, lp = terms[-1]
                    terms[-1] = (lop, self._set_op_join(lp, p_, op))
                else:
                    terms.append((op, p_))
            plan = terms[0][1]
            for op, p_ in terms[1:]:
                if op in ("union", "union_all"):
                    plan = Union([plan, p_], all=(op == "union_all"))
                    if op == "union":
                        plan = Distinct(plan)
                else:  # except / except_all
                    plan = self._set_op_join(plan, p_, op)
            if stmt.order_by:
                keys = []
                for sk in stmt.order_by:
                    e = sk.expr
                    if isinstance(e, Literal) and isinstance(e.value, int):
                        e = Column(plan.schema.field(e.value - 1).name)
                    keys.append(SortKey(e, sk.ascending, sk.nulls_first))
                plan = Sort(plan, keys, fetch=None)
            if stmt.limit is not None or stmt.offset:
                if isinstance(plan, Sort):
                    plan = replace(
                        plan,
                        fetch=(stmt.limit + stmt.offset) if stmt.limit is not None else None,
                    )
                    plan.__post_init__()
                plan = Limit(plan, stmt.limit, stmt.offset)
        return plan

    # ------------------------------------------------------------------

    def _plan_select(self, stmt: SelectStmt, cte_env: dict[str, LogicalPlan],
                     defer_order: bool = False) -> LogicalPlan:
        # FROM
        if stmt.from_tables:
            plan = self._plan_table_ref(stmt.from_tables[0], cte_env)
            for ref in stmt.from_tables[1:]:
                plan = CrossJoin(plan, self._plan_table_ref(ref, cte_env))
        else:
            plan = EmptyRelation(produce_one_row=True)

        # WHERE
        if stmt.where is not None:
            pred = self._bind_subqueries(stmt.where, cte_env)
            plan = Filter(plan, pred)

        # projections: expand *, bind subqueries
        projections: list[Expr] = []
        for e in stmt.projections:
            if isinstance(e, Column) and e.name == "*":
                for f in plan.schema:
                    projections.append(Column(f.name, f.qualifier))
            else:
                projections.append(self._bind_subqueries(e, cte_env))

        having = self._bind_subqueries(stmt.having, cte_env) if stmt.having is not None else None

        # GROUP BY (ordinals refer to select list)
        group_exprs: list[Expr] = []
        for g in stmt.group_by:
            if isinstance(g, int):
                e = projections[g - 1]
                group_exprs.append(e.expr if isinstance(e, Alias) else e)
            else:
                ge = self._bind_subqueries(g, cte_env)
                # GROUP BY may name a select alias
                ge = self._substitute_select_alias(ge, projections)
                group_exprs.append(ge)

        agg_funcs = _collect_aggs(projections + ([having] if having is not None else []))

        if stmt.grouping_sets is not None:
            plan = self._plan_grouping_sets(
                plan, stmt.grouping_sets, group_exprs, agg_funcs, projections, having
            )
            # first branch's projection stands in for ORDER BY resolution
            proj = plan.inputs[0] if isinstance(plan, Union) else plan
        else:
            if group_exprs or agg_funcs:
                agg = Aggregate(plan, group_exprs, agg_funcs)
                rewrite = lambda e: _rewrite_post_agg(e, group_exprs, agg_funcs)
                projections = [rewrite(p) for p in projections]
                plan = agg
                if having is not None:
                    plan = Filter(plan, rewrite(having))

            # window functions compute over the (post-aggregation) input;
            # each unique window expr becomes a __win{i} column
            window_exprs = _collect_windows(projections)
            if window_exprs:
                win = Window(plan, window_exprs)

                def rewrite_win(e: Expr) -> Expr:
                    def repl(x: Expr) -> Expr:
                        if isinstance(x, WindowFunction):
                            return Column(f"__win{window_exprs.index(x)}")
                        return x

                    return transform_expr(e, repl)

                projections = [rewrite_win(p) for p in projections]
                plan = win

            proj = Projection(plan, projections)
            plan = proj

        if stmt.distinct:
            plan = Distinct(plan)

        if defer_order:
            return plan  # union chain: ORDER BY/LIMIT applied above the Union

        # ORDER BY against projection output
        if stmt.order_by:
            keys = []
            for sk in stmt.order_by:
                e = sk.expr
                if isinstance(e, Literal) and isinstance(e.value, int):
                    e = Column(plan.schema.field(e.value - 1).name)
                else:
                    e = self._resolve_order_expr(e, proj, cte_env)
                keys.append(SortKey(e, sk.ascending, sk.nulls_first))
            plan = Sort(plan, keys, fetch=None)

        if stmt.limit is not None or stmt.offset:
            if isinstance(plan, Sort):
                plan = replace(plan, fetch=(stmt.limit + stmt.offset) if stmt.limit is not None else None)
                plan.__post_init__()
            plan = Limit(plan, stmt.limit, stmt.offset)
        return plan

    def _set_op_join(self, left: LogicalPlan, right: LogicalPlan, op: str) -> LogicalPlan:
        """INTERSECT = distinct left SEMI-joined to right on every column;
        EXCEPT = distinct left ANTI-joined. The ALL (bag) forms number
        duplicate rows per side with row_number() partitioned by the whole
        row and include the number in the join key — the k-th copy on the
        left matches only a k-th copy on the right (standard lowering).
        Keys are null-safe: each column contributes (IS NULL flag,
        COALESCE(col, typed default)) so NULLs compare equal per SQL set
        semantics without sentinel collisions."""
        import datetime as _dt

        import pyarrow as _pa

        from ballista_tpu.plan.expressions import IsNull, ScalarFunction, WindowFunction
        from ballista_tpu.plan.logical import Window

        if len(left.schema.fields) != len(right.schema.fields):
            raise PlanningError(f"{op.upper()} arity mismatch")
        for side in (left, right):
            names = [f.name for f in side.schema.fields]
            if len(set(names)) != len(names):
                raise PlanningError(
                    f"{op.upper()} requires distinct output column names; "
                    f"alias the duplicates ({names})"
                )

        def default_for(t):
            if _pa.types.is_floating(t):
                return Literal(0.0)
            if _pa.types.is_integer(t):
                return Literal(0)
            if _pa.types.is_boolean(t):
                return Literal(False)
            if _pa.types.is_date(t):
                return Literal(_dt.date(1970, 1, 1))
            return Literal("")

        bag = op.endswith("_all")
        n_cols = len(left.schema.fields)

        def numbered(side: LogicalPlan) -> LogicalPlan:
            part = tuple(Column(f.name, f.qualifier) for f in side.schema.fields)
            w = Window(side, [WindowFunction("row_number", (), part, ())])
            # __win0 → a stable name distinct from user columns
            return Projection(w, [Column(f.name, f.qualifier)
                                  for f in side.schema.fields]
                              + [Alias(Column("__win0"), "__dup_n")])

        if bag:
            lw = SubqueryAlias(numbered(left), "__setl")
            rw = SubqueryAlias(numbered(right), "__setr")
        else:
            lw = SubqueryAlias(Distinct(left), "__setl")
            rw = SubqueryAlias(right, "__setr")
        on = []
        for lf, rf in list(zip(lw.schema.fields, rw.schema.fields))[:n_cols]:
            lc, rc = Column(lf.name, "__setl"), Column(rf.name, "__setr")
            on.append((IsNull(lc), IsNull(rc)))
            on.append((ScalarFunction("coalesce", (lc, default_for(lf.dtype))),
                       ScalarFunction("coalesce", (rc, default_for(rf.dtype)))))
        if bag:
            on.append((Column("__dup_n", "__setl"), Column("__dup_n", "__setr")))
        jt = "left_semi" if op.startswith("intersect") else "left_anti"
        joined = Join(lw, rw, on, jt, None)
        return Projection(joined, [
            Alias(Column(f.name, "__setl"), f.name)
            for f in lw.schema.fields[:n_cols]
        ])

    def _plan_grouping_sets(self, plan: LogicalPlan, sets: list[list[int]],
                            group_exprs: list[Expr], agg_funcs: list[Expr],
                            projections: list[Expr], having) -> LogicalPlan:
        """ROLLUP/CUBE/GROUPING SETS lowering: one Aggregate branch per
        grouping set, grouped-out keys projected as typed NULLs, branches
        UNION ALLed (the standard expansion; DataFusion lowers the same
        way behind the reference)."""
        from ballista_tpu.plan.expressions import Cast, ScalarFunction

        # window exprs compute AFTER the union (over all grouping-set rows);
        # inside branches they are replaced by their (aggregate) inputs'
        # outputs, referenced by name post-union
        window_exprs = _collect_windows(projections)

        branches: list[LogicalPlan] = []
        for s in sets:
            set_exprs = [group_exprs[i] for i in s]
            dropped = [g for i, g in enumerate(group_exprs) if i not in s]

            def per_branch(e: Expr) -> Expr:
                # grouping(col): 1 when col is grouped-out in this set
                # (constant per branch — the SQL grouping() marker fn);
                # grouped-out OUTPUT keys become typed NULLs; aggregate
                # arguments keep seeing real values and agg subtrees stay
                # structurally identical for _rewrite_post_agg to match
                if isinstance(e, ScalarFunction) and e.name == "grouping" and len(e.args) == 1:
                    arg = e.args[0]
                    if any(_group_key_matches(arg, d) for d in dropped):
                        return Literal(1)
                    if any(_group_key_matches(arg, g) for g in group_exprs):
                        return Literal(0)
                    raise PlanningError(f"grouping({arg}) is not a GROUP BY expression")
                if isinstance(e, AggregateFunction):
                    return e
                for d in dropped:
                    if e == d:
                        return Cast(Literal(None), d.data_type(plan.schema))
                kids = e.children()
                if kids:
                    new_kids = [per_branch(k) for k in kids]
                    if new_kids != kids:
                        return e.with_children(new_kids)
                return e

            node: LogicalPlan = Aggregate(plan, set_exprs, agg_funcs)
            if having is not None:
                node = Filter(node, _rewrite_post_agg(per_branch(having), set_exprs, agg_funcs))
            branch_projs: list[Expr] = []
            for p in projections:
                name = p.name if isinstance(p, Alias) else p.output_name()
                inner = p.expr if isinstance(p, Alias) else p
                if window_exprs:
                    inner = _strip_windows(inner)
                pe = _rewrite_post_agg(per_branch(inner), set_exprs, agg_funcs)
                branch_projs.append(Alias(pe, name))
            branches.append(Projection(node, branch_projs))
        out: LogicalPlan = Union(branches, all=True)

        if window_exprs:
            # rebuild the window exprs against the UNION output (aggregate
            # and grouped-key references resolve by projection name), wrap a
            # Window node, and project the final select list
            name_of = {}
            for p in projections:
                name = p.name if isinstance(p, Alias) else p.output_name()
                inner = p.expr if isinstance(p, Alias) else p
                name_of[str(_strip_windows(inner))] = name

            def to_union_cols(e: Expr) -> Expr:
                key = str(e)
                if key in name_of:
                    return Column(name_of[key])
                # unresolvable aggregate/grouping markers must error BEFORE
                # child remapping could disguise them as evaluable exprs
                if isinstance(e, AggregateFunction) or (
                    isinstance(e, ScalarFunction) and e.name == "grouping"
                ):
                    raise PlanningError(
                        f"window input {e} must appear in the SELECT list when "
                        "windowing over GROUPING SETS"
                    )
                kids = e.children()
                if kids:
                    nk = [to_union_cols(k) for k in kids]
                    if nk != kids:
                        return e.with_children(nk)
                return e

            uwindows = []
            for w in window_exprs:
                uwindows.append(WindowFunction(
                    w.func,
                    tuple(to_union_cols(a) for a in w.args),
                    tuple(to_union_cols(pb) for pb in w.partition_by),
                    tuple(SortKey(to_union_cols(k.expr), k.ascending, k.nulls_first)
                          for k in w.order_by),
                    w.frame,
                ))
            win = Window(out, uwindows)
            final_projs: list[Expr] = []
            for p in projections:
                name = p.name if isinstance(p, Alias) else p.output_name()
                inner = p.expr if isinstance(p, Alias) else p

                def repl(x: Expr) -> Expr:
                    if isinstance(x, WindowFunction):
                        return Column(f"__win{window_exprs.index(x)}")
                    return x

                mapped = transform_expr(inner, repl)
                # non-window parts now reference the union columns by name
                def nonwin(x: Expr) -> Expr:
                    key = str(x)
                    if key in name_of and not isinstance(x, Column):
                        return Column(name_of[key])
                    return x

                mapped = transform_expr(mapped, nonwin)
                _assert_fully_resolved(mapped)
                final_projs.append(Alias(mapped, name))
            out = Projection(win, final_projs)
        return out

    def _resolve_order_expr(self, e: Expr, proj: Projection, cte_env) -> Expr:
        out_schema = proj.schema
        if isinstance(e, Column) and e.qualifier is None:
            if out_schema.maybe_index_of(e.name) is not None:
                return e
        # structural match against a projection expr (e.g. ORDER BY sum(x))
        bound = self._bind_subqueries(e, cte_env)
        for p in proj.exprs:
            inner = p.expr if isinstance(p, Alias) else p
            if inner == bound:
                return Column(p.output_name())
        # falls through: expression over projection-output columns
        return bound

    def _substitute_select_alias(self, e: Expr, projections: list[Expr]) -> Expr:
        if isinstance(e, Column) and e.qualifier is None:
            for p in projections:
                if isinstance(p, Alias) and p.name == e.name:
                    return p.expr
        return e

    # ------------------------------------------------------------------

    def _plan_table_ref(self, ref: Any, cte_env: dict[str, LogicalPlan]) -> LogicalPlan:
        if isinstance(ref, TableName):
            if ref.name in cte_env:
                return SubqueryAlias(cte_env[ref.name], ref.alias or ref.name)
            provider = self.catalog.get(ref.name)
            if provider is None:
                raise PlanningError(f"table not found: {ref.name}")
            return TableScan(ref.name, provider, alias=ref.alias)
        if isinstance(ref, DerivedTable):
            return SubqueryAlias(self.plan_query(ref.select, cte_env), ref.alias)
        from ballista_tpu.sql.ast import ValuesClause

        if isinstance(ref, ValuesClause):
            from ballista_tpu.plan.logical import Values

            # schema derives from the FIRST row: later rows must agree (a
            # clean error beats an opaque ArrowInvalid at execution); None
            # is compatible with anything
            first = ref.rows[0]
            for r in ref.rows[1:]:
                for a, b in zip(first, r):
                    if a is None or b is None:
                        continue
                    ta = float if isinstance(a, float) else type(a)
                    tb = float if isinstance(b, float) else type(b)
                    if isinstance(a, bool) != isinstance(b, bool) or (
                        ta is not tb and not ({ta, tb} == {int, float})
                    ):
                        raise PlanningError(
                            f"VALUES rows mix types: {a!r} vs {b!r}"
                        )
                    if {ta, tb} == {int, float}:
                        raise PlanningError(
                            f"VALUES rows mix int and float ({a!r} vs {b!r}); "
                            "write consistent numeric literals"
                        )
            if any(v is None for v in first):
                raise PlanningError(
                    "NULL in the first VALUES row leaves its column untyped; "
                    "put a typed value first"
                )
            node: LogicalPlan = Values(ref.rows)
            if ref.column_names:
                if len(ref.column_names) != len(node.schema.fields):
                    raise PlanningError(
                        f"VALUES arity {len(node.schema.fields)} != column list "
                        f"{len(ref.column_names)}"
                    )
                node = Projection(node, [
                    Alias(Column(f.name), cn)
                    for f, cn in zip(node.schema.fields, ref.column_names)
                ])
            return SubqueryAlias(node, ref.alias)
        if isinstance(ref, JoinClause):
            left = self._plan_table_ref(ref.left, cte_env)
            right = self._plan_table_ref(ref.right, cte_env)
            if ref.join_type == "cross" or ref.on is None:
                return CrossJoin(left, right)
            on = self._bind_subqueries(ref.on, cte_env)
            keys, residual = split_join_condition(on, left.schema, right.schema)
            return Join(left, right, keys, ref.join_type, residual)
        raise PlanningError(f"unsupported table ref {ref!r}")

    # ------------------------------------------------------------------

    def _bind_subqueries(self, e: Expr, cte_env: dict[str, LogicalPlan]) -> Expr:
        """Replace raw SelectStmt payloads inside subquery exprs with planned
        LogicalPlans. Correlated outer columns remain unresolved names."""

        def fn(x: Expr) -> Expr:
            if isinstance(x, ScalarSubquery) and isinstance(x.plan, SelectStmt):
                return ScalarSubquery(self.plan_query(x.plan, cte_env))
            if isinstance(x, InSubquery) and isinstance(x.plan, SelectStmt):
                return InSubquery(x.expr, self.plan_query(x.plan, cte_env), x.negated)
            if isinstance(x, Exists) and isinstance(x.plan, SelectStmt):
                return Exists(self.plan_query(x.plan, cte_env), x.negated)
            return x

        return transform_expr(e, fn)


# -- helpers ----------------------------------------------------------------


def _group_key_matches(arg: Expr, key: Expr) -> bool:
    """grouping() argument vs a GROUP BY expression: structural equality,
    with qualifier-tolerant Column matching (grouping(t.a) vs GROUP BY a)."""
    if arg == key:
        return True
    if isinstance(arg, Column) and isinstance(key, Column) and arg.name == key.name:
        return arg.qualifier is None or key.qualifier is None or arg.qualifier == key.qualifier
    return False


def _assert_fully_resolved(e: Expr) -> None:
    """Post-union projections must not retain aggregate/grouping nodes —
    they are only evaluable inside the per-set branches."""
    from ballista_tpu.plan.expressions import ScalarFunction

    if isinstance(e, AggregateFunction) or (
        isinstance(e, ScalarFunction) and e.name == "grouping"
    ):
        raise PlanningError(
            f"{e} must appear in the SELECT list to be referenced alongside a "
            "window over GROUPING SETS"
        )
    for c in e.children():
        _assert_fully_resolved(c)


def _strip_windows(e: Expr) -> Expr:
    """Inside grouping-set branches a window expr contributes nothing —
    replace with a typed NULL placeholder (the post-union Window recomputes
    the real value; the final projection overwrites this column)."""
    from ballista_tpu.plan.expressions import Cast

    def repl(x: Expr) -> Expr:
        if isinstance(x, WindowFunction):
            import pyarrow as _pa

            return Cast(Literal(None), _pa.float64())
        return x

    return transform_expr(e, repl)


def _collect_windows(exprs: list[Expr]) -> list[Expr]:
    """Unique WindowFunction expressions, in first-appearance order."""
    seen: list[Expr] = []

    def walk(e: Expr) -> None:
        if isinstance(e, WindowFunction):
            if e not in seen:
                seen.append(e)
            return
        for c in e.children():
            walk(c)

    for e in exprs:
        walk(e)
    return seen


def _collect_aggs(exprs: list[Expr]) -> list[Expr]:
    """Unique aggregate function expressions, in first-appearance order."""
    seen: list[Expr] = []

    def walk(e: Expr) -> None:
        if isinstance(e, AggregateFunction):
            if e not in seen:
                seen.append(e)
            return  # no nested aggs
        for c in e.children():
            walk(c)
        if isinstance(e, (ScalarSubquery, InSubquery, Exists)):
            pass  # subquery aggs belong to the subquery

    for e in exprs:
        walk(e)
    return seen


def _rewrite_post_agg(e: Expr, group_exprs: list[Expr], agg_funcs: list[Expr]) -> Expr:
    """Rewrite an expression evaluated above an Aggregate so every group-expr
    / agg-func occurrence becomes a column reference to the aggregate output."""

    def rec(x: Expr) -> Expr:
        if isinstance(x, Alias):
            return Alias(rec(x.expr), x.name)
        for g in group_exprs:
            if x == g:
                return Column(g.output_name(), g.qualifier if isinstance(g, Column) else None)
        if isinstance(x, AggregateFunction):
            for a in agg_funcs:
                if x == a:
                    return Column(a.output_name())
            raise PlanningError(f"aggregate {x} not in aggregate node")
        kids = x.children()
        if kids:
            return x.with_children([rec(k) for k in kids])
        return x

    return rec(e)


def split_join_condition(on: Expr, left_schema, right_schema):
    """Split an ON condition into equi-key pairs and a residual filter."""
    keys: list[tuple[Expr, Expr]] = []
    residual: list[Expr] = []
    for c in split_conjunction(on):
        pair = _as_equi_pair(c, left_schema, right_schema)
        if pair is not None:
            keys.append(pair)
        else:
            residual.append(c)
    res = None
    if residual:
        from ballista_tpu.plan.expressions import and_

        res = and_(*residual)
    return keys, res


def _resolves(e: Expr, schema) -> bool:
    cols = collect_columns(e)
    if not cols:
        return False  # constants belong in residual
    return all(schema.maybe_index_of(c.name, c.qualifier) is not None for c in cols)


def _as_equi_pair(c: Expr, left_schema, right_schema):
    if isinstance(c, BinaryExpr) and c.op == "=":
        l, r = c.left, c.right
        if _resolves(l, left_schema) and _resolves(r, right_schema):
            return (l, r)
        if _resolves(r, left_schema) and _resolves(l, right_schema):
            return (r, l)
    return None
