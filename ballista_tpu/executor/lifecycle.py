"""Executor lifecycle: drain-time shuffle migration + startup orphan sweep.

Drain protocol (docs/lifecycle.md): when the scheduler drains an
executor, the map outputs it holds are HANDED OFF to a survivor instead
of being declared lost. The migration rides the existing coalesced
Flight path — the destination pulls each location's stored byte range
(CRC-verified against the source's declared checksum), commits it under
its own work dir with the writer's tmp+rename discipline, and this
module rewrites the PartitionLocation IN PLACE. Locations are shared by
reference between `stage.completed` and every reader built from them, so
the rewrite retargets downstream fetches without any stage rerun — the
post-drain `executor_lost` sweep finds nothing left that names the
drained executor.

Hard-kill mid-migration (chaos mode=drain_kill) aborts the loop after N
applied locations; the unrewritten remainder still names the drained
executor, so the same `executor_lost` sweep recomputes exactly those
stages — today's recovery path, byte-identical results.

The startup sweep is the crash-recovery half of orphaned-data GC: an
executor that died uncleanly leaves shuffle/spill job dirs its next
incarnation would never reclaim. The sweep is scoped to the executor's
OWN work dir (per-process identity — no reaching into peers' dirs) and
age-gated so a restart never races a live scheduler's `remove_job_data`.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time

logger = logging.getLogger(__name__)


class DrainKilled(RuntimeError):
    """Chaos mode=drain_kill fired: the drain's migration died after N
    committed locations (simulating a hard-kill mid-handoff)."""


def migration_ticket(loc) -> dict:
    """Flight ticket for one location's byte range, plus the map identity
    the destination bakes into its committed file name."""
    return {
        "path": loc.path,
        "layout": loc.layout,
        "output_partition": loc.output_partition,
        "job_id": loc.job_id,
        "stage_id": loc.stage_id,
        "map_partition": loc.map_partition,
    }


def apply_migration(loc, dest_meta, new_path: str) -> None:
    """Rewrite one PartitionLocation in place to its migrated home. The
    object is shared by reference with every reader already built from it,
    so this single mutation retargets all downstream fetches. Migrated
    ranges always commit as hash layout (each range is a complete IPC
    stream, so the whole-file read is exactly the old range read)."""
    loc.executor_id = dest_meta.id
    loc.host = dest_meta.host
    loc.flight_port = dest_meta.flight_port
    loc.path = new_path
    loc.layout = "hash"


def migrate_via_flight(source_addr: str, dest_addr: str, locations,
                       dest_meta) -> tuple[int, int]:
    """Hand `locations` (all held by the executor at `source_addr`) off to
    the destination executor: one `migrate_pull` action on the DEST data
    plane pulls + commits every range, and each returned commit rewrites
    its location in place. Returns (migrated_count, migrated_bytes).

    Chaos mode=drain_kill aborts after N applied locations with
    DrainKilled — the caller treats the drain as a hard-kill and falls
    back to the recompute path for the unrewritten remainder."""
    import pyarrow.flight as flight

    from ballista_tpu.executor.chaos import drain_kill_after
    from ballista_tpu.flight.client import POOL

    if not locations:
        return 0, 0
    kill_after = drain_kill_after()
    tickets = [migration_ticket(l) for l in locations]
    client = POOL.get(dest_addr)
    action = flight.Action(
        "migrate_pull",
        json.dumps({"source": source_addr, "locations": tickets}).encode())
    count = 0
    nbytes = 0
    for r in client.do_action(action):
        h = json.loads(r.body.to_pybytes().decode())
        apply_migration(locations[int(h["i"])], dest_meta, h["path"])
        count += 1
        nbytes += int(h.get("nbytes", 0))
        if kill_after and count >= kill_after:
            raise DrainKilled(
                f"chaos: drain killed after {count}/{len(locations)} migrated locations")
    return count, nbytes


def migrate_local(locations, dest_meta) -> tuple[int, int]:
    """Shared-work-dir migration (single-process standalone): the files
    are already readable by the surviving data plane, so the handoff is
    pure relabeling — rewrite the owning executor identity, keep the path
    and layout. Honors drain_kill the same way the Flight path does."""
    from ballista_tpu.executor.chaos import drain_kill_after

    kill_after = drain_kill_after()
    count = 0
    nbytes = 0
    for loc in locations:
        loc.executor_id = dest_meta.id
        loc.host = dest_meta.host
        loc.flight_port = dest_meta.flight_port
        count += 1
        nbytes += int(getattr(loc.stats, "num_bytes", 0))
        if kill_after and count >= kill_after:
            raise DrainKilled(
                f"chaos: drain killed after {count}/{len(locations)} migrated locations")
    return count, nbytes


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


def sweep_stale_dirs(work_dir: str, max_age_s: float,
                     now: float | None = None) -> tuple[int, int]:
    """Startup orphan sweep: remove job dirs under this executor's OWN
    work dir whose last modification predates `max_age_s` — artifacts of a
    crashed prior run that no scheduler will ever `remove_job_data` for.
    Age-gated so a fresh restart cannot race a live job's files, and
    bounded to the work dir's immediate children (the job-dir layout,
    shuffle/paths.py). Returns (orphans_reclaimed, bytes_reclaimed)."""
    if max_age_s <= 0 or not work_dir or not os.path.isdir(work_dir):
        return 0, 0
    now = time.time() if now is None else now
    cutoff = now - max_age_s
    orphans = 0
    reclaimed = 0
    try:
        entries = os.listdir(work_dir)
    except OSError:
        return 0, 0
    for name in entries:
        d = os.path.join(work_dir, name)
        try:
            if not os.path.isdir(d) or os.path.getmtime(d) > cutoff:
                continue
        except OSError:
            continue
        nbytes = _dir_bytes(d)
        shutil.rmtree(d, ignore_errors=True)
        orphans += 1
        reclaimed += nbytes
        logger.info("startup sweep reclaimed stale dir %s (%d bytes)", d, nbytes)
    return orphans, reclaimed
