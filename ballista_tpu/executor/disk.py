"""Disk-pressure watermarks: the executor's storage admission ladder.

Two thresholds over the used fraction of the work-dir filesystem
(`shutil.disk_usage`), checked at distinct admission points so pressure
sheds the OPTIONAL writes first and the MANDATORY ones last
(docs/lifecycle.md#watermark-ladder):

- low watermark (`ballista.executor.disk.low.watermark`) — spill
  admission sheds: the sort-shuffle writer stops demoting buffers to
  disk (`spill_allowed`) and the HBM spill pool keeps cold entries in
  the host tier. Queries keep running on the in-memory overcommit
  ladder; only disk-optional writes stop.
- high watermark (`ballista.executor.disk.high.watermark`) — task
  admission rejects: `Executor.run_task` turns new tasks away with a
  retryable DiskExhausted (`admission_blocked`), the scheduler re-pends
  the slice, and the per-executor disk gauges on the heartbeat steer
  placement toward nodes with headroom.

An actual ENOSPC from the filesystem (errno 28) is the ladder's
backstop: the write points wrap it as the same typed `DiskExhausted`
(see `shuffle/writer.py`, `ops/tpu/hbm.py`), so a disk that fills
faster than the watermarks can react still fails blame-aware and
retryable instead of crashing the task untyped.

`disk_status` caches the statvfs result briefly — admission runs per
task and per spill, and the fraction moves on a much coarser clock
than either.
"""

from __future__ import annotations

import errno
import shutil
import time

from ballista_tpu.utils.lru import LruDict

# path → (sampled_at, used_frac, used_bytes, free_bytes); tiny TTL cache
# so per-spill checks don't syscall-storm statvfs
_STATUS_CACHE = LruDict(max_entries=16)
_CACHE_TTL_S = 1.0

# test seam: force the observed used fraction (None = measure). Module
# state, set/cleared by tests and exercises — watermark behavior must be
# provable without actually filling a disk.
_FORCED_FRACTION: float | None = None


def force_used_fraction(frac: float | None) -> None:
    """Test seam: pin the used fraction `disk_status` reports (None =
    measure the real filesystem again). Clears the status cache."""
    global _FORCED_FRACTION
    _FORCED_FRACTION = frac
    _STATUS_CACHE.clear()


def disk_status(path: str) -> tuple[float, int, int]:
    """(used_fraction, used_bytes, free_bytes) for the filesystem holding
    `path`. Never raises: an unstatable path reports zero pressure (the
    write itself will surface the real error, typed)."""
    now = time.time()
    cached = _STATUS_CACHE.get(path)
    if cached is not None and now - cached[0] < _CACHE_TTL_S:
        return cached[1], cached[2], cached[3]
    if _FORCED_FRACTION is not None:
        frac = float(_FORCED_FRACTION)
        total = 1 << 30
        used = int(frac * total)
        out = (frac, used, total - used)
    else:
        try:
            du = shutil.disk_usage(path)
            frac = du.used / du.total if du.total > 0 else 0.0
            out = (frac, int(du.used), int(du.free))
        except OSError:
            out = (0.0, 0, 0)
    _STATUS_CACHE[path] = (now, out[0], out[1], out[2])
    return out


def _watermark(config, key) -> float:
    try:
        return float(config.get(key))
    except Exception:  # noqa: BLE001 — a broken config must not block writes
        return 1.0


def spill_allowed(config, path: str) -> bool:
    """Low-watermark gate for OPTIONAL disk writes (sort-shuffle spills,
    HBM pool disk demotions). False = shed: stay in memory."""
    if config is None:
        return True
    from ballista_tpu.config import EXECUTOR_DISK_LOW_WATERMARK

    return disk_status(path)[0] < _watermark(config, EXECUTOR_DISK_LOW_WATERMARK)


def admission_blocked(config, path: str) -> bool:
    """High-watermark gate for NEW TASK admission. True = the executor
    should reject with a retryable DiskExhausted."""
    if config is None:
        return False
    from ballista_tpu.config import EXECUTOR_DISK_HIGH_WATERMARK

    return disk_status(path)[0] >= _watermark(config, EXECUTOR_DISK_HIGH_WATERMARK)


def wrap_enospc(e: OSError, where: str):
    """Return a typed DiskExhausted for an ENOSPC OSError, else None —
    the write points re-raise anything that isn't actually a full disk."""
    if getattr(e, "errno", None) != errno.ENOSPC:
        return None
    from ballista_tpu.errors import DiskExhausted

    return DiskExhausted(where, f"os error {errno.ENOSPC}: {e}")
