from ballista_tpu.executor.executor_process import main

main()
