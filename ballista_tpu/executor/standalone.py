"""In-process executors + task launcher (standalone mode).

Rebuild of the standalone helpers (scheduler/src/standalone.rs:47,
executor/src/standalone.rs:51): a real SchedulerServer and real Executors
in one process — the full task/shuffle machinery with no gRPC in between.
This is both the `SessionContext::standalone()` backend and the
virtual-cluster layer integration tests build on.
"""

from __future__ import annotations

import concurrent.futures as fut
import tempfile
import threading

from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.ids import new_executor_id
from ballista_tpu.scheduler.server import SchedulerServer, TaskLauncher
from ballista_tpu.scheduler.state.execution_graph import TaskDescription


class InProcessTaskLauncher(TaskLauncher):
    """Runs launched tasks on local Executor objects via a thread pool and
    feeds TaskResults straight back into the scheduler (push-mode shape)."""

    def __init__(self, executors: dict[str, Executor], max_workers: int = 16):
        self.executors = executors
        self.pool = fut.ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="task")

    def launch(self, executor_id: str, tasks: list[TaskDescription], server: SchedulerServer) -> None:
        ex = self.executors[executor_id]

        def run(task: TaskDescription) -> None:
            cfg = server.sessions.get(task.session_id)
            result = ex.run_task(task, cfg)
            server.update_task_status(executor_id, [result])

        for t in tasks:
            self.pool.submit(run, t)

    def cancel_tasks(self, executor_id: str, job_id: str,
                     items: list, server: SchedulerServer) -> None:
        """Propagate CancelTasks to the in-process executor exactly like the
        daemon rpc does (preemptive for process-isolated tasks)."""
        ex = self.executors.get(executor_id)
        if ex is not None:
            for task_id, stage_id in items:
                ex.cancel_task(job_id, stage_id, task_id)

    def remove_job_data(self, executor_id: str, job_id: str, server: SchedulerServer) -> None:
        """Shuffle-GC push, mirroring the daemon's RemoveJobData rpc
        (executor_server.py): containment-checked rmtree of the job dir +
        cancellation-ledger cleanup, with reclaimed bytes counted."""
        import os
        import shutil

        from ballista_tpu.executor.lifecycle import _dir_bytes
        from ballista_tpu.shuffle.paths import contained_path, job_dir, validate_job_id

        ex = self.executors.get(executor_id)
        if ex is None:
            return
        try:
            d = contained_path(ex.work_dir, job_dir(ex.work_dir, validate_job_id(job_id)))
        except (ValueError, PermissionError):
            return
        if os.path.isdir(d):
            ex.gc_reclaimed_bytes += _dir_bytes(d)
            shutil.rmtree(d, ignore_errors=True)
        ex.clear_cancellations(job_id)

    def grant_lease(self, executor_id: str, lease, server: SchedulerServer) -> None:
        ex = self.executors.get(executor_id)
        if ex is not None:
            ex.lease_table.grant(lease)

    def revoke_lease(self, executor_id: str, lease_id: str, server: SchedulerServer) -> None:
        ex = self.executors.get(executor_id)
        if ex is not None:
            ex.lease_table.revoke(lease_id)

    def migrate_partitions(self, src_executor_id: str, dest_executor_id: str,
                           locations: list, server: SchedulerServer) -> tuple[int, int]:
        """Drain handoff for in-process fleets (docs/lifecycle.md). With
        per-executor work dirs + data planes the destination pulls the
        ranges over the real migrate_pull Flight path; with ONE shared
        work dir + data plane (the classic standalone shape) the files are
        already readable by the surviving endpoint, so the handoff is pure
        relabeling."""
        from ballista_tpu.executor import lifecycle

        src = self.executors.get(src_executor_id)
        dest = self.executors.get(dest_executor_id)
        if dest is None or not locations:
            return 0, 0
        if (src is not None and src.work_dir != dest.work_dir
                and src.metadata.flight_port and dest.metadata.flight_port):
            count, nbytes = lifecycle.migrate_via_flight(
                f"{src.metadata.host}:{src.metadata.flight_port}",
                f"{dest.metadata.host}:{dest.metadata.flight_port}",
                locations, dest.metadata)
        else:
            count, nbytes = lifecycle.migrate_local(locations, dest.metadata)
        dest.migrated_partitions += count
        dest.migrated_bytes += nbytes
        return count, nbytes


class StandaloneCluster:
    def __init__(self, num_executors: int = 1, vcores: int = 4,
                 work_dir: str | None = None, config: BallistaConfig | None = None,
                 with_flight: bool = True, engine_factory=None,
                 shards: int | None = None, job_state=None,
                 per_executor_work_dirs: bool = False):
        import os

        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-tpu-")
        self.per_executor_work_dirs = per_executor_work_dirs
        self.flight_server = None
        # per-executor data planes: each executor owns a work-dir subtree
        # and its own Flight server, so drain migration moves real bytes
        # between endpoints (the distributed shape, in-process)
        self.flight_servers: dict[str, object] = {}
        flight_port = 0
        if with_flight and not per_executor_work_dirs:
            from ballista_tpu.flight.server import start_flight_server

            self.flight_server, flight_port = start_flight_server(self.work_dir, "localhost")
        self._shared_flight_port = flight_port
        self.executors: dict[str, Executor] = {}
        for _ in range(num_executors):
            eid = str(new_executor_id())
            ex_work_dir = self.work_dir
            if per_executor_work_dirs:
                ex_work_dir = os.path.join(self.work_dir, eid)
                os.makedirs(ex_work_dir, exist_ok=True)
                if with_flight:
                    from ballista_tpu.flight.server import start_flight_server

                    srv, flight_port = start_flight_server(ex_work_dir, "localhost")
                    self.flight_servers[eid] = srv
            meta = ExecutorMetadata(id=eid, vcores=vcores,
                                    host="localhost", flight_port=flight_port)
            # engine_factory: the ExecutionEngine extension seam
            # (execution_engine.rs:51) for library embedders
            eng = engine_factory() if engine_factory is not None else None
            ex = Executor(ex_work_dir, meta, config=config, engine=eng)
            if config is not None:
                from ballista_tpu.config import EXECUTOR_TASK_ISOLATION

                ex.isolation = str(config.get(EXECUTOR_TASK_ISOLATION))
            self.executors[meta.id] = ex
            if self.flight_server is not None:
                # direct-dispatch target: lease grants + scheduler-less
                # task execution arrive as Flight actions
                self.flight_server.attach_executor(ex)
            elif eid in self.flight_servers:
                self.flight_servers[eid].attach_executor(ex)
        self.launcher = InProcessTaskLauncher(self.executors)
        if shards is None and config is not None:
            from ballista_tpu.config import SCHEDULER_SHARDS

            shards = int(config.get(SCHEDULER_SHARDS))
        self.scheduler = SchedulerServer(self.launcher, job_state=job_state,
                                         shards=shards or 1)
        self.scheduler.start()
        for ex in self.executors.values():
            self.scheduler.register_executor(ex.metadata)

    def add_executor(self, vcores: int = 4, config: BallistaConfig | None = None,
                     engine_factory=None) -> str:
        """Join a fresh executor to the running fleet (the rolling-restart
        harness: drain a node, then add_executor() is its replacement).
        Honors the cluster's data-plane shape — own work dir + Flight
        server under per_executor_work_dirs, shared otherwise."""
        import os

        eid = str(new_executor_id())
        ex_work_dir = self.work_dir
        flight_port = 0
        if self.per_executor_work_dirs:
            ex_work_dir = os.path.join(self.work_dir, eid)
            os.makedirs(ex_work_dir, exist_ok=True)
            from ballista_tpu.flight.server import start_flight_server

            srv, flight_port = start_flight_server(ex_work_dir, "localhost")
            self.flight_servers[eid] = srv
        elif self.flight_server is not None:
            flight_port = self._shared_flight_port
        meta = ExecutorMetadata(id=eid, vcores=vcores,
                                host="localhost", flight_port=flight_port)
        eng = engine_factory() if engine_factory is not None else None
        ex = Executor(ex_work_dir, meta, config=config, engine=eng)
        self.executors[eid] = ex
        if self.flight_server is not None:
            self.flight_server.attach_executor(ex)
        elif eid in self.flight_servers:
            self.flight_servers[eid].attach_executor(ex)
        self.scheduler.register_executor(meta)
        return eid

    def shutdown(self) -> None:
        self.scheduler.stop()
        self.launcher.pool.shutdown(wait=False)
        if self.flight_server is not None:
            self.flight_server.shutdown()
        for srv in self.flight_servers.values():
            srv.shutdown()


class MultiSchedulerCluster:
    """N real SchedulerServer instances over ONE shared executor fleet and
    ONE shared FileJobState directory — the in-process shape of a
    multi-scheduler deployment behind the Flight/gRPC proxy. Clients may
    submit to any live instance (`pick()` round-robins); a killed
    instance's jobs sit in the shared store until a live peer's orphan
    sweep (`resubmit_stuck_jobs` → `recover_jobs(only_active=True)`)
    adopts their stale ownership lease and resumes from the last
    checkpointed stage."""

    def __init__(self, num_schedulers: int = 2, num_executors: int = 2,
                 vcores: int = 4, work_dir: str | None = None,
                 config: BallistaConfig | None = None,
                 lease_s: float = 2.0, shards: int = 1,
                 sweep_interval_s: float = 0.5):
        import os

        from ballista_tpu.scheduler.state.job_state import FileJobState

        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-tpu-")
        self.state_dir = os.path.join(self.work_dir, "job-state")
        self.executors: dict[str, Executor] = {}
        for _ in range(num_executors):
            meta = ExecutorMetadata(id=str(new_executor_id()), vcores=vcores,
                                    host="localhost")
            self.executors[meta.id] = Executor(self.work_dir, meta, config=config)
        self.launcher = InProcessTaskLauncher(self.executors)
        self.schedulers: list[SchedulerServer] = []
        for i in range(num_schedulers):
            # each instance gets its OWN FileJobState handle on the SHARED
            # dir: ownership arbitration runs through the on-disk markers,
            # exactly like separate scheduler processes
            s = SchedulerServer(
                self.launcher, scheduler_id=f"scheduler-{i}",
                job_state=FileJobState(self.state_dir, lease_s=lease_s),
                shards=shards)
            s.start()
            for ex in self.executors.values():
                s.register_executor(ex.metadata)
            self.schedulers.append(s)
        self._rr = 0
        self._killed: set[int] = set()
        self._sweeping = True
        self._sweeper = threading.Thread(
            target=self._sweep_loop, args=(sweep_interval_s,), daemon=True,
            name="multi-scheduler-sweep")
        self._sweeper.start()

    def _sweep_loop(self, interval_s: float) -> None:
        # stands in for SchedulerProcess._expiry_loop: live instances
        # periodically revive stuck jobs and adopt orphans
        import time as _time

        while self._sweeping:
            _time.sleep(interval_s)
            for i, s in enumerate(self.schedulers):
                if i in self._killed:
                    continue
                try:
                    s.resubmit_stuck_jobs()
                except Exception:  # noqa: BLE001 — sweep must survive a flaky store
                    pass

    def alive(self) -> list[SchedulerServer]:
        return [s for i, s in enumerate(self.schedulers) if i not in self._killed]

    def pick(self) -> SchedulerServer:
        live = self.alive()
        self._rr += 1
        return live[self._rr % len(live)]

    def kill(self, i: int) -> None:
        """Chaos-kill instance i: its loops stop AND it loses the shared
        store (a dead process can't write late checkpoints over its
        successor's progress)."""
        from ballista_tpu.scheduler.state.job_state import InMemoryJobState

        self._killed.add(i)
        s = self.schedulers[i]
        s.stop()
        s.job_state = InMemoryJobState()

    def shutdown(self) -> None:
        self._sweeping = False
        for i in range(len(self.schedulers)):
            if i not in self._killed:
                self.schedulers[i].stop()
        self.launcher.pool.shutdown(wait=False)
