"""In-process executors + task launcher (standalone mode).

Rebuild of the standalone helpers (scheduler/src/standalone.rs:47,
executor/src/standalone.rs:51): a real SchedulerServer and real Executors
in one process — the full task/shuffle machinery with no gRPC in between.
This is both the `SessionContext::standalone()` backend and the
virtual-cluster layer integration tests build on.
"""

from __future__ import annotations

import concurrent.futures as fut
import tempfile
import threading

from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.ids import new_executor_id
from ballista_tpu.scheduler.server import SchedulerServer, TaskLauncher
from ballista_tpu.scheduler.state.execution_graph import TaskDescription


class InProcessTaskLauncher(TaskLauncher):
    """Runs launched tasks on local Executor objects via a thread pool and
    feeds TaskResults straight back into the scheduler (push-mode shape)."""

    def __init__(self, executors: dict[str, Executor], max_workers: int = 16):
        self.executors = executors
        self.pool = fut.ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="task")

    def launch(self, executor_id: str, tasks: list[TaskDescription], server: SchedulerServer) -> None:
        ex = self.executors[executor_id]

        def run(task: TaskDescription) -> None:
            cfg = server.sessions.get(task.session_id)
            result = ex.run_task(task, cfg)
            server.update_task_status(executor_id, [result])

        for t in tasks:
            self.pool.submit(run, t)

    def cancel_tasks(self, executor_id: str, job_id: str,
                     items: list, server: SchedulerServer) -> None:
        """Propagate CancelTasks to the in-process executor exactly like the
        daemon rpc does (preemptive for process-isolated tasks)."""
        ex = self.executors.get(executor_id)
        if ex is not None:
            for task_id, stage_id in items:
                ex.cancel_task(job_id, stage_id, task_id)


class StandaloneCluster:
    def __init__(self, num_executors: int = 1, vcores: int = 4,
                 work_dir: str | None = None, config: BallistaConfig | None = None,
                 with_flight: bool = True, engine_factory=None):
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-tpu-")
        self.flight_server = None
        flight_port = 0
        if with_flight:
            from ballista_tpu.flight.server import start_flight_server

            self.flight_server, flight_port = start_flight_server(self.work_dir, "localhost")
        self.executors: dict[str, Executor] = {}
        for _ in range(num_executors):
            meta = ExecutorMetadata(id=str(new_executor_id()), vcores=vcores,
                                    host="localhost", flight_port=flight_port)
            # engine_factory: the ExecutionEngine extension seam
            # (execution_engine.rs:51) for library embedders
            eng = engine_factory() if engine_factory is not None else None
            ex = Executor(self.work_dir, meta, config=config, engine=eng)
            if config is not None:
                from ballista_tpu.config import EXECUTOR_TASK_ISOLATION

                ex.isolation = str(config.get(EXECUTOR_TASK_ISOLATION))
            self.executors[meta.id] = ex
        self.launcher = InProcessTaskLauncher(self.executors)
        self.scheduler = SchedulerServer(self.launcher)
        self.scheduler.start()
        for ex in self.executors.values():
            self.scheduler.register_executor(ex.metadata)

    def shutdown(self) -> None:
        self.scheduler.stop()
        self.launcher.pool.shutdown(wait=False)
        if self.flight_server is not None:
            self.flight_server.shutdown()
