"""Process-isolated task execution (DedicatedExecutor parity).

The reference runs task compute on a dedicated runtime so a misbehaving
task cannot starve the executor's IO/RPC plane
(ballista/executor/src/executor_process.rs — dedicated tokio runtime;
SURVEY §2.3 DedicatedExecutor). A Python thread pool cannot give that
guarantee: task compute shares the GIL with the daemon's gRPC/Flight
threads, and a native crash takes the whole daemon down.

`ballista.executor.task.isolation = process` (daemon flag
`--task-isolation process`) runs EACH task in a fresh spawned worker
process instead:

- true parallelism: vcore workers aggregate CPU across processes instead
  of interleaving on one GIL;
- crash isolation: a segfault/abort in a native kernel fails ONE task
  (reported `retryable`, like the reference's catch_unwind→panic path)
  — the daemon, its Flight server, and its heartbeats keep running;
- real cancellation: CancelTasks terminates the worker process mid-rows,
  not at the next cooperative checkpoint.

The task round-trips the SAME wire contract as the scheduler→executor
hop (TaskDefinitionProto in, TaskStatusProto out), so process isolation
exercises serde end-to-end by construction. Workers use the `spawn`
start method: a clean interpreter cannot inherit wedged locks from the
daemon's gRPC/Arrow threads (fork-safety), at the cost of ~1-2 s
interpreter startup per task — the mode targets long CPU-heavy tasks.
Shuffle outputs land in the shared work dir exactly as in-thread tasks'
do; the daemon's Flight server serves them identically.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import time as _time

log = logging.getLogger(__name__)

CANCEL_POLL_S = 0.2
# slack past the task deadline before the parent SIGTERMs the worker: the
# child checks its own deadline cooperatively and reports a cleaner status;
# the parent kill is the backstop for workers wedged in native code
DEADLINE_GRACE_S = 1.0


def _kill_child(child) -> None:
    child.terminate()
    child.join(timeout=5)
    if child.is_alive():
        child.kill()
        child.join(timeout=5)


def _child_main(conn, task_bytes: bytes, config_pairs: list, meta_fields: tuple,
                work_dir: str, memory_limit_per_task: int) -> None:
    """Worker entry (spawned): decode the task off the wire, run it with a
    fresh single-task Executor, ship the encoded status back."""
    try:
        from ballista_tpu.config import BallistaConfig
        from ballista_tpu.executor.executor import Executor, ExecutorMetadata
        from ballista_tpu.proto import pb
        from ballista_tpu.serde_control import decode_task_definition, encode_task_status

        ex_id, host, flight_port, device_ordinal = meta_fields
        meta = ExecutorMetadata(id=ex_id, host=host, flight_port=flight_port,
                                vcores=1, device_ordinal=device_ordinal)
        cfg = BallistaConfig.from_key_value_pairs(list(config_pairs),
                                                  scrub_restricted=False)
        task = decode_task_definition(
            pb.TaskDefinitionProto.FromString(task_bytes))
        ex = Executor(work_dir, meta, config=cfg)
        ex.memory_limit_per_task = memory_limit_per_task
        result = ex.execute_task(task, cfg)
        conn.send_bytes(encode_task_status(result, ex_id).SerializeToString())
    except BaseException as e:  # noqa: BLE001 — last-resort wire report
        try:
            import traceback

            from ballista_tpu.proto import pb

            conn.send_bytes(pb.TaskStatusProto(
                state="failed", executor_id=meta_fields[0],
                error=f"worker: {type(e).__name__}: {e}\n"
                      f"{traceback.format_exc(limit=8)}",
                retryable=True,
            ).SerializeToString())
        except Exception:  # noqa: BLE001
            pass
    finally:
        conn.close()


def run_task_in_subprocess(executor, task, cfg):
    """Run one task in a spawned worker; returns a TaskResult. Blocks the
    calling vcore thread (slot accounting is unchanged), but the compute
    happens in the child. The parent polls the executor's cancellation
    set and SIGTERMs the child on cancel — preemptive, unlike the
    in-thread cooperative checkpoints."""
    from ballista_tpu.executor.executor import TaskResult
    from ballista_tpu.proto import pb
    from ballista_tpu.serde_control import decode_task_status, encode_task_definition

    base = TaskResult(
        task_id=task.task_id, job_id=task.job_id, stage_id=task.stage_id,
        stage_attempt=task.stage_attempt, partitions=list(task.partitions),
        state="failed",
    )
    try:
        task_bytes = encode_task_definition(task, cfg).SerializeToString()
    except Exception as e:  # noqa: BLE001 — plan not wire-encodable
        log.warning("task %s/%s not encodable for process isolation (%s); "
                    "running in-thread", task.job_id, task.task_id, e)
        return executor.execute_task(task, cfg)

    ctx = mp.get_context("spawn")
    rx, tx = ctx.Pipe(duplex=False)
    meta = executor.metadata
    with executor._lock:
        executor.active_process_tasks += 1
        active = executor.active_process_tasks
    # the session spill pool is EXECUTOR-wide; N concurrent isolated workers
    # must split it, not each claim the full in-thread budget (which would
    # let them reserve N× the executor's memory between them)
    if executor.session_pools is not None:
        child_budget = max(1, executor.session_pools.capacity // max(1, active))
        if executor.memory_limit_per_task:
            child_budget = min(child_budget, executor.memory_limit_per_task)
    else:
        child_budget = executor.memory_limit_per_task
    deadline = float(getattr(task, "deadline_seconds", 0.0) or 0.0)
    started = _time.time()
    try:
        child = ctx.Process(
            target=_child_main,
            args=(tx, task_bytes, cfg.to_key_value_pairs(),
                  (meta.id, meta.host, meta.flight_port, meta.device_ordinal),
                  executor.work_dir, child_budget),
            daemon=True, name=f"task-{task.job_id}-{task.task_id}",
        )
        child.start()
        tx.close()
        payload = None
        while True:
            if rx.poll(CANCEL_POLL_S):
                try:
                    payload = rx.recv_bytes()
                except EOFError:
                    pass  # child died before reporting
                break
            if executor._is_cancelled(task.job_id, task.stage_id, task.task_id):
                _kill_child(child)
                base.state = "cancelled"
                base.error = f"task {task.task_id} cancelled (worker terminated)"
                return base
            if deadline > 0 and _time.time() - started > deadline + DEADLINE_GRACE_S:
                # preemptive deadline enforcement: the child may be wedged in
                # native code where cooperative checkpoints never run
                _kill_child(child)
                executor.tasks_failed += 1
                base.error = (f"task {task.task_id} exceeded its {deadline:.1f}s "
                              f"deadline (worker terminated after "
                              f"{_time.time() - started:.1f}s)")
                base.error_kind = "ExecutionError"
                base.retryable = True
                base.timed_out = True
                log.warning("task %s/%s timed out: %s", task.job_id, task.task_id, base.error)
                return base
            if not child.is_alive():
                # drain any result raced in between poll and death
                if rx.poll(0):
                    try:
                        payload = rx.recv_bytes()
                    except EOFError:
                        pass
                break
        child.join(timeout=10)
        rx.close()
    finally:
        with executor._lock:
            executor.active_process_tasks -= 1
    if payload is None:
        executor.tasks_failed += 1
        base.error = (f"task worker died without a status "
                      f"(exitcode={child.exitcode})")
        base.error_kind = "ExecutionError"
        base.retryable = True  # crash ≠ deterministic failure: retry elsewhere
        log.warning("task %s/%s: %s", task.job_id, task.task_id, base.error)
        return base
    result = decode_task_status(pb.TaskStatusProto.FromString(payload), meta)
    if result.state == "success":
        executor.tasks_run += 1
        return result
    # non-success: keep the parent's task identity (the child's last-resort
    # report may carry none) and graft the child's error detail onto it
    executor.tasks_failed += 1
    base.state = result.state
    base.error = result.error
    base.error_kind = result.error_kind
    base.retryable = result.retryable
    base.timed_out = result.timed_out
    base.fetch_failed_executor_id = result.fetch_failed_executor_id
    base.fetch_failed_stage_id = result.fetch_failed_stage_id
    base.metrics = result.metrics
    return base
