"""Executor core: run one query-stage task and publish shuffle outputs.

Rebuild of Executor::execute_query_stage + the ExecutionEngine seam
(ballista/executor/src/executor.rs:226, execution_engine.rs:51):

- `ExecutionEngine.create_query_stage_exec` prepares a stage plan for this
  executor: stamps the work dir, and (tpu engine) compiles supported
  subtrees to XLA (engine/tpu_engine.py);
- `execute_query_stage` drives the stage's ShuffleWriterExec for every
  partition in the task's slice, converts metadata batches to
  PartitionLocations (zero-byte outputs dropped — the reference's
  sentinel rule, execution_engine.rs:336), catches panics, and returns a
  TaskStatus-shaped result;
- cancellation via a cooperative flag checked between partitions.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import traceback
from dataclasses import dataclass, field

from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
from ballista_tpu.errors import BallistaError, Cancelled, error_to_proto_kind
from ballista_tpu.ids import ExecutorId, new_executor_id
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext, collect_metrics
from ballista_tpu.scheduler.state.execution_graph import TaskDescription
from ballista_tpu.shuffle.types import PartitionLocation
from ballista_tpu.shuffle.writer import ShuffleWriterExec, metadata_to_locations
from ballista_tpu.version import WIRE_PROTOCOL_VERSION

log = logging.getLogger(__name__)


@dataclass
class ExecutorMetadata:
    id: str
    host: str = "localhost"
    grpc_port: int = 0
    flight_port: int = 0
    vcores: int = 4
    wire_version: str = WIRE_PROTOCOL_VERSION
    # chip this executor is pinned to (-1 = unpinned); when pinned with
    # engine=tpu the daemon defaults vcores to 1 so scheduler slots = chips
    device_ordinal: int = -1


@dataclass
class TaskResult:
    task_id: int
    job_id: str
    stage_id: int
    stage_attempt: int
    partitions: list[int]
    state: str  # success | failed | cancelled
    locations: list[PartitionLocation] = field(default_factory=list)
    error: str = ""
    error_kind: str = ""
    retryable: bool = False
    metrics: list = field(default_factory=list)
    # ResultLost identity when a shuffle fetch failed
    fetch_failed_executor_id: str = ""
    fetch_failed_stage_id: int = 0
    # why the fetch failed ("corruption" = checksum mismatch survived the
    # retry-once refetch; rides error_kind as "FetchPartitionError:<cause>"
    # on the wire so no proto change is needed)
    fetch_failed_cause: str = ""
    # the failure was a per-task deadline expiry (feeds quarantine scoring)
    timed_out: bool = False


class ExecutionEngine:
    """THE seam (execution_engine.rs:51): prepare a stage plan to run here."""

    def create_query_stage_exec(self, plan: ExecutionPlan, config: BallistaConfig,
                                stage_attempt: int = 0) -> ExecutionPlan:
        from ballista_tpu.executor.chaos import maybe_inject_chaos

        plan = maybe_inject_chaos(plan, config, stage_attempt)
        engine = str(config.get(EXECUTOR_ENGINE))
        if engine == "tpu":
            from ballista_tpu.engine.tpu_engine import maybe_compile_tpu

            return maybe_compile_tpu(plan, config)
        return plan


class Executor:
    def __init__(self, work_dir: str, metadata: ExecutorMetadata | None = None,
                 engine: ExecutionEngine | None = None,
                 config: BallistaConfig | None = None):
        self.work_dir = work_dir
        self.metadata = metadata or ExecutorMetadata(id=new_executor_id())
        self.engine = engine or ExecutionEngine()
        self.default_config = config or BallistaConfig()
        # (job_id, stage_id, task_id); task_id -1 cancels the whole stage.
        # Task granularity matters for speculation: cancelling the LOSING
        # attempt must not kill its sibling tasks on the same stage.
        self._cancelled: set[tuple[str, int, int]] = set()
        self._lock = threading.Lock()
        self.tasks_run = 0
        self.tasks_failed = 0
        # serving tier: tasks dispatched on the short-query fast lane
        # (single-stage, no execution graph); reported in heartbeats
        self.fast_lane_tasks = 0
        # tasks turned away at admission because the session pool was
        # already saturated (reported in heartbeats; scheduler retries
        # them elsewhere)
        self.pressure_rejections = 0
        # lifecycle & storage counters (docs/lifecycle.md), mirrored onto
        # the heartbeat by the executor process: tasks rejected past the
        # disk high watermark, map outputs handed off by a drain, and
        # bytes reclaimed by the GC sweeps
        self.disk_rejections = 0
        self.migrated_partitions = 0
        self.migrated_bytes = 0
        self.gc_reclaimed_bytes = 0
        self.orphans_reclaimed = 0
        # set while a drain is in progress (SIGTERM or scheduler-initiated);
        # surfaces as lifecycle_state=draining on the heartbeat
        self.draining = False
        self.memory_limit_per_task = 0  # bytes; set by the executor process
        # "thread" (in-process, shared GIL) or "process" (spawned worker per
        # task: true parallelism, crash isolation, preemptive cancel —
        # DedicatedExecutor parity, see process_worker.py)
        self.isolation = "thread"
        # session-shared pools (runtime_cache.rs:59): set by the executor
        # process once the executor-wide capacity is known
        self.session_pools = None  # SessionPoolRegistry | None
        # direct-dispatch lease enforcement: the scheduler pushes grants/
        # revocations here; admit() gates every scheduler-less task. The
        # generation probe fences leases against a silently restarted
        # device daemon (jax-free: the client module only reads its
        # attach cache)
        from ballista_tpu.device_daemon import client as _dclient
        from ballista_tpu.serving.lease import LeaseTable
        self.lease_table = LeaseTable(
            generation_probe=_dclient.attached_generation)
        self._warned_tpu_downgrade = False
        # process-isolated tasks currently inflight (spill budget is split
        # across them; see process_worker.run_task_in_subprocess)
        self.active_process_tasks = 0

    # ------------------------------------------------------------------

    def cancel_task(self, job_id: str, stage_id: int, task_id: int | None = None) -> None:
        with self._lock:
            self._cancelled.add((job_id, stage_id, -1 if task_id is None else task_id))

    def clear_cancellations(self, job_id: str) -> None:
        with self._lock:
            self._cancelled = {c for c in self._cancelled if c[0] != job_id}

    def _is_cancelled(self, job_id: str, stage_id: int, task_id: int = -1) -> bool:
        with self._lock:
            return ((job_id, stage_id, -1) in self._cancelled
                    or (task_id != -1 and (job_id, stage_id, task_id) in self._cancelled))

    # ------------------------------------------------------------------

    def run_task(self, task: TaskDescription, config: BallistaConfig | None = None) -> TaskResult:
        """Dispatch honoring the isolation mode: in-thread, or a spawned
        worker process (DedicatedExecutor parity). A session may OPT IN to
        process isolation via ballista.executor.task.isolation (strictly
        safer than threads); it cannot opt a daemon out of it."""
        cfg = config or self.default_config
        if getattr(task, "fast_lane", False):
            self.fast_lane_tasks += 1
        rejected = self._reject_if_saturated(task)
        if rejected is None:
            rejected = self._reject_if_disk_full(task, cfg)
        if rejected is not None:
            return rejected
        iso = self.isolation
        if iso != "process":
            from ballista_tpu.config import EXECUTOR_TASK_ISOLATION

            iso = str(cfg.get(EXECUTOR_TASK_ISOLATION))
        if iso == "process":
            if str(cfg.get(EXECUTOR_ENGINE)) == "tpu":
                # a spawned worker would re-claim the (exclusively owned)
                # chip and rebuild the device caches per task; device
                # stages stay in-thread where the claim and caches live
                if self.isolation == "process" and not self._warned_tpu_downgrade:
                    # daemon-forced isolation being silently weakened is an
                    # operator surprise; say it loudly, once per executor
                    self._warned_tpu_downgrade = True
                    log.warning(
                        "daemon-forced --task-isolation process is downgraded to "
                        "in-thread for engine=tpu tasks (the spawned worker cannot "
                        "share the parent's TPU runtime); crash isolation and "
                        "preemptive cancel do NOT apply to device stages")
                iso = "thread"
            elif type(self.engine) is not ExecutionEngine:
                # a custom engine seam can't be reconstructed in the child;
                # silently different lowering would be worse than the GIL
                log.warning(
                    "task %s/%s: custom ExecutionEngine %s is not available "
                    "under process isolation; running in-thread",
                    task.job_id, task.task_id, type(self.engine).__name__)
            else:
                from ballista_tpu.executor.process_worker import run_task_in_subprocess

                return run_task_in_subprocess(self, task, cfg)
        return self.execute_task(task, config)

    def _reject_if_saturated(self, task: TaskDescription) -> TaskResult | None:
        """Executor-side admission gate: a task whose session pool is
        already at/over capacity is rejected retryably INSTEAD of starting
        life overcommitted (grow_wait's deadline backstop would force the
        reservation through and deepen the spiral). The failure is
        retryable, so the scheduler re-pends the partition and the health
        scoring steers the retry toward a less-pressured executor."""
        if self.session_pools is None:
            return None
        pool = self.session_pools.get(task.session_id)
        if not pool.saturated:
            return None
        self.pressure_rejections += 1
        log.warning(
            "rejecting task %s/%s at admission: session %s pool saturated "
            "(%.0f%% of %d bytes reserved)", task.job_id, task.task_id,
            task.session_id, pool.pressure() * 100, pool.capacity)
        return TaskResult(
            task_id=task.task_id, job_id=task.job_id, stage_id=task.stage_id,
            stage_attempt=task.stage_attempt, partitions=list(task.partitions),
            state="failed",
            error=(f"executor {self.metadata.id} rejected task at admission: "
                   f"session memory pool saturated ({pool.reserved}/{pool.capacity} bytes)"),
            error_kind="ResourceExhausted", retryable=True,
        )

    def _reject_if_disk_full(self, task: TaskDescription, cfg: BallistaConfig) -> TaskResult | None:
        """High-watermark admission gate (docs/lifecycle.md#watermark-ladder):
        a task admitted onto a nearly-full disk would ENOSPC mid-shuffle-
        write anyway — reject it up front, typed and retryable, so the
        scheduler re-pends the slice and the heartbeat disk gauges steer
        the retry toward an executor with headroom."""
        from ballista_tpu.executor import disk

        if not disk.admission_blocked(cfg, self.work_dir):
            return None
        self.disk_rejections += 1
        used_frac, used, free = disk.disk_status(self.work_dir)
        log.warning(
            "rejecting task %s/%s at admission: disk %.0f%% used (%d bytes free) "
            "is past the high watermark", task.job_id, task.task_id,
            used_frac * 100, free)
        return TaskResult(
            task_id=task.task_id, job_id=task.job_id, stage_id=task.stage_id,
            stage_attempt=task.stage_attempt, partitions=list(task.partitions),
            state="failed",
            error=(f"executor {self.metadata.id} rejected task at admission: "
                   f"disk {used_frac * 100:.0f}% used ({free} bytes free) past "
                   "the high watermark"),
            error_kind="DiskExhausted", retryable=True,
        )

    def execute_task(self, task: TaskDescription, config: BallistaConfig | None = None) -> TaskResult:
        cfg = config or self.default_config
        from ballista_tpu import udf

        udf.load_modules(cfg.get(udf.UDF_MODULES))
        if self.memory_limit_per_task:
            # executor-sized spill budget (cgroup/host-aware, see
            # executor_process.detect_memory_limit) unless the session set
            # one explicitly — the reference's per-executor MemoryPool role
            # (executor_process.rs:465-480)
            from ballista_tpu.config import SORT_SHUFFLE_MEMORY_LIMIT

            cfg.set_default_if_unset(SORT_SHUFFLE_MEMORY_LIMIT, self.memory_limit_per_task)
        base = TaskResult(
            task_id=task.task_id, job_id=task.job_id, stage_id=task.stage_id,
            stage_attempt=task.stage_attempt, partitions=list(task.partitions), state="failed",
        )
        start = time.time()
        deadline = float(getattr(task, "deadline_seconds", 0.0) or 0.0)
        deadline_at = start + deadline if deadline > 0 else 0.0
        try:
            plan = task.plan
            assert isinstance(plan, ShuffleWriterExec), f"stage root must be a shuffle writer: {plan}"
            prepared = self.engine.create_query_stage_exec(plan, cfg, task.stage_attempt)
            locations: list[PartitionLocation] = []
            for p in task.partitions:
                if self._is_cancelled(task.job_id, task.stage_id, task.task_id):
                    raise Cancelled(f"task {task.task_id} cancelled")
                if deadline_at and time.time() > deadline_at:
                    self.tasks_failed += 1
                    base.error = (f"task {task.task_id} exceeded its {deadline:.1f}s deadline "
                                  f"after {time.time() - start:.1f}s")
                    base.error_kind = "ExecutionError"
                    base.retryable = True
                    base.timed_out = True
                    log.warning("task %s/%s timed out: %s", task.job_id, task.task_id, base.error)
                    return base
                ctx = TaskContext(cfg, task_id=f"{task.task_id}", work_dir=self.work_dir)
                ctx.device_ordinal = self.metadata.device_ordinal
                ctx.task_attempt = int(getattr(task, "task_attempt", 0))
                ctx.deadline_at = deadline_at
                # long-running operators (and chaos stragglers) poll this so
                # a CancelTasks push preempts mid-partition, not between
                ctx.cancel_check = (
                    lambda j=task.job_id, s=task.stage_id, t=task.task_id: self._is_cancelled(j, s, t)
                )
                if self.session_pools is not None:
                    # concurrent tasks of one session share the pool: idle
                    # tasks lend spill budget to a heavy sort (try_grow)
                    ctx.memory_pool = self.session_pools.get(task.session_id)
                    if str(cfg.get(EXECUTOR_ENGINE)) == "tpu":
                        # attach the device-side ledger: HBM headroom is
                        # split-accounted from the host spill budget (the
                        # stage compiler resyncs device_reserved from the
                        # device-cache residency each run)
                        from ballista_tpu.ops.tpu import hbm

                        ctx.memory_pool.set_device_capacity(
                            hbm.resolve_hbm_budget(cfg))
                for meta_batch in prepared.execute(p, ctx):
                    locations.extend(
                        metadata_to_locations(
                            meta_batch, task.job_id, task.stage_id, p,
                            self.metadata.id, self.metadata.host, self.metadata.flight_port,
                        )
                    )
            base.state = "success"
            base.locations = locations
            base.metrics = [
                {"depth": d, "name": n, **m} for d, n, m in collect_metrics(prepared)
            ]
            self.tasks_run += 1
            return base
        except Cancelled as e:
            base.state = "cancelled"
            base.error = str(e)
            return base
        except BaseException as e:  # noqa: BLE001 — catch_unwind parity
            from ballista_tpu.errors import FetchFailed

            self.tasks_failed += 1
            base.error = f"{type(e).__name__}: {e}\n{traceback.format_exc(limit=8)}"
            base.error_kind = error_to_proto_kind(e)
            base.retryable = bool(getattr(e, "retryable", False))
            base.timed_out = bool(getattr(e, "timed_out", False))
            if isinstance(e, FetchFailed):
                base.fetch_failed_executor_id = e.executor_id
                base.fetch_failed_stage_id = e.stage_id
                base.fetch_failed_cause = getattr(e, "cause", "")
            log.warning("task %s/%s failed: %s", task.job_id, task.task_id, e)
            return base
