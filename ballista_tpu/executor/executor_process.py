"""Executor daemon process.

Rebuild of executor/src/executor_process.rs: registers with the scheduler
(wire-version gated), serves ExecutorGrpc + the Flight shuffle server,
heartbeats, optionally runs the pull-mode poll loop
(execution_loop.rs:88 — PollWork doubles as heartbeat), sweeps expired
job dirs by TTL (:1042), drains gracefully on SIGTERM.
"""

from __future__ import annotations

import argparse
import logging
import os
import shutil
import signal
import socket
import tempfile
import threading
import time
from concurrent import futures

import grpc

from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.executor.executor_server import ExecutorGrpcService, add_executor_service
from ballista_tpu.flight.server import start_flight_server
from ballista_tpu.ids import new_executor_id
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.grpc_service import scheduler_stub
from ballista_tpu.serde_control import encode_executor_metadata, encode_task_status

log = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 5.0
POLL_INTERVAL_S = 0.25
DIR_TTL_CHECK_S = 300.0


def detect_memory_limit() -> int:
    """Container/host memory in bytes: cgroup v2 → v1 → /proc/meminfo
    (the reference's fraction-of-cgroup/host autodetect,
    executor_process.rs:465-480)."""
    for path in ("/sys/fs/cgroup/memory.max", "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            with open(path) as f:
                raw = f.read().strip()
            if raw != "max":
                v = int(raw)
                if 0 < v < (1 << 60):  # v1 reports ~int64.max when unlimited
                    return v
        except (OSError, ValueError):
            continue
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 4 * 1024**3


def _ensure_native_flight_binary() -> str | None:
    """Build native/ballista-flight-server if missing. flock-serialized
    (concurrent executors on one host must not race g++ over the same
    output) with a negative-result marker so hosts where the build fails
    pay the compile attempt once, not per executor start."""
    import fcntl
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    native = os.path.join(repo, "native")
    bin_path = os.path.join(native, "ballista-flight-server")
    build = os.path.join(native, "build.sh")
    src = os.path.join(native, "flight_shuffle.cpp")

    def fresh() -> bool:
        try:
            return os.path.getmtime(bin_path) >= os.path.getmtime(src)
        except OSError:
            return False

    if os.path.exists(bin_path) and fresh():
        return bin_path
    if not os.path.exists(build):
        return None
    marker = os.path.join(native, ".flight_build_failed")

    def marker_current() -> bool:
        # a failure marker older than the source is void: the code changed
        # since that build failed, so the compile deserves another attempt
        try:
            return os.path.getmtime(marker) >= os.path.getmtime(src)
        except OSError:
            return False

    try:
        with open(os.path.join(native, ".build.lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(bin_path) and fresh():
                return bin_path
            if marker_current():
                return None
            r = subprocess.run(["sh", build], capture_output=True, timeout=300, check=False)
            if os.path.exists(bin_path) and fresh():
                return bin_path
            with open(marker, "w") as f:
                f.write(r.stderr.decode(errors="replace")[-2000:])
            return None
    except Exception:  # noqa: BLE001
        return None


def start_native_flight_server(work_dir: str, bind_host: str, port: int):
    """Spawn the C++ Flight data plane (native/flight_shuffle.cpp — same
    wire contract as flight/server.py). Returns (proc, bound_port) or None
    when the binary is missing or fails to come up."""
    import subprocess

    bin_path = _ensure_native_flight_binary()
    if bin_path is None:
        return None
    try:
        proc = subprocess.Popen(
            [bin_path, "--host", bind_host, "--port", str(port), "--work-dir", work_dir],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        # bounded wait for the PORT line: a wedged bind must not hang startup
        import select

        ready, _, _ = select.select([proc.stdout], [], [], 20.0)
        if not ready:
            proc.terminate()
            return None
        line = proc.stdout.readline().strip()
        if not line.startswith("PORT "):
            proc.terminate()
            return None
        return proc, int(line.split()[1])
    except Exception:  # noqa: BLE001
        return None


class ExecutorProcess:
    def __init__(self, scheduler_addr: str, bind_host: str = "0.0.0.0",
                 external_host: str | None = None, grpc_port: int = 0,
                 flight_port: int = 0, vcores: int | None = None,
                 work_dir: str | None = None, engine: str = "cpu",
                 policy: str = "push", work_dir_ttl_s: float = 4 * 3600,
                 memory_pool_bytes: int = 0, memory_fraction: float = 0.6,
                 flight_impl: str = "auto", device_ordinal: int = -1,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_ca: str | None = None, task_isolation: str = "thread"):
        self.scheduler_addr = scheduler_addr
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-tpu-executor-")
        self.policy = policy
        self.work_dir_ttl_s = work_dir_ttl_s
        if vcores is None and engine == "tpu" and device_ordinal >= 0:
            # one executor per chip ⇒ scheduler slot = chip (SURVEY §7 step
            # 7; reference vcore slot model, executor_process.rs:261): a
            # pinned device runs one stage task at a time
            vcores = 1
        vcores = vcores or (os.cpu_count() or 4)
        host = external_host or socket.gethostname()

        config = BallistaConfig({EXECUTOR_ENGINE: engine})
        if tls_ca:
            from ballista_tpu.config import GRPC_TLS_CA, GRPC_TLS_CERT, GRPC_TLS_KEY

            config.set(GRPC_TLS_CA, tls_ca)
            config.set(GRPC_TLS_CERT, tls_cert or "")
            config.set(GRPC_TLS_KEY, tls_key or "")
        self.flight_server = None
        self.native_flight_proc = None
        # With mTLS configured the data plane must not stay plaintext: the
        # native C++ server has no TLS support yet, so TLS forces the Python
        # Flight server, which serves with the same certificates + required
        # client verification as the control plane.
        flight_tls = bool(tls_cert and tls_key)
        if flight_impl == "native" and flight_tls:
            raise RuntimeError("native flight server does not support TLS; use flight_impl=python")
        if flight_impl in ("auto", "native") and not flight_tls:
            native = start_native_flight_server(self.work_dir, bind_host, flight_port)
            if native is not None:
                self.native_flight_proc, bound_flight = native
                log.info("native C++ flight data plane on :%d", bound_flight)
            elif flight_impl == "native":
                raise RuntimeError("native flight server requested but unavailable")
        if self.native_flight_proc is None:
            self.flight_server, bound_flight = start_flight_server(
                self.work_dir, bind_host, flight_port,
                tls_cert=tls_cert, tls_key=tls_key, tls_client_ca=tls_ca,
            )

        self.memory_pool_bytes = memory_pool_bytes or int(detect_memory_limit() * memory_fraction)
        self.metadata = ExecutorMetadata(
            id=str(new_executor_id()), host=host, flight_port=bound_flight, vcores=vcores,
            device_ordinal=device_ordinal,
        )
        self.config = config
        self.executor = Executor(self.work_dir, self.metadata, config=config)
        self.executor.isolation = task_isolation
        # startup orphan sweep: a crashed prior incarnation that reused this
        # work dir leaves job dirs no scheduler will remove_job_data for;
        # age-gated by the same TTL the background sweep uses, so a fresh
        # restart never races live job files (docs/lifecycle.md#gc)
        from ballista_tpu.executor import lifecycle

        orphans, freed = lifecycle.sweep_stale_dirs(self.work_dir, self.work_dir_ttl_s)
        self.executor.orphans_reclaimed += orphans
        self.executor.gc_reclaimed_bytes += freed
        # per-task static floor (backstop when no session pool is present)
        self.executor.memory_limit_per_task = max(
            64 * 1024 * 1024, self.memory_pool_bytes // max(1, vcores)
        )
        # session-shared pool with try_grow semantics: concurrent tasks of a
        # session draw from ONE executor-sized budget, so idle tasks lend
        # headroom to a heavy sort (runtime_cache.rs:59)
        from ballista_tpu.executor.memory_pool import SessionPoolRegistry

        self.executor.session_pools = SessionPoolRegistry(self.memory_pool_bytes)

        from ballista_tpu.utils.grpc_util import create_channel

        self._channel = create_channel(scheduler_addr, config)
        self._scheduler = scheduler_stub(self._channel)
        self._stopping = threading.Event()
        self._pending_status: list = []
        self._status_lock = threading.Lock()

        from ballista_tpu.utils.grpc_util import server_options

        self.grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8), options=server_options(config)
        )
        self.service = ExecutorGrpcService(self.executor, self._send_status, self.shutdown)
        add_executor_service(self.grpc_server, self.service)
        from ballista_tpu.utils.grpc_util import bind_server_port

        self.grpc_port = bind_server_port(
            self.grpc_server, f"{bind_host}:{grpc_port}", tls_cert, tls_key,
            tls_ca if tls_cert else None,
        )
        self.metadata.grpc_port = self.grpc_port

        from ballista_tpu.executor.health import start_health_server

        self.health_server, self.health_port = start_health_server(
            self.executor, self._stopping, bind_host
        )

    # ------------------------------------------------------------------

    def start(self) -> None:
        self.grpc_server.start()
        self._register()
        threading.Thread(target=self._heartbeat_loop, daemon=True, name="heartbeat").start()
        threading.Thread(target=self._dir_ttl_loop, daemon=True, name="dir-ttl").start()
        if self.policy == "pull":
            threading.Thread(target=self._poll_loop, daemon=True, name="poll").start()
        log.info(
            "executor %s up: grpc=%d flight=%d vcores=%d device=%s work_dir=%s",
            self.metadata.id, self.grpc_port, self.metadata.flight_port,
            self.metadata.vcores,
            self.metadata.device_ordinal if self.metadata.device_ordinal >= 0 else "unpinned",
            self.work_dir,
        )

    def _register(self) -> None:
        req = pb.RegisterExecutorParams(metadata=encode_executor_metadata(self.metadata))
        for attempt in range(30):
            try:
                resp = self._scheduler.RegisterExecutor(req, timeout=5)
                if not resp.success:
                    raise RuntimeError(f"registration rejected: {resp.error}")
                return
            except grpc.RpcError:
                time.sleep(min(2.0, 0.2 * (attempt + 1)))
        raise RuntimeError(f"cannot reach scheduler at {self.scheduler_addr}")

    def _send_status(self, results) -> None:
        if self.policy == "pull":
            with self._status_lock:
                self._pending_status.extend(results)
            return
        req = pb.UpdateTaskStatusParams(executor_id=self.metadata.id)
        for r in results:
            req.task_status.append(encode_task_status(r, self.metadata.id))
        self._scheduler.UpdateTaskStatus(req, timeout=30)

    def _overload_metrics(self) -> list[tuple[str, float]]:
        """Pressure signals piggybacked on the heartbeat's existing
        repeated ExecutorMetricProto field (no wire change): pool
        saturation, lifetime forced-overcommit bytes, admission
        rejections, and local task-queue depth."""
        pools = self.executor.session_pools
        from ballista_tpu.shuffle.integrity import INTEGRITY

        integrity = INTEGRITY.snapshot()
        metrics = [
            ("memory_pressure", pools.aggregate_pressure() if pools else 0.0),
            ("pool_overcommitted_bytes", float(pools.total_overcommitted()) if pools else 0.0),
            ("pressure_rejections", float(self.executor.pressure_rejections)),
            ("queued_tasks", float(self.service._queue.qsize())),
            # serving tier: fast-lane dispatches seen by this executor
            ("fast_lane_tasks", float(self.executor.fast_lane_tasks)),
            # direct dispatch: granted leases + scheduler-less tasks run
            ("active_leases", float(self.executor.lease_table.active_count())),
            ("direct_dispatch_tasks", float(self.executor.lease_table.tasks_total)),
            # shuffle-integrity counters (reader-side verification outcomes)
            ("checksum_failures", float(integrity["checksum_failures"])),
            ("corruption_retries", float(integrity["corruption_retries"])),
        ]
        # lifecycle + disk-pressure gauges (docs/lifecycle.md): the
        # scheduler derives lifecycle_state, steers placement away from
        # full nodes, and triggers the drain state machine off these
        from ballista_tpu.executor import disk as _disk

        _frac, used_b, free_b = _disk.disk_status(self.work_dir)
        metrics.extend([
            ("lifecycle_draining", 1.0 if self.executor.draining else 0.0),
            ("disk_used_bytes", float(used_b)),
            ("disk_free_bytes", float(free_b)),
            ("disk_rejecting",
             1.0 if _disk.admission_blocked(self.config, self.work_dir) else 0.0),
            ("disk_rejections", float(self.executor.disk_rejections)),
            ("migrated_partitions", float(self.executor.migrated_partitions)),
            ("migrated_bytes", float(self.executor.migrated_bytes)),
            ("gc_reclaimed_bytes", float(self.executor.gc_reclaimed_bytes)),
            ("orphans_reclaimed", float(self.executor.orphans_reclaimed)),
        ])
        metrics.extend(self._tpu_metrics())
        return metrics

    @staticmethod
    def _tpu_metrics() -> list[tuple[str, float]]:
        """TPU cold-path gauges from the engine's merged RUN_STATS plus the
        persistent compile cache's hit counters. Guarded on sys.modules so a
        CPU-engine executor never pulls in jax just to heartbeat."""
        import sys

        sc = sys.modules.get("ballista_tpu.ops.tpu.stage_compiler")
        if sc is None:
            return []
        out = []
        stats = sc.RUN_STATS.snapshot()
        for key in ("fill_s", "encode_s", "upload_s", "compile_s",
                    "compile_overlap_s", "exec_s", "device_bytes",
                    "fused_spans", "fused_kernel_s",
                    "mesh_devices", "exchange_bytes_on_device", "exchange_s",
                    "hbm_budget_bytes", "hbm_spill_bytes", "hbm_spill_events",
                    "hbm_reupload_events", "grace_splits", "hbm_oom_retries",
                    "sort_kernel_s", "sort_invocations", "topk_invocations",
                    "topk_rows_kept", "window_invocations",
                    "window_partitions", "sort_full_materializations",
                    "delta_fill_rows",
                    "daemon_attached", "init_platform_probe_s",
                    "init_jax_devices_s", "init_first_compile_s"):
            if key in stats:
                out.append((f"tpu_{key}", float(stats[key])))
        if "hbm_plan" in stats:
            # gauges are floats: the admission ladder's rungs in demotion
            # order (the string hbm_plan_reason stays in RUN_STATS)
            code = {"run_whole": 0.0, "spill_colds": 1.0, "grace_split": 2.0,
                    "cpu_demote": 3.0}
            out.append(("tpu_hbm_plan", code.get(str(stats["hbm_plan"]), -1.0)))
        if "fusion_mode" in stats:
            # gauges are floats: staged=0, fused_xla=1, fused_pallas=2
            code = {"staged": 0.0, "fused_xla": 1.0, "fused_pallas": 2.0}
            out.append(("tpu_fusion_mode",
                        code.get(str(stats["fusion_mode"]), -1.0)))
        # AQE decision counters likewise keep their RUN_STATS names (no
        # tpu_ prefix: they count scheduler replans — skew splits, join
        # mode switches, mesh replans — not this executor's device work)
        for key in ("skew_splits", "coalesced_partitions",
                    "broadcast_promotions", "broadcast_demotions",
                    "aqe_mesh_replans"):
            if key in stats:
                out.append((key, float(stats[key])))
        # warm-daemon multiplexing gauges keep their RUN_STATS names (no
        # tpu_ prefix: they describe the shared daemon, not this
        # executor's own device work — tpu_daemon_attached above says
        # whether THIS process rode it)
        if "daemon_sessions" in stats:
            out.append(("daemon_sessions", float(stats["daemon_sessions"])))
        if "daemon_queue_depth" in stats:
            out.append(("daemon_queue_depth",
                        float(stats["daemon_queue_depth"])))
        # daemon failure-domain recovery counters (ops/tpu/daemon_route.py
        # mirrors the client's process-lifetime totals into RUN_STATS);
        # RUN_STATS names, no tpu_ prefix — they count daemon incarnations
        # and quarantine events, not this executor's device work
        if "daemon_restarts" in stats:
            out.append(("daemon_restarts", float(stats["daemon_restarts"])))
        if "daemon_crashes_detected" in stats:
            out.append(("daemon_crashes_detected",
                        float(stats["daemon_crashes_detected"])))
        if "watchdog_kills" in stats:
            out.append(("watchdog_kills", float(stats["watchdog_kills"])))
        if "poisoned_stages" in stats:
            out.append(("poisoned_stages", float(stats["poisoned_stages"])))
        if "mesh_mode_reason" in stats:
            # gauges are floats: 1 = the collective exchange ran on-device,
            # 0 = demoted to the host split (the string reason stays in
            # RUN_STATS for bench/exercise output)
            mesh = 1.0 if str(stats["mesh_mode_reason"]) == "mesh" else 0.0
            out.append(("tpu_mesh_mode", mesh))
        from ballista_tpu.ops.tpu import runtime

        cc = runtime.compile_cache_stats()
        if cc["dir"]:
            out.append(("tpu_persist_cache_requests", float(cc["requests"])))
            out.append(("tpu_persist_cache_hits", float(cc["hits"])))
        return out

    def _heartbeat_once(self) -> bool:
        """One heartbeat round-trip. Returns the scheduler's reregister
        flag; while draining we do NOT act on it — the scheduler pops a
        drained executor from its fleet, so reregister-while-draining
        means the handoff finished, not that we should rejoin."""
        req = pb.HeartBeatParams(
            executor_id=self.metadata.id,
            metadata=encode_executor_metadata(self.metadata),
            status="active",
        )
        for name, value in self._overload_metrics():
            req.metrics.add(name=name, value=value)
        resp = self._scheduler.HeartBeatFromExecutor(req, timeout=5)
        if resp.reregister and not self.executor.draining:
            self._register()
        return bool(resp.reregister)

    def _heartbeat_loop(self) -> None:
        while not self._stopping.wait(HEARTBEAT_INTERVAL_S):
            try:
                self._heartbeat_once()
            except grpc.RpcError as e:
                log.warning("heartbeat failed: %s", e.code() if hasattr(e, "code") else e)

    def _poll_loop(self) -> None:
        """Pull mode: PollWork carries statuses and pulls new tasks."""
        from ballista_tpu.serde_control import decode_task_definition

        while not self._stopping.wait(POLL_INTERVAL_S):
            with self._status_lock:
                statuses, self._pending_status = self._pending_status, []
            free = max(0, self.metadata.vcores - self.service._queue.qsize())
            req = pb.PollWorkParams(
                metadata=encode_executor_metadata(self.metadata),
                can_accept_task=free > 0,
                free_slots=free,
            )
            for r in statuses:
                req.task_status.append(encode_task_status(r, self.metadata.id))
            try:
                resp = self._scheduler.PollWork(req, timeout=10)
            except grpc.RpcError as e:
                log.warning("poll failed: %s", e)
                continue
            for tp in resp.tasks:
                task = decode_task_definition(tp)
                cfg = BallistaConfig.from_key_value_pairs(
                    [(kv.key, kv.value) for kv in tp.props], scrub_restricted=True
                )
                self.service._queue.put((task, cfg))

    def _dir_ttl_loop(self) -> None:
        from ballista_tpu.executor.lifecycle import _dir_bytes

        while not self._stopping.wait(DIR_TTL_CHECK_S):
            cutoff = time.time() - self.work_dir_ttl_s
            try:
                for name in os.listdir(self.work_dir):
                    p = os.path.join(self.work_dir, name)
                    if os.path.isdir(p) and os.path.getmtime(p) < cutoff:
                        nbytes = _dir_bytes(p)
                        shutil.rmtree(p, ignore_errors=True)
                        self.executor.gc_reclaimed_bytes += nbytes
                        log.info("TTL-swept job dir %s (%d bytes)", p, nbytes)
            except OSError:
                pass

    def drain(self, timeout_s: float | None = None) -> None:
        """SIGTERM-initiated graceful drain (docs/lifecycle.md
        #drain-protocol). Advertises lifecycle_draining=1 on an immediate
        heartbeat — the scheduler's heartbeat handler runs the drain state
        machine (lease revocation, bounded wait, shuffle handoff) — then
        keeps the data plane up until the scheduler drops us from its
        fleet (reregister-while-draining) or the drain timeout lapses,
        and finally shuts down. A second SIGTERM hard-stops immediately;
        anything not handed off recovers via the recompute path."""
        if self._stopping.is_set():
            return
        if self.executor.draining:
            log.info("second SIGTERM during drain: hard stop")
            self.shutdown()
            return
        self.executor.draining = True
        log.info("draining executor %s (SIGTERM)", self.metadata.id)
        if timeout_s is None:
            from ballista_tpu.config import EXECUTOR_DRAIN_TIMEOUT_S

            timeout_s = float(BallistaConfig().get(EXECUTOR_DRAIN_TIMEOUT_S))
        deadline = time.time() + max(0.0, timeout_s)
        while time.time() < deadline and not self._stopping.is_set():
            try:
                dropped = self._heartbeat_once()
            except grpc.RpcError:
                dropped = False
            if dropped and self.service._queue.unfinished_tasks == 0:
                log.info("drain handoff complete; shutting down")
                break
            time.sleep(1.0)
        self.shutdown()

    def shutdown(self) -> None:
        if self._stopping.is_set():
            return
        self._stopping.set()
        try:
            self._scheduler.ExecutorStopped(
                pb.ExecutorStoppedParams(executor_id=self.metadata.id, reason="shutdown"), timeout=3
            )
        except grpc.RpcError:
            pass
        self.service.stop()
        self.grpc_server.stop(grace=2)
        if self.flight_server is not None:
            self.flight_server.shutdown()
        if self.native_flight_proc is not None:
            self.native_flight_proc.terminate()
            try:
                self.native_flight_proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                self.native_flight_proc.kill()
        self.health_server.shutdown()

    def wait(self) -> None:
        try:
            while not self._stopping.wait(1.0):
                pass
        except KeyboardInterrupt:
            self.shutdown()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="ballista_tpu executor daemon")
    ap.add_argument("--scheduler", default="localhost:50050", help="scheduler host:port")
    ap.add_argument("--bind-host", default="0.0.0.0")
    ap.add_argument("--external-host", default=None)
    ap.add_argument("--grpc-port", type=int, default=0)
    ap.add_argument("--flight-port", type=int, default=0)
    ap.add_argument("--concurrent-tasks", type=int, default=None, help="vcores (default: all)")
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--engine", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--policy", choices=("push", "pull"), default="push")
    ap.add_argument("--tls-cert", default=None, help="server certificate chain (PEM)")
    ap.add_argument("--tls-key", default=None, help="server private key (PEM)")
    ap.add_argument("--tls-ca", default=None,
                    help="CA for verifying the scheduler and requiring client certs (mTLS)")
    ap.add_argument("--flight-server", choices=("auto", "python", "native"), default="auto",
                    help="shuffle data plane: native C++ (preferred), python, or auto-fallback")
    ap.add_argument("--task-isolation", choices=("thread", "process"), default="thread",
                    help="process: run each task in a spawned worker — true multi-core "
                         "parallelism, native-crash isolation, preemptive cancel "
                         "(DedicatedExecutor parity); thread: in-process (default)")
    ap.add_argument("--device-ordinal", type=int,
                    default=int(os.environ.get("BALLISTA_DEVICE_ORDINAL", "-1")),
                    help="pin this executor to one accelerator chip (one executor per "
                         "chip; defaults vcores to 1 with --engine tpu). -1 = unpinned")
    ap.add_argument("--memory-pool-bytes", type=int, default=0,
                    help="fixed memory pool size (0 = fraction of cgroup/host)")
    ap.add_argument("--memory-fraction", type=float, default=0.6,
                    help="fraction of detected cgroup/host memory for the pool")
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument("--log-file", default=None, help="also log to this file (rotating)")
    ap.add_argument("--log-rotation", choices=("never", "minutely", "hourly", "daily"),
                    default="daily", help="rotation policy for --log-file")
    args = ap.parse_args(argv)
    from ballista_tpu.utils.log_util import init_logging

    init_logging(args.log_level, args.log_file, args.log_rotation)

    if args.device_ordinal >= 0:
        # must happen before jax's backend initialises: on real TPU hardware
        # each chip is claimed exclusively, so a pinned daemon filters its
        # runtime visibility down to its one chip
        from ballista_tpu.ops.tpu.runtime import bind_process_ordinal

        if bind_process_ordinal(args.device_ordinal):
            log.info("process bound to device ordinal %d", args.device_ordinal)

    proc = ExecutorProcess(
        args.scheduler, args.bind_host, args.external_host, args.grpc_port,
        args.flight_port, args.concurrent_tasks, args.work_dir, args.engine, args.policy,
        memory_pool_bytes=args.memory_pool_bytes, memory_fraction=args.memory_fraction,
        flight_impl=args.flight_server, device_ordinal=args.device_ordinal,
        tls_cert=args.tls_cert, tls_key=args.tls_key, tls_ca=args.tls_ca,
        task_isolation=args.task_isolation,
    )
    # SIGTERM = graceful drain (handoff shuffle outputs, then exit); a
    # second SIGTERM hard-stops. The handler must not block, so the drain
    # state machine runs on its own thread.
    signal.signal(signal.SIGTERM,
                  lambda *_: threading.Thread(target=proc.drain, daemon=True,
                                              name="drain").start())
    proc.start()
    proc.wait()


if __name__ == "__main__":
    main()
