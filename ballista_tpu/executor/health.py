"""Executor HTTP health endpoint (reference: executor/src/health.rs:94).

GET /health → {"status": "healthy", ...liveness facts} — the probe target
for k8s-style deployments; reports degraded once shutdown begins.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def start_health_server(executor, stopping_event, host: str = "0.0.0.0", port: int = 0):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):  # noqa: N802
            if self.path.rstrip("/") not in ("", "/health"):
                self.send_response(404)
                self.end_headers()
                return
            from ballista_tpu.shuffle.integrity import INTEGRITY

            pools = executor.session_pools
            stopping = stopping_event.is_set()
            body = json.dumps({
                "status": "draining" if (stopping or executor.draining) else "healthy",
                # lifecycle facts (docs/lifecycle.md): draining = handoff in
                # progress, stopping = shutdown begun
                "lifecycle_state": ("stopping" if stopping
                                    else "draining" if executor.draining else "active"),
                "executor_id": executor.metadata.id,
                "tasks_run": executor.tasks_run,
                "tasks_failed": executor.tasks_failed,
                "device_ordinal": executor.metadata.device_ordinal,
                "pressure_rejections": executor.pressure_rejections,
                "disk_rejections": executor.disk_rejections,
                "migrated_partitions": executor.migrated_partitions,
                "migrated_bytes": executor.migrated_bytes,
                "gc_reclaimed_bytes": executor.gc_reclaimed_bytes,
                "orphans_reclaimed": executor.orphans_reclaimed,
                "memory_pressure": round(pools.aggregate_pressure(), 4) if pools else 0.0,
                "pool_overcommitted_bytes": pools.total_overcommitted() if pools else 0,
                # shuffle-integrity counters (reader-side verification)
                **INTEGRITY.snapshot(),
            }).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True, name="executor-health")
    t.start()
    return server, server.server_port
