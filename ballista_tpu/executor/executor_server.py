"""ExecutorGrpc service + push-mode task runner pool.

Rebuild of executor/src/executor_server.rs: LaunchMultiTask enqueues task
definitions; a worker pool sized to vcores runs them (TaskRunnerPool
:691); completed statuses are batched back to the owning scheduler via
UpdateTaskStatus; StopExecutor / CancelTasks / RemoveJobData complete the
rpc surface (ballista.proto:984).
"""

from __future__ import annotations

import logging
import queue
import threading

import grpc

from ballista_tpu.executor.executor import Executor
from ballista_tpu.proto import pb
from ballista_tpu.serde_control import decode_task_definition, encode_task_status

log = logging.getLogger(__name__)

SERVICE_NAME = "ballista_tpu.ExecutorGrpc"


class ExecutorGrpcService:
    def __init__(self, executor: Executor, status_sender, shutdown_cb=None):
        """status_sender(results: list[TaskResult]) → ships to scheduler."""
        self.executor = executor
        self.status_sender = status_sender
        self.shutdown_cb = shutdown_cb
        self._queue: "queue.Queue" = queue.Queue()
        self._config_cache: dict = {}
        self._workers: list[threading.Thread] = []
        self._running = True
        for i in range(max(1, executor.metadata.vcores)):
            t = threading.Thread(target=self._worker, daemon=True, name=f"task-runner-{i}")
            t.start()
            self._workers.append(t)

    def _worker(self) -> None:
        while self._running:
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            task, config = item
            try:
                result = self.executor.run_task(task, config)
                try:
                    self.status_sender([result])
                except Exception:  # noqa: BLE001
                    log.exception("failed to report task status")
            finally:
                # unfinished_tasks hits 0 only when queued AND running work
                # is done — the drain path polls it to know the executor is
                # idle (docs/lifecycle.md#drain-protocol)
                self._queue.task_done()

    def stop(self) -> None:
        self._running = False

    # -- rpcs ----------------------------------------------------------------

    def LaunchMultiTask(self, request: pb.LaunchMultiTaskParams, context) -> pb.LaunchMultiTaskResult:
        for tp in request.tasks:
            task = decode_task_definition(tp)
            cfg = self._session_config([(kv.key, kv.value) for kv in tp.props])
            self._queue.put((task, cfg))
        return pb.LaunchMultiTaskResult(success=True)

    def _session_config(self, pairs: list[tuple[str, str]]):
        """Session-scoped config cache (reference: SessionRuntimeCache,
        executor/src/runtime_cache.rs): concurrent tasks of one session
        share one parsed BallistaConfig instead of re-parsing the KV set
        per task. Bounded; keyed on the exact KV tuple."""
        from ballista_tpu.config import BallistaConfig

        key = tuple(pairs)
        cfg = self._config_cache.get(key)
        if cfg is None:
            cfg = BallistaConfig.from_key_value_pairs(list(pairs), scrub_restricted=True)
            if len(self._config_cache) >= 32:
                self._config_cache.pop(next(iter(self._config_cache)))
            self._config_cache[key] = cfg
        # hand out a copy: tasks apply per-task defaults (executor memory
        # budget) and must never mutate the shared cached entry
        return cfg.copy()

    def StopExecutor(self, request: pb.StopExecutorParams, context) -> pb.StopExecutorResult:
        log.info("stop requested (force=%s): %s", request.force, request.reason)
        self.stop()
        if self.shutdown_cb is not None:
            threading.Thread(target=self.shutdown_cb, daemon=True).start()
        return pb.StopExecutorResult()

    def CancelTasks(self, request: pb.CancelTasksParams, context) -> pb.CancelTasksResult:
        for t in request.tasks:
            self.executor.cancel_task(t.job_id, t.stage_id, t.task_id)
        return pb.CancelTasksResult(cancelled=True)

    def RemoveJobData(self, request: pb.RemoveJobDataParams, context) -> pb.RemoveJobDataResult:
        import shutil
        import os

        from ballista_tpu.shuffle.paths import contained_path, job_dir, validate_job_id

        try:
            job_id = validate_job_id(request.job_id)
            d = contained_path(self.executor.work_dir, job_dir(self.executor.work_dir, job_id))
        except (ValueError, PermissionError) as e:
            import grpc

            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)
        self.executor.clear_cancellations(request.job_id)
        return pb.RemoveJobDataResult()


_RPCS = {
    "LaunchMultiTask": (pb.LaunchMultiTaskParams, pb.LaunchMultiTaskResult),
    "StopExecutor": (pb.StopExecutorParams, pb.StopExecutorResult),
    "CancelTasks": (pb.CancelTasksParams, pb.CancelTasksResult),
    "RemoveJobData": (pb.RemoveJobDataParams, pb.RemoveJobDataResult),
}


def add_executor_service(server: grpc.Server, service: ExecutorGrpcService) -> None:
    handlers = {}
    for name, (req_t, _r) in _RPCS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda resp: resp.SerializeToString(),
        )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


def executor_stub(channel: grpc.Channel):
    class Stub:
        pass

    stub = Stub()
    for name, (req_t, resp_t) in _RPCS.items():
        setattr(
            stub, name,
            channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ),
        )
    return stub
