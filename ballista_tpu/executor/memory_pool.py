"""Session-shared memory pool with try_grow semantics.

Rebuild of the reference's per-session RuntimeEnv memory pool
(executor/src/runtime_cache.rs:59): ONE pool per session id, shared by
every concurrent task of that session on this executor — so N small tasks
lend unused budget to one big sort instead of each task being statically
boxed to capacity/vcores. Consumers call try_grow before buffering and
shrink when they spill or finish; a refusal means "spill first".
"""

from __future__ import annotations

import threading
import time  # noqa: F401 — monotonic used by grow_wait and the registry TTL


class MemoryPool:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.reserved = 0
        self._lock = threading.Lock()
        self._freed = threading.Condition(self._lock)
        # forced reservations past capacity (observability: a non-zero value
        # means the deadline backstop fired under real memory pressure)
        self.overcommitted = 0
        # device headroom is split-accounted from host headroom: HBM spill
        # demotes DEVICE bytes to HOST buffers, so one ledger would let a
        # spill storm eat the budget CPU sorts spill against (and vice
        # versa). 0 = no device attached to this session's tasks.
        self.device_capacity = 0
        self.device_reserved = 0

    def set_device_capacity(self, nbytes: int) -> None:
        """Attach (or retune) the device-side ledger; monotonic max so
        concurrent tasks of one session can't shrink each other's view."""
        with self._lock:
            self.device_capacity = max(self.device_capacity, int(nbytes))

    def try_grow_device(self, nbytes: int) -> bool:
        with self._lock:
            if self.device_capacity <= 0:
                return False
            if self.device_reserved + nbytes > self.device_capacity:
                return False
            self.device_reserved += nbytes
            return True

    def shrink_device(self, nbytes: int) -> None:
        with self._lock:
            self.device_reserved = max(0, self.device_reserved - nbytes)

    def sync_device_reserved(self, nbytes: int) -> None:
        """Absolute resync from the device-cache residency snapshot: the
        stage compiler owns the cache (global, LRU, spill-demoting), so the
        ledger mirrors it instead of tracking paired grow/shrink calls that
        cache evictions on OTHER sessions' stages would unbalance."""
        with self._lock:
            self.device_reserved = max(0, int(nbytes))

    def device_pressure(self) -> float:
        """Device-ledger saturation; independent of host `pressure()` by
        construction (the split-accounting contract)."""
        with self._lock:
            if self.device_capacity <= 0:
                return 0.0
            return self.device_reserved / self.device_capacity

    def try_grow(self, nbytes: int) -> bool:
        with self._lock:
            if self.reserved + nbytes > self.capacity:
                return False
            self.reserved += nbytes
            return True

    def grow_wait(self, nbytes: int, timeout_s: float) -> bool:
        """Block until the reservation fits (another task shrinking notifies)
        or the deadline passes; a deadline pass reserves anyway — liveness
        over strictness — and is counted in `overcommitted`. Returns True
        when the reservation stayed within capacity. A single reservation
        larger than the whole pool can never be satisfied by peers
        shrinking, so it overcommits immediately instead of sleeping out
        the deadline (the write-side twin of the reader window's
        oversized-singleton admission)."""
        deadline = time.monotonic() + timeout_s
        with self._freed:
            while self.reserved + nbytes > self.capacity:
                if nbytes > self.capacity or deadline - time.monotonic() <= 0:
                    self.reserved += nbytes
                    self.overcommitted += nbytes
                    return False
                self._freed.wait(timeout=deadline - time.monotonic())
            self.reserved += nbytes
            return True

    def shrink(self, nbytes: int) -> None:
        with self._freed:
            self.reserved = max(0, self.reserved - nbytes)
            self._freed.notify_all()

    def pressure(self) -> float:
        """Saturation as a fraction of capacity; > 1.0 means overcommitted
        reservations are live right now."""
        with self._lock:
            if self.capacity <= 0:
                return 0.0
            return self.reserved / self.capacity

    @property
    def saturated(self) -> bool:
        """A new task landing here would start life overcommitted — the
        executor's admission gate rejects (retryably) instead."""
        with self._lock:
            return self.reserved >= self.capacity


class SessionPoolRegistry:
    """session id → shared MemoryPool (created on first use).

    TTL-evicting, like the reference's SessionRuntimeCache
    (executor/src/runtime_cache.rs:86): executors never hear about session
    removal from the scheduler, so pools idle past the TTL are dropped on
    the next lookup. Eviction also heals leaked reservations from tasks
    that died mid-reserve — the session's next task gets a fresh pool.
    Tasks holding a reference to an evicted pool keep using it safely; only
    new lookups see the fresh one.
    """

    def __init__(self, capacity_per_session: int, ttl_s: float = 3600.0):
        self.capacity = capacity_per_session
        self.ttl_s = ttl_s
        self._pools: dict[str, tuple[MemoryPool, float]] = {}
        self._lock = threading.Lock()

    def get(self, session_id: str) -> MemoryPool:
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            entry = self._pools.get(session_id)
            if entry is None:
                pool = MemoryPool(self.capacity)
            else:
                pool = entry[0]
            self._pools[session_id] = (pool, now)
            return pool

    def remove(self, session_id: str) -> None:
        with self._lock:
            self._pools.pop(session_id, None)

    def _sweep_locked(self, now: float) -> None:
        dead = [sid for sid, (_, used) in self._pools.items() if now - used > self.ttl_s]
        for sid in dead:
            del self._pools[sid]

    def __len__(self) -> int:
        with self._lock:
            return len(self._pools)

    def aggregate_pressure(self) -> float:
        """Max saturation across live session pools — the executor's
        heartbeat pressure score. Max, not mean: admission gating cares
        whether the pool a NEW task would join is already past budget,
        and a fresh session always starts at zero."""
        with self._lock:
            pools = [p for p, _ in self._pools.values()]
        return max((p.pressure() for p in pools), default=0.0)

    def aggregate_device_pressure(self) -> float:
        """Max device-ledger saturation across live session pools — the
        device-side twin of `aggregate_pressure`, kept separate so host
        admission gating never confuses HBM pressure with sort pressure."""
        with self._lock:
            pools = [p for p, _ in self._pools.values()]
        return max((p.device_pressure() for p in pools), default=0.0)

    def total_overcommitted(self) -> int:
        """Lifetime forced-overcommit bytes across live pools (satellite
        observability for MemoryPool.overcommitted)."""
        with self._lock:
            pools = [p for p, _ in self._pools.values()]
        return sum(p.overcommitted for p in pools)
