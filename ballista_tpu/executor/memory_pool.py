"""Session-shared memory pool with try_grow semantics.

Rebuild of the reference's per-session RuntimeEnv memory pool
(executor/src/runtime_cache.rs:59): ONE pool per session id, shared by
every concurrent task of that session on this executor — so N small tasks
lend unused budget to one big sort instead of each task being statically
boxed to capacity/vcores. Consumers call try_grow before buffering and
shrink when they spill or finish; a refusal means "spill first".
"""

from __future__ import annotations

import threading


class MemoryPool:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.reserved = 0
        self._lock = threading.Lock()

    def try_grow(self, nbytes: int) -> bool:
        with self._lock:
            if self.reserved + nbytes > self.capacity:
                return False
            self.reserved += nbytes
            return True

    def grow(self, nbytes: int) -> None:
        """Unchecked growth — the liveness escape hatch after a consumer has
        spilled everything it can and still needs one batch of headroom."""
        with self._lock:
            self.reserved += nbytes

    def shrink(self, nbytes: int) -> None:
        with self._lock:
            self.reserved = max(0, self.reserved - nbytes)


class SessionPoolRegistry:
    """session id → shared MemoryPool (created on first use)."""

    def __init__(self, capacity_per_session: int):
        self.capacity = capacity_per_session
        self._pools: dict[str, MemoryPool] = {}
        self._lock = threading.Lock()

    def get(self, session_id: str) -> MemoryPool:
        with self._lock:
            p = self._pools.get(session_id)
            if p is None:
                p = MemoryPool(self.capacity)
                self._pools[session_id] = p
            return p

    def remove(self, session_id: str) -> None:
        with self._lock:
            self._pools.pop(session_id, None)
