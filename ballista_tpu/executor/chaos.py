"""Chaos fault injection.

Rebuild of ChaosExec + ChaosCreatingRule (core/src/execution_plans/
chaos_exec.rs:49, scheduler/src/state/aqe/optimizer_rule/chaos_exec.rs:58):
when `ballista.chaos.enabled` is on, the executor's engine seam wraps every
leaf operator in a ChaosExec that — with seeded probability — injects a
transient error (retryable), a fatal error, a panic (non-BallistaError
exception), or a delay. Robustness tests run real queries under injected
failures and assert the retry machinery converges.

Determinism: the RNG seed mixes (config seed, job, stage, partition,
attempt) so a retried task sees DIFFERENT luck — exactly what makes
transient-fault tests terminate.
"""

from __future__ import annotations

import hashlib
import time
from typing import Iterator

from ballista_tpu.config import (
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    BallistaConfig,
)
from ballista_tpu.errors import ExecutionError
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext


class ChaosExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, seed: int, probability: float, mode: str,
                 stage_attempt: int = 0):
        super().__init__(input.df_schema)
        self.input = input
        self.seed = seed
        self.probability = probability
        self.mode = mode
        self.stage_attempt = stage_attempt

    def children(self):
        return [self.input]

    def with_children(self, c):
        return ChaosExec(c[0], self.seed, self.probability, self.mode, self.stage_attempt)

    def node_str(self) -> str:
        return f"ChaosExec: mode={self.mode} p={self.probability}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator:
        h = hashlib.sha256(
            f"{self.seed}|{ctx.task_id}|{partition}|{self.stage_attempt}".encode()
        ).digest()
        roll = int.from_bytes(h[:8], "big") / 2**64
        if roll < self.probability:
            if self.mode == "transient":
                raise ExecutionError(f"chaos: injected transient failure (roll={roll:.4f})", retryable=True)
            if self.mode == "fatal":
                raise ExecutionError(f"chaos: injected fatal failure (roll={roll:.4f})", retryable=False)
            if self.mode == "panic":
                raise RuntimeError("chaos: injected panic")
            if self.mode == "delay":
                time.sleep(0.2)
        return self.input.execute(partition, ctx)


def maybe_inject_chaos(plan: ExecutionPlan, config: BallistaConfig, stage_attempt: int = 0) -> ExecutionPlan:
    if not bool(config.get(CHAOS_ENABLED)):
        return plan
    seed = int(config.get(CHAOS_SEED))
    prob = float(config.get(CHAOS_PROBABILITY))
    mode = str(config.get(CHAOS_MODE))

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if not kids:
            return ChaosExec(n, seed, prob, mode, stage_attempt)
        return n.with_children([walk(c) for c in kids])

    return walk(plan)
