"""Chaos fault injection.

Rebuild of ChaosExec + ChaosCreatingRule (core/src/execution_plans/
chaos_exec.rs:49, scheduler/src/state/aqe/optimizer_rule/chaos_exec.rs:58):
when `ballista.chaos.enabled` is on, the executor's engine seam wraps every
leaf operator in a ChaosExec that — with seeded probability — injects a
transient error (retryable), a fatal error, a panic (non-BallistaError
exception), or a delay. Robustness tests run real queries under injected
failures and assert the retry machinery converges.

Determinism: the RNG seed mixes (config seed, job, stage, partition,
attempt) so a retried task sees DIFFERENT luck — exactly what makes
transient-fault tests terminate.

Mode 'corrupt' is the exception: it is a SERVE-time fault (the Flight
server flips a bit in the bytes it streams, keyed by `corrupt_roll`/
`flip_bit` below), so ChaosExec itself treats it as a no-op at execute
time. The data plane has no session config, hence the env knobs
BALLISTA_CHAOS_CORRUPT_P / BALLISTA_CHAOS_CORRUPT_ONCE / BALLISTA_CHAOS_SEED
documented on `ballista.chaos.mode`.

Mode 'skew' faults the shuffle-writer PARTITIONER rather than leaf
execution (wrapping leaves would hide device-compiled stages from the
chain matcher, same trap 'hbm_oom' avoids): when armed, every bucketed
ShuffleWriterExec reroutes a seeded fraction of rows into one hot reduce
partition via `skew_remap_pids` below. The reroute is a pure function of
the row's KEY HASH — never of row position — so equal keys still
co-locate, both sides of a co-partitioned join skew identically, and
query results stay byte-identical while one partition absorbs the load.
Deterministic fuel for the AQE skew-split defense (docs/aqe.md).

Modes 'daemon_crash' and 'daemon_hang' fault the DEVICE-DAEMON process
(docs/device_daemon.md#failure-domain) and never wrap the plan either —
wrapping leaves would hide device stages from the chain matcher, and the
fault must fire in the DAEMON's process, not the executor's. The session
config carries the arming (`ballista.chaos.daemon.arm` picks the point:
pre_execute / mid_execute / post_execute; `ballista.chaos.daemon.once`
bounds it to the first armed request per socket) to the daemon, whose
execute handler kills itself uncleanly (daemon_crash → os._exit(137)) or
wedges until the execute watchdog fires (daemon_hang → diagnosed exit 4
with a <socket>.crash.json post-mortem). Deterministic fuel for the
crash-recovery / quarantine ladder in ops/tpu/daemon_route.py.

Mode 'disk_full' faults the STORAGE path and never wraps the plan: the
shuffle writer's commit points and the spill pool's disk demotions poll
`maybe_disk_full` below, which raises a typed DiskExhausted on a seeded
roll keyed by (seed, job, stage, partition[, attempt]). With
`ballista.chaos.disk.once` (the default) a hit is recorded so the
RETRY of the same slice heals — the injected ENOSPC is transient
storage pressure, and robustness tests assert no job ever fails to it.

Mode 'drain_kill' faults the DRAIN state machine (docs/lifecycle.md):
BALLISTA_CHAOS_DRAIN_KILL_AFTER=N makes a graceful drain's shuffle
migration die after N committed locations (`drain_kill_after` below),
exercising the hard-kill-mid-drain fallback to the recompute path.

Mode 'hbm_oom' is the other plan-wrapping exception: it faults the DEVICE memory path,
which chaos cannot reach by wrapping plan leaves — the TPU engine seam
runs after chaos injection, and a ChaosExec-wrapped scan would hide the
stage from the device compiler's chain matcher entirely (silently testing
the CPU path instead). It arms module state in `ops.tpu.hbm` instead:
the admission budget shrinks to BALLISTA_CHAOS_HBM_BUDGET bytes (default
1 MiB) and, with BALLISTA_CHAOS_HBM_OOM_N > 0, the Nth device upload
raises a synthetic RESOURCE_EXHAUSTED once. CPU-exercisable: the whole
out-of-core ladder (spill, grace, OOM-retry) runs under interpret-mode
jax in tier-1.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Iterator

import numpy as np

from ballista_tpu.config import (
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    CHAOS_SKEW_FRACTION,
    CHAOS_STRAGGLER_DELAY_S,
    CHAOS_STRAGGLER_PARTITION,
    CHAOS_STRAGGLER_STAGE,
    BallistaConfig,
)
from ballista_tpu.errors import Cancelled, ExecutionError
from ballista_tpu.ops.hashing import splitmix64
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext


def corrupt_roll(seed: int, key: str, p: float) -> bool:
    """Seeded decision for chaos mode=corrupt: should THIS serve of the
    range identified by `key` flip a bit? Pure function of (seed, key) so
    a test replaying the same serves sees the same corruption."""
    if p <= 0.0:
        return False
    h = hashlib.sha256(f"{seed}|corrupt|{key}".encode()).digest()
    return int.from_bytes(h[:8], "big") / 2**64 < p


def flip_bit(data: bytes, seed: int, key: str) -> bytes:
    """Deterministically flip one bit of `data` (position and bit index
    both derived from the seed+key hash). Returns a new bytes object —
    the stored file is never touched, only the served copy."""
    if not data:
        return data
    h = hashlib.sha256(f"{seed}|corrupt|{key}".encode()).digest()
    pos = int.from_bytes(h[8:16], "big") % len(data)
    bit = h[0] % 8
    out = bytearray(data)
    out[pos] ^= 1 << bit
    return bytes(out)


# disk_full once-mode ledger: keys that already fired, so the RETRY of an
# injected ENOSPC heals (the module's determinism principle, applied to a
# fault whose whole point is "transient storage pressure"). Keyed without
# the attempt so the marker survives into the retry.
from ballista_tpu.utils.lru import LruDict

_DISK_FULL_FIRED = LruDict(max_entries=4096)


def disk_full_params(config: BallistaConfig) -> tuple[int, float, bool] | None:
    """(seed, probability, once) when chaos mode=disk_full is armed, else
    None. The shuffle writer and the spill pool poll this at their write
    points — disk_full never wraps the plan (the fault lives in the
    storage path, not leaf execution)."""
    try:
        if not bool(config.get(CHAOS_ENABLED)):
            return None
        if str(config.get(CHAOS_MODE)) != "disk_full":
            return None
        from ballista_tpu.config import CHAOS_DISK_ONCE

        return (int(config.get(CHAOS_SEED)), float(config.get(CHAOS_PROBABILITY)),
                bool(config.get(CHAOS_DISK_ONCE)))
    except Exception:
        return None


def maybe_disk_full(config: BallistaConfig | None, job_id: str, stage_id: int,
                    partition: int, attempt: int, where: str) -> None:
    """Raise a synthetic DiskExhausted at a shuffle-write / spill-demote
    point when chaos mode=disk_full rolls a hit. In once mode the roll is
    keyed WITHOUT the attempt and a hit is recorded, so the retried task
    finds the marker and heals; otherwise the attempt joins the key and a
    retry simply sees different luck."""
    if config is None:
        return
    params = disk_full_params(config)
    if params is None:
        return
    seed, p, once = params
    key = f"{job_id}|{stage_id}|{partition}"
    if once:
        if _DISK_FULL_FIRED.get(key) is not None:
            return  # already failed this slice once: the retry heals
        h = hashlib.sha256(f"{seed}|disk_full|{key}".encode()).digest()
        if int.from_bytes(h[:8], "big") / 2**64 >= p:
            return
        _DISK_FULL_FIRED.setdefault(key, True)
    else:
        h = hashlib.sha256(f"{seed}|disk_full|{key}|{attempt}".encode()).digest()
        if int.from_bytes(h[:8], "big") / 2**64 >= p:
            return
    from ballista_tpu.errors import DiskExhausted

    raise DiskExhausted(where, "chaos: injected ENOSPC (os error 28)")


def drain_kill_after() -> int:
    """Chaos mode=drain_kill arming: BALLISTA_CHAOS_DRAIN_KILL_AFTER=N
    hard-kills a drain's migration after N committed locations (0 =
    disarmed). Env-armed like the serve-time corrupt knobs: the migration
    runs in the scheduler/launcher context, which has no session config."""
    try:
        return int(os.environ.get("BALLISTA_CHAOS_DRAIN_KILL_AFTER", "0"))
    except ValueError:
        return 0


def skew_params(config: BallistaConfig) -> tuple[int, float] | None:
    """(seed, fraction) when chaos mode=skew is armed, else None. The
    shuffle writer polls this per task — skew never wraps the plan."""
    try:
        if not bool(config.get(CHAOS_ENABLED)):
            return None
        if str(config.get(CHAOS_MODE)) != "skew":
            return None
        return int(config.get(CHAOS_SEED)), float(config.get(CHAOS_SKEW_FRACTION))
    except Exception:
        return None


def skew_remap_pids(h: np.ndarray, k: int, seed: int, fraction: float) -> np.ndarray:
    """Chaos mode=skew partitioner remap: route ~`fraction` of rows to the
    hot partition `seed % k`, the rest to their honest `h % k` home.

    The reroute decision re-mixes the row's key hash with a seeded salt,
    so it is a pure function of the KEY — equal keys always land together
    (results stay byte-identical) and every writer of a co-partitioned
    exchange, host- or device-hashed, skews the same rows."""
    h = h.astype(np.uint64, copy=False)
    pids = (h % np.uint64(k)).astype(np.uint64)
    if k <= 1 or fraction <= 0.0:
        return pids
    hot = np.uint64(seed % k)
    if fraction >= 1.0:
        return np.full_like(pids, hot)
    salt = splitmix64(np.array([seed & 0xFFFFFFFFFFFFFFFF], dtype=np.uint64))[0]
    mixed = splitmix64(h ^ salt)
    threshold = np.uint64(int(fraction * float(2**64)))
    return np.where(mixed < threshold, hot, pids)


class ChaosExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, seed: int, probability: float, mode: str,
                 stage_attempt: int = 0, straggler_delay_s: float = 5.0,
                 straggler_partition: int = -1):
        super().__init__(input.df_schema)
        self.input = input
        self.seed = seed
        self.probability = probability
        self.mode = mode
        self.stage_attempt = stage_attempt
        self.straggler_delay_s = straggler_delay_s
        self.straggler_partition = straggler_partition

    def children(self):
        return [self.input]

    def with_children(self, c):
        return ChaosExec(c[0], self.seed, self.probability, self.mode, self.stage_attempt,
                         self.straggler_delay_s, self.straggler_partition)

    def node_str(self) -> str:
        return f"ChaosExec: mode={self.mode} p={self.probability}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator:
        if self.mode == "straggler":
            self._maybe_straggle(partition, ctx)
            return self.input.execute(partition, ctx)
        h = hashlib.sha256(
            f"{self.seed}|{ctx.task_id}|{partition}|{self.stage_attempt}".encode()
        ).digest()
        roll = int.from_bytes(h[:8], "big") / 2**64
        if roll < self.probability:
            if self.mode == "transient":
                raise ExecutionError(f"chaos: injected transient failure (roll={roll:.4f})", retryable=True)
            if self.mode == "fatal":
                raise ExecutionError(f"chaos: injected fatal failure (roll={roll:.4f})", retryable=False)
            if self.mode == "panic":
                raise RuntimeError("chaos: injected panic")
            if self.mode == "delay":
                time.sleep(0.2)
            if self.mode == "overload":
                return self._overloaded_execute(partition, ctx)
        return self.input.execute(partition, ctx)

    def _overloaded_execute(self, partition: int, ctx: TaskContext) -> Iterator:
        """Synthetic memory pressure: reserve the session pool's whole
        capacity for this partition's duration (grow_wait with a zero
        deadline forces the reservation through, counting it in
        `overcommitted`) plus a queue delay. Deterministic fuel for
        overload tests: while the hit partition runs, the pool reads
        saturated, so the executor's admission gate rejects new tasks and
        the heartbeat pressure score goes to >= 1."""
        pool = getattr(ctx, "memory_pool", None)
        held = 0
        if pool is not None:
            # one byte PAST capacity: even an idle pool ends up overcommitted
            held = max(2, pool.capacity + 1)
            pool.grow_wait(held, timeout_s=0.0)
        try:
            time.sleep(min(self.straggler_delay_s, 0.5))
            yield from self.input.execute(partition, ctx)
        finally:
            if pool is not None:
                pool.shrink(held)

    def _maybe_straggle(self, partition: int, ctx: TaskContext) -> None:
        """Deterministic slow-partition injection: the roll is keyed on the
        PARTITION alone (task ids differ across attempts/schedulers, so
        mixing them in would make 'which partition straggles' a lottery),
        and only attempt 0 straggles — a speculative duplicate of the same
        partition must be able to win."""
        if getattr(ctx, "task_attempt", 0) != 0:
            return
        if self.straggler_partition >= 0:
            hit = partition == self.straggler_partition
        else:
            h = hashlib.sha256(f"{self.seed}|straggler|{partition}".encode()).digest()
            hit = int.from_bytes(h[:8], "big") / 2**64 < self.probability
        if not hit:
            return
        # sleep in small increments so a CancelTasks push (speculation's
        # loser-kill) or the task deadline preempts the straggler mid-nap
        deadline_at = float(getattr(ctx, "deadline_at", 0.0) or 0.0)
        cancel_check = getattr(ctx, "cancel_check", None)
        end = time.time() + self.straggler_delay_s
        while time.time() < end:
            if cancel_check is not None and cancel_check():
                raise Cancelled("chaos: straggler cancelled mid-delay")
            if deadline_at and time.time() > deadline_at:
                err = ExecutionError("chaos: straggler exceeded task deadline", retryable=True)
                err.timed_out = True
                raise err
            time.sleep(min(0.05, max(0.0, end - time.time())))


def _sync_hbm_chaos(enabled: bool, mode: str) -> None:
    """Arm or disarm the hbm_oom override in ops.tpu.hbm. Always syncs —
    a previous session's armed state must not leak into a chaos-off run.
    `ops.tpu.hbm` is import-light (no jax at module scope), so this does
    not drag a backend into CPU-only executors."""
    from ballista_tpu.ops.tpu import hbm

    if enabled and mode == "hbm_oom":
        hbm.arm_chaos(
            int(os.environ.get("BALLISTA_CHAOS_HBM_BUDGET", str(1 << 20))),
            int(os.environ.get("BALLISTA_CHAOS_HBM_OOM_N", "0")))
    else:
        hbm.disarm_chaos()


def maybe_inject_chaos(plan: ExecutionPlan, config: BallistaConfig, stage_attempt: int = 0) -> ExecutionPlan:
    enabled = bool(config.get(CHAOS_ENABLED))
    mode = str(config.get(CHAOS_MODE)) if enabled else ""
    _sync_hbm_chaos(enabled, mode)
    if not enabled or mode in ("hbm_oom", "skew", "daemon_crash", "daemon_hang",
                               "disk_full", "drain_kill"):
        # these modes never wrap the plan (see module docstring): the
        # faults live in the device upload path / the shuffle partitioner /
        # the device-daemon process / the storage and drain paths, not in
        # leaf execution
        return plan
    seed = int(config.get(CHAOS_SEED))
    prob = float(config.get(CHAOS_PROBABILITY))
    delay_s = float(config.get(CHAOS_STRAGGLER_DELAY_S))
    straggler_part = int(config.get(CHAOS_STRAGGLER_PARTITION))
    straggler_stage = int(config.get(CHAOS_STRAGGLER_STAGE))
    if mode == "straggler" and straggler_stage >= 0:
        # stage roots are ShuffleWriterExecs carrying their stage id; leave
        # other stages' plans untouched so the straggle fires exactly once
        if getattr(plan, "stage_id", -1) != straggler_stage:
            return plan

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if not kids:
            return ChaosExec(n, seed, prob, mode, stage_attempt, delay_s, straggler_part)
        return n.with_children([walk(c) for c in kids])

    return walk(plan)
