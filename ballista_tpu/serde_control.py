"""Control-plane message serde: dataclasses ↔ protobuf.

Covers TaskDefinition/TaskStatus/ExecutorMetadata/JobStatus — the messages
the SchedulerGrpc and ExecutorGrpc services exchange (reference:
serde/scheduler/{to,from}_proto.rs).
"""

from __future__ import annotations

import logging

from ballista_tpu.executor.executor import ExecutorMetadata, TaskResult
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.state.execution_graph import TaskDescription
from ballista_tpu.serde import (
    decode_location,
    decode_plan,
    decode_schema,
    encode_location,
    encode_plan,
    encode_schema,
)


def encode_executor_metadata(m: ExecutorMetadata) -> pb.ExecutorMetadataProto:
    out = pb.ExecutorMetadataProto(
        id=m.id, host=m.host, grpc_port=m.grpc_port, flight_port=m.flight_port,
        vcores=m.vcores, wire_version=m.wire_version,
    )
    if m.device_ordinal >= 0:  # explicit presence: ordinal 0 is a valid chip
        out.device_ordinal = m.device_ordinal
    return out


def decode_executor_metadata(p: pb.ExecutorMetadataProto) -> ExecutorMetadata:
    return ExecutorMetadata(
        id=p.id, host=p.host, grpc_port=p.grpc_port, flight_port=p.flight_port,
        vcores=p.vcores, wire_version=p.wire_version,
        device_ordinal=p.device_ordinal if p.HasField("device_ordinal") else -1,
    )


def _encoded_plan_bytes(t: TaskDescription, config=None) -> bytes:
    """Per-task plan restriction + stage-plan encode cache.

    The plan shipped to a task is RESTRICTED to the task's partition slice
    (scan file-groups and reader location lists outside the slice become
    empty; see scheduler/task_builder.py — the reference's
    state/task_builder.rs:18-64). Encodings are memoized ON the shared
    stage-plan object, keyed by the partition slice, so retries and
    multi-partition slices reuse bytes; the cache's lifetime is the plan's
    (replanned/retried stages build new plan objects and re-encode; no
    id() aliasing). Plans are never mutated after task hand-out begins
    (AQE rewrites happen at resolution, before the first task is popped)."""
    from ballista_tpu.scheduler.task_builder import restrict_plan_to_partitions

    restricted = restrict_plan_to_partitions(t.plan, t.partitions, config)
    if restricted is t.plan:
        hit = getattr(t.plan, "_encoded_task_plan", None)
        if hit is None:
            hit = encode_plan(t.plan).SerializeToString()
            t.plan._encoded_task_plan = hit
        return hit
    cache = getattr(t.plan, "_encoded_task_plan_slices", None)
    if cache is None:
        cache = {}
        t.plan._encoded_task_plan_slices = cache
    key = tuple(sorted(set(t.partitions)))
    hit = cache.get(key)
    if hit is None:
        hit = encode_plan(restricted).SerializeToString()
        cache[key] = hit
    return hit


def encode_task_definition(t: TaskDescription, config=None) -> pb.TaskDefinitionProto:
    out = pb.TaskDefinitionProto(
        task_id=t.task_id, job_id=t.job_id, stage_id=t.stage_id,
        stage_attempt=t.stage_attempt, session_id=t.session_id,
        deadline_seconds=t.deadline_seconds, task_attempt=t.task_attempt,
    )
    out.partitions.extend(t.partitions)
    out.plan.ParseFromString(_encoded_plan_bytes(t, config))
    return out


def decode_task_definition(p: pb.TaskDefinitionProto) -> TaskDescription:
    # the fast-lane flag has no proto field (no protoc here); the reserved
    # task-id band IS the wire encoding — graph tasks never reach it
    from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE

    return TaskDescription(
        job_id=p.job_id, stage_id=p.stage_id, stage_attempt=p.stage_attempt,
        task_id=p.task_id, partitions=list(p.partitions),
        plan=decode_plan(p.plan), session_id=p.session_id,
        deadline_seconds=p.deadline_seconds, task_attempt=p.task_attempt,
        fast_lane=p.task_id >= FAST_TASK_ID_BASE,
    )


def encode_task_status(r: TaskResult, executor_id: str) -> pb.TaskStatusProto:
    out = pb.TaskStatusProto(
        task_id=r.task_id, job_id=r.job_id, stage_id=r.stage_id,
        stage_attempt=r.stage_attempt, executor_id=executor_id,
        state=r.state, error=r.error, error_kind=r.error_kind, retryable=r.retryable,
        fetch_failed_executor_id=r.fetch_failed_executor_id,
        fetch_failed_stage_id=r.fetch_failed_stage_id,
        timed_out=r.timed_out,
    )
    out.partitions.extend(r.partitions)
    for l in r.locations:
        out.shuffle_partitions.append(
            pb.ShuffleWritePartitionProto(
                output_partition=l.output_partition, path=l.path,
                num_rows=l.stats.num_rows, num_bytes=l.stats.num_bytes, layout=l.layout,
                map_partition=l.map_partition,
            )
        )
    for m in r.metrics or []:
        mp = pb.OperatorMetricProto(
            name=str(m.get("name", "")), output_rows=int(m.get("output_rows", 0)),
            elapsed_ns=int(m.get("elapsed_ns", 0)), depth=int(m.get("depth", 0)),
        )
        for k, v in m.items():
            if k in ("name", "output_rows", "elapsed_ns", "depth"):
                continue
            if isinstance(v, (int, bool)):
                mp.extra[str(k)] = int(v)
            else:
                # extras are integer counters by contract (Metrics.extra:
                # dict[str, int]); anything else would vanish remotely, so
                # say so instead of a silent local-vs-distributed skew
                logging.getLogger(__name__).warning(
                    "dropping non-integer operator metric extra %s=%r (%s)",
                    k, v, m.get("name", ""))
        out.metrics.append(mp)
    if r.locations:
        out.map_partition = r.locations[0].map_partition
    return out


def decode_task_status(p: pb.TaskStatusProto, executor_meta: ExecutorMetadata | None) -> TaskResult:
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    locations = []
    if p.state == "success" and executor_meta is not None:
        for sp in p.shuffle_partitions:
            locations.append(
                PartitionLocation(
                    map_partition=sp.map_partition,
                    job_id=p.job_id, stage_id=p.stage_id,
                    output_partition=sp.output_partition,
                    executor_id=executor_meta.id, host=executor_meta.host,
                    flight_port=executor_meta.flight_port, path=sp.path,
                    layout=sp.layout or "hash",
                    stats=PartitionStats(num_rows=sp.num_rows, num_bytes=sp.num_bytes),
                )
            )
    return TaskResult(
        task_id=p.task_id, job_id=p.job_id, stage_id=p.stage_id,
        stage_attempt=p.stage_attempt, partitions=list(p.partitions),
        state=p.state, locations=locations, error=p.error,
        error_kind=p.error_kind, retryable=p.retryable,
        metrics=[
            {"name": m.name, "output_rows": m.output_rows, "elapsed_ns": m.elapsed_ns,
             "depth": m.depth, **dict(m.extra)}
            for m in p.metrics
        ],
        fetch_failed_executor_id=p.fetch_failed_executor_id,
        fetch_failed_stage_id=p.fetch_failed_stage_id,
        # the cause rides the kind tag ("FetchPartitionError:corruption") —
        # blame-aware recovery without a proto change
        fetch_failed_cause=(
            p.error_kind.split(":", 1)[1]
            if p.error_kind.startswith("FetchPartitionError:") else ""),
        timed_out=p.timed_out,
    )


def encode_job_status(status: dict) -> pb.JobStatusProto:
    out = pb.JobStatusProto(
        job_id=status["job_id"], job_name=status.get("job_name", ""),
        state=status["state"], error=status.get("error", ""),
        completed_stages=status.get("completed_stages", 0),
        total_stages=status.get("total_stages", 0),
    )
    if status.get("schema") is not None:
        out.schema.CopyFrom(encode_schema(status["schema"]))
    for l in status.get("partitions", []) or []:
        out.partitions.append(encode_location(l))
    return out


def decode_job_status(p: pb.JobStatusProto) -> dict:
    out = {
        "job_id": p.job_id, "job_name": p.job_name, "state": p.state,
        "error": p.error, "completed_stages": p.completed_stages,
        "total_stages": p.total_stages,
        "partitions": [decode_location(l) for l in p.partitions],
    }
    if p.HasField("schema"):
        out["schema"] = decode_schema(p.schema)
    return out
