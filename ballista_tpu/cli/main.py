"""Interactive SQL REPL (rebuild of ballista-cli).

Run against a standalone in-process cluster or a remote scheduler:

    python -m ballista_tpu.cli                      # standalone
    python -m ballista_tpu.cli --host HOST --port N # remote

Dot-commands (ballista-cli/src/command.rs):
  .help | .tables | .schema <table> | .timing on|off | .quit
  CREATE EXTERNAL TABLE t STORED AS PARQUET LOCATION 'path';
  EXPLAIN [ANALYZE] <query>;  SET key = value;
"""

from __future__ import annotations

import argparse
import sys
import time

from ballista_tpu.config import BallistaConfig
from ballista_tpu.version import BALLISTA_VERSION


def format_table(tbl, max_rows: int = 100) -> str:
    if tbl.num_rows == 0:
        return "(0 rows)"
    df = tbl.slice(0, max_rows).to_pandas()
    body = df.to_string(index=False)
    suffix = f"\n({tbl.num_rows} rows)" if tbl.num_rows > max_rows else f"\n({tbl.num_rows} rows)"
    return body + suffix


class Repl:
    def __init__(self, ctx, timing: bool = True):
        self.ctx = ctx
        self.timing = timing

    def run_command(self, line: str) -> bool:
        """Returns False to exit."""
        cmd = line.strip()
        if not cmd:
            return True
        if cmd in (".quit", ".exit", "\\q"):
            return False
        if cmd == ".help":
            print(__doc__)
            return True
        if cmd == ".tables":
            for t in self.ctx.catalog.names():
                print(t)
            return True
        if cmd.startswith(".schema"):
            name = cmd.split(None, 1)[1] if " " in cmd else ""
            p = self.ctx.catalog.get(name)
            if p is None:
                print(f"table not found: {name}")
            else:
                for f in p.arrow_schema():
                    print(f"  {f.name}: {f.type}")
            return True
        if cmd.startswith(".timing"):
            self.timing = "on" in cmd
            print(f"timing {'on' if self.timing else 'off'}")
            return True
        try:
            t0 = time.time()
            out = self.ctx.sql(cmd).collect()
            elapsed = time.time() - t0
            print(format_table(out))
            if self.timing:
                print(f"Elapsed {elapsed:.3f} seconds.")
        except Exception as e:  # noqa: BLE001
            print(f"Error: {e}", file=sys.stderr)
        return True

    def loop(self) -> None:
        print(f"ballista_tpu CLI v{BALLISTA_VERSION} — .help for help, .quit to exit")
        buf: list[str] = []
        while True:
            try:
                prompt = "ballista> " if not buf else "      ..> "
                line = input(prompt)
            except (EOFError, KeyboardInterrupt):
                print()
                return
            if line.strip().startswith("."):
                if not self.run_command(line):
                    return
                continue
            buf.append(line)
            if line.rstrip().endswith(";"):
                stmt = "\n".join(buf)
                buf = []
                if not self.run_command(stmt):
                    return


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="ballista_tpu SQL CLI")
    ap.add_argument("--host", default=None, help="scheduler host (remote mode)")
    ap.add_argument("--port", type=int, default=50050)
    ap.add_argument("--engine", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("-c", "--command", default=None, help="run one statement and exit")
    ap.add_argument("-f", "--file", default=None, help="run statements from a file")
    args = ap.parse_args(argv)

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE

    cfg = BallistaConfig({EXECUTOR_ENGINE: args.engine})
    if args.host:
        ctx = SessionContext.remote(f"{args.host}:{args.port}", cfg)
    else:
        ctx = SessionContext.standalone(cfg, num_executors=1, vcores=args.concurrency)

    repl = Repl(ctx)
    if args.command:
        repl.run_command(args.command)
        return
    if args.file:
        with open(args.file) as f:
            for stmt in f.read().split(";"):
                if stmt.strip():
                    repl.run_command(stmt + ";")
        return
    repl.loop()


if __name__ == "__main__":
    main()
