from ballista_tpu.cli.main import main

main()
