"""Terminal cluster monitor (TUI).

Rebuild of ballista-cli's ratatui monitor (ballista-cli/src/tui/, ~10 kLoC
hexagonal Rust) as a compact curses app over the scheduler REST API: live
jobs / executors / per-job stage tables with metric percentiles, job
cancellation, and drill-down. The domain/render split keeps everything
below `run_tui` testable without a terminal.

  python -m ballista_tpu.cli.tui --host 127.0.0.1 --rest-port 50080
  keys: Tab switch pane · j/k move · Enter stages · c cancel · q quit
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.request


class RestClient:
    def __init__(self, base: str):
        self.base = base.rstrip("/")

    def _get(self, path: str):
        with urllib.request.urlopen(f"{self.base}{path}", timeout=5) as r:
            return json.load(r)

    def state(self) -> dict:
        return self._get("/api/state")

    def jobs(self) -> list[dict]:
        return self._get("/api/jobs")

    def executors(self) -> list[dict]:
        return self._get("/api/executors")

    def stages(self, job_id: str) -> list[dict]:
        return self._get(f"/api/job/{job_id}/stages")

    def cancel(self, job_id: str) -> None:
        req = urllib.request.Request(f"{self.base}/api/job/{job_id}/cancel", method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            r.read()


# ---------------------------------------------------------------- rendering


def fmt_duration(start_s, end_s) -> str:
    if not start_s:
        return "-"
    end = end_s or time.time()
    s = max(0.0, end - start_s)
    return f"{s:.1f}s" if s < 120 else f"{s / 60:.1f}m"


def render_header(state: dict) -> str:
    return (
        f" ballista_tpu {state.get('version', '?')} · scheduler {state.get('scheduler_id', '?')}"
        f" · executors {state.get('executors', 0)} · jobs {state.get('jobs', 0)}"
    )


def render_jobs(jobs: list[dict], selected: int, width: int = 120) -> list[str]:
    lines = [f" {'JOB':<12} {'NAME':<16} {'STATE':<11} {'STAGES':<8} {'ELAPSED':<8}"]
    for i, j in enumerate(jobs):
        stages = f"{j.get('completed_stages', 0)}/{j.get('total_stages', 0)}"
        row = (
            f" {j.get('job_id', '')[:12]:<12} {j.get('job_name', '')[:16]:<16} "
            f"{j.get('state', ''):<11} {stages:<8} "
            f"{fmt_duration(j.get('queued_at'), j.get('ended_at')):<8}"
        )
        lines.append((">" if i == selected else " ") + row[1:width])
    return lines


def render_executors(execs: list[dict], selected: int, width: int = 120) -> list[str]:
    lines = [f" {'EXECUTOR':<16} {'HOST':<18} {'GRPC':<6} {'FLIGHT':<7} {'SLOTS':<9} {'SEEN':<6}"]
    now = time.time()
    for i, e in enumerate(execs):
        slots = f"{e.get('free_slots', 0)}/{e.get('total_slots', 0)}"
        seen = f"{now - e.get('last_seen', now):.0f}s"
        row = (
            f" {e.get('id', '')[:16]:<16} {e.get('host', '')[:18]:<18} "
            f"{e.get('grpc_port', 0):<6} {e.get('flight_port', 0):<7} {slots:<9} {seen:<6}"
        )
        lines.append((">" if i == selected else " ") + row[1:width])
    return lines


def render_stages(stages: list[dict], width: int = 120) -> list[str]:
    lines = [f" {'STAGE':<6} {'STATE':<11} {'TASKS':<16} {'TOP OPERATORS (p50 ms)':<60}"]
    for s in stages:
        tasks = f"{s.get('completed', 0)}✓ {s.get('running', 0)}▶ {s.get('pending', 0)}·"
        pcts = s.get("metric_percentiles", [])
        tops = sorted(pcts, key=lambda p: -p.get("elapsed_ms_p50", 0))[:2]
        ops = "; ".join(
            f"{p['name'].split(':')[0]} {p.get('elapsed_ms_p50', 0):.1f}" for p in tops
        )
        lines.append(
            f" {s.get('stage_id', 0):<6} {s.get('state', ''):<11} {tasks:<16} {ops[:60]:<60}"[:width]
        )
    return lines


# ------------------------------------------------------------------ the app


def run_tui(base_url: str, refresh_s: float = 1.0) -> None:  # pragma: no cover
    import curses

    client = RestClient(base_url)

    def app(scr):
        curses.curs_set(0)
        scr.timeout(int(refresh_s * 1000))
        pane = 0  # 0 jobs, 1 executors
        sel = 0
        drill: str | None = None
        msg = ""
        while True:
            try:
                state = client.state()
                jobs = client.jobs()
                execs = client.executors()
            except Exception as e:  # noqa: BLE001
                scr.erase()
                scr.addstr(0, 0, f" cannot reach scheduler: {e} (q quits)")
                scr.refresh()
                if scr.getch() in (ord("q"), 27):
                    return
                continue
            h, w = scr.getmaxyx()
            scr.erase()
            if h < 4 or w < 20:
                try:
                    scr.addstr(0, 0, "window too small"[: max(0, w - 1)])
                except curses.error:
                    pass
                scr.refresh()
                if scr.getch() == ord("q"):
                    return
                continue
            scr.addstr(0, 0, render_header(state)[: w - 1], curses.A_BOLD)
            if drill is not None:
                try:
                    body = render_stages(client.stages(drill), w - 1)
                except Exception:  # noqa: BLE001
                    body = [" job gone"]
                scr.addstr(1, 0, f" stages of {drill} (Esc back)"[: w - 1], curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - 3]):
                    scr.addstr(2 + i, 0, line[: w - 1])
            else:
                rows = jobs if pane == 0 else execs
                sel = max(0, min(sel, len(rows) - 1))
                body = render_jobs(jobs, sel, w - 1) if pane == 0 else render_executors(execs, sel, w - 1)
                title = " [Jobs] Executors " if pane == 0 else " Jobs [Executors] "
                scr.addstr(1, 0, title[: w - 1], curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - 3]):
                    scr.addstr(2 + i, 0, line[: w - 1])
            if msg:
                scr.addstr(h - 1, 0, msg[: w - 1], curses.A_REVERSE)
                msg = ""
            scr.refresh()
            ch = scr.getch()
            if ch in (ord("q"),):
                return
            if ch == 27:  # Esc
                drill = None
            elif drill is not None:
                # drilled view: only cancel (of the DRILLED job) is live —
                # list navigation would silently move a hidden selection
                if ch == ord("c"):
                    try:
                        client.cancel(drill)
                        msg = f" cancel requested for {drill}"
                    except Exception as e:  # noqa: BLE001
                        msg = f" cancel failed: {e}"
            elif ch == ord("\t"):
                pane, sel = 1 - pane, 0
            elif ch in (ord("j"), curses.KEY_DOWN):
                sel += 1
            elif ch in (ord("k"), curses.KEY_UP):
                sel = max(0, sel - 1)
            elif ch in (curses.KEY_ENTER, 10, 13) and pane == 0 and jobs:
                drill = jobs[min(sel, len(jobs) - 1)]["job_id"]
            elif ch == ord("c") and pane == 0 and jobs:
                jid = jobs[min(sel, len(jobs) - 1)]["job_id"]
                try:
                    client.cancel(jid)
                    msg = f" cancel requested for {jid}"
                except Exception as e:  # noqa: BLE001
                    msg = f" cancel failed: {e}"

    curses.wrapper(app)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="ballista_tpu cluster monitor (TUI)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--rest-port", type=int, default=50080)
    ap.add_argument("--refresh", type=float, default=1.0)
    args = ap.parse_args(argv)
    run_tui(f"http://{args.host}:{args.rest_port}", args.refresh)


if __name__ == "__main__":
    main()
