"""Terminal cluster monitor (TUI).

Rebuild of ballista-cli's ratatui monitor (ballista-cli/src/tui/, ~10 kLoC
hexagonal Rust) as a curses app over the scheduler REST API: live jobs /
executors / scheduler-config panes with cluster-history sparklines, job
filtering and sorting, job→stage→operator drill-down with metric
percentiles, job cancellation, and a help overlay. The domain/render split
keeps everything below `run_tui` testable without a terminal.

  python -m ballista_tpu.cli.tui --host 127.0.0.1 --rest-port 50080
  keys: Tab panes · j/k move · Enter drill · / filter · s sort
        c cancel · ? help · Esc back · q quit
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.request


class RestClient:
    def __init__(self, base: str):
        self.base = base.rstrip("/")

    def _get(self, path: str):
        with urllib.request.urlopen(f"{self.base}{path}", timeout=5) as r:
            return json.load(r)

    def state(self) -> dict:
        return self._get("/api/state")

    def jobs(self) -> list[dict]:
        return self._get("/api/jobs")

    def executors(self) -> list[dict]:
        return self._get("/api/executors")

    def stages(self, job_id: str) -> list[dict]:
        return self._get(f"/api/job/{job_id}/stages")

    def config(self) -> dict:
        return self._get("/api/config")

    def cancel(self, job_id: str) -> None:
        req = urllib.request.Request(f"{self.base}/api/job/{job_id}/cancel", method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            r.read()


# ------------------------------------------------------- history + sparkline

SPARK_CHARS = " ▁▂▃▄▅▆▇█"


def sparkline(vals: list[float], width: int = 30) -> str:
    """Render the last `width` samples as a unicode sparkline (the ratatui
    Sparkline widget analog). Empty/flat series render as a low bar."""
    vals = [max(0.0, float(v)) for v in vals][-width:]
    if not vals:
        return ""
    hi = max(vals)
    if hi <= 0:
        return SPARK_CHARS[1] * len(vals)
    out = []
    for v in vals:
        i = 1 + int(round(v / hi * (len(SPARK_CHARS) - 2)))
        out.append(SPARK_CHARS[min(i, len(SPARK_CHARS) - 1)])
    return "".join(out)


class History:
    """Fixed-window ring of cluster samples feeding the header sparklines:
    running jobs, busy slots, and completions/second (per-tick deltas are
    divided by `tick_s`, the sampling interval)."""

    def __init__(self, window: int = 120, tick_s: float = 1.0):
        self.window = window
        self.tick_s = max(tick_s, 1e-9)
        self.running_jobs: list[float] = []
        self.busy_slots: list[float] = []
        self.completed_rate: list[float] = []
        self._last_completed: int | None = None

    def sample(self, jobs: list[dict], execs: list[dict]) -> None:
        running = sum(1 for j in jobs if j.get("state") in ("RUNNING", "QUEUED"))
        busy = sum(e.get("total_slots", 0) - e.get("free_slots", 0) for e in execs)
        done = sum(1 for j in jobs if j.get("state") in ("SUCCESSFUL", "FAILED", "CANCELLED"))
        rate = (0.0 if self._last_completed is None
                else max(0, done - self._last_completed) / self.tick_s)
        self._last_completed = done
        for series, v in ((self.running_jobs, running), (self.busy_slots, busy),
                          (self.completed_rate, rate)):
            series.append(float(v))
            del series[: max(0, len(series) - self.window)]


# --------------------------------------------------------- filtering/sorting

JOB_SORT_KEYS = ("queued", "elapsed", "state", "name")


def filter_jobs(jobs: list[dict], query: str) -> list[dict]:
    """Case-insensitive substring match over id, name, and state."""
    if not query:
        return jobs
    q = query.lower()
    return [j for j in jobs
            if q in str(j.get("job_id", "")).lower()
            or q in str(j.get("job_name", "")).lower()
            or q in str(j.get("state", "")).lower()]


def sort_jobs(jobs: list[dict], key: str) -> list[dict]:
    now = time.time()
    if key == "elapsed":
        return sorted(jobs, key=lambda j: -(
            (j.get("ended_at") or now) - (j.get("queued_at") or now)))
    if key == "state":
        return sorted(jobs, key=lambda j: str(j.get("state", "")))
    if key == "name":
        return sorted(jobs, key=lambda j: str(j.get("job_name", "")))
    return sorted(jobs, key=lambda j: -(j.get("queued_at") or 0))  # newest first


# ---------------------------------------------------------------- rendering


def fmt_duration(start_s, end_s) -> str:
    if not start_s:
        return "-"
    end = end_s or time.time()
    s = max(0.0, end - start_s)
    return f"{s:.1f}s" if s < 120 else f"{s / 60:.1f}m"


def render_header(state: dict, hist: History | None = None, width: int = 120) -> list[str]:
    lines = [
        f" ballista_tpu {state.get('version', '?')} · scheduler {state.get('scheduler_id', '?')}"
        f" · executors {state.get('executors', 0)} · jobs {state.get('jobs', 0)}"
    ]
    if hist is not None and hist.running_jobs:
        w = max(8, (width - 30) // 3)
        lines.append(
            f" act {sparkline(hist.running_jobs, w)} "
            f"slots {sparkline(hist.busy_slots, w)} "
            f"done/s {sparkline(hist.completed_rate, w)}"[:width])
    return lines


def render_jobs(jobs: list[dict], selected: int, width: int = 120,
                query: str = "", sort_key: str = "queued") -> list[str]:
    tag = f" [filter:{query}]" if query else ""
    lines = [f" {'JOB':<12} {'NAME':<16} {'STATE':<11} {'STAGES':<8} "
             f"{'ELAPSED':<8} sort:{sort_key}{tag}"]
    for i, j in enumerate(jobs):
        stages = f"{j.get('completed_stages', 0)}/{j.get('total_stages', 0)}"
        row = (
            f" {j.get('job_id', '')[:12]:<12} {j.get('job_name', '')[:16]:<16} "
            f"{j.get('state', ''):<11} {stages:<8} "
            f"{fmt_duration(j.get('queued_at'), j.get('ended_at')):<8}"
        )
        lines.append((">" if i == selected else " ") + row[1:width])
    return lines


def render_executors(execs: list[dict], selected: int, width: int = 120) -> list[str]:
    lines = [f" {'EXECUTOR':<16} {'HOST':<18} {'GRPC':<6} {'FLIGHT':<7} "
             f"{'SLOTS':<9} {'DEV':<4} {'SEEN':<6}"]
    now = time.time()
    for i, e in enumerate(execs):
        slots = f"{e.get('free_slots', 0)}/{e.get('total_slots', 0)}"
        seen = f"{now - e.get('last_seen', now):.0f}s"
        dev = e.get("device_ordinal")
        row = (
            f" {e.get('id', '')[:16]:<16} {e.get('host', '')[:18]:<18} "
            f"{e.get('grpc_port', 0):<6} {e.get('flight_port', 0):<7} {slots:<9} "
            f"{('-' if dev is None else dev):<4} {seen:<6}"
        )
        lines.append((">" if i == selected else " ") + row[1:width])
    return lines


def render_stages(stages: list[dict], selected: int = -1, width: int = 120) -> list[str]:
    lines = [f" {'STAGE':<6} {'STATE':<11} {'TASKS':<16} {'TOP OPERATORS (p50 ms)':<60}"]
    for i, s in enumerate(stages):
        tasks = f"{s.get('completed', 0)}✓ {s.get('running', 0)}▶ {s.get('pending', 0)}·"
        pcts = s.get("metric_percentiles", [])
        tops = sorted(pcts, key=lambda p: -p.get("elapsed_ms_p50", 0))[:2]
        ops = "; ".join(
            f"{p['name'].split(':')[0]} {p.get('elapsed_ms_p50', 0):.1f}" for p in tops
        )
        row = f" {s.get('stage_id', 0):<6} {s.get('state', ''):<11} {tasks:<16} {ops[:60]:<60}"
        lines.append(((">" if i == selected else " ") + row[1:])[:width])
    return lines


def render_operators(stage: dict, width: int = 120) -> list[str]:
    """Full per-operator metric table for one stage: every operator from the
    percentile summary, indented by plan depth, with elapsed p50/p90/p99 and
    output rows (the ratatui query-detail metric table analog)."""
    lines = [f" stage {stage.get('stage_id', '?')} operators "
             f"({stage.get('completed', 0)} tasks done)",
             f" {'OPERATOR':<38} {'TASKS':<6} {'P50ms':<9} {'P90ms':<9} "
             f"{'P99ms':<9} {'ROWS':<12}"]
    for p in stage.get("metric_percentiles", []):
        name = ("  " * int(p.get("depth", 0)) + p.get("name", "").split(":")[0])[:38]
        lines.append(
            f" {name:<38} {p.get('tasks', 0):<6} {p.get('elapsed_ms_p50', 0):<9.1f} "
            f"{p.get('elapsed_ms_p90', 0):<9.1f} {p.get('elapsed_ms_p99', 0):<9.1f} "
            f"{p.get('output_rows_total', 0):<12}"[:width])
    if len(lines) == 2:
        lines.append(" (no task metrics yet)")
    return lines


def render_config(cfg: dict, width: int = 120, offset: int = 0) -> list[str]:
    lines = [
        f" scheduler {cfg.get('scheduler_id', '?')} · v{cfg.get('version', '?')} · "
        f"task-distribution={cfg.get('task_distribution', '?')} · "
        f"executor-timeout={cfg.get('executor_timeout_s', '?')}s · "
        f"job-state={cfg.get('job_state_backend', '?')}"[:width],
        f" {'SESSION CONFIG KEY':<44} {'TYPE':<6} {'DEFAULT':<14} DESCRIPTION",
    ]
    entries = cfg.get("session_config_entries", [])
    offset = max(0, min(offset, len(entries) - 1))  # clamp: never scroll blank
    for e in entries[offset:]:
        d = str(e.get("default"))
        lines.append(
            f" {e.get('name', '')[:44]:<44} {e.get('type', ''):<6} {d[:14]:<14} "
            f"{e.get('description', '')}"[:width])
    return lines


def render_help(width: int = 120) -> list[str]:
    return [line[:width] for line in (
        " ballista_tpu monitor — keys",
        "",
        "   Tab        cycle panes (Jobs / Executors / Config)",
        "   j / k, ↓/↑ move selection (scrolls Config)",
        "   Enter      Jobs: drill into stages; stages: operator metrics",
        "   Esc        back out one level",
        "   /          filter jobs (type, Enter applies, Esc clears)",
        "   s          cycle job sort: queued → elapsed → state → name",
        "   c          cancel selected (or drilled) job",
        "   ?          toggle this help",
        "   q          quit",
    )]


# ------------------------------------------------------------------ the app


def run_tui(base_url: str, refresh_s: float = 1.0) -> None:  # pragma: no cover
    import curses

    client = RestClient(base_url)
    hist = History(tick_s=refresh_s)

    def app(scr):
        curses.curs_set(0)
        scr.timeout(int(refresh_s * 1000))
        pane = 0  # 0 jobs, 1 executors, 2 config
        sel = 0
        drill: str | None = None       # job id whose stages are shown
        stages_shown: list[dict] = []  # last rendered stage list
        op_stage: int | None = None    # stage id whose operators are shown
        stage_sel = 0
        cfg_off = 0
        cfg_cache: dict | None = None
        query, typing = "", False
        sort_i = 0
        show_help = False
        msg = ""
        while True:
            try:
                state = client.state()
                jobs_raw = client.jobs()
                execs = client.executors()
            except Exception as e:  # noqa: BLE001
                scr.erase()
                _, ew = scr.getmaxyx()
                try:
                    scr.addstr(0, 0, f" cannot reach scheduler: {e} (q quits)"[: ew - 1])
                except curses.error:
                    pass
                scr.refresh()
                if scr.getch() in (ord("q"), 27):
                    return
                continue
            hist.sample(jobs_raw, execs)
            jobs = sort_jobs(filter_jobs(jobs_raw, query), JOB_SORT_KEYS[sort_i])
            h, w = scr.getmaxyx()
            scr.erase()
            if h < 5 or w < 20:
                try:
                    scr.addstr(0, 0, "window too small"[: max(0, w - 1)])
                except curses.error:
                    pass
                scr.refresh()
                if scr.getch() == ord("q"):
                    return
                continue
            head = render_header(state, hist, w - 1)
            for i, line in enumerate(head):
                scr.addstr(i, 0, line[: w - 1], curses.A_BOLD if i == 0 else 0)
            top = len(head)
            if show_help:
                body = render_help(w - 1)
                scr.addstr(top, 0, " help (? closes)"[: w - 1], curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - top - 2]):
                    scr.addstr(top + 1 + i, 0, line[: w - 1])
            elif drill is not None:
                try:
                    stages = client.stages(drill)
                except Exception:  # noqa: BLE001
                    stages, msg = [], " job gone"
                stages_shown = stages  # Enter drills what was RENDERED
                if op_stage is not None:
                    st = next((s for s in stages if s.get("stage_id") == op_stage), None)
                    body = render_operators(st, w - 1) if st else [" stage gone"]
                    scr.addstr(top, 0, f" {drill} / stage {op_stage} (Esc back)"[: w - 1],
                               curses.A_UNDERLINE)
                else:
                    stage_sel = max(0, min(stage_sel, len(stages) - 1))
                    body = render_stages(stages, stage_sel, w - 1)
                    scr.addstr(top, 0,
                               f" stages of {drill} (Enter operators · Esc back)"[: w - 1],
                               curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - top - 2]):
                    scr.addstr(top + 1 + i, 0, line[: w - 1])
            elif pane == 2:
                try:
                    if cfg_cache is None:  # static payload: fetch once per entry
                        cfg_cache = client.config()
                    body = render_config(cfg_cache, w - 1, cfg_off)
                except Exception as e:  # noqa: BLE001
                    body = [f" config unavailable: {e}"]
                scr.addstr(top, 0, " Jobs  Executors [Config] "[: w - 1], curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - top - 2]):
                    scr.addstr(top + 1 + i, 0, line[: w - 1])
            else:
                rows = jobs if pane == 0 else execs
                sel = max(0, min(sel, len(rows) - 1))
                body = (render_jobs(jobs, sel, w - 1, query, JOB_SORT_KEYS[sort_i])
                        if pane == 0 else render_executors(execs, sel, w - 1))
                title = " [Jobs] Executors  Config " if pane == 0 else " Jobs [Executors] Config "
                scr.addstr(top, 0, title[: w - 1], curses.A_UNDERLINE)
                for i, line in enumerate(body[: h - top - 2]):
                    scr.addstr(top + 1 + i, 0, line[: w - 1])
            status = f" /{query}" if typing else msg
            if status:
                scr.addstr(h - 1, 0, status[: w - 1], curses.A_REVERSE)
                msg = ""
            scr.refresh()
            ch = scr.getch()
            if typing:
                if ch in (curses.KEY_ENTER, 10, 13):
                    typing = False
                elif ch == 27:
                    typing, query = False, ""
                elif ch in (curses.KEY_BACKSPACE, 127, 8):
                    query = query[:-1]
                elif 32 <= ch < 127:
                    query += chr(ch)
                continue
            if ch == ord("q"):
                return
            if ch == ord("?"):
                show_help = not show_help
            elif show_help:
                show_help = ch != 27  # Esc closes; other keys are inert
            elif ch == 27:  # Esc backs out one level
                if op_stage is not None:
                    op_stage = None
                elif drill is not None:
                    drill = None
                else:
                    query = ""
            elif drill is not None:
                if ch == ord("c"):
                    try:
                        client.cancel(drill)
                        msg = f" cancel requested for {drill}"
                    except Exception as e:  # noqa: BLE001
                        msg = f" cancel failed: {e}"
                elif op_stage is None:
                    if ch in (ord("j"), curses.KEY_DOWN):
                        stage_sel += 1
                    elif ch in (ord("k"), curses.KEY_UP):
                        stage_sel = max(0, stage_sel - 1)
                    elif ch in (curses.KEY_ENTER, 10, 13) and stages_shown:
                        op_stage = stages_shown[
                            min(stage_sel, len(stages_shown) - 1)]["stage_id"]
            elif ch == ord("\t"):
                pane, sel, cfg_off, cfg_cache = (pane + 1) % 3, 0, 0, None
            elif ch == ord("/") and pane == 0:
                typing = True
            elif ch == ord("s") and pane == 0:
                sort_i = (sort_i + 1) % len(JOB_SORT_KEYS)
            elif ch in (ord("j"), curses.KEY_DOWN):
                if pane == 2:
                    cfg_off += 1
                else:
                    sel += 1
            elif ch in (ord("k"), curses.KEY_UP):
                if pane == 2:
                    cfg_off = max(0, cfg_off - 1)
                else:
                    sel = max(0, sel - 1)
            elif ch in (curses.KEY_ENTER, 10, 13) and pane == 0 and jobs:
                drill, stage_sel = jobs[min(sel, len(jobs) - 1)]["job_id"], 0
            elif ch == ord("c") and pane == 0 and jobs:
                jid = jobs[min(sel, len(jobs) - 1)]["job_id"]
                try:
                    client.cancel(jid)
                    msg = f" cancel requested for {jid}"
                except Exception as e:  # noqa: BLE001
                    msg = f" cancel failed: {e}"

    curses.wrapper(app)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="ballista_tpu cluster monitor (TUI)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--rest-port", type=int, default=50080)
    ap.add_argument("--refresh", type=float, default=1.0)
    args = ap.parse_args(argv)
    run_tui(f"http://{args.host}:{args.rest_port}", args.refresh)


if __name__ == "__main__":
    main()
