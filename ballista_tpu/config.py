"""Typed session configuration.

Rebuild of the reference's `BallistaConfig` (ballista/core/src/config.rs):
a registry of `ConfigEntry`s — name, description, type, default — with
validation at parse time, round-tripped over the wire as key/value pairs so
every job carries its full session config to the scheduler and executors
(reference: SessionConfigHelperExt::to_key_value_pairs,
ballista/core/src/extension.rs:293).

TPU-native additions live under `ballista.tpu.*` (engine selection, shape
bucketing, device-memory budget) — these are the knobs the reference never
needed because CPU engines don't recompile per shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ballista_tpu.errors import ConfigurationError

# -- keys (reference: core/src/config.rs:32-160) ----------------------------

JOB_NAME = "ballista.job.name"
DEFAULT_SHUFFLE_PARTITIONS = "ballista.shuffle.partitions"
SHUFFLE_COMPRESSION_CODEC = "ballista.shuffle.compression.codec"
SHUFFLE_READER_MAX_REQUESTS = "ballista.shuffle.reader.max.requests"
SHUFFLE_READER_MAX_PER_ADDR = "ballista.shuffle.reader.max.requests.per.address"
SHUFFLE_READER_MAX_BYTES = "ballista.shuffle.reader.max.inflight.bytes"
SHUFFLE_READER_FORCE_REMOTE = "ballista.shuffle.reader.force_remote_read"
SHUFFLE_BLOCK_TRANSPORT = "ballista.shuffle.block.transport"
SHUFFLE_FETCH_COALESCE = "ballista.shuffle.fetch.coalesce"
SHUFFLE_MMAP = "ballista.shuffle.mmap.enabled"
SHUFFLE_CHECKSUM_ENABLED = "ballista.shuffle.checksum.enabled"
SORT_SHUFFLE_ENABLED = "ballista.shuffle.sort.enabled"
SORT_SHUFFLE_MEMORY_LIMIT = "ballista.shuffle.sort.memory.limit"
SORT_SHUFFLE_POOL_WAIT_S = "ballista.shuffle.sort.memory.wait.seconds"
BROADCAST_JOIN_THRESHOLD = "ballista.optimizer.broadcast.join.threshold.bytes"
BROADCAST_JOIN_ROWS_THRESHOLD = "ballista.optimizer.broadcast.join.threshold.rows"
BROADCAST_SEMI_KEYS_THRESHOLD = "ballista.optimizer.broadcast.semi.keys.threshold.rows"
MAX_PARTITIONS_PER_TASK = "ballista.scheduler.max_partitions_per_task"
JOB_RESUBMIT_INTERVAL_MS = "ballista.scheduler.job.resubmit.interval.ms"
# scheduler scale-out: sharded event loops + direct-dispatch leases
SCHEDULER_SHARDS = "ballista.scheduler.shards"
SCHEDULER_LEASE_ENABLED = "ballista.scheduler.lease.enabled"
SCHEDULER_LEASE_TTL_S = "ballista.scheduler.lease.ttl.seconds"
SCHEDULER_LEASE_SLOTS = "ballista.scheduler.lease.slots"
SCHEDULER_LEASE_BAND_SIZE = "ballista.scheduler.lease.band.size"
PLANNER_ADAPTIVE_ENABLED = "ballista.planner.adaptive.enabled"
AQE_TARGET_PARTITION_BYTES = "ballista.planner.adaptive.coalesce.target.bytes"
AQE_MIN_PARTITION_BYTES = "ballista.planner.adaptive.coalesce.min.bytes"
AQE_COALESCE_MERGED_FACTOR = "ballista.planner.adaptive.coalesce.merged.factor"
AQE_EMPTY_PROPAGATION = "ballista.planner.adaptive.empty.propagation"
AQE_DYNAMIC_JOIN_SELECTION = "ballista.planner.adaptive.join.selection"
AQE_ALTER_FANOUT = "ballista.planner.adaptive.alter.fanout"
AQE_JOIN_HEDGE_FACTOR = "ballista.planner.adaptive.join.hedge.factor"
# AQE skew defense: hot reduce partitions split into slice tasks
AQE_SKEW_ENABLED = "ballista.aqe.skew.enabled"
AQE_SKEW_FACTOR = "ballista.aqe.skew.factor"
AQE_SKEW_MIN_BYTES = "ballista.aqe.skew.min.bytes"
AQE_SKEW_MAX_SLICES = "ballista.aqe.skew.max.slices"
GRPC_CLIENT_MAX_MESSAGE_SIZE = "ballista.grpc.client.max.message.size.bytes"
GRPC_SERVER_MAX_MESSAGE_SIZE = "ballista.grpc.server.max.message.size.bytes"
FLIGHT_PROXY = "ballista.client.flight.proxy"
CLIENT_JOB_TIMEOUT_S = "ballista.client.job.timeout.seconds"
PUSH_STATUS = "ballista.client.push.status"
GRPC_TLS_CA = "ballista.grpc.tls.ca.path"
GRPC_TLS_CERT = "ballista.grpc.tls.cert.path"
GRPC_TLS_KEY = "ballista.grpc.tls.key.path"
IO_RETRIES = "ballista.io.retries.times"
IO_RETRY_WAIT_MS = "ballista.io.retry.wait.time.ms"
# overload protection: scheduler admission control + load shedding
ADMISSION_ENABLED = "ballista.admission.enabled"
ADMISSION_MAX_PENDING_JOBS = "ballista.admission.max.pending.jobs"
ADMISSION_MAX_INFLIGHT_PER_SESSION = "ballista.admission.max.inflight.per.session"
ADMISSION_SHED_DEPTH = "ballista.admission.shed.queue.depth"
ADMISSION_DRAIN_DEPTH = "ballista.admission.drain.queue.depth"
ADMISSION_SHED_LOOP_LAG_S = "ballista.admission.shed.loop.lag.seconds"
ADMISSION_SHED_MEMORY_PRESSURE = "ballista.admission.shed.memory.pressure"
ADMISSION_MIN_RETRY_AFTER_MS = "ballista.admission.min.retry.after.ms"
ADMISSION_INTERACTIVE_MAX_PENDING = "ballista.admission.interactive.max.pending.jobs"
# high-QPS serving tier: plan cache / prepared statements / result cache /
# short-query fast lane
SERVING_PLAN_CACHE = "ballista.serving.plan.cache.enabled"
SERVING_PLAN_CACHE_ENTRIES = "ballista.serving.plan.cache.max.entries"
SERVING_RESULT_CACHE = "ballista.serving.result.cache.enabled"
SERVING_RESULT_CACHE_ENTRIES = "ballista.serving.result.cache.max.entries"
SERVING_RESULT_CACHE_BYTES = "ballista.serving.result.cache.max.bytes"
SERVING_RESULT_MAX_BYTES = "ballista.serving.result.cache.max.result.bytes"
SERVING_FAST_LANE = "ballista.serving.fast.lane.enabled"
SERVING_FAST_LANE_TIMEOUT_S = "ballista.serving.fast.lane.timeout.seconds"
# streaming ingestion + incremental maintenance (docs/streaming.md)
SERVING_INCREMENTAL = "ballista.serving.incremental.enabled"
SERVING_INCREMENTAL_STATE_ENTRIES = "ballista.serving.incremental.state.max.entries"
SERVING_INCREMENTAL_STATE_BYTES = "ballista.serving.incremental.state.max.bytes"
SERVING_SUBSCRIPTION_QUEUE = "ballista.serving.incremental.subscription.queue.depth"
INGEST_DELTA_RETAIN_BYTES = "ballista.ingest.delta.retained.max.bytes"
INGEST_DELTA_RETAIN_VERSIONS = "ballista.ingest.delta.retained.max.versions"
INGEST_COMPACTION_DIR = "ballista.ingest.compaction.dir"
# overload protection: Flight data plane
FLIGHT_MAX_STREAMS = "ballista.flight.max.streams"
FLIGHT_ACCEPT_QUEUE = "ballista.flight.accept.queue.depth"
FLIGHT_STALL_TIMEOUT_S = "ballista.flight.stream.stall.timeout.seconds"
FLIGHT_BREAKER_THRESHOLD = "ballista.flight.breaker.failure.threshold"
FLIGHT_BREAKER_COOLDOWN_S = "ballista.flight.breaker.cooldown.seconds"
# overload protection: client backoff
CLIENT_SUBMIT_RETRIES = "ballista.client.submit.max.retries"
CLIENT_BACKOFF_BASE_MS = "ballista.client.backoff.base.ms"
CLIENT_BACKOFF_MAX_MS = "ballista.client.backoff.max.ms"
CHAOS_ENABLED = "ballista.chaos.enabled"
CHAOS_SEED = "ballista.chaos.seed"
CHAOS_PROBABILITY = "ballista.chaos.probability"
CHAOS_MODE = "ballista.chaos.mode"
CHAOS_STRAGGLER_DELAY_S = "ballista.chaos.straggler.delay.seconds"
CHAOS_STRAGGLER_PARTITION = "ballista.chaos.straggler.partition"
CHAOS_STRAGGLER_STAGE = "ballista.chaos.straggler.stage"
CHAOS_SKEW_FRACTION = "ballista.chaos.skew.fraction"
CHAOS_DAEMON_ARM = "ballista.chaos.daemon.arm"
CHAOS_DAEMON_ONCE = "ballista.chaos.daemon.once"
CHAOS_DISK_ONCE = "ballista.chaos.disk.once"
# straggler defense (speculation / deadlines)
SPECULATION_ENABLED = "ballista.scheduler.speculation.enabled"
SPECULATION_QUANTILE = "ballista.scheduler.speculation.quantile"
SPECULATION_MULTIPLIER = "ballista.scheduler.speculation.multiplier"
SPECULATION_MIN_RUNTIME_S = "ballista.scheduler.speculation.min.runtime.seconds"
TASK_DEADLINE_S = "ballista.scheduler.task.deadline.seconds"
TASK_DEADLINE_MULTIPLIER = "ballista.scheduler.task.deadline.multiplier"
COLLECT_STATISTICS = "ballista.collect_statistics"
TARGET_PARTITIONS = "ballista.target.partitions"
BATCH_SIZE = "ballista.batch.size"
REPARTITION_JOINS = "ballista.repartition.joins"
REPARTITION_AGGREGATIONS = "ballista.repartition.aggregations"
PARQUET_PRUNING = "ballista.parquet.pruning"
EXECUTOR_ENGINE = "ballista.executor.engine"
EXECUTOR_TASK_ISOLATION = "ballista.executor.task.isolation"
# executor lifecycle & storage failure domain (docs/lifecycle.md)
EXECUTOR_DISK_LOW_WATERMARK = "ballista.executor.disk.low.watermark"
EXECUTOR_DISK_HIGH_WATERMARK = "ballista.executor.disk.high.watermark"
EXECUTOR_DATA_TTL_S = "ballista.executor.data.ttl.seconds"
EXECUTOR_DRAIN_TIMEOUT_S = "ballista.executor.drain.timeout.seconds"
# TPU-native knobs
TPU_SHAPE_BUCKETS = "ballista.tpu.shape.buckets"
TPU_MAX_DEVICE_BYTES = "ballista.tpu.max.device.bytes"
TPU_HASH_TABLE_LOAD = "ballista.tpu.hash.table.load.factor"
TPU_ALLOW_F32_MONEY = "ballista.tpu.allow.f32.money"
TPU_MIN_ROWS = "ballista.tpu.min.rows"
TPU_BROADCAST_JOIN_ROWS = "ballista.tpu.broadcast.join.threshold.rows"
TPU_COLLECTIVE_EXCHANGE = "ballista.tpu.collective.exchange"
TPU_PALLAS = "ballista.tpu.pallas.enabled"
# whole-stage fusion (stage_compiler fusion planner + cost model)
TPU_FUSION_ENABLED = "ballista.tpu.fusion.enabled"
TPU_FUSION_MODE = "ballista.tpu.fusion.mode"
TPU_FUSION_MIN_ROWS = "ballista.tpu.fusion.min.rows"
TPU_FUSION_PALLAS_MAX_GROUPS = "ballista.tpu.fusion.pallas.max.groups"
TPU_FUSION_PALLAS_MAX_PROBE = "ballista.tpu.fusion.pallas.max.probe.rows"
# on-device sort / window / top-k stage family
TPU_SORT_ENABLED = "ballista.tpu.sort.enabled"
TPU_SORT_PALLAS_MAX_ROWS = "ballista.tpu.sort.pallas.max.rows"
TPU_TOPK_ENABLED = "ballista.tpu.topk.enabled"
TPU_TOPK_MAX_K = "ballista.tpu.topk.max.k"
# cold-path pipeline (fill/compile overlap + persistent XLA compile cache)
TPU_FILL_THREADS = "ballista.tpu.fill.threads"
TPU_FILL_CHUNK_ROWS = "ballista.tpu.fill.chunk_rows"
TPU_COMPILE_OVERLAP = "ballista.tpu.compile.overlap"
TPU_COMPILE_CACHE_DIR = "ballista.tpu.compile.cache_dir"
# out-of-core execution (HBM-budgeted admission, host spill, grace fallback)
TPU_HBM_BUDGET_BYTES = "ballista.tpu.hbm.budget.bytes"
TPU_HBM_BUDGET_FRACTION = "ballista.tpu.hbm.budget.fraction"
TPU_HBM_SPILL_ENABLED = "ballista.tpu.hbm.spill.enabled"
TPU_HBM_SPILL_HOST_BYTES = "ballista.tpu.hbm.spill.host.bytes"
TPU_HBM_SPILL_DIR = "ballista.tpu.hbm.spill.dir"
TPU_HBM_GRACE_BUCKETS = "ballista.tpu.hbm.grace.buckets"
TPU_HBM_GRACE_DEPTH = "ballista.tpu.hbm.grace.max.depth"
# mesh-wide stage execution (planner mesh merge + on-device all_to_all exchange)
TPU_MESH_ENABLED = "ballista.tpu.mesh.enabled"
TPU_MESH_DEVICES = "ballista.tpu.mesh.devices"
TPU_MESH_EXCHANGE_CAPACITY = "ballista.tpu.mesh.exchange.capacity.rows"
TPU_MESH_MIN_ROWS = "ballista.tpu.mesh.min.rows"
TPU_MESH_MAX_INPUT_BYTES = "ballista.tpu.mesh.max.input.bytes"
# warm device-runtime daemon (ballista_tpu/device_daemon/)
TPU_DAEMON_ENABLED = "ballista.tpu.daemon.enabled"
TPU_DAEMON_SOCKET = "ballista.tpu.daemon.socket"
TPU_DAEMON_SPAWN = "ballista.tpu.daemon.spawn"
TPU_DAEMON_ATTACH_TIMEOUT_MS = "ballista.tpu.daemon.attach.timeout.ms"
TPU_DAEMON_SESSION_QUOTA_BYTES = "ballista.tpu.daemon.session.hbm.quota.bytes"
TPU_DAEMON_EXECUTE_TIMEOUT_S = "ballista.tpu.daemon.execute.timeout.s"
TPU_DAEMON_POISON_TTL_S = "ballista.tpu.daemon.poison.ttl.s"
# debug verifiers
DEBUG_PLAN_VERIFY = "ballista.debug.plan.verify"


@dataclass(frozen=True)
class ConfigEntry:
    """One typed config key (reference: ConfigEntry, config.rs:403)."""

    name: str
    description: str
    ty: type  # bool | int | float | str
    default: Any
    validator: Callable[[Any], bool] | None = None
    choices: tuple[str, ...] | None = None

    def parse(self, raw: Any) -> Any:
        try:
            if self.ty is bool:
                if isinstance(raw, bool):
                    v: Any = raw
                else:
                    s = str(raw).strip().lower()
                    if s not in ("true", "false", "1", "0"):
                        raise ValueError(s)
                    v = s in ("true", "1")
            else:
                v = self.ty(raw)
        except (ValueError, TypeError):
            raise ConfigurationError(
                f"invalid value {raw!r} for {self.name} (expected {self.ty.__name__})"
            ) from None
        if self.choices is not None and v not in self.choices:
            raise ConfigurationError(
                f"invalid value {v!r} for {self.name}; expected one of {self.choices}"
            )
        if self.validator is not None and not self.validator(v):
            raise ConfigurationError(f"value {v!r} out of range for {self.name}")
        return v


def _env_bool(name: str, default: bool) -> bool:
    """Escape-hatch defaults: data-plane optimizations (mmap serving, fetch
    coalescing) default ON but can be killed fleet-wide with an env var on
    the affected host — no session-config change required. The Flight
    server, which never sees a session config, consults the same vars."""
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


def _env_int(name: str, default: int) -> int:
    """Integer escape hatch for daemons with no session config (Flight
    server, admission control on a shared scheduler)."""
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_str(name: str, default: str) -> str:
    import os

    return os.environ.get(name, default)


def _pos(v: Any) -> bool:
    return v > 0


def _nonneg(v: Any) -> bool:
    return v >= 0


_ENTRIES: list[ConfigEntry] = [
    ConfigEntry(JOB_NAME, "Human-readable job name shown in the UI/REST API.", str, ""),
    ConfigEntry(DEFAULT_SHUFFLE_PARTITIONS, "Output partition count for hash repartitions.", int, 16, _pos),
    ConfigEntry(
        SHUFFLE_COMPRESSION_CODEC,
        "IPC compression for shuffle files and Flight streams.",
        str, "lz4", choices=("none", "lz4", "zstd"),
    ),
    ConfigEntry(SHUFFLE_READER_MAX_REQUESTS, "Reduce-side fetch governor: max concurrent fetch requests.", int, 64, _pos),
    ConfigEntry(SHUFFLE_READER_MAX_PER_ADDR, "Reduce-side fetch governor: max concurrent fetches per executor address.", int, 8, _pos),
    ConfigEntry(SHUFFLE_READER_MAX_BYTES, "Reduce-side fetch governor: in-flight byte budget.", int, 256 * 1024 * 1024, _pos),
    ConfigEntry(SHUFFLE_READER_FORCE_REMOTE, "Testing: fetch shuffle partitions over Flight even when local.", bool, False),
    ConfigEntry(SHUFFLE_BLOCK_TRANSPORT, "Fetch remote shuffle partitions as raw 8 MiB IPC blocks (no decode/re-encode).", bool, True),
    ConfigEntry(SHUFFLE_FETCH_COALESCE, "Coalesce a reduce task's fetches: all map outputs owned by one executor stream back in a single RPC (M small RPCs become one per executor). Env escape hatch: BALLISTA_SHUFFLE_COALESCE=0.", bool, _env_bool("BALLISTA_SHUFFLE_COALESCE", True)),
    ConfigEntry(SHUFFLE_MMAP, "Serve and read shuffle files through memory maps (zero-copy buffer slices instead of seek+read copies). Env escape hatch: BALLISTA_SHUFFLE_MMAP=0 (also honored by the Flight server, which has no session config).", bool, _env_bool("BALLISTA_SHUFFLE_MMAP", True)),
    ConfigEntry(SHUFFLE_CHECKSUM_ENABLED, "End-to-end shuffle integrity: writers record a checksum per output-partition byte range (hash layout: .crc sidecar; sort layout: 5th index-entry field), Flight servers ship it in per-location headers, and readers verify the received bytes BEFORE decoding. A mismatch retries the fetch once in place, then escalates as FetchFailed(cause=corruption) so the upstream stage recomputes and the serving executor takes a corruption strike. Disabling only stops WRITING checksums — readers always verify when a stored value is present. Env escape hatch: BALLISTA_SHUFFLE_CHECKSUM=0 (also honored by the Flight server, which has no session config).", bool, _env_bool("BALLISTA_SHUFFLE_CHECKSUM", True)),
    ConfigEntry(SORT_SHUFFLE_ENABLED, "Use sort-based shuffle (M consolidated bucket files + index) for hash repartitions.", bool, True),
    ConfigEntry(SORT_SHUFFLE_MEMORY_LIMIT, "Bytes of buffered batches before sort-shuffle spills (0 = unlimited).", int, 256 * 1024 * 1024, _nonneg),
    ConfigEntry(SORT_SHUFFLE_POOL_WAIT_S, "How long a writer with nothing left to spill blocks for session-pool headroom before overcommitting (liveness backstop).", float, 10.0, _nonneg),
    ConfigEntry(BROADCAST_JOIN_THRESHOLD, "Max build-side bytes to lower a join to a broadcast exchange.", int, 10 * 1024 * 1024, _nonneg),
    ConfigEntry(BROADCAST_JOIN_ROWS_THRESHOLD, "Max build-side rows to lower a join to a broadcast exchange.", int, 1_000_000, _nonneg),
    ConfigEntry(BROADCAST_SEMI_KEYS_THRESHOLD, "Max build-side rows to collect a filterless semi/anti join's membership keys instead of co-partitioning (the build ships join keys only, so the collect threshold relaxes past the row-broadcast one).", int, 8_000_000, _nonneg),
    ConfigEntry(MAX_PARTITIONS_PER_TASK, "Group up to N partitions into one task (partition slices).", int, 1, _pos),
    ConfigEntry(JOB_RESUBMIT_INTERVAL_MS, "Periodically re-offer jobs holding runnable-but-unscheduled tasks (0 = off; offers otherwise fire on task/executor events only).", int, 0, _nonneg),
    ConfigEntry(SCHEDULER_SHARDS, "Scheduler event-loop shards: jobs partition by crc32(job_id) mod N, each shard running its own event loop and admission-lag EWMA.", int, 1, _pos),
    ConfigEntry(SCHEDULER_LEASE_ENABLED, "Direct-dispatch leases: mint revocable executor capacity slices so prepared-statement clients can skip the scheduler on the hot path.", bool, False),
    ConfigEntry(SCHEDULER_LEASE_TTL_S, "Direct-dispatch lease lifetime; expired tokens are rejected at the executor and swept by the scheduler.", float, 30.0, _pos),
    ConfigEntry(SCHEDULER_LEASE_SLOTS, "Executor task slots reserved per direct-dispatch lease (taken out of the shared slot ledger).", int, 2, _pos),
    ConfigEntry(SCHEDULER_LEASE_BAND_SIZE, "Task ids reserved per lease; direct-dispatch ids live in a private band above all scheduler-assigned ids.", int, 10_000, _pos),
    ConfigEntry(PLANNER_ADAPTIVE_ENABLED, "Adaptive query execution: replan remaining stages with runtime stats.", bool, True),
    ConfigEntry(AQE_TARGET_PARTITION_BYTES, "AQE coalescing: target bytes per post-shuffle partition.", int, 64 * 1024 * 1024, _pos),
    ConfigEntry(AQE_MIN_PARTITION_BYTES, "AQE coalescing: never coalesce below this size.", int, 1024 * 1024, _pos),
    ConfigEntry(AQE_COALESCE_MERGED_FACTOR, "AQE coalescing: merged-partition slack factor.", float, 1.2, _pos),
    ConfigEntry(AQE_EMPTY_PROPAGATION, "AQE: prune stages proven empty by runtime stats.", bool, True),
    ConfigEntry(AQE_DYNAMIC_JOIN_SELECTION, "AQE: choose join strategy at runtime from actual input sizes.", bool, True),
    ConfigEntry(AQE_ALTER_FANOUT, "AQE: shrink a resolving stage's hash fan-out when observed input volume proves the planned bucket count too high.", bool, True),
    ConfigEntry(
        AQE_JOIN_HEDGE_FACTOR,
        "AQE join hedging: a join whose build-side row ESTIMATE lands within "
        "this factor of the broadcast row threshold (estimate * factor > "
        "threshold) is too close to call at plan time, so the planner keeps "
        "the partitioned layout with a deferred DynamicJoinSelectionExec "
        "carrying the broadcast preference. Runtime bytes then decide both "
        "ways: a build that finishes tiny is promoted to CollectLeft (with "
        "probe-shuffle elision when it finishes first), one that comes in "
        "oversized is DEMOTED to the partitioned join the hedge preserved. "
        "0 disables hedging (estimates commit broadcast statically, the "
        "pre-hedge behavior).",
        float, 4.0, _nonneg,
    ),
    ConfigEntry(
        AQE_SKEW_ENABLED,
        "AQE skew defense: split a hot reduce partition into K slice tasks "
        "at stage resolution when its observed bytes exceed both the "
        "median-multiple factor and the bytes floor.",
        bool, True,
    ),
    ConfigEntry(
        AQE_SKEW_FACTOR,
        "AQE skew defense: a reduce partition is hot when its combined input "
        "bytes exceed factor * median(partition bytes).",
        float, 4.0, lambda v: v >= 1.0,
    ),
    ConfigEntry(
        AQE_SKEW_MIN_BYTES,
        "AQE skew defense: never split a partition below this byte size "
        "(splitting tiny skew trades task overhead for nothing).",
        int, 16 * 1024 * 1024, _pos,
    ),
    ConfigEntry(
        AQE_SKEW_MAX_SLICES,
        "AQE skew defense: hard cap on the slice tasks one hot partition "
        "splits into (the actual count is ceil(bytes/coalesce-target) "
        "clamped here and to the partition's map-output count).",
        int, 8, lambda v: v >= 2,
    ),
    ConfigEntry(GRPC_CLIENT_MAX_MESSAGE_SIZE, "Client-side gRPC message ceiling.", int, 256 * 1024 * 1024, _pos),
    ConfigEntry(CLIENT_JOB_TIMEOUT_S, "How long a client waits for a submitted job before giving up.", int, 600, _pos),
    ConfigEntry(GRPC_SERVER_MAX_MESSAGE_SIZE, "Server-side gRPC message ceiling.", int, 256 * 1024 * 1024, _pos),
    ConfigEntry(
        FLIGHT_PROXY,
        "Scheduler Flight proxy address (host:port). When set, result "
        "partitions are fetched through the scheduler instead of directly "
        "from executors (for clients that cannot reach executors).",
        str, "",
    ),
    ConfigEntry(
        PUSH_STATUS,
        "Use the server-streaming execute_query_push rpc (scheduler pushes "
        "state changes) instead of polling get_job_status.",
        bool, False,
    ),
    ConfigEntry(
        GRPC_TLS_CA,
        "CA certificate (PEM) used to verify gRPC peers; presence turns on "
        "TLS for outbound control-plane channels.",
        str, "",
    ),
    ConfigEntry(
        GRPC_TLS_CERT,
        "This party's certificate chain (PEM) presented on gRPC connections "
        "(mTLS client auth when dialing, server identity when listening).",
        str, "",
    ),
    ConfigEntry(
        GRPC_TLS_KEY,
        "Private key (PEM) matching ballista.grpc.tls.cert.path.",
        str, "",
    ),
    ConfigEntry(IO_RETRIES, "Shuffle fetch retry attempts.", int, 3, _nonneg),
    ConfigEntry(IO_RETRY_WAIT_MS, "Base backoff between shuffle fetch retries.", int, 100, _nonneg),
    ConfigEntry(
        ADMISSION_ENABLED,
        "Scheduler admission control: bound pending jobs and per-session in-flight "
        "quotas, shedding excess submissions with a typed ClusterOverloaded "
        "rejection + retry_after_ms hint instead of queueing without bound. "
        "Env escape hatch: BALLISTA_ADMISSION=0.",
        bool, _env_bool("BALLISTA_ADMISSION", True),
    ),
    ConfigEntry(
        ADMISSION_MAX_PENDING_JOBS,
        "Max jobs queued/planning cluster-wide before new submissions are shed. "
        "Env: BALLISTA_ADMISSION_MAX_PENDING.",
        int, _env_int("BALLISTA_ADMISSION_MAX_PENDING", 256), _pos,
    ),
    ConfigEntry(
        ADMISSION_MAX_INFLIGHT_PER_SESSION,
        "Max non-terminal jobs one session may hold; the quota halves while the "
        "cluster is shedding. Env: BALLISTA_ADMISSION_SESSION_QUOTA.",
        int, _env_int("BALLISTA_ADMISSION_SESSION_QUOTA", 64), _pos,
    ),
    ConfigEntry(
        ADMISSION_SHED_DEPTH,
        "Pending-job depth at which the overload state machine leaves normal for "
        "shedding (quotas halve; hysteresis exits at half this depth). Env: "
        "BALLISTA_ADMISSION_SHED_DEPTH.",
        int, _env_int("BALLISTA_ADMISSION_SHED_DEPTH", 128), _pos,
    ),
    ConfigEntry(
        ADMISSION_DRAIN_DEPTH,
        "Pending-job depth at which shedding escalates to draining: ALL new "
        "submissions are rejected until the backlog drains below the shed depth. "
        "Env: BALLISTA_ADMISSION_DRAIN_DEPTH.",
        int, _env_int("BALLISTA_ADMISSION_DRAIN_DEPTH", 224), _pos,
    ),
    ConfigEntry(
        ADMISSION_SHED_LOOP_LAG_S,
        "Scheduler event-loop lag (post→handle latency) that forces shedding even "
        "with a shallow queue — a wedged loop means depth is lying.",
        float, 2.0, _pos,
    ),
    ConfigEntry(
        ADMISSION_SHED_MEMORY_PRESSURE,
        "Aggregate executor memory-pressure score (0-1, from heartbeats) above "
        "which the scheduler sheds: executors near pool saturation reject tasks "
        "anyway, so admitting more jobs only grows the retry storm.",
        float, 0.9, lambda v: 0.0 < v <= 1.0,
    ),
    ConfigEntry(
        ADMISSION_MIN_RETRY_AFTER_MS,
        "Floor for the retry_after_ms hint carried by ClusterOverloaded "
        "rejections (the drain-rate estimate can be optimistic right after a "
        "burst).",
        int, 100, _nonneg,
    ),
    ConfigEntry(
        ADMISSION_INTERACTIVE_MAX_PENDING,
        "Per-lane admission: max in-flight jobs in the interactive lane (plan-"
        "cache hits known to be single-stage, prepared executions). The batch "
        "lane keeps the global max-pending cap; shedding/draining degrade the "
        "batch lane first so short repeat queries survive a batch overload. "
        "Env: BALLISTA_ADMISSION_INTERACTIVE_MAX_PENDING.",
        int, _env_int("BALLISTA_ADMISSION_INTERACTIVE_MAX_PENDING", 512), _pos,
    ),
    ConfigEntry(
        SERVING_PLAN_CACHE,
        "Serving tier: cache physical-plan templates keyed on the normalized "
        "optimized logical plan (literals lifted to parameters) plus the session "
        "config fingerprint. Repeats of a query shape skip physical planning; "
        "exact-text repeats also skip parsing and optimization. "
        "Env escape hatch: BALLISTA_SERVING_PLAN_CACHE=0.",
        bool, _env_bool("BALLISTA_SERVING_PLAN_CACHE", True),
    ),
    ConfigEntry(
        SERVING_PLAN_CACHE_ENTRIES,
        "Plan-template cache entry cap (LRU). The exact-text L1 cache holds 4x "
        "this many entries. Env: BALLISTA_SERVING_PLAN_ENTRIES.",
        int, _env_int("BALLISTA_SERVING_PLAN_ENTRIES", 256), _pos,
    ),
    ConfigEntry(
        SERVING_RESULT_CACHE,
        "Serving tier: cache final result tables keyed on (normalized plan, "
        "bound parameters, table versions); any re-registration of a referenced "
        "table invalidates by version bump. Results are served inline to "
        "in-process clients only. Off by default: it changes freshness "
        "semantics. Env escape hatch: BALLISTA_SERVING_RESULT_CACHE=1.",
        bool, _env_bool("BALLISTA_SERVING_RESULT_CACHE", False),
    ),
    ConfigEntry(
        SERVING_RESULT_CACHE_ENTRIES,
        "Result cache entry cap (LRU). Env: BALLISTA_SERVING_RESULT_ENTRIES.",
        int, _env_int("BALLISTA_SERVING_RESULT_ENTRIES", 512), _pos,
    ),
    ConfigEntry(
        SERVING_RESULT_CACHE_BYTES,
        "Result cache byte budget across all cached tables (LRU evicts past "
        "it). Env: BALLISTA_SERVING_RESULT_BYTES.",
        int, _env_int("BALLISTA_SERVING_RESULT_BYTES", 64 * 1024 * 1024), _pos,
    ),
    ConfigEntry(
        SERVING_RESULT_MAX_BYTES,
        "Largest single result the cache will hold; bigger results are never "
        "cached (they would evict many small interactive results). Env: "
        "BALLISTA_SERVING_RESULT_MAX_RESULT_BYTES.",
        int, _env_int("BALLISTA_SERVING_RESULT_MAX_RESULT_BYTES", 4 * 1024 * 1024), _pos,
    ),
    ConfigEntry(
        SERVING_FAST_LANE,
        "Serving tier: dispatch single-stage plans straight to warm executors "
        "from the submit path, bypassing the execution-graph/event-loop "
        "machinery; failures and timeouts fall back to the full DAG path. "
        "Env escape hatch: BALLISTA_SERVING_FAST_LANE=0.",
        bool, _env_bool("BALLISTA_SERVING_FAST_LANE", True),
    ),
    ConfigEntry(
        SERVING_FAST_LANE_TIMEOUT_S,
        "Seconds a fast-lane job may run before the straggler sweep demotes it "
        "to the full DAG path (covers executors lost mid-flight, which fast "
        "jobs otherwise would not notice).",
        float, 30.0, _pos,
    ),
    ConfigEntry(
        SERVING_INCREMENTAL,
        "Serving tier: maintain eligible cached results incrementally on "
        "append (delta query over retained appends merged into cached "
        "aggregation state) instead of recomputing from scratch. Ineligible "
        "shapes fall back to full recompute with a recorded reason. "
        "Env escape hatch: BALLISTA_SERVING_INCREMENTAL=0.",
        bool, _env_bool("BALLISTA_SERVING_INCREMENTAL", True),
    ),
    ConfigEntry(
        SERVING_INCREMENTAL_STATE_ENTRIES,
        "Aggregation-state cache entry cap (LRU): one entry per (plan "
        "template, bound values) holds the pre-finisher accumulator rows a "
        "maintained refresh merges deltas into. "
        "Env: BALLISTA_SERVING_INCREMENTAL_STATE_ENTRIES.",
        int, _env_int("BALLISTA_SERVING_INCREMENTAL_STATE_ENTRIES", 256), _pos,
    ),
    ConfigEntry(
        SERVING_INCREMENTAL_STATE_BYTES,
        "Aggregation-state cache byte budget (LRU evicts past it; an evicted "
        "state falls back to bootstrap recompute on the next refresh). "
        "Env: BALLISTA_SERVING_INCREMENTAL_STATE_BYTES.",
        int, _env_int("BALLISTA_SERVING_INCREMENTAL_STATE_BYTES", 64 * 1024 * 1024), _pos,
    ),
    ConfigEntry(
        SERVING_SUBSCRIPTION_QUEUE,
        "Continuous queries: bounded per-subscription push queue depth; when "
        "a slow consumer falls behind, the oldest undelivered refresh is "
        "dropped (freshest-wins) and counted. "
        "Env: BALLISTA_SERVING_SUBSCRIPTION_QUEUE.",
        int, _env_int("BALLISTA_SERVING_SUBSCRIPTION_QUEUE", 32), _pos,
    ),
    ConfigEntry(
        INGEST_DELTA_RETAIN_BYTES,
        "Append ingestion: byte budget for retained per-version delta sets "
        "across all tables. Crossing it folds the oldest deltas into the "
        "table's base version (parquet spool) instead of dropping rows, so "
        "memory cannot grow with append rate. "
        "Env: BALLISTA_INGEST_DELTA_RETAIN_BYTES.",
        int, _env_int("BALLISTA_INGEST_DELTA_RETAIN_BYTES", 64 * 1024 * 1024), _pos,
    ),
    ConfigEntry(
        INGEST_DELTA_RETAIN_VERSIONS,
        "Append ingestion: max retained delta versions per table; older "
        "versions are folded (compacted) into the base. A maintained refresh "
        "older than the fold horizon falls back to full recompute with "
        "reason delta-compacted. Env: BALLISTA_INGEST_DELTA_RETAIN_VERSIONS.",
        int, _env_int("BALLISTA_INGEST_DELTA_RETAIN_VERSIONS", 64), _pos,
    ),
    ConfigEntry(
        INGEST_COMPACTION_DIR,
        "Append ingestion: directory delta compaction spools folded parquet "
        "parts into (empty = a per-scheduler temp dir). "
        "Env: BALLISTA_INGEST_COMPACTION_DIR.",
        str, _env_str("BALLISTA_INGEST_COMPACTION_DIR", ""),
    ),
    ConfigEntry(
        FLIGHT_MAX_STREAMS,
        "Flight data plane: max concurrent do_get/do_action streams per server; "
        "excess callers wait in a bounded accept queue and are then rejected "
        "UNAVAILABLE. Env escape hatch (servers have no session config): "
        "BALLISTA_FLIGHT_MAX_STREAMS.",
        int, _env_int("BALLISTA_FLIGHT_MAX_STREAMS", 64), _pos,
    ),
    ConfigEntry(
        FLIGHT_ACCEPT_QUEUE,
        "Flight data plane: how many callers may wait for a stream slot before "
        "new ones are rejected immediately. Env: BALLISTA_FLIGHT_ACCEPT_QUEUE.",
        int, _env_int("BALLISTA_FLIGHT_ACCEPT_QUEUE", 128), _nonneg,
    ),
    ConfigEntry(
        FLIGHT_STALL_TIMEOUT_S,
        "Flight data plane: a do_get consumer that pulls no batch for this long "
        "is cut off (frees the server-side buffers and the stream slot instead "
        "of wedging on a dead peer). 0 disables. Env: BALLISTA_FLIGHT_STALL_TIMEOUT_S.",
        float, _env_float("BALLISTA_FLIGHT_STALL_TIMEOUT_S", 30.0), _nonneg,
    ),
    ConfigEntry(
        FLIGHT_BREAKER_THRESHOLD,
        "Flight client circuit breaker: consecutive failures to one address that "
        "trip it open (fail-fast, no dial, until a half-open probe succeeds). "
        "0 disables.",
        int, 5, _nonneg,
    ),
    ConfigEntry(
        FLIGHT_BREAKER_COOLDOWN_S,
        "Flight client circuit breaker: seconds an open breaker waits before "
        "allowing one half-open probe.",
        float, 5.0, _pos,
    ),
    ConfigEntry(
        CLIENT_SUBMIT_RETRIES,
        "Max client retries of a shed submission (ClusterOverloaded / "
        "RESOURCE_EXHAUSTED), honoring the server's retry_after_ms hint with "
        "jitter; also bounds retries of idempotent RPCs on UNAVAILABLE.",
        int, 5, _nonneg,
    ),
    ConfigEntry(
        CLIENT_BACKOFF_BASE_MS,
        "Client retry backoff base (exponential, full jitter).",
        int, 100, _pos,
    ),
    ConfigEntry(
        CLIENT_BACKOFF_MAX_MS,
        "Client retry backoff ceiling.",
        int, 10_000, _pos,
    ),
    ConfigEntry(CHAOS_ENABLED, "Fault injection: wrap leaf operators in chaos nodes.", bool, False),
    ConfigEntry(CHAOS_SEED, "Fault injection RNG seed.", int, 0, _nonneg),
    ConfigEntry(CHAOS_PROBABILITY, "Per-task fault probability.", float, 0.05, lambda v: 0.0 <= v <= 1.0),
    ConfigEntry(
        CHAOS_MODE, "Fault kind to inject. 'overload' synthesizes memory "
        "pressure (the hit task overcommits its session pool for the "
        "partition's duration) plus a queue delay — deterministic fuel for "
        "overload-protection tests. 'corrupt' is a SERVE-time fault (seeded "
        "bit-flip as the Flight server streams shuffle bytes, so stored files "
        "stay pristine and a refetch can heal): because the data plane has no "
        "session config, it is armed via env on the executor — "
        "BALLISTA_CHAOS_CORRUPT_P (probability per served range), "
        "BALLISTA_CHAOS_CORRUPT_ONCE=1 (corrupt only the first serve of each "
        "range: deterministic transient corruption), BALLISTA_CHAOS_SEED. "
        "'hbm_oom' exercises the out-of-core TPU path: it deterministically "
        "shrinks the device memory budget the stage compiler admits against "
        "(no plan wrapping — a wrapped scan leaf would hide the stage from "
        "the device compiler), armed via env on the executor — "
        "BALLISTA_CHAOS_HBM_BUDGET (forced budget bytes, default 1 MiB) and "
        "BALLISTA_CHAOS_HBM_OOM_N (additionally raise a synthetic "
        "RESOURCE_EXHAUSTED on the Nth device upload, 0 = never; fires once, "
        "so the evict-spill-retry rung can be observed converging). 'skew' "
        "faults the shuffle-writer PARTITIONER (no plan wrapping): a seeded "
        "fraction of rows — chosen as a pure function of the row's key hash, "
        "so equal keys always co-locate and results stay byte-identical — is "
        "rerouted to one hot reduce partition (ballista.chaos.skew.fraction), "
        "deterministic fuel for the AQE skew-split defense. 'daemon_crash' / "
        "'daemon_hang' fault the device-runtime DAEMON (no plan wrapping — "
        "the fault fires inside the daemon's execute handler, at the arming "
        "point ballista.chaos.daemon.arm): daemon_crash hard-exits the daemon "
        "process (SIGKILL-style, exit 137) so the client's typed "
        "DaemonCrashed → respawn-and-retry → poison-quarantine ladder is "
        "exercised end to end; daemon_hang wedges the execute thread so the "
        "per-request watchdog trips, writes the <socket>.crash.json "
        "post-mortem, and exits 4 (docs/device_daemon.md#failure-domain). "
        "'disk_full' faults the STORAGE path (no plan wrapping): the shuffle "
        "writer's commit points and the spill pool's disk demotions raise a "
        "typed DiskExhausted on a seeded roll keyed by (seed, job, stage, "
        "partition) — with ballista.chaos.disk.once (the default) a hit is "
        "recorded so the retried slice heals, proving an injected ENOSPC "
        "fails no job. 'drain_kill' faults the graceful-drain state machine "
        "(no plan wrapping): armed via env on the scheduler side — "
        "BALLISTA_CHAOS_DRAIN_KILL_AFTER=N aborts a drain's shuffle-output "
        "migration after N committed locations, exercising the hard-kill "
        "fallback to the executor-lost recompute path (docs/lifecycle.md).",
        str, "transient",
        choices=("transient", "fatal", "panic", "delay", "straggler", "overload",
                 "corrupt", "hbm_oom", "skew", "daemon_crash", "daemon_hang",
                 "disk_full", "drain_kill"),
    ),
    ConfigEntry(
        CHAOS_STRAGGLER_DELAY_S,
        "chaos mode=straggler: seconds the straggling partition sleeps before "
        "producing its batches (first task attempt only, so a speculative or "
        "retried attempt escapes the injected delay).",
        float, 5.0, _nonneg,
    ),
    ConfigEntry(
        CHAOS_STRAGGLER_PARTITION,
        "chaos mode=straggler: partition index to delay deterministically "
        "(-1 = pick by seeded per-partition roll against the chaos probability).",
        int, -1, lambda v: v >= -1,
    ),
    ConfigEntry(
        CHAOS_STRAGGLER_STAGE,
        "chaos mode=straggler: restrict injection to this stage id (-1 = every "
        "stage). Partition indices repeat across stages — a shuffle reader in a "
        "single-task final stage drives the same indices the scan did — so "
        "tests that need exactly one straggling task pin the stage too.",
        int, -1, lambda v: v >= -1,
    ),
    ConfigEntry(
        CHAOS_SKEW_FRACTION,
        "chaos mode=skew: approximate fraction of shuffled rows rerouted to "
        "the hot reduce partition (seeded; the hot partition index is "
        "seed % K). Rerouting is keyed on the row's key hash, never on row "
        "position, so both sides of a co-partitioned join skew identically "
        "and query results are unchanged.",
        float, 0.5, lambda v: 0.0 <= v <= 1.0,
    ),
    ConfigEntry(
        CHAOS_DAEMON_ARM,
        "chaos mode=daemon_crash/daemon_hang: the arming point inside the "
        "device daemon's execute handler where the fault fires — "
        "pre_execute (before the plan decodes), mid_execute (holding the "
        "device, before the stage runs), or post_execute (results computed, "
        "reply not yet sent). The session config carries the arming to the "
        "daemon; the executor-side plan is never wrapped "
        "(docs/device_daemon.md#failure-domain).",
        str, "mid_execute",
        lambda v: v in ("pre_execute", "mid_execute", "post_execute"),
    ),
    ConfigEntry(
        CHAOS_DAEMON_ONCE,
        "chaos mode=daemon_crash/daemon_hang: limit the fault to the FIRST "
        "armed request per daemon socket, via a marker file next to the "
        "socket that deliberately survives daemon respawns — so the "
        "respawn-and-retry recovery path succeeds deterministically. False "
        "= every incarnation dies, which exercises the poison-stage "
        "quarantine instead.",
        bool, True,
    ),
    ConfigEntry(
        CHAOS_DISK_ONCE,
        "chaos mode=disk_full: inject the ENOSPC only on the FIRST hit per "
        "(job, stage, partition) slice — the retry of the failed task finds "
        "the recorded marker and heals, modelling transient disk pressure. "
        "False = every attempt re-rolls (the attempt joins the seed key, so "
        "a retry sees different luck).",
        bool, True,
    ),
    ConfigEntry(
        SPECULATION_ENABLED,
        "Launch duplicate attempts of a stage's slowest running tasks once the "
        "stage is mostly complete; the first attempt to finish wins and the "
        "loser is cancelled.",
        bool, True,
    ),
    ConfigEntry(
        SPECULATION_QUANTILE,
        "Fraction of a stage's tasks that must have finished before its "
        "remaining running tasks become speculation candidates.",
        float, 0.75, lambda v: 0.0 < v <= 1.0,
    ),
    ConfigEntry(
        SPECULATION_MULTIPLIER,
        "A running task is speculated when its elapsed runtime exceeds this "
        "multiple of the stage's median completed-task duration.",
        float, 1.5, _pos,
    ),
    ConfigEntry(
        SPECULATION_MIN_RUNTIME_S,
        "Never speculate a task running for less than this many seconds "
        "(guards against duplicating short tasks on noisy timings).",
        float, 1.0, _nonneg,
    ),
    ConfigEntry(
        TASK_DEADLINE_S,
        "Hard per-task deadline floor in seconds (0 = no deadline). The "
        "effective deadline is max(this, multiplier x observed median stage "
        "task duration); the executor aborts the attempt at the deadline and "
        "reports a retryable timeout.",
        float, 0.0, _nonneg,
    ),
    ConfigEntry(
        TASK_DEADLINE_MULTIPLIER,
        "Adaptive deadline: multiple of the stage's median completed-task "
        "duration allowed before a running task is timed out (only once "
        "enough samples exist; 0 disables the adaptive part).",
        float, 0.0, _nonneg,
    ),
    ConfigEntry(COLLECT_STATISTICS, "Collect table statistics at registration.", bool, True),
    ConfigEntry(TARGET_PARTITIONS, "Planner parallelism target (scan partitioning).", int, 8, _pos),
    ConfigEntry(BATCH_SIZE, "Rows per record batch in operator pipelines.", int, 64 * 1024, _pos),
    ConfigEntry(REPARTITION_JOINS, "Insert hash repartitions to parallelize joins.", bool, True),
    ConfigEntry(REPARTITION_AGGREGATIONS, "Insert hash repartitions to parallelize aggregations.", bool, True),
    ConfigEntry(PARQUET_PRUNING, "Prune parquet row groups with min/max statistics.", bool, True),
    ConfigEntry(
        EXECUTOR_ENGINE,
        "Operator engine for query stages: 'tpu' compiles supported subtrees to "
        "XLA with cpu fallback; 'cpu' is Arrow-native.",
        str, "cpu", choices=("cpu", "tpu"),
    ),
    ConfigEntry(
        EXECUTOR_TASK_ISOLATION,
        "Task execution mode: 'process' runs each task in a spawned worker "
        "(true multi-core parallelism, native-crash isolation, preemptive "
        "cancel — DedicatedExecutor parity); 'thread' runs in-process. A "
        "session setting 'process' opts its tasks in on any executor; a "
        "daemon started with --task-isolation process applies it to all "
        "tasks and cannot be opted out per-session. Exception: with "
        "engine=tpu tasks always run in-thread (the spawned worker cannot "
        "share the parent's TPU runtime), and the executor logs a warning "
        "when that downgrades a forced 'process' setting.",
        str, "thread", choices=("thread", "process"),
    ),
    ConfigEntry(
        TPU_SHAPE_BUCKETS,
        "Comma-separated row-count buckets batches are padded to before jit "
        "(bounds XLA recompilation).",
        str, "4096,16384,65536,262144,1048576",
    ),
    ConfigEntry(TPU_MAX_DEVICE_BYTES, "Per-stage HBM budget before falling back to cpu/spill.", int, 12 * 1024**3, _pos),
    ConfigEntry(TPU_HASH_TABLE_LOAD, "Open-addressing hash table load factor for device joins/aggs.", float, 0.5, lambda v: 0.0 < v <= 0.9),
    ConfigEntry(TPU_ALLOW_F32_MONEY, "Allow lossy float32 for decimal columns (faster, inexact).", bool, False),
    ConfigEntry(TPU_MIN_ROWS, "Below this many input rows a stage stays on cpu (compile cost dominates).", int, 8192, _nonneg),
    ConfigEntry(TPU_BROADCAST_JOIN_ROWS, "With engine=tpu: max build-side rows to collect a join build instead of co-partitioning. Device joins probe an HBM-resident sorted build table, so the collect budget is orders of magnitude past the CPU broadcast threshold; a partitioned join hides the chain from the stage compiler entirely.", int, 16_000_000, _nonneg),
    ConfigEntry(
        TPU_PALLAS,
        "Legacy switch predating ballista.tpu.fusion.mode: when true the "
        "fusion cost model requests fused_pallas for every eligible stage "
        "(f32 sums / i32 counts; exact int64 money stays on XLA). Prefer "
        "ballista.tpu.fusion.mode=fused_pallas.",
        bool, False,
    ),
    ConfigEntry(
        TPU_FUSION_ENABLED,
        "Whole-stage fusion in the TPU stage compiler. On, the fusion "
        "planner groups a stage's operator chain into fusible spans "
        "(predicates, projections, join probe+gather, aggregation) and the "
        "cost model picks fused-Pallas / fused-XLA / staged per stage "
        "(RUN_STATS fusion_mode records the choice). Off, every stage "
        "compiles in staged mode when eligible (per-span sub-kernels with "
        "HBM intermediates), else fused-XLA.",
        bool, True,
    ),
    ConfigEntry(
        TPU_FUSION_MODE,
        "Fusion mode override: auto (cost model decides), staged, "
        "fused_xla, or fused_pallas. Forced modes are still clamped to "
        "what the stage supports (the fallback ladder is fused_pallas → "
        "fused_xla → staged-ineligible → fused_xla; RUN_STATS fusion_mode "
        "reports the mode that actually ran).",
        str, "auto", lambda v: v in ("auto", "staged", "fused_xla", "fused_pallas"),
    ),
    ConfigEntry(
        TPU_FUSION_MIN_ROWS,
        "Cost model: below this many total stage input rows the planner "
        "prefers the staged path when the stage is staged-eligible "
        "(per-span dispatch overhead is noise at small sizes and the "
        "span timings feed the roofline taps).",
        int, 4096, _nonneg,
    ),
    ConfigEntry(
        TPU_FUSION_PALLAS_MAX_GROUPS,
        "Cost model / compiler: max group-domain cardinality routed to the "
        "Pallas hash-aggregate kernel (multi-tile one-hot accumulation). "
        "Hard kernel ceiling is 4096 lanes; larger domains use the "
        "fused-XLA sorted segmented reduction.",
        int, 4096, _pos,
    ),
    ConfigEntry(
        TPU_FUSION_PALLAS_MAX_PROBE,
        "Cost model / compiler: max direct-mode build table entries routed "
        "to the Pallas hash-probe kernel (the key→row table must fit "
        "VMEM-resident per block). Larger tables probe via the XLA gather.",
        int, 1 << 18, _pos,
    ),
    ConfigEntry(
        TPU_SORT_ENABLED,
        "On-device sort / window / top-k stage family: when true the TPU "
        "engine wraps eligible SortExec and WindowExec subtrees so ORDER "
        "BY, window-aggregate, and ORDER BY ... LIMIT stages compute their "
        "ordering permutation on device over the int64 lane encoding "
        "(results stay byte-identical to the CPU engine; ineligible shapes "
        "decline with a recorded reason and run on the host).",
        bool, True,
    ),
    ConfigEntry(
        TPU_SORT_PALLAS_MAX_ROWS,
        "Cost model: max padded sort lanes (rows rounded up to a power of "
        "two) routed to the Pallas bitonic segmented-sort kernel family. "
        "Larger stages demote to the fused-XLA stable sort with the reason "
        "recorded in fusion_reason.",
        int, 1 << 17, _pos,
    ),
    ConfigEntry(
        TPU_TOPK_ENABLED,
        "Fused top-k for ORDER BY ... LIMIT final stages: select the k "
        "smallest/largest lanes by chunked bitonic folding without ever "
        "materializing the full sorted order. Off (or when the shape is "
        "ineligible), LIMIT stages fall back to full sort + slice and "
        "RUN_STATS sort_full_materializations counts it.",
        bool, True,
    ),
    ConfigEntry(
        TPU_TOPK_MAX_K,
        "Cost model: max LIMIT fetch routed to the fused top-k kernel (the "
        "kept set must stay a small power-of-two chunk per fold round). "
        "Larger fetches use full sort + slice.",
        int, 1024, _pos,
    ),
    ConfigEntry(
        TPU_COLLECTIVE_EXCHANGE,
        "Use ICI collectives (shard_map all_to_all) instead of file shuffle for "
        "co-scheduled intra-slice stages.",
        bool, False,
    ),
    ConfigEntry(
        TPU_HBM_BUDGET_BYTES,
        "Out-of-core admission: per-stage device-memory budget in bytes the "
        "HBM planner admits stage working sets against (probe table + "
        "dictionary LUTs + join build tables). 0 = auto: "
        "ballista.tpu.hbm.budget.fraction of the detected device memory "
        "(jax memory_stats bytes_limit), falling back to "
        "ballista.tpu.max.device.bytes when detection is unavailable "
        "(CPU-jax). Every admission decision lands in RUN_STATS as "
        "hbm_plan / hbm_plan_reason.",
        int, 0, _nonneg,
    ),
    ConfigEntry(
        TPU_HBM_BUDGET_FRACTION,
        "Out-of-core admission: fraction of detected device memory used as "
        "the HBM budget when ballista.tpu.hbm.budget.bytes is 0 (headroom "
        "for XLA scratch and fusion intermediates).",
        float, 0.85, lambda v: 0.0 < v <= 1.0,
    ),
    ConfigEntry(
        TPU_HBM_SPILL_ENABLED,
        "Out-of-core spill: cold DeviceTableCache entries demote to host "
        "buffers (and past the host budget, to attempt-unique tmp+rename "
        "spill files) instead of being dropped, and re-upload transparently "
        "on the next touch. Off, eviction drops the entry and a re-touch "
        "pays the full re-encode + re-upload.",
        bool, True,
    ),
    ConfigEntry(
        TPU_HBM_SPILL_HOST_BYTES,
        "Out-of-core spill: host-buffer budget of the spill pool. Entries "
        "past it demote to the disk tier (npz files written with the CPU "
        "spill pool's tmp+rename discipline). Host-buffer bytes are "
        "split-accounted against the session memory pool's device headroom, "
        "never against the CPU sort-spill budget.",
        int, 2 * 1024**3, _pos,
    ),
    ConfigEntry(
        TPU_HBM_SPILL_DIR,
        "Out-of-core spill: directory for disk-tier spill files. Empty = "
        "the system temp directory. Files are attempt-unique and removed "
        "when their entry is dropped or re-uploaded.",
        str, "",
    ),
    ConfigEntry(
        TPU_HBM_GRACE_BUCKETS,
        "Grace fallback: sub-bucket fan-out per recursion level. When a "
        "hash-join stage's working set exceeds the HBM budget, the build "
        "side is re-split by a secondary hash into this many sub-buckets "
        "per level (buckets^depth total) and the stage kernel runs once per "
        "sub-bucket, sequentially, with probe rows kept in producer order.",
        int, 4, lambda v: v >= 2,
    ),
    ConfigEntry(
        TPU_HBM_GRACE_DEPTH,
        "Grace fallback: max recursion depth of the secondary-hash split "
        "(buckets^depth sub-buckets at the deepest rung). A working set "
        "that still exceeds the budget at this depth demotes the stage to "
        "the CPU engine — the always-correct final rung. 0 disables grace "
        "entirely (over-budget join stages demote straight to CPU).",
        int, 2, _nonneg,
    ),
    ConfigEntry(
        TPU_MESH_ENABLED,
        "Mesh-wide stage execution: the distributed planner merges an "
        "intra-host hash-shuffle producer stage into its single consumer "
        "and ships the merged stage as ONE task spanning the device mesh; "
        "the repartition runs as an on-device all_to_all (MeshExchangeExec) "
        "instead of shuffle files + Flight fetches. Requires "
        "ballista.executor.engine = tpu; stages that don't fit (multiple "
        "consumers, broadcast edges, unsupported dtypes, capacity overflow) "
        "keep or demote to the per-partition path.",
        bool, False,
    ),
    ConfigEntry(
        TPU_MESH_DEVICES,
        "Device-mesh width for mesh-wide stages. 0 = every visible device "
        "(make_mesh falls back to CPU virtual devices when the default "
        "platform has fewer). A mesh below 2 devices demotes the exchange "
        "to the host split.",
        int, 0, _nonneg,
    ),
    ConfigEntry(
        TPU_MESH_EXCHANGE_CAPACITY,
        "Fixed per-(sender, destination) slot capacity of the on-device "
        "all_to_all exchange, in rows. The host-side gate "
        "(require_exchange_capacity) raises ExchangeCapacityExceeded and "
        "demotes the stage when routed rows exceed it — no row is ever "
        "silently truncated.",
        int, 1 << 20, _pos,
    ),
    ConfigEntry(
        TPU_MESH_MIN_ROWS,
        "Below this many producer rows a mesh exchange is not worth the "
        "collective dispatch; the stage demotes to the host split "
        "(mesh_mode_reason = demoted:small-input).",
        int, 0, _nonneg,
    ),
    ConfigEntry(
        TPU_MESH_MAX_INPUT_BYTES,
        "AQE guard: at stage resolution, a mesh exchange whose observed "
        "input stages exceed this many bytes is demoted to the "
        "per-partition path before execution (the fixed-capacity collective "
        "would overflow anyway; skip the wasted dispatch). 0 = no limit.",
        int, 0, _nonneg,
    ),
    ConfigEntry(
        TPU_FILL_THREADS,
        "Host threads encoding scan columns during the device fill. 0 = auto "
        "(pipelined: column k+1 encodes while column k uploads, bounded "
        "in-flight host stacks); 1 = strict serial encode→upload, one column "
        "at a time (the pre-pipeline behavior). Env escape hatch: "
        "BALLISTA_TPU_FILL_THREADS.",
        int, _env_int("BALLISTA_TPU_FILL_THREADS", 0), _nonneg,
    ),
    ConfigEntry(
        TPU_FILL_CHUNK_ROWS,
        "Split each column's [P, N] device upload into row chunks of this "
        "many rows along N (double-buffered device_put: the host releases "
        "each chunk as soon as it is issued and XLA overlaps the copies). "
        "0 = one transfer per column. Ignored under a collective-exchange "
        "mesh (sharded puts stay whole). Env escape hatch: "
        "BALLISTA_TPU_FILL_CHUNK_ROWS.",
        int, _env_int("BALLISTA_TPU_FILL_CHUNK_ROWS", 0), _nonneg,
    ),
    ConfigEntry(
        TPU_COMPILE_OVERLAP,
        "Overlap XLA compilation and join build-side preparation with the "
        "device table fill: the compile key (shapes, dtypes, dict sizes) is "
        "known once every column is encoded, so tracing starts on a "
        "background thread while uploads are still streaming, and build "
        "sides collect concurrently with the probe-side fill. RUN_STATS "
        "reports the hidden seconds as compile_overlap_s. Env escape "
        "hatch: BALLISTA_TPU_COMPILE_OVERLAP=0.",
        bool, _env_bool("BALLISTA_TPU_COMPILE_OVERLAP", True),
    ),
    ConfigEntry(
        TPU_COMPILE_CACHE_DIR,
        "Directory for JAX's persistent (on-disk) XLA compilation cache. "
        "When set, compiled stage programs survive process restarts: a "
        "re-admitted or redeployed executor fetches its XLA binaries from "
        "disk instead of recompiling (RUN_STATS xla_compile_s ~ 0 on warm "
        "starts). Empty = disabled. Env default: BALLISTA_TPU_COMPILE_CACHE "
        "(also honored by bare runtime users with no session config).",
        str, _env_str("BALLISTA_TPU_COMPILE_CACHE", ""),
    ),
    ConfigEntry(
        TPU_DAEMON_ENABLED,
        "Warm device-runtime daemon: when true, TPU stage execution first "
        "tries to attach to the persistent device daemon "
        "(ballista_tpu/device_daemon/) over its unix socket and ship the "
        "stage there — one long-lived process owns the platform init, the "
        "device table cache, the HBM budget, and the persistent XLA compile "
        "cache, so every attached caller skips the cold init. Attach "
        "failure falls back to the in-process engine with the reason in "
        "RUN_STATS daemon_mode/daemon_mode_reason. Off by default: the "
        "in-process engine is unchanged unless a session opts in.",
        bool, False,
    ),
    ConfigEntry(
        TPU_DAEMON_SOCKET,
        "Unix-domain socket path of the device daemon. Empty = the "
        "per-user default under the system temp dir "
        "(ballista-tpu-daemon-<uid>.sock). The daemon's structured init "
        "probe report lives next to the socket at <socket>.probe.json.",
        str, "",
    ),
    ConfigEntry(
        TPU_DAEMON_SPAWN,
        "Spawn-and-adopt: when attach finds no live daemon, start one "
        "(detached, `python -m ballista_tpu.device_daemon`) and attach to "
        "it instead of falling back in-process. The spawned daemon "
        "outlives the client so later processes warm-attach.",
        bool, False,
    ),
    ConfigEntry(
        TPU_DAEMON_ATTACH_TIMEOUT_MS,
        "Milliseconds the daemon client waits for the socket to accept "
        "and answer a ping before falling back to the in-process engine "
        "(also bounds the spawn-and-adopt wait for the socket to appear).",
        int, 2000, _pos,
    ),
    ConfigEntry(
        TPU_DAEMON_SESSION_QUOTA_BYTES,
        "Per-session HBM quota enforced by the daemon's admission layer: "
        "stages shipped by this session are admitted against "
        "min(ballista.tpu.hbm.budget.*, this quota), so one attached "
        "tenant's working set cannot evict every other session's resident "
        "tables — spill/grace decisions become quota-aware. 0 = no "
        "per-session ceiling.",
        int, 0, _nonneg,
    ),
    ConfigEntry(
        TPU_DAEMON_EXECUTE_TIMEOUT_S,
        "Floor (seconds) of the per-request execute deadline both sides of "
        "the daemon protocol enforce: the client derives the actual bound "
        "from the stage's byte estimate (floor + bytes at a pessimistic "
        "16 MiB/s, capped at 8x the floor — "
        "protocol.derive_execute_timeout_s) and ships it in the request "
        "header; the daemon's watchdog kills the process on overrun with a "
        "post-mortem at <socket>.crash.json (all thread stacks, the "
        "offending request header, rusage) so a wedged XLA call cannot "
        "hold the chip hostage. The client waits slightly longer than the "
        "deadline, so the watchdog's diagnosed kill wins the race.",
        int, 120, _pos,
    ),
    ConfigEntry(
        TPU_DAEMON_POISON_TTL_S,
        "Seconds a stage fingerprint stays in the on-disk poison quarantine "
        "(<socket>.poison.json) after crashing "
        "two daemon incarnations. While quarantined, respawned daemons "
        "refuse the stage and clients demote it straight to the "
        "in-process/CPU ladder (RUN_STATS daemon_failover=poisoned) — no "
        "crash loops. After the TTL the stage may try the daemon again.",
        int, 600, _pos,
    ),
    ConfigEntry(
        EXECUTOR_DISK_LOW_WATERMARK,
        "Low disk-pressure watermark: when the used fraction of the "
        "executor work-dir filesystem (shutil.disk_usage) reaches this "
        "level, the executor SHEDS SPILL ADMISSION — the sort-shuffle "
        "writer stops demoting buffers to disk (falling back to the "
        "in-memory overcommit ladder) and the HBM spill pool keeps cold "
        "entries in the host tier instead of taking the disk tier. "
        "Queries keep running; only optional disk writes stop "
        "(docs/lifecycle.md#watermark-ladder).",
        float, 0.90, lambda v: 0.0 < v <= 1.0,
    ),
    ConfigEntry(
        EXECUTOR_DISK_HIGH_WATERMARK,
        "High disk-pressure watermark: at/above this used fraction the "
        "executor REJECTS NEW TASK ADMISSION with a retryable "
        "DiskExhausted (RESOURCE_EXHAUSTED semantics, riding the overload "
        "machinery) — the scheduler re-pends the slice and the "
        "per-executor disk gauges on the heartbeat steer placement toward "
        "nodes with headroom. Must be >= the low watermark.",
        float, 0.95, lambda v: 0.0 < v <= 1.0,
    ),
    ConfigEntry(
        EXECUTOR_DATA_TTL_S,
        "Orphaned-data GC TTL in seconds: the scheduler's fleet sweep "
        "removes scheduler state AND fans RemoveJobData to every live "
        "executor for jobs that have been terminal (successful / failed / "
        "cancelled) longer than this; the executor-local work-dir sweep "
        "uses the same horizon for job directories no live scheduler "
        "claims. 0 disables the scheduler-driven sweep (the executor "
        "work-dir TTL remains the backstop).",
        int, 6 * 3600, _nonneg,
    ),
    ConfigEntry(
        EXECUTOR_DRAIN_TIMEOUT_S,
        "Graceful-drain budget in seconds: how long a drain waits for the "
        "executor's running tasks to finish before giving up and falling "
        "back to the executor-lost recompute path. The shuffle-output "
        "migration that follows the wait is not itself bounded by this "
        "(a partially migrated drain still saves the migrated stages).",
        float, 30.0, _pos,
    ),
    ConfigEntry(
        DEBUG_PLAN_VERIFY,
        "Run the static plan verifier (analysis/plan_check.py) over every "
        "staged plan at submit time and after each AQE replan, failing the "
        "job with PlanVerificationError on an invariant violation (stage-"
        "boundary schema mismatch, partition-count drift on a shuffle edge, "
        "mesh gating, task-id band collisions) instead of executing a "
        "corrupt DAG. Cheap (pure graph walk, no IO) but off by default; "
        "plan-stability tests run it unconditionally. Env escape hatch: "
        "BALLISTA_PLAN_VERIFY=1.",
        bool, _env_bool("BALLISTA_PLAN_VERIFY", False),
    ),
]

VALID_ENTRIES: dict[str, ConfigEntry] = {e.name: e for e in _ENTRIES}


@dataclass(frozen=True)
class EnvKnob:
    """An environment-only knob: read by a daemon at import/startup time,
    with no session-config equivalent (session config arrives after the
    value is needed — e.g. module-cache sizing, native-lib discovery).
    Registered here so the knob-sync analysis pass can verify every
    BALLISTA_* env read maps to something documented; entries render into
    docs/configs.md alongside the session keys."""

    name: str
    description: str
    ty: type
    default: Any


_ENV_KNOBS: list[EnvKnob] = [
    EnvKnob(
        "BALLISTA_NATIVE_LIB",
        "Explicit path to the native kernels .so (ops/native.py); unset = "
        "discover next to the package, missing = numpy fallback.",
        str, "",
    ),
    EnvKnob(
        "BALLISTA_DEVICE_ORDINAL",
        "Pin this executor's TPU device ordinal (-1 = auto). Read once at "
        "executor startup, before any session config exists.",
        int, -1,
    ),
    EnvKnob(
        "BALLISTA_TPU_COMPILE_CACHE_ENTRIES",
        "Entry cap of the in-process compiled-stage LruDict in the TPU "
        "stage compiler (import-time sizing).",
        int, 64,
    ),
    EnvKnob(
        "BALLISTA_TPU_LUT_CACHE_ENTRIES",
        "Entry cap of the device lookup-table LruDict (dictionary-encoded "
        "string columns) in the TPU stage compiler.",
        int, 256,
    ),
    EnvKnob(
        "BALLISTA_TPU_BUILD_CACHE_ENTRIES",
        "Entry cap of the join build-table LruDict in the TPU stage compiler.",
        int, 32,
    ),
    EnvKnob(
        "BALLISTA_TPU_BUILD_CACHE_BYTES",
        "Byte budget of the join build-table LruDict (HBM-resident arrays).",
        int, 2 * 1024**3,
    ),
    EnvKnob(
        "BALLISTA_TPU_FINAL_CACHE_ENTRIES",
        "Entry cap of the final-stage program LruDict (ops/tpu/final_stage.py).",
        int, 64,
    ),
    EnvKnob(
        "BALLISTA_TPU_DAEMON_INIT_TIMEOUT_S",
        "Per-phase ceiling (seconds) of the device daemon's supervised init "
        "state machine (platform probe → jax.devices() → first compile). A "
        "phase that overruns gets a faulthandler stack snapshot written "
        "into the probe report at <socket>.probe.json, then the daemon "
        "exits — a hung platform claim is diagnosed, never waited out.",
        int, 240,
    ),
    EnvKnob(
        "BALLISTA_CHAOS_DRAIN_KILL_AFTER",
        "chaos mode=drain_kill arming: abort a graceful drain's shuffle-"
        "output migration after this many committed locations (simulating "
        "a hard kill mid-drain; the scheduler falls back to the executor-"
        "lost recompute path). 0 = disarmed. Env-only: the migration runs "
        "in scheduler/launcher context, which has no session config.",
        int, 0,
    ),
    EnvKnob(
        "BALLISTA_BENCH_DAEMON_CHAOS",
        "bench.py opt-in: run dev/daemon_chaos_exercise.py --quick in the "
        "device leg as a sanity probe before the timed iterations (the "
        "daemon failure domain must hold on this machine; divergence fails "
        "the leg). Env-only: bench plumbing, not engine config.",
        bool, False,
    ),
    EnvKnob(
        "BALLISTA_BENCH_LIFECYCLE",
        "bench.py opt-in: run dev/lifecycle_exercise.py --quick (graceful "
        "drain / disk_full / rolling-restart smoke, docs/lifecycle.md) and "
        "record the verdict under lifecycle_smoke in the bench artifact. "
        "Env-only: bench plumbing, not engine config.",
        bool, False,
    ),
    EnvKnob(
        "BALLISTA_TPU_DAEMON_IDLE_TIMEOUT_S",
        "Device daemon self-termination after this many seconds with no "
        "request and no live parent (--parent-pid). 0 = persist forever "
        "(the default: a warm daemon is the point).",
        int, 0,
    ),
]

ENV_KNOBS: dict[str, EnvKnob] = {k.name: k for k in _ENV_KNOBS}

# Keys a remote client may NOT override on the shared daemons
# (reference: restricted-config scrubbing, extension.rs:302).
RESTRICTED_KEYS = frozenset({GRPC_SERVER_MAX_MESSAGE_SIZE})


class BallistaConfig:
    """Validated session config; unknown `ballista.*` keys are rejected,
    other namespaces (e.g. datafusion-style passthrough) are carried opaque.
    """

    def __init__(self, settings: dict[str, Any] | None = None):
        self._settings: dict[str, Any] = {}
        self._extra: dict[str, str] = {}
        for k, v in (settings or {}).items():
            self.set(k, v)

    def set(self, key: str, value: Any) -> "BallistaConfig":
        entry = VALID_ENTRIES.get(key)
        if entry is not None:
            self._settings[key] = entry.parse(value)
        elif key.startswith("ballista.catalog.") or key.startswith("ballista.udf."):
            # open namespaces: table registrations / UDF module references
            # shipped with the session
            self._extra[key] = str(value)
        elif key.startswith("ballista."):
            raise ConfigurationError(f"unknown config key: {key}")
        else:
            self._extra[key] = str(value)
        return self

    def set_default_if_unset(self, key: str, value: Any) -> None:
        """Apply a host-derived default without overriding an explicit
        session setting (executor-side memory sizing)."""
        if key not in self._settings:
            self.set(key, value)

    def get(self, key: str) -> Any:
        if key in self._settings:
            return self._settings[key]
        entry = VALID_ENTRIES.get(key)
        if entry is not None:
            return entry.default
        return self._extra.get(key)

    def __getitem__(self, key: str) -> Any:
        return self.get(key)

    # -- wire round-trip (reference: extension.rs:293-302) ------------------

    def to_key_value_pairs(self) -> list[tuple[str, str]]:
        out = [(k, _fmt(v)) for k, v in sorted(self._settings.items())]
        out.extend(sorted(self._extra.items()))
        return out

    @classmethod
    def from_key_value_pairs(
        cls, pairs: list[tuple[str, str]], scrub_restricted: bool = False
    ) -> "BallistaConfig":
        cfg = cls()
        for k, v in pairs:
            if scrub_restricted and k in RESTRICTED_KEYS:
                continue
            cfg.set(k, v)
        return cfg

    def copy(self) -> "BallistaConfig":
        c = BallistaConfig()
        c._settings = dict(self._settings)
        c._extra = dict(self._extra)
        return c

    def shape_buckets(self) -> list[int]:
        return sorted(int(x) for x in str(self.get(TPU_SHAPE_BUCKETS)).split(",") if x.strip())

    def __repr__(self) -> str:
        return f"BallistaConfig({self._settings!r})"


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def generate_config_docs() -> str:
    """Docs-as-code: render the registry as markdown
    (reference: core/src/bin/update_config_docs.rs → docs/.../configs.md).
    """
    lines = [
        "# Configuration keys",
        "",
        "<!-- GENERATED FILE — do not edit by hand. -->",
        "<!-- Rendered from the config.py registry by dev/gen_configs.py; -->",
        "<!-- the knob-sync analysis pass fails CI when this file is stale. -->",
        "",
        "All keys are set per-session and shipped with every job as key/value",
        "pairs; executors apply them when building the task's runtime.",
        "",
        "| key | type | default | description |",
        "|-----|------|---------|-------------|",
    ]
    for e in _ENTRIES:
        lines.append(f"| `{e.name}` | {e.ty.__name__} | `{_fmt(e.default)}` | {e.description} |")
    lines.extend([
        "",
        "## Environment-only knobs",
        "",
        "Read by daemons at import/startup time, before any session config",
        "exists; no `ballista.*` equivalent. (Env *escape hatches* for session",
        "keys are documented inline in the table above.)",
        "",
        "| variable | type | default | description |",
        "|----------|------|---------|-------------|",
    ])
    for k in _ENV_KNOBS:
        lines.append(f"| `{k.name}` | {k.ty.__name__} | `{_fmt(k.default)}` | {k.description} |")
    lines.append("")
    return "\n".join(lines)
