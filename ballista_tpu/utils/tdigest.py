"""T-Digest quantile sketch (merging-digest variant).

Rebuild of the sketch the reference's RuntimeStatsExec keeps per partition
(core/src/execution_plans/runtime_stats.rs:77) to drive the dynamic range
repartitioner's quantile cuts. Mergeable across partitions; serializable
(sketch_to_proto analog via to_list/from_list).
"""

from __future__ import annotations

import math

import numpy as np


class TDigest:
    def __init__(self, compression: int = 200):
        self.compression = compression
        self.means: np.ndarray = np.zeros(0)
        self.weights: np.ndarray = np.zeros(0)
        self._buf_means: list[float] = []
        self._buf_weights: list[float] = []

    def add_array(self, values: np.ndarray) -> None:
        v = values[~np.isnan(values)] if values.dtype.kind == "f" else values
        if len(v) == 0:
            return
        # pre-cluster large inputs cheaply: sort + fixed-size chunks
        v = np.sort(v.astype(np.float64))
        chunk = max(1, len(v) // (self.compression * 4))
        if chunk > 1:
            usable = len(v) - len(v) % chunk
            m = v[:usable].reshape(-1, chunk).mean(axis=1)
            w = np.full(len(m), chunk, dtype=np.float64)
            if usable < len(v):
                m = np.append(m, v[usable:].mean())
                w = np.append(w, len(v) - usable)
        else:
            m, w = v, np.ones(len(v))
        self._buf_means.extend(m.tolist())
        self._buf_weights.extend(w.tolist())
        if len(self._buf_means) > self.compression * 8:
            self._compress()

    def merge(self, other: "TDigest") -> None:
        other._compress()
        self._buf_means.extend(other.means.tolist())
        self._buf_weights.extend(other.weights.tolist())
        self._compress()

    def _compress(self) -> None:
        means = np.concatenate([self.means, np.array(self._buf_means)])
        weights = np.concatenate([self.weights, np.array(self._buf_weights)])
        self._buf_means, self._buf_weights = [], []
        if len(means) == 0:
            return
        order = np.argsort(means)
        means, weights = means[order], weights[order]
        total = weights.sum()
        out_m: list[float] = []
        out_w: list[float] = []
        cum = 0.0
        cur_m, cur_w = means[0], weights[0]
        for m, w in zip(means[1:], weights[1:]):
            q = (cum + cur_w / 2) / total
            limit = 4 * total * q * (1 - q) / self.compression
            if cur_w + w <= max(limit, 1.0):
                cur_m = (cur_m * cur_w + m * w) / (cur_w + w)
                cur_w += w
            else:
                out_m.append(cur_m)
                out_w.append(cur_w)
                cum += cur_w
                cur_m, cur_w = m, w
        out_m.append(cur_m)
        out_w.append(cur_w)
        self.means = np.array(out_m)
        self.weights = np.array(out_w)

    @property
    def count(self) -> float:
        return float(self.weights.sum() + sum(self._buf_weights))

    def quantile(self, q: float) -> float:
        self._compress()
        if len(self.means) == 0:
            return math.nan
        if len(self.means) == 1:
            return float(self.means[0])
        cum = np.cumsum(self.weights) - self.weights / 2
        target = q * self.weights.sum()
        return float(np.interp(target, cum, self.means))

    def quantile_cuts(self, k: int) -> list[float]:
        """k-1 cut points splitting the distribution into k even ranges."""
        return [self.quantile((i + 1) / k) for i in range(k - 1)]

    # -- serde (sketch_to_proto analog) -------------------------------------

    def to_list(self) -> list[list[float]]:
        self._compress()
        return [self.means.tolist(), self.weights.tolist()]

    @classmethod
    def from_list(cls, data: list[list[float]], compression: int = 200) -> "TDigest":
        d = cls(compression)
        d.means = np.array(data[0])
        d.weights = np.array(data[1])
        return d
