"""Graphviz visualization of stage DAGs.

Rebuild of ExecutionGraphDot (scheduler/src/state/execution_graph_dot.rs:47)
and the core diagram helper (core/src/diagram.rs:43): render a job's stage
graph (and each stage's operator tree) as dot text for the REST API /
EXPLAIN tooling.
"""

from __future__ import annotations

_STATE_COLORS = {
    "unresolved": "lightgray",
    "resolved": "lightyellow",
    "running": "lightblue",
    "successful": "lightgreen",
    "failed": "lightcoral",
}


def _esc(s: str) -> str:
    return s.replace('"', '\\"').replace("\n", "\\l")


def graph_to_dot(graph) -> str:
    """graph: scheduler.state.execution_graph.ExecutionGraph"""
    lines = [
        "digraph G {",
        "  rankdir=BT;",
        f'  label="job {graph.job_id} [{graph.status.value}]";',
        "  node [shape=box, style=filled];",
    ]
    for sid in sorted(graph.stages):
        s = graph.stages[sid]
        color = _STATE_COLORS.get(s.state.value, "white")
        summary = s.spec.plan.node_str()
        lines.append(
            f'  stage_{sid} [label="stage {sid}\\n{_esc(summary)}\\n'
            f"{s.state.value} {len(s.completed)}/{s.spec.partitions} parts\", fillcolor={color}];"
        )
    for sid, outs in graph.output_links.items():
        for o in outs:
            lines.append(f"  stage_{sid} -> stage_{o};")
    lines.append("}")
    return "\n".join(lines)


def plan_to_dot(plan) -> str:
    """Operator-tree dot for one physical plan (diagram.rs analog)."""
    lines = ["digraph P {", "  node [shape=box];"]
    counter = [0]

    def walk(node) -> int:
        my = counter[0]
        counter[0] += 1
        lines.append(f'  n{my} [label="{_esc(node.node_str())}"];')
        for c in node.children():
            ci = walk(c)
            lines.append(f"  n{ci} -> n{my};")
        return my

    walk(plan)
    lines.append("}")
    return "\n".join(lines)
