"""Bounded LRU mapping for module-level caches.

Lived in ops/tpu/stage_compiler.py through PR 8, but CPU-side modules
(shuffle reader, physical planner) need the same discipline and must NOT
import the stage compiler to get it: the executor heartbeat keys its TPU
gauges on `sys.modules.get("ballista_tpu.ops.tpu.stage_compiler")`, so an
import from the CPU path would make every executor look TPU-resident.
stage_compiler re-exports this class for back-compat.

The bounded-cache analysis pass requires every module-level mutable cache
to be one of these (or carry an explicit suppression with a reason).
"""

from __future__ import annotations

import threading


class LruDict:
    """Thread-safe LRU mapping with an entry cap and an optional byte budget
    (`sizer(value)` → bytes). Long-lived executor sessions touch unbounded
    stage populations; module caches must evict, not leak."""

    def __init__(self, max_entries: int, max_bytes: int = 0, sizer=None):
        import collections

        self._od: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = int(max_bytes)
        self._sizer = sizer
        self._bytes = 0
        self.evictions = 0

    def get(self, key, default=None):
        with self._lock:
            try:
                self._od.move_to_end(key)
            except KeyError:
                return default
            return self._od[key][0]

    def __getitem__(self, key):
        _MISS = object()
        got = self.get(key, _MISS)
        if got is _MISS:
            raise KeyError(key)
        return got

    def __setitem__(self, key, value) -> None:
        size = int(self._sizer(value)) if self._sizer else 0
        with self._lock:
            old = self._od.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._od[key] = (value, size)
            self._bytes += size
            while len(self._od) > self.max_entries or (
                self.max_bytes and self._bytes > self.max_bytes and len(self._od) > 1
            ):
                _, (_, sz) = self._od.popitem(last=False)
                self._bytes -= sz
                self.evictions += 1

    def setdefault(self, key, default):
        """Atomic get-or-insert (the shuffle fetch governor keys per
        (address, limits) and must hand every caller the same instance)."""
        size = int(self._sizer(default)) if self._sizer else 0
        with self._lock:
            try:
                self._od.move_to_end(key)
                return self._od[key][0]
            except KeyError:
                pass
            self._od[key] = (default, size)
            self._bytes += size
            while len(self._od) > self.max_entries or (
                self.max_bytes and self._bytes > self.max_bytes and len(self._od) > 1
            ):
                _, (_, sz) = self._od.popitem(last=False)
                self._bytes -= sz
                self.evictions += 1
            return default

    def pop(self, key, default=None):
        """Remove and return an entry without counting it as an eviction
        (callers that fold data elsewhere first — e.g. delta compaction —
        own the removal; `evictions` stays a pure pressure signal)."""
        with self._lock:
            got = self._od.pop(key, None)
            if got is None:
                return default
            self._bytes -= got[1]
            return got[0]

    def items(self) -> list:
        """Point-in-time [(key, value)] snapshot (LRU → MRU order) without
        touching recency — observability reads must not distort eviction."""
        with self._lock:
            return [(k, v[0]) for k, v in self._od.items()]

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._od

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def clear(self) -> None:
        with self._lock:
            self._od.clear()
            self._bytes = 0
