"""Daemon logging init with rotation.

Rebuild of the reference's tracing-appender setup (LogRotationPolicy
minutely/hourly/daily/never, core/src/config.rs:898): both daemons log to
stderr by default; with --log-file they also write a rotating file so a
long-lived scheduler/executor can't fill its disk with one unbounded log.
"""

from __future__ import annotations

import logging
import logging.handlers

ROTATION_POLICIES = ("never", "minutely", "hourly", "daily")

_WHEN = {"minutely": "M", "hourly": "H", "daily": "midnight"}
_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"


def init_logging(level: str = "INFO", log_file: str | None = None,
                 rotation: str = "daily", backups: int = 7) -> None:
    handlers: list[logging.Handler] = [logging.StreamHandler()]
    if log_file:
        if rotation == "never":
            fh: logging.Handler = logging.FileHandler(log_file)
        else:
            if rotation not in _WHEN:
                raise ValueError(f"log rotation must be one of {ROTATION_POLICIES}")
            fh = logging.handlers.TimedRotatingFileHandler(
                log_file, when=_WHEN[rotation], backupCount=backups
            )
        handlers.append(fh)
    logging.basicConfig(level=level, format=_FORMAT, handlers=handlers, force=True)
