"""gRPC channel/server construction with tuned options.

Rebuild of GrpcClientConfig / GrpcServerConfig +
create_grpc_client_endpoint / create_grpc_server
(core/src/utils.rs:59,133,308,344): message-size ceilings and keepalive
applied consistently everywhere a channel or server is built, driven by
the same `ballista.grpc.*` session keys the reference uses.
"""

from __future__ import annotations

import grpc

from ballista_tpu.config import (
    GRPC_CLIENT_MAX_MESSAGE_SIZE,
    GRPC_SERVER_MAX_MESSAGE_SIZE,
    GRPC_TLS_CA,
    GRPC_TLS_CERT,
    GRPC_TLS_KEY,
    BallistaConfig,
)

KEEPALIVE_MS = 30_000
KEEPALIVE_TIMEOUT_MS = 10_000


def client_options(config: BallistaConfig | None = None) -> list[tuple]:
    n = int((config or BallistaConfig()).get(GRPC_CLIENT_MAX_MESSAGE_SIZE))
    return [
        ("grpc.max_send_message_length", n),
        ("grpc.max_receive_message_length", n),
        ("grpc.keepalive_time_ms", KEEPALIVE_MS),
        ("grpc.keepalive_timeout_ms", KEEPALIVE_TIMEOUT_MS),
        ("grpc.keepalive_permit_without_calls", 1),
    ]


def server_options(config: BallistaConfig | None = None) -> list[tuple]:
    n = int((config or BallistaConfig()).get(GRPC_SERVER_MAX_MESSAGE_SIZE))
    return [
        ("grpc.max_send_message_length", n),
        ("grpc.max_receive_message_length", n),
        ("grpc.keepalive_time_ms", KEEPALIVE_MS),
        ("grpc.keepalive_timeout_ms", KEEPALIVE_TIMEOUT_MS),
    ]


def _read(path: str | None) -> bytes | None:
    if not path:
        return None
    with open(path, "rb") as f:
        return f.read()


def create_channel(addr: str, config: BallistaConfig | None = None) -> grpc.Channel:
    """TLS when the session carries a CA (ballista.grpc.tls.ca.path);
    cert+key additionally enable mTLS client auth. Plaintext otherwise —
    the reference's GrpcClientConfig TLS switch (core/src/utils.rs:59)."""
    cfg = config or BallistaConfig()
    ca = _read(str(cfg.get(GRPC_TLS_CA) or ""))
    if ca:
        creds = grpc.ssl_channel_credentials(
            root_certificates=ca,
            private_key=_read(str(cfg.get(GRPC_TLS_KEY) or "")),
            certificate_chain=_read(str(cfg.get(GRPC_TLS_CERT) or "")),
        )
        return grpc.secure_channel(addr, creds, options=client_options(cfg))
    return grpc.insecure_channel(addr, options=client_options(cfg))


def bind_server_port(server: grpc.Server, bind: str,
                     tls_cert: str | None = None, tls_key: str | None = None,
                     tls_client_ca: str | None = None) -> int:
    """add_secure_port when a server cert is configured (client CA →
    REQUIRED client certs = mTLS); add_insecure_port otherwise."""
    if tls_cert and tls_key:
        creds = grpc.ssl_server_credentials(
            [(_read(tls_key), _read(tls_cert))],
            root_certificates=_read(tls_client_ca),
            require_client_auth=bool(tls_client_ca),
        )
        return server.add_secure_port(bind, creds)
    return server.add_insecure_port(bind)
