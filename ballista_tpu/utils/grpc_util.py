"""gRPC channel/server construction with tuned options.

Rebuild of GrpcClientConfig / GrpcServerConfig +
create_grpc_client_endpoint / create_grpc_server
(core/src/utils.rs:59,133,308,344): message-size ceilings and keepalive
applied consistently everywhere a channel or server is built, driven by
the same `ballista.grpc.*` session keys the reference uses.
"""

from __future__ import annotations

import grpc

from ballista_tpu.config import (
    GRPC_CLIENT_MAX_MESSAGE_SIZE,
    GRPC_SERVER_MAX_MESSAGE_SIZE,
    BallistaConfig,
)

KEEPALIVE_MS = 30_000
KEEPALIVE_TIMEOUT_MS = 10_000


def client_options(config: BallistaConfig | None = None) -> list[tuple]:
    n = int((config or BallistaConfig()).get(GRPC_CLIENT_MAX_MESSAGE_SIZE))
    return [
        ("grpc.max_send_message_length", n),
        ("grpc.max_receive_message_length", n),
        ("grpc.keepalive_time_ms", KEEPALIVE_MS),
        ("grpc.keepalive_timeout_ms", KEEPALIVE_TIMEOUT_MS),
        ("grpc.keepalive_permit_without_calls", 1),
    ]


def server_options(config: BallistaConfig | None = None) -> list[tuple]:
    n = int((config or BallistaConfig()).get(GRPC_SERVER_MAX_MESSAGE_SIZE))
    return [
        ("grpc.max_send_message_length", n),
        ("grpc.max_receive_message_length", n),
        ("grpc.keepalive_time_ms", KEEPALIVE_MS),
        ("grpc.keepalive_timeout_ms", KEEPALIVE_TIMEOUT_MS),
    ]


def create_channel(addr: str, config: BallistaConfig | None = None) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=client_options(config))
