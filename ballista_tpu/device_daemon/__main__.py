"""CLI: `python -m ballista_tpu.device_daemon --socket /path.sock`.

Runs the warm device-runtime daemon in the foreground (spawn-and-adopt
clients detach it themselves via start_new_session). Exit codes: 0 clean
shutdown, 2 socket already owned by a live daemon, 3 init phase timed
out (probe report + stack snapshot at <socket>.probe.json), 4 execute
watchdog killed a wedged request (post-mortem with the offending request
header and all thread stacks at <socket>.crash.json)."""

from __future__ import annotations

import argparse
import logging
import sys

from ballista_tpu.device_daemon import protocol
from ballista_tpu.device_daemon.server import DaemonServer, serve_flight


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ballista_tpu.device_daemon")
    ap.add_argument("--socket", default=protocol.default_socket_path())
    ap.add_argument("--parent-pid", type=int, default=0,
                    help="exit when this pid dies (bench legs, tests); "
                         "0 = no parent watch")
    ap.add_argument("--device-ordinal", type=int, default=-1,
                    help="pin the daemon's chip via bind_process_ordinal "
                         "before jax init; -1 = unpinned")
    ap.add_argument("--idle-timeout-s", type=int, default=None,
                    help="override BALLISTA_TPU_DAEMON_IDLE_TIMEOUT_S")
    ap.add_argument("--flight-port", type=int, default=0,
                    help="also serve Flight do_exchange on this port "
                         "(0 = UDS only)")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s daemon %(message)s")
    kw = {}
    if args.idle_timeout_s is not None:
        kw["idle_timeout_s"] = args.idle_timeout_s
    server = DaemonServer(args.socket, parent_pid=args.parent_pid,
                          device_ordinal=args.device_ordinal, **kw)
    try:
        if args.flight_port:
            serve_flight(server, args.flight_port)
        return server.serve_forever()
    except RuntimeError as e:
        print(f"device_daemon: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
