"""Warm device-runtime daemon: one persistent process owns the TPU.

Every process that inits the TPU platform pays the full claim + backend
init + XLA compile cost — and on this pool the claim itself has hung for
entire bench rounds. This package moves device ownership into ONE
long-lived daemon process (`python -m ballista_tpu.device_daemon`): it
inits the platform once behind a supervised, phase-instrumented state
machine, owns the device table cache / HBM budget / persistent XLA
compile cache, and serves stage execution to any local client over a
unix-domain socket (Arrow IPC framing; a Flight do_exchange variant
exists where the Flight stack is importable).

Executors, dev exercises, and bench.py attach instead of initing:
`client.attach(config)` under the `ballista.tpu.daemon.*` knobs, with
in-process execution as the always-available fallback (the reason lands
in RUN_STATS daemon_mode/daemon_mode_reason). See docs/device_daemon.md.

Import discipline: this package's `client` module must stay importable
without jax (it is reached from executor/scheduler-adjacent code that
the jax-guard analysis pass keeps off the jax import graph); only
`server` touches the device runtime, and only inside functions.
"""
