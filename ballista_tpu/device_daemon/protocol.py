"""Wire protocol between the device daemon and its clients.

Framing on the unix-domain socket — every message, both directions, is

    [4-byte big-endian header length][JSON header][body bytes]

where the header declares its body's length under ``body_len`` (0 when
absent). Headers are small JSON dicts (op, session, partitions, stats);
bodies carry the bulk bytes: a serde-encoded physical plan on an
``execute`` request, concatenated Arrow IPC streams (one per partition,
offsets in the header's ``segments``) on its response. Keeping the
header out-of-band of the Arrow payload means a client can parse an
error response without touching pyarrow, and the daemon can route a
request before the plan bytes are decoded.

Requests are one message; responses are one message; connections are
per-request (unix sockets make connect ~free, and it keeps a crashed
client from wedging a daemon-side stream parser mid-frame).

Ops: ``ping`` (liveness, answered during init), ``status`` (init phase
report + session/queue/cache counters), ``execute`` (run one stage),
``clear_caches`` (evict daemon-resident device state), ``shutdown``.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import tempfile

# bump when the header schema changes incompatibly; a daemon refuses
# mismatched clients loudly instead of mis-parsing their frames
PROTOCOL_VERSION = 1

_LEN = struct.Struct(">I")
# a header is a few KB of JSON; anything bigger is a framing bug, not a
# request — refuse before allocating
MAX_HEADER_BYTES = 4 * 1024 * 1024


class ProtocolError(RuntimeError):
    """Malformed frame or peer hangup mid-message."""


def default_socket_path() -> str:
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"ballista-tpu-daemon-{uid}.sock")


def probe_report_path(socket_path: str) -> str:
    """The daemon's structured init report lives NEXT TO the socket so a
    watcher can diagnose a hung init without a live daemon to ask."""
    return socket_path + ".probe.json"


def crash_report_path(socket_path: str) -> str:
    """The execute watchdog's post-mortem artifact: written by a daemon
    whose in-flight request overran its deadline, immediately before the
    process exits. Next to the socket for the same reason as the probe
    report — the corpse must be readable without a live daemon."""
    return socket_path + ".crash.json"


def poison_path(socket_path: str) -> str:
    """The on-disk poison-stage quarantine shared by every client of this
    socket AND by respawned daemons (which refuse quarantined stages):
    {tag: {crashes, updated, fingerprint}} with TTL'd entries."""
    return socket_path + ".poison.json"


def daemon_log_path(socket_path: str) -> str:
    return socket_path + ".log"


def derive_execute_timeout_s(floor_s: float, est_bytes: int) -> float:
    """The execute deadline both sides agree on: the knob
    `ballista.tpu.daemon.execute.timeout.s` is the floor for small stages,
    the bound grows with the stage's estimated bytes at a pessimistic
    16 MiB/s (encode + upload + XLA compile + exec all counted), and the
    same knob caps the growth at 8x — a wedged XLA call must trip the
    watchdog in bounded time no matter how big the stage claimed to be."""
    floor_s = max(1.0, float(floor_s))
    derived = floor_s + max(0, int(est_bytes)) / float(16 * 1024 * 1024)
    return min(derived, floor_s * 8.0)


def send_msg(sock: socket.socket, header: dict, body: bytes = b"") -> None:
    header = dict(header)
    header["body_len"] = len(body)
    hb = json.dumps(header).encode()
    sock.sendall(_LEN.pack(len(hb)) + hb + body)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ProtocolError(f"peer closed mid-message ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    hlen = _LEN.unpack(recv_exact(sock, _LEN.size))[0]
    if hlen > MAX_HEADER_BYTES:
        raise ProtocolError(f"header length {hlen} exceeds {MAX_HEADER_BYTES}")
    header = json.loads(recv_exact(sock, hlen).decode())
    body = recv_exact(sock, int(header.get("body_len", 0)))
    return header, body


def batches_to_ipc(batches, schema) -> bytes:
    """One partition's batches as one Arrow IPC stream (zero batches is a
    valid stream: schema only — an empty partition round-trips as empty)."""
    import io

    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as w:
        for b in batches:
            w.write_batch(b)
    return sink.getvalue()


def ipc_to_batches(buf: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(pa.py_buffer(buf)) as r:
        return list(r)


def pack_results(results: dict) -> tuple[list, bytes]:
    """{partition: [batches]} → (segments, body). Segments are
    [partition, offset, length] triples into the concatenated body."""
    segments: list = []
    chunks: list[bytes] = []
    off = 0
    for part in sorted(results):
        batches = results[part]
        if batches:
            schema = batches[0].schema
        else:
            # an empty partition still needs a schema to frame a stream;
            # borrow any sibling's (all partitions share the stage schema)
            schema = next((bs[0].schema for bs in results.values() if bs), None)
            if schema is None:
                segments.append([part, off, 0])
                continue
        buf = batches_to_ipc(batches, schema)
        segments.append([part, off, len(buf)])
        chunks.append(buf)
        off += len(buf)
    return segments, b"".join(chunks)


def unpack_results(segments: list, body: bytes) -> dict:
    out: dict = {}
    for part, off, length in segments:
        out[int(part)] = ipc_to_batches(body[off:off + length]) if length else []
    return out
