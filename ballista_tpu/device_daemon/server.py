"""The device-runtime daemon process.

One long-lived process owns the TPU: it binds its unix socket FIRST
(ping/status answer while init is still running — a watcher can follow
the claim phase by phase), then runs platform init as a supervised,
phase-instrumented state machine:

    platform_probe   import jax + configure the runtime (fast, pure host)
    jax_devices      jax.devices() — the backend claim; THE statement
                     that has hung whole bench rounds on this pool
    first_compile    a tiny jitted matmul through XLA end-to-end

Each phase runs under a bounded wall-clock ceiling
(BALLISTA_TPU_DAEMON_INIT_TIMEOUT_S). The probe report at
<socket>.probe.json is rewritten (tmp+rename) on every transition, so
the on-disk record always names the phase in flight and how long it has
been there. On overrun the supervisor dumps every thread's stack into
the report via faulthandler and exits the process: a hang inside a C
extension cannot be cancelled, so the honest move is to die with a
diagnosis instead of holding the socket open forever.

After init the daemon serves stage execution: a client ships a
serde-encoded raw stage subtree + its session config; the daemon runs it
through the SAME maybe_compile_tpu entry the in-process engine uses
(byte parity by construction), under the client session's HBM quota
(hbm.session_quota), and streams the result batches back as Arrow IPC.
Device dispatch is serialized — one stage on the device at a time — and
the wait count is exported as daemon_queue_depth.

The RUNTIME failure domain mirrors the init one
(docs/device_daemon.md#failure-domain): every execute runs under a
per-request watchdog whose deadline the client derived from the stage's
byte estimate (protocol.derive_execute_timeout_s, floored/capped by
ballista.tpu.daemon.execute.timeout.s). A request that overruns is
wedged inside an uncancellable XLA call, so the watchdog dumps every
thread's stack plus the offending request header into
<socket>.crash.json and exits nonzero — the chip must not be held
hostage. A boot GENERATION token minted at bind time is echoed in every
ping/status/execute response: clients key their attach cache on it
(recycled pids cannot alias daemons) and the serving tier's leases
carry it to fence direct dispatch against a silently restarted daemon.
Stages quarantined in <socket>.poison.json by a client that watched
them kill two daemon incarnations are refused outright — a respawned
daemon never crash-loops on a poison stage.
"""

from __future__ import annotations

import contextlib
import faulthandler
import io
import json
import logging
import os
import socket
import sys
import threading
import time
import traceback

from ballista_tpu.device_daemon import protocol

log = logging.getLogger(__name__)

INIT_PHASES = ("platform_probe", "jax_devices", "first_compile")
_INIT_TIMEOUT_S = int(os.environ.get("BALLISTA_TPU_DAEMON_INIT_TIMEOUT_S", "240"))
_IDLE_TIMEOUT_S = int(os.environ.get("BALLISTA_TPU_DAEMON_IDLE_TIMEOUT_S", "0"))
# a session with no execute for this long is dropped from the registry
SESSION_TTL_S = 300.0


class DaemonServer:
    def __init__(self, socket_path: str, *, parent_pid: int = 0,
                 device_ordinal: int = -1, work_dir: str = "",
                 init_timeout_s: int = _INIT_TIMEOUT_S,
                 idle_timeout_s: int = _IDLE_TIMEOUT_S):
        self.socket_path = socket_path
        self.report_path = protocol.probe_report_path(socket_path)
        self.crash_path = protocol.crash_report_path(socket_path)
        self.parent_pid = parent_pid
        self.device_ordinal = device_ordinal
        self.work_dir = work_dir or os.path.join(
            os.path.dirname(socket_path) or ".", "daemon_work")
        self.init_timeout_s = init_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.started_at = time.time()
        self.last_request_at = time.time()
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        # init state machine
        self._init_lock = threading.Lock()
        self._phases: dict[str, dict] = {
            p: {"name": p, "status": "pending", "s": 0.0} for p in INIT_PHASES}
        self._phase_started_at = 0.0
        self._current_phase: str | None = None
        self._init_ok = False
        self._init_error: str | None = None
        self._init_done = threading.Event()
        self._probe_extra: dict = {}
        # execution
        self._exec_lock = threading.Lock()  # one stage on the device at a time
        self._queue_depth = 0
        self._counters_lock = threading.Lock()
        self.execute_count = 0
        self.clear_count = 0
        self._sessions: dict[str, dict] = {}
        # boot generation token: minted at bind, echoed in every response.
        # Empty until the socket is bound — a daemon that never owned the
        # address has no incarnation to name.
        self.generation = ""
        # per-request execute watchdog: in-flight requests keyed by a
        # monotonic id; the watchdog thread kills the process (with a
        # crash artifact) when one overruns its deadline
        self._inflight_lock = threading.Lock()
        self._inflight: dict[int, dict] = {}
        self._inflight_seq = 0

    # ---------------------------------------------------------- init phases

    def _phase(self, name: str):
        server = self

        class _Scope:
            def __enter__(self):
                with server._init_lock:
                    server._current_phase = name
                    server._phase_started_at = time.time()
                    server._phases[name]["status"] = "running"
                server._write_report()
                return self

            def __exit__(self, et, ev, tb):
                dt = time.time() - server._phase_started_at
                with server._init_lock:
                    server._phases[name]["s"] = round(dt, 3)
                    server._phases[name]["status"] = "error" if et else "ok"
                    if et:
                        server._phases[name]["error"] = f"{et.__name__}: {ev}"[:500]
                    server._current_phase = None
                server._write_report()
                return False

        return _Scope()

    def _init_main(self) -> None:
        try:
            with self._phase("platform_probe"):
                from ballista_tpu.ops.tpu import runtime

                if self.device_ordinal >= 0:
                    runtime.bind_process_ordinal(self.device_ordinal)
                jax = runtime.ensure_jax()
                self._probe_extra["jax_version"] = getattr(jax, "__version__", "?")
                self._probe_extra["jax_platforms"] = (
                    os.environ.get("JAX_PLATFORMS") or "(default)")
            with self._phase("jax_devices"):
                devs = jax.devices()
                d = devs[0]
                self._probe_extra["platform"] = d.platform
                self._probe_extra["device_kind"] = d.device_kind
                self._probe_extra["device_count"] = len(devs)
            with self._phase("first_compile"):
                jnp = jax.numpy
                x = jnp.ones((128, 128), dtype=jnp.float32)
                jax.jit(lambda a: a @ a)(x).block_until_ready()
            self._init_ok = True
        except Exception:  # noqa: BLE001 — the report is the diagnosis
            self._init_error = traceback.format_exc(limit=20)
        finally:
            self._init_done.set()
            self._write_report()

    def _supervise_init(self) -> None:
        """Watch the init thread against the per-phase ceiling. A phase
        that overruns cannot be cancelled (it is wedged inside a C
        extension), so: snapshot every thread's stack into the probe
        report, then exit the process with a distinct code."""
        while not self._init_done.wait(1.0):
            with self._init_lock:
                phase, t0 = self._current_phase, self._phase_started_at
            if phase and time.time() - t0 > self.init_timeout_s:
                buf = io.StringIO()
                faulthandler.dump_traceback(file=buf)
                with self._init_lock:
                    self._phases[phase]["status"] = "timeout"
                    self._phases[phase]["s"] = round(time.time() - t0, 3)
                self._probe_extra["stack"] = buf.getvalue()[-8000:]
                self._init_error = (
                    f"init phase {phase!r} exceeded {self.init_timeout_s}s")
                self._write_report()
                log.error("%s — exiting with stack snapshot in %s",
                          self._init_error, self.report_path)
                os._exit(3)

    def _write_report(self) -> None:
        with self._init_lock:
            report = {
                "pid": os.getpid(),
                "socket": self.socket_path,
                "ok": self._init_ok,
                "error": self._init_error,
                "phases": [dict(self._phases[p]) for p in INIT_PHASES],
                "phase_timeout_s": self.init_timeout_s,
                "written_at": round(time.time() - self.started_at, 3),
            }
            report.update(self._probe_extra)
        tmp = self.report_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, self.report_path)
        except OSError:  # report is best-effort; never kill init over it
            log.warning("could not write probe report %s", self.report_path,
                        exc_info=True)

    # ------------------------------------------------------------- serving

    def _bind(self) -> socket.socket:
        # stale-socket handling daemon-side: if the path exists, probe it.
        # A live daemon answering ping means we must NOT steal the address;
        # a dead one (connection refused) gets unlinked.
        if os.path.exists(self.socket_path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.settimeout(1.0)
                probe.connect(self.socket_path)
                probe.close()
                raise RuntimeError(
                    f"daemon already serving {self.socket_path}")
            except (ConnectionRefusedError, socket.timeout, FileNotFoundError,
                    OSError):
                probe.close()
                with contextlib.suppress(OSError):
                    os.unlink(self.socket_path)
        lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        lst.bind(self.socket_path)
        lst.listen(16)
        # the address is ours: mint this incarnation's generation token
        # (time + pid — unique even across pid recycling) and remove the
        # previous corpse's artifacts, so post-mortem tooling never reads
        # a stale probe/crash report as if it were this daemon's
        self.generation = f"{int(time.time() * 1e6):x}-{os.getpid():x}"
        for stale in (self.report_path, self.crash_path):
            with contextlib.suppress(OSError):
                os.unlink(stale)
        return lst

    def serve_forever(self) -> int:
        os.makedirs(self.work_dir, exist_ok=True)
        self._listener = self._bind()
        # mark this process so clear_device_caches() inside the daemon
        # never tries to route back to a daemon (self-attach recursion)
        from ballista_tpu.device_daemon import client as dclient

        dclient.mark_in_daemon()
        self._write_report()
        threading.Thread(target=self._init_main, name="daemon-init",
                         daemon=True).start()
        threading.Thread(target=self._supervise_init, name="daemon-init-watch",
                         daemon=True).start()
        threading.Thread(target=self._reaper, name="daemon-reaper",
                         daemon=True).start()
        threading.Thread(target=self._watchdog, name="daemon-exec-watch",
                         daemon=True).start()
        log.info("device daemon pid=%d serving %s", os.getpid(), self.socket_path)
        self._listener.settimeout(1.0)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()
        finally:
            with contextlib.suppress(OSError):
                self._listener.close()
            with contextlib.suppress(OSError):
                os.unlink(self.socket_path)
        return 0

    def _reaper(self) -> None:
        """Parent-death + idle watchdog: a daemon spawned for a bench leg
        or a test must not outlive its reason to exist and sit on the
        device claim forever."""
        while not self._stop.wait(2.0):
            if self.parent_pid:
                try:
                    os.kill(self.parent_pid, 0)
                except OSError:
                    log.info("parent pid %d gone; exiting", self.parent_pid)
                    self.shutdown()
                    return
            if (self.idle_timeout_s > 0
                    and time.time() - self.last_request_at > self.idle_timeout_s):
                log.info("idle for %ds; exiting", self.idle_timeout_s)
                self.shutdown()
                return

    def shutdown(self) -> None:
        self._stop.set()
        with contextlib.suppress(OSError):
            if self._listener is not None:
                self._listener.close()

    # ------------------------------------------------- execute watchdog

    @contextlib.contextmanager
    def _watched(self, header: dict, deadline_s: float):
        """Register one execute request with the watchdog for its on-device
        span. The entry carries everything the post-mortem needs: the
        request header (minus the bulky config pairs), the session, and a
        mutable phase the handler advances (recompile → execute → pack)."""
        entry = {
            "header": {k: v for k, v in header.items() if k != "pairs"},
            "session": str(header.get("session") or "anonymous"),
            "phase": "recompile",
            "started": time.time(),
            "deadline_s": float(deadline_s),
        }
        with self._inflight_lock:
            self._inflight_seq += 1
            rid = self._inflight_seq
            self._inflight[rid] = entry
        try:
            yield entry
        finally:
            with self._inflight_lock:
                self._inflight.pop(rid, None)

    def _watchdog(self) -> None:
        """Kill the process when an in-flight execute overruns its
        deadline. Same rationale as the init supervisor: a wedged XLA call
        cannot be cancelled, so the honest move is a diagnosed death — the
        crash artifact names the offending request, and the client's
        respawn ladder (plus the poison quarantine on a repeat) takes it
        from there."""
        while not self._stop.wait(0.5):
            now = time.time()
            with self._inflight_lock:
                overrun = [dict(e) for e in self._inflight.values()
                           if e["deadline_s"] > 0
                           and now - e["started"] > e["deadline_s"]]
            if overrun:
                worst = max(overrun, key=lambda e: now - e["started"])
                self._write_crash_report("watchdog", worst)
                log.error(
                    "execute watchdog: request %s overran %.1fs deadline in "
                    "phase %s — exiting with crash report at %s",
                    worst["header"].get("tag"), worst["deadline_s"],
                    worst["phase"], self.crash_path)
                os._exit(4)

    def _write_crash_report(self, kind: str, entry: dict) -> None:
        """<socket>.crash.json: every thread's stack (faulthandler), the
        offending request header, session, phase, and process rusage —
        written tmp+rename immediately before the process exits."""
        from ballista_tpu.ops.tpu import runtime

        # faulthandler writes at the fd level (it must work even when the
        # interpreter is wedged), so dump through a real file, not StringIO
        stacks = ""
        try:
            with open(self.crash_path + ".stacks", "w+") as f:
                faulthandler.dump_traceback(file=f)
                f.seek(0)
                stacks = f.read()
            os.unlink(self.crash_path + ".stacks")
        except Exception:  # noqa: BLE001 — post-mortem must still be written
            stacks = "".join(
                f"\nThread {tid}:\n" + "".join(traceback.format_stack(frame))
                for tid, frame in sys._current_frames().items())
        report = {
            "kind": kind,
            "pid": os.getpid(),
            "generation": self.generation,
            "socket": self.socket_path,
            "request": entry.get("header", {}),
            "session": entry.get("session"),
            "phase": entry.get("phase"),
            "deadline_s": entry.get("deadline_s"),
            "elapsed_s": round(time.time() - entry.get("started", time.time()), 3),
            "rusage": runtime.process_rusage(),
            "stacks": stacks[-16000:],
            "written_at": time.time(),
        }
        tmp = self.crash_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, self.crash_path)
        except OSError:
            log.warning("could not write crash report %s", self.crash_path,
                        exc_info=True)

    # ---------------------------------------------------- chaos arming

    def _maybe_chaos(self, cfg, point: str) -> None:
        """Deterministic daemon-fault injection (executor/chaos.py modes
        daemon_crash / daemon_hang). Armed through the session config the
        client already ships, at exactly one arming point
        (ballista.chaos.daemon.arm). With ballista.chaos.daemon.once a
        marker file next to the socket limits the fault to the FIRST
        armed request PER SOCKET — the marker survives respawns, so the
        retry against the fresh daemon succeeds (the recovery test);
        without it every incarnation dies and the poison quarantine is
        what breaks the crash loop (the quarantine test)."""
        from ballista_tpu.config import (
            CHAOS_DAEMON_ARM,
            CHAOS_DAEMON_ONCE,
            CHAOS_ENABLED,
            CHAOS_MODE,
        )

        if not bool(cfg.get(CHAOS_ENABLED)):
            return
        mode = str(cfg.get(CHAOS_MODE))
        if mode not in ("daemon_crash", "daemon_hang"):
            return
        if str(cfg.get(CHAOS_DAEMON_ARM)) != point:
            return
        if bool(cfg.get(CHAOS_DAEMON_ONCE)):
            marker = f"{self.socket_path}.chaos.{mode}.{point}"
            try:
                os.close(os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            except FileExistsError:
                return  # already fired once for this socket
            except OSError:
                pass  # unmarkable filesystem: fire anyway, stay deterministic
        if mode == "daemon_crash":
            log.error("chaos: daemon_crash armed at %s — dying uncleanly", point)
            os._exit(137)  # SIGKILL's exit code: an undiagnosed death
        log.error("chaos: daemon_hang armed at %s — wedging the execute "
                  "thread until the watchdog fires", point)
        while True:  # the watchdog converts this into a diagnosed kill
            time.sleep(0.25)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                header, body = protocol.recv_msg(conn)
                self.last_request_at = time.time()
                resp_header, resp_body = self._dispatch(header, body)
                protocol.send_msg(conn, resp_header, resp_body)
        except protocol.ProtocolError:
            pass  # client went away mid-frame; its problem, not ours
        except Exception:  # noqa: BLE001 — one bad request must not kill serving
            log.warning("request failed", exc_info=True)
            with contextlib.suppress(Exception):
                protocol.send_msg(conn, {"ok": False,
                                         "error": traceback.format_exc(limit=5)})

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, header: dict, body: bytes) -> tuple[dict, bytes]:
        op = header.get("op")
        if header.get("v", protocol.PROTOCOL_VERSION) != protocol.PROTOCOL_VERSION:
            return {"ok": False, "error": "protocol version mismatch"}, b""
        if op == "ping":
            return {"ok": True, "pid": os.getpid(), "gen": self.generation,
                    "ready": self._init_ok}, b""
        if op == "status":
            return {"ok": True, **self._status()}, b""
        if op == "shutdown":
            self.shutdown()
            return {"ok": True}, b""
        if op == "clear_caches":
            return self._handle_clear()
        if op == "execute":
            return self._handle_execute(header, body)
        return {"ok": False, "error": f"unknown op {op!r}"}, b""

    def _status(self) -> dict:
        with self._init_lock:
            init = {
                "ok": self._init_ok,
                "error": self._init_error,
                "phases": [dict(self._phases[p]) for p in INIT_PHASES],
                "current": self._current_phase,
            }
        self._prune_sessions()
        with self._counters_lock:
            sessions = {sid: {"quota_bytes": s["quota_bytes"],
                              "executes": s["executes"]}
                        for sid, s in self._sessions.items()}
        compiled_entries = 0
        persist = {}
        if self._init_ok:
            import ballista_tpu.ops.tpu.stage_compiler as sc
            from ballista_tpu.ops.tpu import runtime

            compiled_entries = len(sc._COMPILE_CACHE)
            persist = runtime.compile_cache_stats()
        return {
            "pid": os.getpid(),
            "gen": self.generation,
            "uptime_s": round(time.time() - self.started_at, 1),
            "ready": self._init_ok,
            "init": init,
            "sessions": len(sessions),
            "session_detail": sessions,
            "queue_depth": self._queue_depth,
            "execute_count": self.execute_count,
            "clear_count": self.clear_count,
            "compiled_entries": compiled_entries,
            "persist_cache": persist,
            "platform": self._probe_extra.get("platform"),
            "device_kind": self._probe_extra.get("device_kind"),
        }

    def _prune_sessions(self) -> None:
        cutoff = time.time() - SESSION_TTL_S
        with self._counters_lock:
            for sid in [s for s, v in self._sessions.items()
                        if v["last_used"] < cutoff]:
                del self._sessions[sid]

    def _handle_clear(self) -> tuple[dict, bytes]:
        if not self._init_ok:
            return {"ok": True, "note": "init incomplete; nothing resident"}, b""
        import ballista_tpu.ops.tpu.stage_compiler as sc

        sc.clear_device_caches()
        with self._counters_lock:
            self.clear_count += 1
        return {"ok": True}, b""

    def _handle_execute(self, header: dict, body: bytes) -> tuple[dict, bytes]:
        # block until init lands (bounded: the supervisor kills the process
        # on a hung phase, which drops this connection — the client sees
        # the error and falls back in-process)
        self._init_done.wait()
        if not self._init_ok:
            return {"ok": False,
                    "error": f"daemon init failed: {self._init_error}"}, b""
        from ballista_tpu import serde
        from ballista_tpu.config import (
            TPU_DAEMON_ENABLED,
            TPU_DAEMON_EXECUTE_TIMEOUT_S,
            TPU_DAEMON_POISON_TTL_S,
            TPU_DAEMON_SESSION_QUOTA_BYTES,
            BallistaConfig,
        )
        from ballista_tpu.device_daemon import client as dclient
        from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
        from ballista_tpu.ops.tpu import hbm
        from ballista_tpu.plan.physical import TaskContext

        import ballista_tpu.ops.tpu.stage_compiler as sc

        cfg = BallistaConfig.from_key_value_pairs(
            [(k, v) for k, v in header.get("pairs", [])], scrub_restricted=True)
        # never re-enter the daemon path from inside the daemon
        cfg.set(TPU_DAEMON_ENABLED, False)
        tag = str(header.get("tag", ""))
        poison_ttl = float(cfg.get(TPU_DAEMON_POISON_TTL_S))
        if tag and dclient.is_poisoned(self.socket_path, tag, poison_ttl):
            # this stage has killed two daemon incarnations already; refusing
            # it here is what breaks the crash loop — the client demotes it
            # to the in-process/CPU ladder
            return {"ok": False, "poisoned": True, "gen": self.generation,
                    "error": f"stage {tag} is quarantined in "
                             f"{protocol.poison_path(self.socket_path)}; "
                             "run it in-process"}, b""
        deadline_s = float(header.get("deadline_s") or 0.0)
        if deadline_s <= 0:
            deadline_s = protocol.derive_execute_timeout_s(
                float(cfg.get(TPU_DAEMON_EXECUTE_TIMEOUT_S)), 0)
        try:
            with self._watched(header, deadline_s) as went:
                self._maybe_chaos(cfg, "pre_execute")
                plan = serde.plan_from_bytes(body)
                compiled = maybe_compile_tpu(plan, cfg)
                emit_pid = header.get("emit_pid")
                if emit_pid is not None:
                    if not isinstance(compiled, sc.TpuStageExec):
                        return {"ok": False, "gen": self.generation, "error":
                                "device-routed stage did not recompile to a "
                                "device stage daemon-side; client must run it "
                                "locally"}, b""
                    compiled.emit_pid = (list(emit_pid[0]), int(emit_pid[1]))

                session = str(header.get("session") or "anonymous")
                quota = int(cfg.get(TPU_DAEMON_SESSION_QUOTA_BYTES))
                with self._counters_lock:
                    s = self._sessions.setdefault(
                        session, {"quota_bytes": quota, "executes": 0,
                                  "last_used": time.time()})
                    s["quota_bytes"] = quota
                    s["last_used"] = time.time()
                    s["executes"] += 1
                    self._queue_depth += 1
                try:
                    with self._exec_lock:
                        with self._counters_lock:
                            self._queue_depth -= 1
                        # the deadline covers the on-device span, not the
                        # queue wait behind other sessions: restart the
                        # clock now that the device is ours
                        went["phase"] = "execute"
                        went["started"] = time.time()
                        self._maybe_chaos(cfg, "mid_execute")
                        ctx = TaskContext(
                            cfg, task_id=f"daemon-{self.execute_count}",
                            work_dir=self.work_dir)
                        ctx.device_ordinal = self.device_ordinal
                        partitions = [int(p)
                                      for p in header.get("partitions", [])]
                        # snapshot the engine stats so the mirror below can
                        # diff: a routed final/mesh stage publishes its inner
                        # partial-stage recs under THEIR tags, not the
                        # request's
                        before = {t: dict(r)
                                  for t, r in sc.RUN_STATS.stages().items()}
                        with hbm.session_quota(quota):
                            results = {p: list(compiled.execute(p, ctx))
                                       for p in partitions}
                    with self._counters_lock:
                        self.execute_count += 1
                except Exception:  # noqa: BLE001
                    with self._counters_lock:
                        self._queue_depth = max(0, self._queue_depth)
                    return {"ok": False, "gen": self.generation,
                            "error": traceback.format_exc(limit=10)}, b""
                went["phase"] = "pack"
                segments, resp_body = protocol.pack_results(results)
                # mirror this run's engine stats back to the caller: the
                # client's RUN_STATS (heartbeat, bench events) reports the
                # device work even though it happened in this process. Merge
                # every rec the request CHANGED (a daemon-routed final/mesh
                # stage runs inner partial stages under their own tags), with
                # the request's own tag applied last so it wins collisions.
                stats: dict = {}
                after = sc.RUN_STATS.stages()
                changed = [t for t, r in after.items() if r != before.get(t)]
                for t in sorted(changed, key=lambda t: t == tag):
                    stats.update({k: v for k, v in after[t].items()
                                  if isinstance(v, (int, float, str, bool))})
                init_s = {p["name"]: p["s"]
                          for p in self._status()["init"]["phases"]}
                self._maybe_chaos(cfg, "post_execute")
                return {"ok": True, "segments": segments, "stats": stats,
                        "gen": self.generation,
                        "sessions": len(self._sessions),
                        "queue_depth": self._queue_depth,
                        "init_phase_s": init_s,
                        "device_runs": getattr(compiled, "tpu_count", 0),
                        "cpu_fallbacks": getattr(compiled, "fallback_count", 0),
                        }, resp_body
        except Exception:  # noqa: BLE001 — serde/compile failures pre-exec
            return {"ok": False, "gen": self.generation,
                    "error": traceback.format_exc(limit=10)}, b""


# ------------------------------------------------------- Flight variant

def serve_flight(server: DaemonServer, port: int):
    """Optional Flight `do_exchange` front-end over the same dispatcher,
    for callers that already speak Flight (the serving tier's proxies).
    The request header rides the descriptor command; result batches
    stream back with the partition index in app_metadata and the stats
    header as a trailing metadata-only message. Returns the running
    Flight server, or None when the Flight stack is not importable."""
    try:
        import pyarrow.flight as flight
    except Exception:  # noqa: BLE001 — optional dependency surface
        log.info("pyarrow.flight unavailable; UDS only")
        return None

    class _DaemonFlight(flight.FlightServerBase):
        def __init__(self):
            super().__init__(f"grpc://127.0.0.1:{port}")

        def do_exchange(self, context, descriptor, reader, writer):
            header = json.loads(descriptor.command.decode())
            body = bytes.fromhex(header.pop("body_hex", ""))
            resp, resp_body = server._dispatch(header, body)
            results = (protocol.unpack_results(resp.get("segments", []), resp_body)
                       if resp.get("ok") and "segments" in resp else {})
            started = False
            for part in sorted(results):
                for b in results[part]:
                    if not started:
                        writer.begin(b.schema)
                        started = True
                    writer.write_with_metadata(b, str(part).encode())
            resp.pop("segments", None)
            writer.write_metadata(json.dumps(resp).encode())

    fs = _DaemonFlight()
    threading.Thread(target=fs.serve, daemon=True).start()
    return fs
