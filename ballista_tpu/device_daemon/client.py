"""Attach-first client for the device-runtime daemon.

This module must stay importable WITHOUT jax: it is reached (lazily)
from the stage compiler and from scheduler/executor-adjacent callers the
jax-guard analysis pass keeps off the jax import graph. Everything here
is sockets + JSON + pyarrow IPC; the device runtime lives daemon-side.

Attach policy (`attach(config)` under the ballista.tpu.daemon.* knobs):

1. daemon disabled          → (None, "in_process", "daemon disabled")
2. live daemon answers ping → (client, "attached", socket path)
3. stale socket (file exists, connect refused) → unlink it, then
4. spawn knob on            → spawn `python -m ballista_tpu.device_daemon`
   detached, wait for its socket within the attach timeout, adopt it
5. otherwise                → (None, "in_process", the failure reason)

The result is cached per (socket, daemon pid, generation token): a
process that attached once keeps its client until the daemon dies or is
replaced — a recycled pid alone cannot alias a NEW daemon onto an old
attachment, because the bind-time generation token must match too — at
which point the next attach retries the ladder from the top. Fallback
is never an error — the in-process engine is always behind it.

Failure domain (docs/device_daemon.md#failure-domain): a daemon that
dies mid-request surfaces as the typed `DaemonCrashed`; the stage
dispatcher (ops/tpu/daemon_route.py) respawns and retries ONCE per
stage fingerprint, and a second crash of the same fingerprint lands in
the on-disk poison quarantine (`<socket>.poison.json`) this module
maintains, so respawned daemons refuse the stage and it demotes to the
in-process/CPU ladder instead of crash-looping.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

from ballista_tpu.device_daemon import protocol

# set inside the daemon process itself: clear_attached_caches() and
# attach() become no-ops there, so daemon-side stage execution can never
# recurse into another daemon
_IN_DAEMON = False

_CACHE_LOCK = threading.Lock()
# socket path → (DaemonClient, daemon_pid, generation) for processes that attached
# analysis: ignore[bounded-cache] one entry per daemon socket this process attaches to; bounded by deployment topology (typically 1)
_ATTACHED: dict[str, tuple["DaemonClient", int, str]] = {}

# a stage fingerprint gets ONE respawn-and-retry; the second crash
# poisons it (docs/device_daemon.md#failure-domain)
POISON_CRASH_THRESHOLD = 2

# process-lifetime failure-domain counters, mirrored into RUN_STATS by
# ops/tpu/daemon_route.py so they ride the executor heartbeat
_COUNTERS_LOCK = threading.Lock()
_COUNTERS = {"daemon_restarts": 0, "daemon_crashes_detected": 0,
             "watchdog_kills": 0, "poisoned_stages": 0}


def mark_in_daemon() -> None:
    global _IN_DAEMON
    _IN_DAEMON = True


def reset_attach_cache() -> None:
    """Test hook: forget cached attachments (e.g. after killing a daemon)."""
    with _CACHE_LOCK:
        _ATTACHED.clear()


def drop_attached(path: str) -> None:
    """Forget one cached attachment (a detected crash invalidates it)."""
    with _CACHE_LOCK:
        _ATTACHED.pop(path, None)


def attached_generation(path: str | None = None) -> str:
    """Generation token of the daemon this process is attached to ("" when
    not attached). With no path, the most recent attachment wins — the
    common deployment has exactly one daemon per host. Used by the
    serving tier's lease fencing (serving/lease.py)."""
    with _CACHE_LOCK:
        if path is not None:
            cached = _ATTACHED.get(path)
            return cached[2] if cached else ""
        gen = ""
        for _, _, g in _ATTACHED.values():
            gen = g
        return gen


def bump_counter(key: str, n: int = 1) -> int:
    with _COUNTERS_LOCK:
        _COUNTERS[key] = _COUNTERS.get(key, 0) + n
        return _COUNTERS[key]


def failure_counters() -> dict:
    with _COUNTERS_LOCK:
        return dict(_COUNTERS)


def reset_failure_counters() -> None:
    """Test hook."""
    with _COUNTERS_LOCK:
        for k in _COUNTERS:
            _COUNTERS[k] = 0


class DaemonUnavailable(RuntimeError):
    pass


class DaemonCrashed(DaemonUnavailable):
    """The daemon died (or stopped answering) MID-REQUEST: the request was
    sent and the reply never completed. Distinct from DaemonUnavailable's
    connect-time failure because the remediation differs — a crash mid-
    execute gets a bounded respawn-and-retry, a dead socket just falls
    back in-process. `reason` is one of eof/reset/timeout/send."""

    def __init__(self, msg: str, reason: str = "eof"):
        super().__init__(msg)
        self.reason = reason


class DaemonClient:
    """One request per connection; safe to share across threads."""

    # default request ceiling covers the cheap control ops (status, clear,
    # shutdown); execute always passes an explicit deadline derived from
    # the stage's byte estimate (protocol.derive_execute_timeout_s) — the
    # former 3600s blanket default let a wedged XLA call hold a client
    # for an hour. Attach liveness is separately bounded by ping's 2s.
    def __init__(self, socket_path: str, timeout_s: float = 60.0):
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        # generation token of the daemon this client last spoke to;
        # refreshed by ping (attach stores it in the cache key)
        self.generation = ""

    def _request(self, header: dict, body: bytes = b"",
                 timeout_s: float | None = None) -> tuple[dict, bytes]:
        header = dict(header)
        header["v"] = protocol.PROTOCOL_VERSION
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sent = False
        try:
            sock.settimeout(timeout_s if timeout_s is not None else self.timeout_s)
            try:
                sock.connect(self.socket_path)
            except OSError as e:
                raise DaemonUnavailable(f"connect {self.socket_path}: {e}") from e
            try:
                protocol.send_msg(sock, header, body)
                sent = True
                resp, resp_body = protocol.recv_msg(sock)
            except socket.timeout as e:
                # past the derived deadline with no reply: the daemon-side
                # watchdog should already have killed the process — treat
                # the silence as a crash either way (same remediation)
                raise DaemonCrashed(
                    f"daemon unresponsive past deadline: {e}",
                    reason="timeout") from e
            except (protocol.ProtocolError, OSError) as e:
                # EOF / ECONNRESET after the request went out = the daemon
                # died mid-frame; before the send it's a plain availability
                # failure (attach-time, benign)
                if sent:
                    raise DaemonCrashed(
                        f"daemon hung up mid-request: {e}",
                        reason="reset" if isinstance(e, ConnectionResetError)
                        else "eof") from e
                raise DaemonCrashed(f"daemon refused request: {e}",
                                    reason="send") from e
        finally:
            sock.close()
        return resp, resp_body

    def ping(self, timeout_s: float = 2.0) -> dict:
        resp, _ = self._request({"op": "ping"}, timeout_s=timeout_s)
        self.generation = str(resp.get("gen", ""))
        return resp

    def status(self) -> dict:
        resp, _ = self._request({"op": "status"})
        if not resp.get("ok"):
            raise DaemonUnavailable(resp.get("error", "status failed"))
        return resp

    def wait_ready(self, timeout_s: float, poll_s: float = 0.5) -> dict:
        """Poll status until init lands; raises with the init report's
        last phase on timeout or daemon death. Tolerates the socket not
        being bound yet (a just-spawned daemon binds before init, but the
        bind itself takes a beat)."""
        deadline = time.time() + timeout_s
        last: dict = {}
        while time.time() < deadline:
            try:
                last = self.status()
            except DaemonUnavailable as e:
                last = {"init": {"current": f"socket not up ({e})"}}
                time.sleep(poll_s)
                continue
            if last.get("ready"):
                return last
            init = last.get("init") or {}
            if init.get("error"):
                raise DaemonUnavailable(f"daemon init failed: {init['error']}")
            time.sleep(poll_s)
        phase = ((last.get("init") or {}).get("current")) or "unknown"
        raise DaemonUnavailable(
            f"daemon not ready within {timeout_s}s (init phase: {phase})")

    def execute(self, plan_bytes: bytes, pairs: list, partitions: list,
                *, emit_pid=None, session: str = "", tag: str = "",
                deadline_s: float = 0.0,
                timeout_s: float | None = None) -> tuple[dict, dict]:
        """Ship one stage; returns ({partition: [batches]}, response header
        with daemon-side stats). Raises DaemonCrashed when the daemon dies
        mid-request (the caller's respawn/quarantine ladder handles it),
        DaemonUnavailable on connect failure, and RuntimeError when the
        daemon reports an execution error — the last two mean 'run it
        in-process instead'. `deadline_s` rides the header so the daemon's
        watchdog enforces the SAME bound server-side; the client socket
        waits a little longer, so the watchdog's diagnosed kill (crash
        artifact + nonzero exit) wins the race against a bare timeout."""
        header = {
            "op": "execute",
            "pairs": [[str(k), str(v)] for k, v in pairs],
            "partitions": [int(p) for p in partitions],
            "session": session or f"{socket.gethostname()}:{os.getpid()}",
            "tag": tag,
        }
        if deadline_s > 0:
            header["deadline_s"] = round(float(deadline_s), 3)
            if timeout_s is None:
                timeout_s = deadline_s * 1.25 + 15.0
        if emit_pid is not None:
            header["emit_pid"] = [list(emit_pid[0]), int(emit_pid[1])]
        resp, body = self._request(header, plan_bytes, timeout_s=timeout_s)
        if not resp.get("ok"):
            err = RuntimeError(f"daemon execute failed: {resp.get('error')}")
            # a respawned daemon refusing a quarantined stage is a clean
            # demotion signal, not a crash — mark it so the dispatcher
            # doesn't count it against the fingerprint again
            err.poisoned = bool(resp.get("poisoned"))
            raise err
        return protocol.unpack_results(resp.get("segments", []), body), resp

    def clear_caches(self) -> None:
        resp, _ = self._request({"op": "clear_caches"})
        if not resp.get("ok"):
            raise RuntimeError(f"daemon clear failed: {resp.get('error')}")

    def shutdown(self) -> None:
        try:
            self._request({"op": "shutdown"}, timeout_s=2.0)
        except DaemonUnavailable:
            pass  # already gone — the goal state


# --------------------------------------------------------------- attach

def resolve_socket(config) -> str:
    from ballista_tpu.config import TPU_DAEMON_SOCKET

    return str(config.get(TPU_DAEMON_SOCKET)) or protocol.default_socket_path()


def _clean_stale_socket(path: str) -> bool:
    """A socket file nobody answers on is litter from a dead daemon:
    unlink it so a spawn (ours or a later one) can bind. True if the path
    was stale and removed."""
    if not os.path.exists(path):
        return False
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(1.0)
        probe.connect(path)
        return False  # something is listening; not stale
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            return False
        return True
    finally:
        probe.close()


def spawn_daemon(socket_path: str, *, parent_pid: int = 0,
                 idle_timeout_s: int | None = None,
                 env: dict | None = None) -> subprocess.Popen:
    """Start a detached daemon process; stdout/stderr land next to the
    socket at <socket>.log. The caller still has to wait for the socket."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    e = dict(os.environ if env is None else env)
    e["PYTHONPATH"] = pkg_root + os.pathsep + e.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "ballista_tpu.device_daemon",
           "--socket", socket_path]
    if parent_pid:
        cmd += ["--parent-pid", str(parent_pid)]
    if idle_timeout_s is not None:
        cmd += ["--idle-timeout-s", str(idle_timeout_s)]
    logf = open(protocol.daemon_log_path(socket_path), "ab")
    try:
        return subprocess.Popen(cmd, stdin=subprocess.DEVNULL, stdout=logf,
                                stderr=logf, start_new_session=True, env=e)
    finally:
        logf.close()


def attach(config) -> tuple[DaemonClient | None, str, str]:
    """The attach-first ladder. Returns (client|None, mode, reason) where
    mode is "attached" or "in_process"; never raises."""
    from ballista_tpu.config import (
        TPU_DAEMON_ATTACH_TIMEOUT_MS,
        TPU_DAEMON_ENABLED,
        TPU_DAEMON_SPAWN,
    )

    if _IN_DAEMON:
        return None, "in_process", "inside daemon"
    if not config.get(TPU_DAEMON_ENABLED):
        return None, "in_process", "daemon disabled"
    path = resolve_socket(config)
    timeout_s = int(config.get(TPU_DAEMON_ATTACH_TIMEOUT_MS)) / 1000.0

    with _CACHE_LOCK:
        cached = _ATTACHED.get(path)
    if cached is not None:
        client, pid, gen = cached
        try:
            p = client.ping()
            # a recycled pid can alias a NEW daemon onto an old
            # attachment — the bind-time generation token cannot. Both
            # must match, else the ladder reruns and re-keys the cache.
            if p.get("pid") == pid and str(p.get("gen", "")) == gen:
                return client, "attached", path
        except DaemonUnavailable:
            pass
        with _CACHE_LOCK:  # daemon died or was replaced; retry the ladder
            _ATTACHED.pop(path, None)

    client = DaemonClient(path)
    deadline = time.time() + timeout_s
    try:
        p = client.ping(timeout_s=max(0.2, timeout_s))
        with _CACHE_LOCK:
            _ATTACHED[path] = (client, int(p.get("pid", 0)),
                               str(p.get("gen", "")))
        return client, "attached", path
    except DaemonUnavailable as e:
        reason = str(e)

    stale = _clean_stale_socket(path)
    if stale:
        reason = f"stale socket removed: {path}"
    if not config.get(TPU_DAEMON_SPAWN):
        return None, "in_process", f"attach_failed: {reason}"

    try:
        spawn_daemon(path)
    except OSError as e:
        return None, "in_process", f"spawn_failed: {e}"
    while time.time() < deadline:
        try:
            p = client.ping(timeout_s=0.5)
            with _CACHE_LOCK:
                _ATTACHED[path] = (client, int(p.get("pid", 0)),
                                   str(p.get("gen", "")))
            return client, "attached", f"spawned: {path}"
        except DaemonUnavailable:
            time.sleep(0.1)
    return None, "in_process", (
        f"spawn_timeout: daemon socket {path} did not come up within "
        f"{timeout_s:.1f}s")


def clear_attached_caches() -> bool:
    """Route clear_device_caches() through to any daemon this process is
    attached to: an attached executor's clear must evict DAEMON-resident
    device state, not just its own (empty) in-process caches. Best-effort;
    True when at least one daemon acknowledged. No-op inside the daemon
    itself (the daemon's own clear already ran locally)."""
    if _IN_DAEMON:
        return False
    with _CACHE_LOCK:
        clients = [c for c, _, _ in _ATTACHED.values()]
    ok = False
    for c in clients:
        try:
            c.clear_caches()
            ok = True
        except (DaemonUnavailable, RuntimeError):
            pass
    return ok


# ------------------------------------------------ poison-stage quarantine

def _load_poison(path: str, ttl_s: float) -> dict:
    """Read + TTL-prune the quarantine next to `path`'s socket. Never
    raises: a corrupt or missing file is an empty quarantine."""
    try:
        with open(protocol.poison_path(path)) as f:
            entries = json.load(f).get("entries", {})
    except (OSError, ValueError):
        return {}
    cutoff = time.time() - max(1.0, float(ttl_s))
    return {t: e for t, e in entries.items()
            if isinstance(e, dict) and float(e.get("updated", 0)) >= cutoff}


def _store_poison(path: str, entries: dict) -> None:
    tmp = protocol.poison_path(path) + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"entries": entries}, f, indent=1)
        os.replace(tmp, protocol.poison_path(path))
    except OSError:
        pass  # quarantine is best-effort; a lost write costs one retry


def record_stage_crash(path: str, tag: str, fingerprint: str,
                       ttl_s: float) -> int:
    """Count one daemon crash against a stage fingerprint; returns the
    crash count within the TTL window. At POISON_CRASH_THRESHOLD the
    stage is quarantined: respawned daemons refuse it and clients demote
    it straight to the in-process ladder until the entry expires."""
    entries = _load_poison(path, ttl_s)
    e = entries.setdefault(tag, {"crashes": 0, "fingerprint": fingerprint[:300]})
    e["crashes"] = int(e.get("crashes", 0)) + 1
    e["updated"] = time.time()
    _store_poison(path, entries)
    return e["crashes"]


def is_poisoned(path: str, tag: str, ttl_s: float) -> bool:
    e = _load_poison(path, ttl_s).get(tag)
    return e is not None and int(e.get("crashes", 0)) >= POISON_CRASH_THRESHOLD


def clear_poison(path: str) -> None:
    """Test hook: lift the quarantine for a socket."""
    try:
        os.unlink(protocol.poison_path(path))
    except OSError:
        pass


def read_crash_report(path: str) -> dict | None:
    """The watchdog's post-mortem artifact (<socket>.crash.json), or None.
    Fresh daemon binds remove stale ones, so an existing report belongs
    to the most recent corpse."""
    try:
        with open(protocol.crash_report_path(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
