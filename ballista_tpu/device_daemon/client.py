"""Attach-first client for the device-runtime daemon.

This module must stay importable WITHOUT jax: it is reached (lazily)
from the stage compiler and from scheduler/executor-adjacent callers the
jax-guard analysis pass keeps off the jax import graph. Everything here
is sockets + JSON + pyarrow IPC; the device runtime lives daemon-side.

Attach policy (`attach(config)` under the ballista.tpu.daemon.* knobs):

1. daemon disabled          → (None, "in_process", "daemon disabled")
2. live daemon answers ping → (client, "attached", socket path)
3. stale socket (file exists, connect refused) → unlink it, then
4. spawn knob on            → spawn `python -m ballista_tpu.device_daemon`
   detached, wait for its socket within the attach timeout, adopt it
5. otherwise                → (None, "in_process", the failure reason)

The result is cached per (socket, daemon pid): a process that attached
once keeps its client until the daemon dies, at which point the next
attach retries the ladder from the top. Fallback is never an error —
the in-process engine is always behind it.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

from ballista_tpu.device_daemon import protocol

# set inside the daemon process itself: clear_attached_caches() and
# attach() become no-ops there, so daemon-side stage execution can never
# recurse into another daemon
_IN_DAEMON = False

_CACHE_LOCK = threading.Lock()
# socket path → (DaemonClient, daemon_pid) for processes that attached
# analysis: ignore[bounded-cache] one entry per daemon socket this process attaches to; bounded by deployment topology (typically 1)
_ATTACHED: dict[str, tuple["DaemonClient", int]] = {}


def mark_in_daemon() -> None:
    global _IN_DAEMON
    _IN_DAEMON = True


def reset_attach_cache() -> None:
    """Test hook: forget cached attachments (e.g. after killing a daemon)."""
    with _CACHE_LOCK:
        _ATTACHED.clear()


class DaemonUnavailable(RuntimeError):
    pass


class DaemonClient:
    """One request per connection; safe to share across threads."""

    # default request ceiling: generous — a cold full-scale stage (fill +
    # XLA compile + exec) legitimately takes minutes; attach liveness is
    # separately bounded by ping's own 2s timeout
    def __init__(self, socket_path: str, timeout_s: float = 3600.0):
        self.socket_path = socket_path
        self.timeout_s = timeout_s

    def _request(self, header: dict, body: bytes = b"",
                 timeout_s: float | None = None) -> tuple[dict, bytes]:
        header = dict(header)
        header["v"] = protocol.PROTOCOL_VERSION
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(timeout_s if timeout_s is not None else self.timeout_s)
            try:
                sock.connect(self.socket_path)
            except OSError as e:
                raise DaemonUnavailable(f"connect {self.socket_path}: {e}") from e
            protocol.send_msg(sock, header, body)
            try:
                resp, resp_body = protocol.recv_msg(sock)
            except (protocol.ProtocolError, OSError) as e:
                raise DaemonUnavailable(f"daemon hung up: {e}") from e
        finally:
            sock.close()
        return resp, resp_body

    def ping(self, timeout_s: float = 2.0) -> dict:
        resp, _ = self._request({"op": "ping"}, timeout_s=timeout_s)
        return resp

    def status(self) -> dict:
        resp, _ = self._request({"op": "status"})
        if not resp.get("ok"):
            raise DaemonUnavailable(resp.get("error", "status failed"))
        return resp

    def wait_ready(self, timeout_s: float, poll_s: float = 0.5) -> dict:
        """Poll status until init lands; raises with the init report's
        last phase on timeout or daemon death. Tolerates the socket not
        being bound yet (a just-spawned daemon binds before init, but the
        bind itself takes a beat)."""
        deadline = time.time() + timeout_s
        last: dict = {}
        while time.time() < deadline:
            try:
                last = self.status()
            except DaemonUnavailable as e:
                last = {"init": {"current": f"socket not up ({e})"}}
                time.sleep(poll_s)
                continue
            if last.get("ready"):
                return last
            init = last.get("init") or {}
            if init.get("error"):
                raise DaemonUnavailable(f"daemon init failed: {init['error']}")
            time.sleep(poll_s)
        phase = ((last.get("init") or {}).get("current")) or "unknown"
        raise DaemonUnavailable(
            f"daemon not ready within {timeout_s}s (init phase: {phase})")

    def execute(self, plan_bytes: bytes, pairs: list, partitions: list,
                *, emit_pid=None, session: str = "", tag: str = "",
                timeout_s: float | None = None) -> tuple[dict, dict]:
        """Ship one stage; returns ({partition: [batches]}, response header
        with daemon-side stats). Raises DaemonUnavailable on transport
        failure and RuntimeError when the daemon reports an execution
        error — both mean 'run it in-process instead'."""
        header = {
            "op": "execute",
            "pairs": [[str(k), str(v)] for k, v in pairs],
            "partitions": [int(p) for p in partitions],
            "session": session or f"{socket.gethostname()}:{os.getpid()}",
            "tag": tag,
        }
        if emit_pid is not None:
            header["emit_pid"] = [list(emit_pid[0]), int(emit_pid[1])]
        resp, body = self._request(header, plan_bytes, timeout_s=timeout_s)
        if not resp.get("ok"):
            raise RuntimeError(f"daemon execute failed: {resp.get('error')}")
        return protocol.unpack_results(resp.get("segments", []), body), resp

    def clear_caches(self) -> None:
        resp, _ = self._request({"op": "clear_caches"})
        if not resp.get("ok"):
            raise RuntimeError(f"daemon clear failed: {resp.get('error')}")

    def shutdown(self) -> None:
        try:
            self._request({"op": "shutdown"}, timeout_s=2.0)
        except DaemonUnavailable:
            pass  # already gone — the goal state


# --------------------------------------------------------------- attach

def resolve_socket(config) -> str:
    from ballista_tpu.config import TPU_DAEMON_SOCKET

    return str(config.get(TPU_DAEMON_SOCKET)) or protocol.default_socket_path()


def _clean_stale_socket(path: str) -> bool:
    """A socket file nobody answers on is litter from a dead daemon:
    unlink it so a spawn (ours or a later one) can bind. True if the path
    was stale and removed."""
    if not os.path.exists(path):
        return False
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(1.0)
        probe.connect(path)
        return False  # something is listening; not stale
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            return False
        return True
    finally:
        probe.close()


def spawn_daemon(socket_path: str, *, parent_pid: int = 0,
                 idle_timeout_s: int | None = None,
                 env: dict | None = None) -> subprocess.Popen:
    """Start a detached daemon process; stdout/stderr land next to the
    socket at <socket>.log. The caller still has to wait for the socket."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    e = dict(os.environ if env is None else env)
    e["PYTHONPATH"] = pkg_root + os.pathsep + e.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "ballista_tpu.device_daemon",
           "--socket", socket_path]
    if parent_pid:
        cmd += ["--parent-pid", str(parent_pid)]
    if idle_timeout_s is not None:
        cmd += ["--idle-timeout-s", str(idle_timeout_s)]
    logf = open(protocol.daemon_log_path(socket_path), "ab")
    try:
        return subprocess.Popen(cmd, stdin=subprocess.DEVNULL, stdout=logf,
                                stderr=logf, start_new_session=True, env=e)
    finally:
        logf.close()


def attach(config) -> tuple[DaemonClient | None, str, str]:
    """The attach-first ladder. Returns (client|None, mode, reason) where
    mode is "attached" or "in_process"; never raises."""
    from ballista_tpu.config import (
        TPU_DAEMON_ATTACH_TIMEOUT_MS,
        TPU_DAEMON_ENABLED,
        TPU_DAEMON_SPAWN,
    )

    if _IN_DAEMON:
        return None, "in_process", "inside daemon"
    if not config.get(TPU_DAEMON_ENABLED):
        return None, "in_process", "daemon disabled"
    path = resolve_socket(config)
    timeout_s = int(config.get(TPU_DAEMON_ATTACH_TIMEOUT_MS)) / 1000.0

    with _CACHE_LOCK:
        cached = _ATTACHED.get(path)
    if cached is not None:
        client, pid = cached
        try:
            if client.ping().get("pid") == pid:
                return client, "attached", path
        except DaemonUnavailable:
            pass
        with _CACHE_LOCK:  # daemon died or was replaced; retry the ladder
            _ATTACHED.pop(path, None)

    client = DaemonClient(path)
    deadline = time.time() + timeout_s
    try:
        pid = int(client.ping(timeout_s=max(0.2, timeout_s)).get("pid", 0))
        with _CACHE_LOCK:
            _ATTACHED[path] = (client, pid)
        return client, "attached", path
    except DaemonUnavailable as e:
        reason = str(e)

    stale = _clean_stale_socket(path)
    if stale:
        reason = f"stale socket removed: {path}"
    if not config.get(TPU_DAEMON_SPAWN):
        return None, "in_process", f"attach_failed: {reason}"

    try:
        spawn_daemon(path)
    except OSError as e:
        return None, "in_process", f"spawn_failed: {e}"
    while time.time() < deadline:
        try:
            pid = int(client.ping(timeout_s=0.5).get("pid", 0))
            with _CACHE_LOCK:
                _ATTACHED[path] = (client, pid)
            return client, "attached", f"spawned: {path}"
        except DaemonUnavailable:
            time.sleep(0.1)
    return None, "in_process", (
        f"spawn_timeout: daemon socket {path} did not come up within "
        f"{timeout_s:.1f}s")


def clear_attached_caches() -> bool:
    """Route clear_device_caches() through to any daemon this process is
    attached to: an attached executor's clear must evict DAEMON-resident
    device state, not just its own (empty) in-process caches. Best-effort;
    True when at least one daemon acknowledged. No-op inside the daemon
    itself (the daemon's own clear already ran locally)."""
    if _IN_DAEMON:
        return False
    with _CACHE_LOCK:
        clients = [c for c, _ in _ATTACHED.values()]
    ok = False
    for c in clients:
        try:
            c.clear_caches()
            ok = True
        except (DaemonUnavailable, RuntimeError):
            pass
    return ok
