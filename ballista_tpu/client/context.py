"""Session context + DataFrame front end.

The reference's client surface (ballista/client/src/extension.rs):
`SessionContext::standalone()/remote()` with SQL and DataFrame entry points.
Modes here:

- "local":      plan and execute in this process (DataFusion-alone analog).
- "standalone": in-process scheduler + executor over the real task/shuffle
                machinery (reference: standalone.rs) — wired in
                client/remote.py once the control plane exists.
- "remote":     gRPC to an external scheduler.
"""

from __future__ import annotations

import concurrent.futures as _fut
from typing import Any, Optional

import pyarrow as pa

from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
from ballista_tpu.errors import PlanningError
from ballista_tpu.ids import SessionId, new_session_id
from ballista_tpu.plan.logical import Explain, LogicalPlan
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext
from ballista_tpu.plan.provider import Catalog, MemoryTable, ParquetTable, TableProvider
from ballista_tpu.sql.ast import (
    CreateExternalTable,
    DropTable,
    ShowColumns,
    ExplainStmt,
    SelectStmt,
    SetVariable,
    ShowTables,
)
from ballista_tpu.sql.optimizer import optimize
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.planner import SqlPlanner


class SessionContext:
    def __init__(self, config: BallistaConfig | None = None, mode: str = "local",
                 num_executors: int = 1, vcores: int = 4, scheduler_url: str = ""):
        self.config = config or BallistaConfig()
        self.mode = mode
        self.catalog = Catalog()
        self.session_id: SessionId = new_session_id()
        self._cluster = None  # StandaloneCluster (standalone mode)
        self._remote = None  # RemoteSchedulerClient (remote mode)
        self._num_executors = num_executors
        self._vcores = vcores
        self._scheduler_url = scheduler_url

    @classmethod
    def standalone(cls, config: BallistaConfig | None = None, num_executors: int = 1,
                   vcores: int = 4) -> "SessionContext":
        """In-process scheduler + executors over the real task/shuffle
        machinery (reference: SessionContextExt::standalone(),
        client/src/extension.rs:146)."""
        return cls(config, mode="standalone", num_executors=num_executors, vcores=vcores)

    @classmethod
    def remote(cls, scheduler_url: str, config: BallistaConfig | None = None) -> "SessionContext":
        """Connect to an external scheduler over gRPC
        (reference: SessionContextExt::remote())."""
        return cls(config, mode="remote", scheduler_url=scheduler_url)

    def _ensure_cluster(self):
        if self._cluster is None:
            from ballista_tpu.executor.standalone import StandaloneCluster

            self._cluster = StandaloneCluster(self._num_executors, self._vcores, config=self.config)
        return self._cluster

    def _ensure_remote(self):
        if self._remote is None:
            from ballista_tpu.client.remote import RemoteSchedulerClient

            self._remote = RemoteSchedulerClient(self._scheduler_url, self.config)
        return self._remote

    def shutdown(self) -> None:
        if self._cluster is not None:
            self._cluster.shutdown()
            self._cluster = None

    # -- registration -------------------------------------------------------

    def register_table(self, name: str, provider: TableProvider) -> None:
        self.catalog.register(name, provider)
        if isinstance(provider, ParquetTable):
            # ship the registration with the session so remote planning sees it
            self.config.set(f"ballista.catalog.table.{name.lower()}", provider.path)

    def _has_memory_tables(self) -> bool:
        from ballista_tpu.plan.provider import MemoryTable

        return any(isinstance(p, MemoryTable) for p in self.catalog.tables.values())

    def register_udf(self, name: str, fn, return_type) -> None:
        """Register a scalar UDF for this session (BallistaFunctionRegistry
        analog). Local execution resolves it immediately; for remote
        clusters the defining module is recorded in the session config and
        imported by executors (functions ship by reference, like the
        reference's code-registered function sets)."""
        from ballista_tpu import udf

        u = udf.register_udf(name, fn, return_type)
        if u.module:
            existing = self.config.get(udf.UDF_MODULES) or ""
            mods = [m for m in existing.split(",") if m]
            if u.module not in mods:
                mods.append(u.module)
                self.config.set(udf.UDF_MODULES, ",".join(mods))

    def register_parquet(self, name: str, path: str) -> None:
        self.catalog.register(name, ParquetTable(path))
        # ship the registration with the session so remote planning sees it
        self.config.set(f"ballista.catalog.table.{name.lower()}", path)

    def register_record_batches(self, name: str, batches: list[pa.RecordBatch]) -> None:
        self.catalog.register(name, MemoryTable(batches))

    def register_arrow_table(self, name: str, table: pa.Table, partitions: int = 1) -> None:
        self.catalog.register(name, MemoryTable.from_table(table, partitions))

    def deregister_table(self, name: str) -> None:
        self.catalog.deregister(name)

    # -- append ingestion ----------------------------------------------------

    def append(self, table: str, data) -> dict:
        """Append rows to a registered table without rewriting its files.

        Bumps the table's version: cached results over the table either
        maintain incrementally from the retained delta or recompute
        (docs/streaming.md). `data` is a pa.Table, RecordBatch, or list of
        batches; columns match the table schema by name and are cast to its
        types. Returns {"table", "version", "rows"}.
        """
        name = table.lower()
        provider = self.catalog.get(name)
        schema = provider.arrow_schema() if provider is not None else None
        batches = conform_append_batches(data, schema)
        rows = sum(b.num_rows for b in batches)
        if self.mode == "standalone":
            scheduler = self._ensure_cluster().scheduler
            sid = scheduler.sessions.create_or_update(
                self.config.to_key_value_pairs(), str(self.session_id))
            return scheduler.append_data(name, batches, sid)
        if self.mode == "remote":
            return self._ensure_remote().append_data(name, batches)
        # local mode: overlay the registered provider in place; the planner
        # unions the base scan with the overlay (AppendedTable)
        if provider is None:
            raise PlanningError(f"table not found: {table}")
        from ballista_tpu.plan.provider import AppendedTable

        if not isinstance(provider, AppendedTable):
            provider = AppendedTable(provider)
            self.catalog.register(name, provider)
        version = provider.append(batches)
        return {"table": name, "version": version, "rows": rows}

    # -- SQL ---------------------------------------------------------------

    def sql(self, query: str) -> "DataFrame":
        stmt = parse_sql(query)
        if isinstance(stmt, CreateExternalTable):
            self.register_parquet(stmt.name, stmt.location)
            return DataFrame._empty(self, f"created table {stmt.name}")
        if isinstance(stmt, DropTable):
            self.deregister_table(stmt.name)
            return DataFrame._empty(self, f"dropped table {stmt.name}")
        if isinstance(stmt, ShowColumns):
            provider = self.catalog.get(stmt.table)
            if provider is None:
                raise PlanningError(f"table not found: {stmt.table}")
            from ballista_tpu.plan.logical import TableScan
            from ballista_tpu.plan.provider import MemoryTable as MT

            sch = provider.df_schema()
            tbl = pa.table({
                "column_name": pa.array([f.name for f in sch]),
                "data_type": pa.array([str(f.dtype) for f in sch]),
                "is_nullable": pa.array(["YES" if f.nullable else "NO" for f in sch]),
            })
            return DataFrame(self, TableScan("columns", MT.from_table(tbl)))
        if isinstance(stmt, ShowTables):
            tbl = pa.table({"table_name": pa.array(self.catalog.names())})
            from ballista_tpu.plan.logical import TableScan
            from ballista_tpu.plan.provider import MemoryTable as MT

            return DataFrame(self, TableScan("tables", MT.from_table(tbl)))
        if isinstance(stmt, SetVariable):
            self.config.set(stmt.key, stmt.value)
            return DataFrame._empty(self, f"set {stmt.key}")
        if isinstance(stmt, ExplainStmt):
            inner = SqlPlanner(self.catalog).plan_query(stmt.inner)
            return DataFrame(self, Explain(inner, stmt.analyze, stmt.verbose))
        if isinstance(stmt, SelectStmt):
            plan = SqlPlanner(self.catalog).plan_query(stmt)
            return DataFrame(self, plan, sql_text=query)
        raise PlanningError(f"unsupported statement {type(stmt).__name__}")

    def prepare(self, query: str) -> "ClientPreparedStatement":
        """Prepare a parameterized SELECT once; `execute(params)` then
        binds fresh literal values into the cached plan template without
        re-parsing or re-planning (the serving tier's prepared-statement
        surface). Parameter slots are the statement's literals in plan
        walk order."""
        return ClientPreparedStatement(self, query)

    def table(self, name: str) -> "DataFrame":
        from ballista_tpu.plan.logical import TableScan

        provider = self.catalog.get(name)
        if provider is None:
            raise PlanningError(f"table not found: {name}")
        return DataFrame(self, TableScan(name, provider))

    # -- planning / execution ----------------------------------------------

    def optimize(self, plan: LogicalPlan) -> LogicalPlan:
        return optimize(plan)

    def create_physical_plan(self, plan: LogicalPlan) -> ExecutionPlan:
        from ballista_tpu.engine.physical_planner import PhysicalPlanner

        optimized = optimize(plan)
        return PhysicalPlanner(self.config).plan(optimized)

    def execute_collect(self, physical: ExecutionPlan) -> pa.Table:
        engine_name = str(self.config.get(EXECUTOR_ENGINE))
        if engine_name == "tpu":
            from ballista_tpu.engine.tpu_engine import maybe_compile_tpu

            physical = maybe_compile_tpu(physical, self.config)
        ctx = TaskContext(self.config)
        n = physical.output_partition_count()
        batches: list[pa.RecordBatch] = []
        if n == 1:
            batches.extend(physical.execute(0, ctx))
        else:
            with _fut.ThreadPoolExecutor(max_workers=min(n, 16)) as pool:
                futs = [pool.submit(lambda p=p: list(physical.execute(p, ctx))) for p in range(n)]
                for f in futs:
                    batches.extend(f.result())
        batches = [b for b in batches if b.num_rows]
        schema = physical.schema()
        if not batches:
            return pa.table({f.name: pa.array([], f.type) for f in schema}, schema=schema)
        return pa.Table.from_batches(batches, schema=schema)


class ClientPreparedStatement:
    """Client handle for a prepared statement. Prepare parses and plans
    the statement once (server-side for standalone/remote, in-process for
    local mode); execute() binds parameter values and collects. The slot
    order is the statement's literal order in plan walk order — the handle
    exposes `num_params` and `type_tags` so callers can check it."""

    def __init__(self, ctx: SessionContext, query: str):
        self.ctx = ctx
        self.sql = query
        self.statement_id = ""
        self._local_lift = None
        if ctx.mode == "standalone" and not ctx._has_memory_tables():
            # memory tables never ship to the scheduler (same rule as
            # _collect_standalone) — those statements prepare in-process
            scheduler = ctx._ensure_cluster().scheduler
            sid = scheduler.sessions.create_or_update(
                ctx.config.to_key_value_pairs(), str(ctx.session_id))
            handle = scheduler.prepare_statement(query, sid)
        elif ctx.mode == "remote":
            handle = ctx._ensure_remote().prepare_statement(query)
        else:
            from ballista_tpu.serving.normalize import lift_parameters
            from ballista_tpu.sql.ast import SelectStmt as _Sel

            stmt = parse_sql(query)
            if not isinstance(stmt, _Sel):
                raise PlanningError("only SELECT statements can be prepared")
            lift = lift_parameters(optimize(SqlPlanner(ctx.catalog).plan_query(stmt)))
            if not lift.cacheable:
                raise PlanningError(f"statement cannot be parameterized: {lift.reason}")
            self._local_lift = lift
            handle = {"statement_id": "local", "num_params": len(lift.values),
                      "type_tags": list(lift.type_tags)}
        self.statement_id = handle["statement_id"]
        self.num_params = int(handle["num_params"])
        self.type_tags = list(handle["type_tags"])

    def execute(self, params=None) -> pa.Table:
        from ballista_tpu.config import CLIENT_JOB_TIMEOUT_S
        from ballista_tpu.errors import ExecutionError

        if self.ctx.mode == "standalone" and self._local_lift is None:
            scheduler = self.ctx._ensure_cluster().scheduler
            sid = scheduler.sessions.create_or_update(
                self.ctx.config.to_key_value_pairs(), str(self.ctx.session_id))
            job_id = scheduler.execute_prepared(
                self.statement_id, params, sid, inline_results=True)
            status = scheduler.wait_for_job(
                job_id, timeout=float(self.ctx.config.get(CLIENT_JOB_TIMEOUT_S)))
            if status["state"] != "successful":
                raise ExecutionError(
                    f"job {job_id} {status['state']}: {status.get('error', '')}")
            return fetch_job_results(status, self.ctx.config)
        if self.ctx.mode == "remote" and self._local_lift is None:
            client = self.ctx._ensure_remote()
            job_id = client.execute_prepared(self.statement_id, params)
            status = client.wait_for_job(
                job_id, timeout=float(self.ctx.config.get(CLIENT_JOB_TIMEOUT_S)))
            if status["state"] != "successful":
                raise ExecutionError(
                    f"job {job_id} {status['state']}: {status.get('error', '')}")
            return fetch_job_results(status, self.ctx.config)
        # local mode: bind into the retained tagged plan and execute here
        from ballista_tpu.serving.normalize import bind_logical

        values = tuple(params) if params is not None else self._local_lift.values
        if len(values) != self.num_params:
            raise PlanningError(
                f"statement takes {self.num_params} parameters, got {len(values)}")
        bound = bind_logical(self._local_lift.tagged, values)
        physical = self.ctx.create_physical_plan(bound)
        return self.ctx.execute_collect(physical)

    def subscribe(self, params=None) -> "ClientSubscription":
        """Continuous-query mode: subscribe this statement to the versions
        of the tables it reads. Every append (or DDL) that touches one of
        them re-executes the statement — incrementally when the plan shape
        is maintainable — and pushes the refreshed result; `next()` blocks
        for it. The first result (current table state) arrives immediately."""
        if self.ctx.mode == "standalone" and self._local_lift is None:
            scheduler = self.ctx._ensure_cluster().scheduler
            sub = scheduler.subscribe_statement(
                self.statement_id, params, str(self.ctx.session_id))
            return ClientSubscription(self.ctx, sub=sub)
        if self.ctx.mode == "remote" and self._local_lift is None:
            stream = self.ctx._ensure_remote().subscribe_query(self.statement_id, params)
            return ClientSubscription(self.ctx, stream=stream)
        raise PlanningError(
            "continuous queries need a scheduler (standalone or remote mode)")

    def close(self) -> None:
        if (self.ctx.mode == "standalone" and self._local_lift is None
                and self.ctx._cluster is not None):
            self.ctx._cluster.scheduler.close_prepared(self.statement_id)


class ClientSubscription:
    """Handle for a continuous query. `next(timeout)` blocks for the next
    refreshed result table; `close()` unsubscribes. Standalone mode drains
    the scheduler's in-process subscription queue; remote mode drains the
    SubscribeQuery push stream and fetches each refresh's partitions."""

    def __init__(self, ctx: SessionContext, sub=None, stream=None):
        self.ctx = ctx
        self._sub = sub
        self._stream = stream
        self.subscription_id = sub.sub_id if sub is not None else ""

    def next(self, timeout: float = 30.0) -> pa.Table:
        from ballista_tpu.errors import ExecutionError

        if self._sub is not None:
            import queue as _q

            try:
                st = self._sub.queue.get(timeout=timeout)
            except _q.Empty:
                raise ExecutionError(
                    f"no refresh within {timeout}s on {self.subscription_id}") from None
        else:
            st = self._stream.next(timeout=timeout)
            if not self.subscription_id:
                self.subscription_id = self._stream.sub_id
        if st.get("state") != "successful":
            raise ExecutionError(
                f"subscription refresh {st.get('state')}: {st.get('error', '')}")
        return fetch_job_results(st, self.ctx.config)

    def close(self) -> None:
        if self._sub is not None and self.ctx._cluster is not None:
            self.ctx._cluster.scheduler.unsubscribe(self._sub.sub_id)
        elif self._stream is not None:
            self._stream.close()


class DataFrame:
    """Lazy logical-plan wrapper (reference: DataFusion DataFrame surface
    re-exported through ballista's prelude)."""

    def __init__(self, ctx: SessionContext, plan: LogicalPlan, sql_text: str | None = None):
        self.ctx = ctx
        self.plan = plan
        self.sql_text = sql_text

    @classmethod
    def _empty(cls, ctx: SessionContext, note: str) -> "DataFrame":
        tbl = pa.table({"result": pa.array([note])})
        from ballista_tpu.plan.logical import TableScan
        from ballista_tpu.plan.provider import MemoryTable as MT

        return cls(ctx, TableScan("result", MT.from_table(tbl)))

    # -- transformations ----------------------------------------------------

    def select(self, *exprs) -> "DataFrame":
        from ballista_tpu.plan.expressions import col as _col
        from ballista_tpu.plan.logical import Projection

        es = [(_col(e) if isinstance(e, str) else e) for e in exprs]
        return DataFrame(self.ctx, Projection(self.plan, es))

    def filter(self, predicate) -> "DataFrame":
        from ballista_tpu.plan.logical import Filter as F

        return DataFrame(self.ctx, F(self.plan, predicate))

    def aggregate(self, group_exprs, agg_exprs) -> "DataFrame":
        from ballista_tpu.plan.logical import Aggregate as A

        return DataFrame(self.ctx, A(self.plan, list(group_exprs), list(agg_exprs)))

    def sort(self, *keys) -> "DataFrame":
        from ballista_tpu.plan.logical import Sort as S

        return DataFrame(self.ctx, S(self.plan, list(keys)))

    def limit(self, fetch: int, skip: int = 0) -> "DataFrame":
        from ballista_tpu.plan.logical import Limit as L

        return DataFrame(self.ctx, L(self.plan, fetch, skip))

    def join(self, other: "DataFrame", on: list, how: str = "inner") -> "DataFrame":
        from ballista_tpu.plan.expressions import col as _col
        from ballista_tpu.plan.logical import Join as J

        pairs = []
        for item in on:
            if isinstance(item, str):
                pairs.append((_col(item), _col(item)))
            else:
                l, r = item
                pairs.append((_col(l) if isinstance(l, str) else l, _col(r) if isinstance(r, str) else r))
        return DataFrame(self.ctx, J(self.plan, other.plan, pairs, how))

    # -- actions ------------------------------------------------------------

    def logical_plan(self) -> LogicalPlan:
        return self.plan

    def optimized_plan(self) -> LogicalPlan:
        return self.ctx.optimize(self.plan)

    def explain_text(self) -> str:
        logical = self.ctx.optimize(self.plan)
        physical = self.ctx.create_physical_plan(self.plan)
        return f"logical plan:\n{logical.display()}\nphysical plan:\n{physical.display()}"

    def collect(self) -> pa.Table:
        if isinstance(self.plan, Explain):
            return self._collect_explain()
        if self.ctx.mode == "standalone":
            return self._collect_standalone()
        if self.ctx.mode == "remote":
            return self.ctx._ensure_remote().collect(self)
        physical = self.ctx.create_physical_plan(self.plan)
        return self.ctx.execute_collect(physical)

    def _collect_standalone(self) -> pa.Table:
        """Submit through the in-process scheduler: real stages, real
        shuffle files, results fetched from the final stage's partitions
        (the DistributedQueryExec flow, distributed_query.rs:211)."""
        from ballista_tpu.errors import ExecutionError

        cluster = self.ctx._ensure_cluster()
        scheduler = cluster.scheduler
        session_id = scheduler.sessions.create_or_update(
            self.ctx.config.to_key_value_pairs(), str(self.ctx.session_id)
        )
        if self.sql_text is not None and not self.ctx._has_memory_tables():
            # inline_results: this process can accept a result table right
            # in the status dict (serving-tier result-cache hits)
            job_id = scheduler.submit_sql(self.sql_text, session_id, inline_results=True)
        else:
            # in-memory tables can't be re-resolved from SQL on the
            # scheduler: plan CLIENT-side and submit the physical plan
            # (MemoryScanNode ships the batches as IPC bytes) — the
            # reference's BallistaQueryPlanner flow
            physical = self.ctx.create_physical_plan(self.plan)
            job_id = scheduler.submit_physical_plan(physical, session_id)
        from ballista_tpu.config import CLIENT_JOB_TIMEOUT_S

        status = scheduler.wait_for_job(
            job_id, timeout=float(self.ctx.config.get(CLIENT_JOB_TIMEOUT_S)))
        if status["state"] != "successful":
            raise ExecutionError(f"job {job_id} {status['state']}: {status.get('error', '')}")
        return fetch_job_results(status, self.ctx.config)

    def _collect_explain(self) -> pa.Table:
        assert isinstance(self.plan, Explain)
        logical = self.ctx.optimize(self.plan.input)
        physical = self.ctx.create_physical_plan(self.plan.input)
        types = ["logical_plan", "physical_plan"]
        plans = [logical.display(), physical.display()]
        if self.plan.analyze and self.ctx.mode == "standalone":
            # distributed EXPLAIN ANALYZE: run the job through the cluster,
            # then render per-stage operator metrics from the scheduler
            # (reference: DistributedExplainAnalyzeExec + GetJobMetrics)
            inner = DataFrame(self.ctx, self.plan.input)
            inner.collect()
            sched = self.ctx._cluster.scheduler
            with sched._jobs_lock:
                g = list(sched.jobs.values())[-1]
            lines = []
            for sid in sorted(g.stage_metrics):
                lines.append(f"stage {sid}:")
                for m in g.stage_metrics[sid][:100]:
                    lines.append(
                        f"  {'  ' * int(m.get('depth', 0))}{m.get('name', '')}: "
                        f"rows={m.get('output_rows', 0)} "
                        f"elapsed_ms={m.get('elapsed_ns', 0) / 1e6:.2f}"
                    )
            types.append("analyzed_plan (distributed)")
            plans.append("\n".join(lines))
        elif self.plan.analyze and self.ctx.mode == "remote":
            # remote EXPLAIN ANALYZE: submit the physical plan, then fetch
            # per-stage operator metrics over the GetJobMetrics rpc
            from ballista_tpu.errors import ExecutionError

            client = self.ctx._ensure_remote()
            job_id = client.execute_physical(physical)
            status = client.wait_for_job(job_id)
            if status["state"] != "successful":
                raise ExecutionError(
                    f"job {job_id} {status['state']}: {status.get('error', '')}"
                )
            metrics = client.job_metrics(job_id)
            lines = []
            for sp in metrics.stages:
                lines.append(f"stage {sp.stage_id}:")
                for m in list(sp.metrics)[:100]:
                    lines.append(
                        f"  {'  ' * m.depth}{m.name}: rows={m.output_rows} "
                        f"elapsed_ms={m.elapsed_ns / 1e6:.2f}"
                    )
            types.append("analyzed_plan (distributed)")
            plans.append("\n".join(lines))
        elif self.plan.analyze:
            # compile FIRST so the analyzed tree is the executed tree —
            # execute_collect compiles a local copy, which would leave the
            # displayed plan with empty metrics (and hide the TPU stages)
            if str(self.ctx.config.get(EXECUTOR_ENGINE)) == "tpu":
                from ballista_tpu.engine.tpu_engine import maybe_compile_tpu

                physical = maybe_compile_tpu(physical, self.ctx.config)
            self.ctx.execute_collect(physical)
            from ballista_tpu.plan.physical import collect_metrics

            lines = []
            for depth, name, m in collect_metrics(physical):
                lines.append(f"{'  ' * depth}{name}: rows={m['output_rows']} elapsed_ms={m['elapsed_ns'] / 1e6:.2f}")
            types.append("analyzed_plan")
            plans.append("\n".join(lines))
        return pa.table({"plan_type": pa.array(types), "plan": pa.array(plans)})

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    def show(self, n: int = 20) -> None:
        print(self.collect().slice(0, n).to_pandas().to_string())


def conform_append_batches(data, schema: pa.Schema | None) -> list[pa.RecordBatch]:
    """Normalize append input (Table / RecordBatch / list of batches) to
    record batches conforming to the table schema: columns match by NAME
    (not position) and cast to the declared types, so callers can append a
    column subset order-independently. Missing columns are an explicit
    error rather than a silent null fill — appends must be self-complete.
    With no schema (table unknown client-side) the rows ship as-is and the
    server-side scan alignment does the work."""
    if isinstance(data, pa.RecordBatch):
        tbl = pa.Table.from_batches([data])
    elif isinstance(data, pa.Table):
        tbl = data
    else:
        batches = list(data)
        if not batches:
            raise PlanningError("append needs at least one row batch")
        tbl = pa.Table.from_batches(batches)
    if schema is None:
        return tbl.combine_chunks().to_batches()
    cols = []
    for f in schema:
        idx = tbl.schema.get_field_index(f.name)
        if idx < 0:
            raise PlanningError(f"append is missing column {f.name!r}")
        cols.append(tbl.column(idx).cast(f.type))
    return pa.Table.from_arrays(cols, schema=schema).combine_chunks().to_batches()


def fetch_job_results(status: dict, config: BallistaConfig) -> pa.Table:
    """Fetch a successful job's final-stage partitions (local fast path or
    Flight) and assemble the client result table."""
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.shuffle.reader import fetch_partition

    from ballista_tpu.config import FLIGHT_PROXY, SHUFFLE_READER_FORCE_REMOTE

    # serving-tier result-cache hit: the table rode back in the status
    # dict; nothing to fetch
    inline = status.get("inline_result")
    if inline is not None:
        return inline
    schema = status["schema"].to_arrow() if status.get("schema") is not None else None
    locs = sorted(status.get("partitions", []), key=lambda l: (l.output_partition, l.map_partition))
    ctx = TaskContext(config)
    # a configured Flight proxy implies executors are not client-reachable:
    # never take the same-path local shortcut (it only holds when the client
    # shares the executor's filesystem)
    force = bool(config.get(SHUFFLE_READER_FORCE_REMOTE)) or bool(config.get(FLIGHT_PROXY))
    batches = []
    for loc in locs:
        for b in fetch_partition(loc, ctx, force_remote=force):
            if b.num_rows:
                batches.append(b)
    if not batches:
        if schema is None:
            return pa.table({})
        return pa.table({f.name: pa.array([], f.type) for f in schema}, schema=schema)
    return pa.Table.from_batches(batches, schema=batches[0].schema)
