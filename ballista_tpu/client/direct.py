"""Lease-based direct dispatch: the prepared-statement hot path goes
scheduler-less.

The serving tier's fast lane still pays one scheduler round trip per
query (submit → plan-cache hit → slot reservation → launch). With a
lease (`ballista_tpu/serving/lease.py`) the scheduler leaves the hot
path entirely: it mints a revocable capacity slice on one warm executor
ONCE, and the client — which already holds the bound plan template via
its prepared statement — binds parameters, allocates task ids from the
lease's reserved band, and runs the single-stage job straight against
the executor (in-process seam or the executor's Flight endpoint). The
scheduler only hears about completed work afterwards, through
`SchedulerServer.reconcile_direct_dispatch`.

Demotion contract: ANY rejection (revoked/expired lease, band
exhausted, capacity, multi-stage plan, no executor headroom) falls back
to `SchedulerServer.execute_prepared` — the ordinary graph path — and
returns byte-identical results, because both paths execute the same
bound plan and fetch through `fetch_job_results`.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ballista_tpu.config import BallistaConfig
from ballista_tpu.errors import BallistaError, ExecutionError
from ballista_tpu.ids import new_job_id
from ballista_tpu.scheduler.state.execution_graph import TaskDescription

log = logging.getLogger(__name__)


class LeaseRejected(Exception):
    """A direct-dispatch admission check failed; carries the reason the
    executor (or transport) gave. The dispatcher demotes on it."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class LocalLeaseTransport:
    """In-process transport: admission through the executor's LeaseTable,
    execution via Executor.run_task — the standalone-mode seam the
    Flight transport mirrors on the wire."""

    def __init__(self, executors: dict):
        self.executors = executors

    def run(self, lease, task: TaskDescription, config=None):
        ex = self.executors.get(lease.executor_id)
        if ex is None:
            raise LeaseRejected("unknown-executor")
        reason = ex.lease_table.admit(lease.lease_id, task.task_id)
        if reason is not None:
            raise LeaseRejected(reason)
        try:
            return ex.run_task(task, config)
        finally:
            ex.lease_table.release(lease.lease_id)


class FlightLeaseTransport:
    """Wire transport: one `lease_dispatch` Flight action per task against
    the executor endpoint named in the lease (header line + proto)."""

    def __init__(self):
        self._conns: dict[str, object] = {}
        self._lock = threading.Lock()

    def _connect(self, lease):
        import pyarrow.flight as flight

        key = f"{lease.host}:{lease.flight_port}"
        with self._lock:
            conn = self._conns.get(key)
            if conn is None:
                conn = self._conns[key] = flight.connect(f"grpc://{key}")
            return conn

    def run(self, lease, task: TaskDescription, config=None):
        import pyarrow.flight as flight

        from ballista_tpu.executor.executor import ExecutorMetadata
        from ballista_tpu.proto import pb
        from ballista_tpu.serde_control import (
            decode_task_status, encode_task_definition)

        head = json.dumps({"lease_id": lease.lease_id,
                           "executor_id": lease.executor_id}).encode()
        payload = encode_task_definition(task, config).SerializeToString()
        conn = self._connect(lease)
        results = list(conn.do_action(
            flight.Action("lease_dispatch", head + b"\n" + payload)))
        header = json.loads(results[0].body.to_pybytes().decode())
        if "rejected" in header:
            raise LeaseRejected(str(header["rejected"]))
        status = pb.TaskStatusProto.FromString(results[1].body.to_pybytes())
        meta = ExecutorMetadata(id=lease.executor_id, host=lease.host,
                                flight_port=lease.flight_port)
        return decode_task_status(status, meta)


class DirectDispatcher:
    """Client-side direct-dispatch driver for one prepared statement.

    The scheduler stays on the CONTROL path only: `prepare` registers the
    statement, `_lease` mints/refreshes the capacity token, demotions go
    back through `execute_prepared`, and every completed direct job is
    reconciled after the client already has its bytes."""

    def __init__(self, scheduler, transport, session_id: str,
                 slots: int | None = None, ttl_s: float | None = None):
        self.scheduler = scheduler
        self.transport = transport
        self.session_id = session_id
        self.slots = slots
        self.ttl_s = ttl_s
        self.statement_id: str | None = None
        self._stmt_key: str | None = None
        self._lease = None
        self._lock = threading.Lock()
        # outcome counters: the qps exercise's direct_dispatch_rate reads
        # direct / (direct + demoted)
        self.stats = {"direct": 0, "demoted": 0, "tasks": 0}

    # -- control path (scheduler) ------------------------------------------

    def prepare(self, sql: str) -> str:
        out = self.scheduler.prepare_statement(sql, self.session_id)
        self.statement_id = out["statement_id"]
        stmt = self.scheduler.serving.get_prepared(self.statement_id)
        self._stmt_key = stmt.key
        return self.statement_id

    def _acquire_lease(self):
        with self._lock:
            if self._lease is not None and self._lease.rejection() is None:
                return self._lease
            self._lease = self.scheduler.mint_executor_lease(
                self.session_id, slots=self.slots, ttl_s=self.ttl_s)
            return self._lease

    def invalidate_lease(self) -> None:
        with self._lock:
            self._lease = None

    def _demote(self, params, reason: str):
        """Byte-identical fallback: the ordinary prepared-statement path
        through the scheduler (graph or fast lane)."""
        log.debug("direct dispatch demoted (%s); falling back to scheduler", reason)
        self.invalidate_lease()
        self.scheduler.leases.note_demoted()
        self.scheduler.metrics.record_direct_dispatch("demoted")
        self.stats["demoted"] += 1
        job_id = self.scheduler.execute_prepared(
            self.statement_id, params, session_id=self.session_id)
        status = self.scheduler.wait_for_job(job_id)
        if status["state"] != "successful":
            raise ExecutionError(
                f"job {job_id} {status['state']}: {status.get('error', '')}")
        return status

    # -- hot path (scheduler-less) -----------------------------------------

    def _bind_single_stage(self, params, job_id: str):
        """Bind params into the cached template and stage it; None unless
        the plan is single-stage (direct dispatch is the fast lane's
        contract: one stage, no shuffle dependencies)."""
        from ballista_tpu.scheduler.planner import DistributedPlanner, merge_mesh_stages
        from ballista_tpu.serving.normalize import bind_physical

        template = self.scheduler.serving.lookup_template(
            self._stmt_key, tuple(params) if params is not None else ())
        if template is None:
            return None, None
        cfg = self.scheduler.sessions.get(self.session_id) or BallistaConfig()
        bound = bind_physical(template.physical, tuple(params or ()))
        stages = merge_mesh_stages(
            DistributedPlanner(job_id).plan_query_stages(bound), cfg)
        if len(stages) != 1:
            return None, cfg
        return stages[0], cfg

    def execute(self, params=None):
        """Run one bound query: direct against the leased executor when
        everything lines up, demoted to the scheduler path otherwise.
        Returns the job-status dict (same shape both ways)."""
        if self.statement_id is None:
            raise BallistaError("prepare() first")
        job_id = f"direct-{new_job_id()}"
        try:
            stage, cfg = self._bind_single_stage(params, job_id)
        except Exception as e:  # noqa: BLE001 — planning trouble → scheduler owns it
            return self._demote(params, f"bind-failed: {e}")
        if stage is None:
            return self._demote(params, "not-single-stage" if cfg else "template-evicted")
        # append ingestion: retained deltas live on the scheduler and graft
        # at dispatch time — a direct launch of the cached template would
        # scan stale base files, so any appended table demotes
        if not self.scheduler.ingest.empty():
            from ballista_tpu.serving.normalize import collect_scan_tables

            touched = collect_scan_tables(stage.plan)
            if touched & self.scheduler.ingest.tables_with_deltas():
                return self._demote(params, "appended-table")
        lease = self._acquire_lease()
        if lease is None:
            return self._demote(params, "no-lease")
        locations = []
        try:
            for p in range(stage.partitions):
                task_id = lease.take_task_id()
                if task_id is None:
                    raise LeaseRejected("band-exhausted")
                task = TaskDescription(
                    job_id=job_id, stage_id=stage.stage_id, stage_attempt=0,
                    task_id=task_id, partitions=[p], plan=stage.plan,
                    session_id=self.session_id, fast_lane=True)
                result = self.transport.run(lease, task, cfg)
                if result.state != "success":
                    raise LeaseRejected(f"task-failed: {result.error}")
                locations.extend(result.locations or [])
        except LeaseRejected as e:
            return self._demote(params, e.reason)
        self.stats["direct"] += 1
        self.stats["tasks"] += stage.partitions
        self.scheduler.metrics.record_direct_dispatch("dispatched")
        # asynchronous reconciliation: the client already has its result
        # locations; the scheduler folds the accounting in after the fact
        self.scheduler.reconcile_direct_dispatch(
            {"lease_id": lease.lease_id, "job_id": job_id,
             "tasks": stage.partitions})
        return {
            "job_id": job_id, "job_name": "", "state": "successful",
            "error": "", "completed_stages": 1, "total_stages": 1,
            "queued_at": time.time(), "ended_at": time.time(),
            "fast_lane": True, "direct_dispatch": True,
            "schema": stage.plan.input.df_schema,
            "partitions": sorted(
                locations, key=lambda l: (l.output_partition, l.map_partition)),
        }

    def direct_dispatch_rate(self) -> float:
        total = self.stats["direct"] + self.stats["demoted"]
        return self.stats["direct"] / total if total else 0.0
