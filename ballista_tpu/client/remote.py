"""Remote scheduler client: the client side of the distributed query flow.

Rebuild of DistributedQueryExec (core/src/execution_plans/
distributed_query.rs:64,211): CreateUpdateSession with the full session
config (catalog registrations ride along as KV pairs), ExecuteQuery (SQL
or physical-plan protobuf), GetJobStatus polling, then fetch result
partitions from executors over Flight (local fast path applies when
colocated).

Overload cooperation: submissions shed by the scheduler's admission gate
come back as RESOURCE_EXHAUSTED with a `retry-after-ms` hint in trailing
metadata; this client honors the hint with jittered exponential backoff
instead of hammering an already-overloaded control plane. Idempotent
RPCs (GetJobStatus, CreateUpdateSession) retry on transient UNAVAILABLE/
DEADLINE_EXCEEDED, and wait_for_job's poll interval grows exponentially
so long jobs don't keep a tight 10 Hz poll loop open per client.
"""

from __future__ import annotations

import logging
import random
import time

import grpc
import pyarrow as pa

from ballista_tpu.config import (
    CLIENT_BACKOFF_BASE_MS,
    CLIENT_BACKOFF_MAX_MS,
    CLIENT_JOB_TIMEOUT_S,
    CLIENT_SUBMIT_RETRIES,
    BallistaConfig,
)
from ballista_tpu.errors import ClusterOverloaded, ExecutionError, GrpcError
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.grpc_service import scheduler_stub
from ballista_tpu.serde import encode_plan
from ballista_tpu.serde_control import decode_job_status

log = logging.getLogger(__name__)

# the floor sets the best-case tail latency a polling client can observe:
# at the old 100ms floor a 5ms fast-lane query always took >=100ms
# end-to-end, wiping out the serving tier's win. 10ms keeps short-query
# p99 honest; the exponential growth still backs long jobs off to the cap.
POLL_INTERVAL_S = 0.01
POLL_INTERVAL_MAX_S = 2.0

# transient codes worth retrying on idempotent rpcs
_TRANSIENT = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.DEADLINE_EXCEEDED)


def _retry_after_ms(e: grpc.RpcError) -> int | None:
    """Extract the scheduler's backoff hint from a RESOURCE_EXHAUSTED
    rejection: trailing metadata first, message text as fallback."""
    try:
        for k, v in (e.trailing_metadata() or ()):
            if k == "retry-after-ms":
                return int(v)
    except Exception:  # noqa: BLE001 — metadata shape varies by transport
        pass
    import re

    m = re.search(r"retry_after_ms=(\d+)", str(e.details() if hasattr(e, "details") else e))
    return int(m.group(1)) if m else None


class RemoteSchedulerClient:
    def __init__(self, scheduler_url: str, config: BallistaConfig):
        addr = scheduler_url.replace("df://", "").replace("grpc://", "")
        from ballista_tpu.utils.grpc_util import create_channel

        self.channel = create_channel(addr, config)
        self.stub = scheduler_stub(self.channel)
        self.config = config
        self.session_id: str = ""
        self.submit_retries = 0  # observability: backoffs taken on submit

    def _settings(self) -> list[pb.KeyValuePair]:
        return [pb.KeyValuePair(key=k, value=v) for k, v in self.config.to_key_value_pairs()]

    def _backoff_s(self, attempt: int, hint_ms: int | None = None) -> float:
        """Jittered exponential backoff, floored at the server's
        retry_after_ms hint when one was given: the server knows its own
        drain rate better than our exponent does."""
        base = int(self.config.get(CLIENT_BACKOFF_BASE_MS))
        cap = int(self.config.get(CLIENT_BACKOFF_MAX_MS))
        ms = min(cap, base * (2 ** attempt))
        if hint_ms is not None:
            ms = min(cap, max(ms, hint_ms))
        # full jitter (0.5x..1.0x) decorrelates a herd of rejected clients
        return ms * (0.5 + random.random() * 0.5) / 1000.0

    def _call_idempotent(self, fn, req, what: str, timeout: float = 10.0):
        """Retry an idempotent rpc on transient UNAVAILABLE /
        DEADLINE_EXCEEDED with jittered backoff (satellite: wait_for_job
        must not raise through the caller mid-poll on a scheduler blip)."""
        retries = int(self.config.get(CLIENT_SUBMIT_RETRIES))
        for attempt in range(retries + 1):
            try:
                return fn(req, timeout=timeout)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code not in _TRANSIENT or attempt >= retries:
                    raise
                wait = self._backoff_s(attempt)
                log.warning("%s transient failure (%s); retry %d/%d in %.2fs",
                            what, code, attempt + 1, retries, wait)
                time.sleep(wait)

    def ensure_session(self) -> str:
        req = pb.CreateSessionParams(session_id=self.session_id)
        req.settings.extend(self._settings())
        resp = self._call_idempotent(self.stub.CreateUpdateSession, req, "CreateUpdateSession")
        self.session_id = resp.session_id
        return self.session_id

    def _submit(self, req) -> str:
        """ExecuteQuery with overload cooperation: RESOURCE_EXHAUSTED
        rejections back off honoring the scheduler's retry_after_ms hint,
        then resubmit; a still-overloaded cluster after all retries
        surfaces a typed ClusterOverloaded to the caller."""
        retries = int(self.config.get(CLIENT_SUBMIT_RETRIES))
        for attempt in range(retries + 1):
            try:
                return self.stub.ExecuteQuery(req, timeout=30).job_id
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    hint = _retry_after_ms(e)
                    if attempt >= retries:
                        raise ClusterOverloaded(
                            f"submission rejected after {retries} retries: "
                            f"{e.details() if hasattr(e, 'details') else e}",
                            retry_after_ms=hint or 1000,
                        ) from None
                    wait = self._backoff_s(attempt, hint)
                    self.submit_retries += 1
                    log.info("cluster overloaded; resubmitting in %.2fs (hint=%sms, retry %d/%d)",
                             wait, hint, attempt + 1, retries)
                    time.sleep(wait)
                    continue
                if code in _TRANSIENT and attempt < retries:
                    time.sleep(self._backoff_s(attempt))
                    continue
                raise GrpcError(f"ExecuteQuery failed: {e}") from None

    def execute_sql(self, sql: str, job_name: str = "") -> str:
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(sql=sql, session_id=sid, job_name=job_name)
        req.settings.extend(self._settings())
        return self._submit(req)

    def execute_physical(self, physical, job_name: str = "") -> str:
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(session_id=sid, job_name=job_name)
        req.physical_plan.CopyFrom(encode_plan(physical))
        req.settings.extend(self._settings())
        return self._submit(req)

    def execute_sql_push(self, sql: str, job_name: str = "", timeout: float = 600.0) -> dict:
        """Submit + watch in ONE server-streaming rpc (execute_query_push):
        the scheduler pushes each state change; returns the terminal status.
        An admission rejection terminates the stream with
        RESOURCE_EXHAUSTED, surfaced as a typed ClusterOverloaded."""
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(sql=sql, session_id=sid, job_name=job_name)
        req.settings.extend(self._settings())
        last: dict | None = None
        try:
            for event in self.stub.ExecuteQueryPush(req, timeout=timeout):
                if event.HasField("status"):
                    last = decode_job_status(event.status)
                    if last["state"] in ("successful", "failed", "cancelled"):
                        return last
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                raise ClusterOverloaded(
                    f"push submission shed: {e.details() if hasattr(e, 'details') else e}",
                    retry_after_ms=_retry_after_ms(e) or 1000,
                ) from None
            raise GrpcError(f"ExecuteQueryPush failed: {e}") from None
        if last is None:
            raise ExecutionError("push stream ended without a terminal status")
        return last

    def wait_for_job(self, job_id: str, timeout: float = 600.0) -> dict:
        deadline = time.time() + timeout
        # jittered floor: a herd of clients submitting together must not
        # poll in lockstep — each client's cadence starts (and grows) at a
        # random phase, so the scheduler sees a smear instead of spikes
        poll = POLL_INTERVAL_S * (1.0 + random.random())
        while time.time() < deadline:
            resp = self._call_idempotent(
                self.stub.GetJobStatus, pb.GetJobStatusParams(job_id=job_id), "GetJobStatus")
            status = decode_job_status(resp.status)
            if status["state"] in ("successful", "failed", "cancelled"):
                return status
            time.sleep(poll)
            # exponential poll growth: fast feedback on short jobs, gentle
            # on the scheduler for long ones; jittering the factor keeps
            # initially-synchronized clients from re-converging
            poll = min(POLL_INTERVAL_MAX_S, poll * (1.25 + 0.5 * random.random()))
        raise ExecutionError(f"job {job_id} timed out")

    # -- prepared statements -------------------------------------------------

    def prepare_statement(self, sql: str) -> dict:
        """PrepareStatement rpc: plan once server-side, get back a handle
        {statement_id, num_params, type_tags} (JSON in the job_id field —
        the rpc reuses the ExecuteQuery message pair)."""
        import json

        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(sql=sql, session_id=sid)
        req.settings.extend(self._settings())
        try:
            resp = self.stub.PrepareStatement(req, timeout=30)
        except grpc.RpcError as e:
            raise GrpcError(f"PrepareStatement failed: {e}") from None
        return json.loads(resp.job_id)

    def execute_prepared(self, statement_id: str, params=None, job_name: str = "") -> str:
        """ExecutePrepared rpc with overload cooperation (same backoff
        contract as _submit); params travel JSON-encoded with type tags."""
        import json

        from ballista_tpu.serving.normalize import encode_params

        sid = self.ensure_session()
        body = {"statement_id": statement_id}
        if params is not None:
            body["params"] = encode_params(params)
        req = pb.ExecuteQueryParams(sql=json.dumps(body), session_id=sid, job_name=job_name)
        retries = int(self.config.get(CLIENT_SUBMIT_RETRIES))
        for attempt in range(retries + 1):
            try:
                return self.stub.ExecutePrepared(req, timeout=30).job_id
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    hint = _retry_after_ms(e)
                    if attempt >= retries:
                        raise ClusterOverloaded(
                            f"prepared execution rejected after {retries} retries: "
                            f"{e.details() if hasattr(e, 'details') else e}",
                            retry_after_ms=hint or 1000,
                        ) from None
                    self.submit_retries += 1
                    time.sleep(self._backoff_s(attempt, hint))
                    continue
                if code in _TRANSIENT and attempt < retries:
                    time.sleep(self._backoff_s(attempt))
                    continue
                raise GrpcError(f"ExecutePrepared failed: {e}") from None

    # -- append ingestion / continuous queries -------------------------------

    def append_data(self, table: str, batches: list[pa.RecordBatch]) -> dict:
        """AppendData rpc: ship appended rows to the scheduler's ingest
        registry. The rpc reuses the ExecuteQuery message pair — the table
        name rides in job_name, the batches ride as a MemoryScanNode plan
        (the same IPC carrier memory-table submissions use), and the
        response's job_id field carries {table, version, rows} as JSON."""
        import json

        from ballista_tpu.plan.physical import MemoryScanExec
        from ballista_tpu.plan.schema import DFSchema

        sid = self.ensure_session()
        schema = batches[0].schema if batches else pa.schema([])
        scan = MemoryScanExec(DFSchema.from_arrow(schema), batches, 1)
        req = pb.ExecuteQueryParams(session_id=sid, job_name=table)
        req.physical_plan.CopyFrom(encode_plan(scan))
        req.settings.extend(self._settings())
        try:
            resp = self.stub.AppendData(req, timeout=30)
        except grpc.RpcError as e:
            raise GrpcError(f"AppendData failed: {e}") from None
        return json.loads(resp.job_id)

    def subscribe_query(self, statement_id: str, params=None) -> "SubscriptionStream":
        """SubscribeQuery rpc: open a server-streaming continuous query on
        a prepared statement. The first frame is a handshake carrying the
        subscription id; each subsequent frame is a refreshed job status
        whose partitions the caller fetches."""
        import json

        from ballista_tpu.serving.normalize import encode_params

        sid = self.ensure_session()
        body = {"statement_id": statement_id}
        if params is not None:
            body["params"] = encode_params(params)
        req = pb.ExecuteQueryParams(sql=json.dumps(body), session_id=sid)
        req.settings.extend(self._settings())
        call = self.stub.SubscribeQuery(req)
        return SubscriptionStream(call)

    def cancel_job(self, job_id: str) -> None:
        self.stub.CancelJob(pb.CancelJobParams(job_id=job_id), timeout=10)

    def job_metrics(self, job_id: str):
        return self.stub.GetJobMetrics(pb.GetJobMetricsParams(job_id=job_id), timeout=10)

    def collect(self, df) -> pa.Table:
        from ballista_tpu.client.context import fetch_job_results
        from ballista_tpu.config import PUSH_STATUS

        timeout = float(self.config.get(CLIENT_JOB_TIMEOUT_S))
        sql_ok = df.sql_text is not None and not df.ctx._has_memory_tables()
        if sql_ok and bool(self.config.get(PUSH_STATUS)):
            status = self.execute_sql_push(df.sql_text, timeout=timeout)
        elif sql_ok:
            job_id = self.execute_sql(df.sql_text)
            status = self.wait_for_job(job_id, timeout=timeout)
        else:
            # memory tables can't be re-resolved from SQL on the scheduler:
            # plan client-side, ship the physical plan (MemoryScanNode
            # carries the batches as IPC bytes)
            physical = df.ctx.create_physical_plan(df.plan)
            job_id = self.execute_physical(physical)
            status = self.wait_for_job(job_id, timeout=timeout)
        if status["state"] != "successful":
            raise ExecutionError(
                f"job {status.get('job_id', '?')} {status['state']}: {status.get('error', '')}"
            )
        return fetch_job_results(status, self.config)


class SubscriptionStream:
    """Client side of a SubscribeQuery stream: a drain thread decouples the
    gRPC iterator from the caller so `next(timeout)` can time out without
    tearing down the stream. The handshake frame (job_id only, no status)
    carries the subscription id; every later frame is a refresh status."""

    def __init__(self, call):
        import queue
        import threading

        self.call = call
        self.sub_id = ""
        self.queue: "queue.Queue[dict]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._drain, name="subscription-drain", daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        try:
            for event in self.call:
                if event.HasField("status"):
                    self.queue.put(decode_job_status(event.status))
                elif not self.sub_id and event.job_id:
                    self.sub_id = event.job_id
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code != grpc.StatusCode.CANCELLED:  # close() cancels; not an error
                self.queue.put({"state": "failed", "error": f"subscription stream: {e}"})

    def next(self, timeout: float = 30.0) -> dict:
        import queue

        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            raise ExecutionError(
                f"no refresh within {timeout}s on subscription {self.sub_id or '?'}"
            ) from None

    def close(self) -> None:
        self.call.cancel()
