"""Remote scheduler client: the client side of the distributed query flow.

Rebuild of DistributedQueryExec (core/src/execution_plans/
distributed_query.rs:64,211): CreateUpdateSession with the full session
config (catalog registrations ride along as KV pairs), ExecuteQuery (SQL
or physical-plan protobuf), GetJobStatus polling, then fetch result
partitions from executors over Flight (local fast path applies when
colocated).
"""

from __future__ import annotations

import time

import grpc
import pyarrow as pa

from ballista_tpu.config import CLIENT_JOB_TIMEOUT_S, BallistaConfig
from ballista_tpu.errors import ExecutionError, GrpcError
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.grpc_service import scheduler_stub
from ballista_tpu.serde import encode_plan
from ballista_tpu.serde_control import decode_job_status

POLL_INTERVAL_S = 0.1


class RemoteSchedulerClient:
    def __init__(self, scheduler_url: str, config: BallistaConfig):
        addr = scheduler_url.replace("df://", "").replace("grpc://", "")
        from ballista_tpu.utils.grpc_util import create_channel

        self.channel = create_channel(addr, config)
        self.stub = scheduler_stub(self.channel)
        self.config = config
        self.session_id: str = ""

    def _settings(self) -> list[pb.KeyValuePair]:
        return [pb.KeyValuePair(key=k, value=v) for k, v in self.config.to_key_value_pairs()]

    def ensure_session(self) -> str:
        req = pb.CreateSessionParams(session_id=self.session_id)
        req.settings.extend(self._settings())
        resp = self.stub.CreateUpdateSession(req, timeout=10)
        self.session_id = resp.session_id
        return self.session_id

    def execute_sql(self, sql: str, job_name: str = "") -> str:
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(sql=sql, session_id=sid, job_name=job_name)
        req.settings.extend(self._settings())
        try:
            resp = self.stub.ExecuteQuery(req, timeout=30)
        except grpc.RpcError as e:
            raise GrpcError(f"ExecuteQuery failed: {e}") from None
        return resp.job_id

    def execute_physical(self, physical, job_name: str = "") -> str:
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(session_id=sid, job_name=job_name)
        req.physical_plan.CopyFrom(encode_plan(physical))
        req.settings.extend(self._settings())
        resp = self.stub.ExecuteQuery(req, timeout=30)
        return resp.job_id

    def execute_sql_push(self, sql: str, job_name: str = "", timeout: float = 600.0) -> dict:
        """Submit + watch in ONE server-streaming rpc (execute_query_push):
        the scheduler pushes each state change; returns the terminal status."""
        sid = self.ensure_session()
        req = pb.ExecuteQueryParams(sql=sql, session_id=sid, job_name=job_name)
        req.settings.extend(self._settings())
        last: dict | None = None
        try:
            for event in self.stub.ExecuteQueryPush(req, timeout=timeout):
                if event.HasField("status"):
                    last = decode_job_status(event.status)
                    if last["state"] in ("successful", "failed", "cancelled"):
                        return last
        except grpc.RpcError as e:
            raise GrpcError(f"ExecuteQueryPush failed: {e}") from None
        if last is None:
            raise ExecutionError("push stream ended without a terminal status")
        return last

    def wait_for_job(self, job_id: str, timeout: float = 600.0) -> dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            resp = self.stub.GetJobStatus(pb.GetJobStatusParams(job_id=job_id), timeout=10)
            status = decode_job_status(resp.status)
            if status["state"] in ("successful", "failed", "cancelled"):
                return status
            time.sleep(POLL_INTERVAL_S)
        raise ExecutionError(f"job {job_id} timed out")

    def cancel_job(self, job_id: str) -> None:
        self.stub.CancelJob(pb.CancelJobParams(job_id=job_id), timeout=10)

    def job_metrics(self, job_id: str):
        return self.stub.GetJobMetrics(pb.GetJobMetricsParams(job_id=job_id), timeout=10)

    def collect(self, df) -> pa.Table:
        from ballista_tpu.client.context import fetch_job_results
        from ballista_tpu.config import PUSH_STATUS

        timeout = float(self.config.get(CLIENT_JOB_TIMEOUT_S))
        sql_ok = df.sql_text is not None and not df.ctx._has_memory_tables()
        if sql_ok and bool(self.config.get(PUSH_STATUS)):
            status = self.execute_sql_push(df.sql_text, timeout=timeout)
        elif sql_ok:
            job_id = self.execute_sql(df.sql_text)
            status = self.wait_for_job(job_id, timeout=timeout)
        else:
            # memory tables can't be re-resolved from SQL on the scheduler:
            # plan client-side, ship the physical plan (MemoryScanNode
            # carries the batches as IPC bytes)
            physical = df.ctx.create_physical_plan(df.plan)
            job_id = self.execute_physical(physical)
            status = self.wait_for_job(job_id, timeout=timeout)
        if status["state"] != "successful":
            raise ExecutionError(
                f"job {status.get('job_id', '?')} {status['state']}: {status.get('error', '')}"
            )
        return fetch_job_results(status, self.config)
