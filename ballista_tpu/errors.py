"""Error model.

Mirrors the reference's `BallistaError` taxonomy
(ballista/core/src/error.rs:37): distinct variants for planning vs execution
vs transport vs cancellation matter because the scheduler's retry policy
branches on them (fetch failures → recompute upstream stage; task failures →
bounded per-stage retries; cancellation → no retry).
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base class for all engine errors."""

    retryable: bool = False


class NotImplementedError_(BallistaError):
    pass


class GeneralError(BallistaError):
    pass


class PlanningError(BallistaError):
    """SQL analysis / planning failed. Never retryable."""


class SqlParseError(PlanningError):
    pass


class SchemaError(PlanningError):
    pass


class ExecutionError(BallistaError):
    """An operator failed at runtime on the executor."""

    def __init__(self, msg: str, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable


class FetchFailed(BallistaError):
    """A shuffle partition could not be fetched.

    Carries enough identity for the scheduler to mark the *upstream* stage's
    output lost and recompute it (reference: ResultLost failure reason,
    ballista.proto:595, handled by rerun_successful_stage,
    scheduler/src/state/execution_graph.rs:216).
    """

    retryable = True

    def __init__(self, executor_id: str, job_id: str, stage_id: int, map_partition: int,
                 msg: str = "", cause: str = ""):
        tag = f" [{cause}]" if cause else ""
        super().__init__(
            f"fetch failed from executor={executor_id} {job_id}/{stage_id}/{map_partition}{tag}: {msg}"
        )
        self.executor_id = executor_id
        self.job_id = job_id
        self.stage_id = stage_id
        self.map_partition = map_partition
        # "corruption" when checksum verification failed twice for the same
        # map output: the scheduler additionally strikes the SERVING
        # executor's health score (its disk, not the network, is suspect)
        self.cause = cause


class IoError(BallistaError):
    retryable = True


class DataCorrupted(IoError):
    """Shuffle bytes failed checksum verification (client-side before
    decode, or a local read against the stored value). Retryable exactly
    ONCE in place — a transient in-transit flip heals on refetch — then
    escalated as FetchFailed(cause="corruption") so the upstream stage
    recomputes and the serving executor takes a corruption strike."""

    def __init__(self, where: str, expected: str, actual: str, detail: str = ""):
        extra = f" ({detail})" if detail else ""
        super().__init__(
            f"shuffle data corrupted at {where}: checksum {actual} != expected {expected}{extra}"
        )
        self.where = where
        self.expected = expected
        self.actual = actual


class DiskExhausted(IoError):
    """A shuffle write or spill demotion hit ENOSPC (or the executor's
    high disk watermark). Retryable and blame-aware like `DataCorrupted`:
    the failure names the WRITING executor's disk, so the scheduler
    re-pends the partition and the per-executor disk gauges steer the
    retry toward a node with headroom instead of hammering the full one."""

    def __init__(self, where: str, detail: str = ""):
        extra = f": {detail}" if detail else ""
        super().__init__(f"disk exhausted at {where}{extra}")
        self.where = where


class ShortRead(IoError):
    """A requested shuffle byte range extends past the file's actual size
    (torn write, truncated disk, stale index). Typed and retryable so the
    Flight server can refuse to stream a short range instead of silently
    ending the stream early."""

    def __init__(self, path: str, offset: int, length: int, size: int):
        super().__init__(
            f"shuffle file truncated: {path} has {size} bytes, range needs "
            f"[{offset}, {offset + length})"
        )
        self.path = path
        self.offset = offset
        self.length = length
        self.size = size


class GrpcError(BallistaError):
    retryable = True


class ClusterOverloaded(BallistaError):
    """The scheduler shed this submission (admission quota exceeded or the
    cluster is in a shedding/draining overload state). Always retryable;
    `retry_after_ms` is the server's backoff hint, computed from the
    admission queue's observed drain rate. Surfaced over gRPC as
    RESOURCE_EXHAUSTED with a `retry-after-ms` trailing-metadata entry."""

    retryable = True

    def __init__(self, msg: str, retry_after_ms: int = 1000, reason: str = "quota"):
        super().__init__(msg)
        self.retry_after_ms = max(0, int(retry_after_ms))
        self.reason = reason  # quota | depth | shedding | draining


class CircuitOpen(IoError):
    """Client-side circuit breaker for a Flight address is open: recent
    consecutive failures tripped it and the cooldown has not elapsed.
    Fails fast (no dial) so a dead/overloaded data-plane peer cannot tie
    up every reduce task in connect timeouts."""

    def __init__(self, addr: str, retry_after_s: float):
        super().__init__(f"circuit open for {addr} (retry in {retry_after_s:.1f}s)")
        self.addr = addr
        self.retry_after_s = retry_after_s


class Cancelled(BallistaError):
    """Task/job cancelled; terminal, not a failure for retry accounting."""


class TokioError(BallistaError):
    """Internal concurrency failure (named for parity with the reference)."""


class ConfigurationError(BallistaError):
    pass


def error_to_proto_kind(err: BaseException) -> str:
    """Stable string tag used in TaskStatus/FailedTask wire messages."""
    if isinstance(err, FetchFailed):
        # the cause rides the kind tag ("FetchPartitionError:corruption")
        # so blame-aware recovery crosses the wire without a proto change
        return f"FetchPartitionError:{err.cause}" if err.cause else "FetchPartitionError"
    if isinstance(err, ClusterOverloaded):
        return "ResourceExhausted"
    if isinstance(err, Cancelled):
        return "TaskKilled"
    if isinstance(err, DataCorrupted):
        return "DataCorrupted"
    if isinstance(err, DiskExhausted):
        return "DiskExhausted"
    if isinstance(err, (IoError, GrpcError)):
        return "IoError"
    if isinstance(err, ExecutionError):
        return "ExecutionError"
    if isinstance(err, PlanningError):
        return "PlanningError"
    return "GeneralError"
