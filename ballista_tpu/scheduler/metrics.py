"""Scheduler metrics collection.

Rebuild of SchedulerMetricsCollector (scheduler/src/metrics/mod.rs:64):
Noop + in-memory implementations, with a Prometheus text exposition
renderer (metrics/prometheus.rs:42 equivalent — histograms for job
execution/planning, counters for outcomes, pending-tasks gauge) served by
the REST API at /api/metrics.
"""

from __future__ import annotations

import threading
import time


class SchedulerMetricsCollector:
    def record_submitted(self, job_id: str) -> None: ...

    def record_completed(self, job_id: str, exec_seconds: float) -> None: ...

    def record_failed(self, job_id: str) -> None: ...

    def record_cancelled(self, job_id: str) -> None: ...

    def record_planning_ms(self, job_id: str, ms: float) -> None: ...

    def set_pending_tasks(self, n: int) -> None: ...

    def record_protocol_mismatch(self) -> None: ...

    def record_speculative_launched(self, job_id: str, stage_id: int) -> None: ...

    def record_task_timeout(self, executor_id: str) -> None: ...

    def set_quarantined_executors(self, n: int) -> None: ...

    def record_job_rejected(self, reason: str, lane: str = "batch") -> None: ...

    def set_overload_state(self, state: str) -> None: ...

    def record_pressure_rejection(self, executor_id: str) -> None: ...

    # -- serving tier (plan/result caches, fast lane, lanes) ---------------

    def record_plan_cache(self, hit: bool) -> None: ...

    def record_result_cache(self, hit: bool) -> None: ...

    def record_fast_lane(self, outcome: str) -> None: ...

    def record_lane_admitted(self, lane: str) -> None: ...

    # -- direct-dispatch leases (scheduler scale-out) ----------------------

    def record_lease(self, event: str) -> None: ...

    def record_direct_dispatch(self, outcome: str) -> None: ...

    # -- incremental maintenance (append ingestion, delta refresh) ---------

    def record_append(self, rows: int) -> None: ...

    def record_incremental(self, outcome: str) -> None: ...


class NoopMetricsCollector(SchedulerMetricsCollector):
    pass


_LATENCY_BUCKETS = [0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0]
_PLANNING_BUCKETS = [1.0, 5.0, 25.0, 100.0, 500.0, 2500.0, 10000.0]


class _Histogram:
    def __init__(self, buckets: list[float]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.n += 1
        self.total += v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, help_: str) -> list[str]:
        out = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        acc = 0
        for b, c in zip(self.buckets, self.counts):
            acc += c
            out.append(f'{name}_bucket{{le="{b}"}} {acc}')
        out.append(f'{name}_bucket{{le="+Inf"}} {self.n}')
        out.append(f"{name}_sum {self.total}")
        out.append(f"{name}_count {self.n}")
        return out


class InMemoryMetricsCollector(SchedulerMetricsCollector):
    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        self.protocol_mismatches = 0
        self.pending_tasks = 0
        self.speculative_launched = 0
        self.task_timeouts = 0
        self.quarantined_executors = 0
        # overload protection: rejections by reason + current posture
        self.jobs_rejected: dict[str, int] = {}
        self.jobs_rejected_by_lane: dict[str, int] = {}
        self.overload_state = "normal"
        self.pressure_rejections = 0
        # serving tier: cache outcomes, fast-lane outcomes, lane admissions
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.result_cache_hits = 0
        self.result_cache_misses = 0
        self.fast_lane: dict[str, int] = {}  # executed | fallback
        self.lane_admitted: dict[str, int] = {}
        # direct dispatch: lease lifecycle + dispatch outcomes
        self.lease_events: dict[str, int] = {}  # minted | revoked | expired
        self.direct_dispatch: dict[str, int] = {}  # dispatched | reconciled | demoted
        # incremental maintenance: appends + refresh outcomes
        self.appends = 0
        self.appended_rows = 0
        self.incremental: dict[str, int] = {}  # maintained | state_render | bootstrap | recompute
        self.exec_hist = _Histogram(_LATENCY_BUCKETS)
        self.plan_hist = _Histogram(_PLANNING_BUCKETS)

    def record_submitted(self, job_id: str) -> None:
        with self._lock:
            self.submitted += 1

    def record_completed(self, job_id: str, exec_seconds: float) -> None:
        with self._lock:
            self.completed += 1
            self.exec_hist.observe(exec_seconds)

    def record_failed(self, job_id: str) -> None:
        with self._lock:
            self.failed += 1

    def record_cancelled(self, job_id: str) -> None:
        with self._lock:
            self.cancelled += 1

    def record_planning_ms(self, job_id: str, ms: float) -> None:
        with self._lock:
            self.plan_hist.observe(ms)

    def set_pending_tasks(self, n: int) -> None:
        with self._lock:
            self.pending_tasks = n

    def record_protocol_mismatch(self) -> None:
        with self._lock:
            self.protocol_mismatches += 1

    def record_speculative_launched(self, job_id: str, stage_id: int) -> None:
        with self._lock:
            self.speculative_launched += 1

    def record_task_timeout(self, executor_id: str) -> None:
        with self._lock:
            self.task_timeouts += 1

    def set_quarantined_executors(self, n: int) -> None:
        with self._lock:
            self.quarantined_executors = n

    def record_job_rejected(self, reason: str, lane: str = "batch") -> None:
        with self._lock:
            self.jobs_rejected[reason] = self.jobs_rejected.get(reason, 0) + 1
            self.jobs_rejected_by_lane[lane] = self.jobs_rejected_by_lane.get(lane, 0) + 1

    def record_plan_cache(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.plan_cache_hits += 1
            else:
                self.plan_cache_misses += 1

    def record_result_cache(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.result_cache_hits += 1
            else:
                self.result_cache_misses += 1

    def record_fast_lane(self, outcome: str) -> None:
        with self._lock:
            self.fast_lane[outcome] = self.fast_lane.get(outcome, 0) + 1

    def record_lane_admitted(self, lane: str) -> None:
        with self._lock:
            self.lane_admitted[lane] = self.lane_admitted.get(lane, 0) + 1

    def record_lease(self, event: str) -> None:
        with self._lock:
            self.lease_events[event] = self.lease_events.get(event, 0) + 1

    def record_direct_dispatch(self, outcome: str) -> None:
        with self._lock:
            self.direct_dispatch[outcome] = self.direct_dispatch.get(outcome, 0) + 1

    def record_append(self, rows: int) -> None:
        with self._lock:
            self.appends += 1
            self.appended_rows += rows

    def record_incremental(self, outcome: str) -> None:
        with self._lock:
            self.incremental[outcome] = self.incremental.get(outcome, 0) + 1

    def set_overload_state(self, state: str) -> None:
        with self._lock:
            self.overload_state = state

    def record_pressure_rejection(self, executor_id: str) -> None:
        with self._lock:
            self.pressure_rejections += 1

    def jobs_rejected_total(self) -> int:
        with self._lock:
            return sum(self.jobs_rejected.values())

    def render_prometheus(self) -> str:
        with self._lock:
            lines = []
            for name, v, help_ in [
                ("ballista_scheduler_jobs_submitted_total", self.submitted, "Jobs submitted"),
                ("ballista_scheduler_jobs_completed_total", self.completed, "Jobs completed"),
                ("ballista_scheduler_jobs_failed_total", self.failed, "Jobs failed"),
                ("ballista_scheduler_jobs_cancelled_total", self.cancelled, "Jobs cancelled"),
                ("ballista_scheduler_protocol_mismatch_total", self.protocol_mismatches, "Executor wire-version mismatches"),
                ("ballista_scheduler_speculative_tasks_total", self.speculative_launched, "Speculative task attempts launched"),
                ("ballista_scheduler_task_timeouts_total", self.task_timeouts, "Tasks expired past their deadline"),
                ("ballista_scheduler_pending_tasks", self.pending_tasks, "Pending task gauge"),
                ("ballista_scheduler_quarantined_executors", self.quarantined_executors, "Executors in quarantine/probation"),
                ("ballista_scheduler_pressure_rejections_total", self.pressure_rejections, "Tasks rejected by saturated executor memory pools"),
            ]:
                lines.append(f"# HELP {name} {help_}")
                kind = "gauge" if name.endswith(("pending_tasks", "quarantined_executors")) else "counter"
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {v}")
            lines.append("# HELP ballista_scheduler_jobs_rejected_total Jobs shed by admission control, by reason")
            lines.append("# TYPE ballista_scheduler_jobs_rejected_total counter")
            for reason in sorted(self.jobs_rejected):
                lines.append(f'ballista_scheduler_jobs_rejected_total{{reason="{reason}"}} {self.jobs_rejected[reason]}')
            lines.append("# HELP ballista_scheduler_jobs_rejected_by_lane_total Jobs shed by admission control, by lane")
            lines.append("# TYPE ballista_scheduler_jobs_rejected_by_lane_total counter")
            for lane in sorted(self.jobs_rejected_by_lane):
                lines.append(f'ballista_scheduler_jobs_rejected_by_lane_total{{lane="{lane}"}} {self.jobs_rejected_by_lane[lane]}')
            lines.append("# HELP ballista_scheduler_jobs_admitted_by_lane_total Jobs admitted, by lane")
            lines.append("# TYPE ballista_scheduler_jobs_admitted_by_lane_total counter")
            for lane in sorted(self.lane_admitted):
                lines.append(f'ballista_scheduler_jobs_admitted_by_lane_total{{lane="{lane}"}} {self.lane_admitted[lane]}')
            lines.append("# HELP ballista_scheduler_plan_cache_total Serving plan-cache lookups, by outcome")
            lines.append("# TYPE ballista_scheduler_plan_cache_total counter")
            lines.append(f'ballista_scheduler_plan_cache_total{{outcome="hit"}} {self.plan_cache_hits}')
            lines.append(f'ballista_scheduler_plan_cache_total{{outcome="miss"}} {self.plan_cache_misses}')
            lines.append("# HELP ballista_scheduler_result_cache_total Serving result-cache lookups, by outcome")
            lines.append("# TYPE ballista_scheduler_result_cache_total counter")
            lines.append(f'ballista_scheduler_result_cache_total{{outcome="hit"}} {self.result_cache_hits}')
            lines.append(f'ballista_scheduler_result_cache_total{{outcome="miss"}} {self.result_cache_misses}')
            lines.append("# HELP ballista_scheduler_fast_lane_total Fast-lane dispatches, by outcome")
            lines.append("# TYPE ballista_scheduler_fast_lane_total counter")
            for outcome in sorted(self.fast_lane):
                lines.append(f'ballista_scheduler_fast_lane_total{{outcome="{outcome}"}} {self.fast_lane[outcome]}')
            lines.append("# HELP ballista_scheduler_lease_events_total Direct-dispatch lease lifecycle events, by kind")
            lines.append("# TYPE ballista_scheduler_lease_events_total counter")
            for event in sorted(self.lease_events):
                lines.append(f'ballista_scheduler_lease_events_total{{event="{event}"}} {self.lease_events[event]}')
            lines.append("# HELP ballista_scheduler_direct_dispatch_total Direct-dispatch jobs, by outcome")
            lines.append("# TYPE ballista_scheduler_direct_dispatch_total counter")
            for outcome in sorted(self.direct_dispatch):
                lines.append(f'ballista_scheduler_direct_dispatch_total{{outcome="{outcome}"}} {self.direct_dispatch[outcome]}')
            lines.append("# HELP ballista_scheduler_appends_total Append-ingestion calls accepted")
            lines.append("# TYPE ballista_scheduler_appends_total counter")
            lines.append(f"ballista_scheduler_appends_total {self.appends}")
            lines.append("# HELP ballista_scheduler_appended_rows_total Rows accepted by append ingestion")
            lines.append("# TYPE ballista_scheduler_appended_rows_total counter")
            lines.append(f"ballista_scheduler_appended_rows_total {self.appended_rows}")
            lines.append("# HELP ballista_scheduler_incremental_total Version-bumped serving refreshes, by outcome")
            lines.append("# TYPE ballista_scheduler_incremental_total counter")
            for outcome in sorted(self.incremental):
                lines.append(f'ballista_scheduler_incremental_total{{outcome="{outcome}"}} {self.incremental[outcome]}')
            lines.append("# HELP ballista_scheduler_overload_state Overload posture (0=normal 1=shedding 2=draining)")
            lines.append("# TYPE ballista_scheduler_overload_state gauge")
            state_code = {"normal": 0, "shedding": 1, "draining": 2}.get(self.overload_state, 0)
            lines.append(f"ballista_scheduler_overload_state {state_code}")
            lines.extend(self.exec_hist.render(
                "ballista_scheduler_job_exec_time_seconds", "Job execution wall time"))
            lines.extend(self.plan_hist.render(
                "ballista_scheduler_planning_time_ms", "Job planning time"))
            return "\n".join(lines) + "\n"
