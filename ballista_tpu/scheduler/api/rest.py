"""Scheduler REST API.

Rebuild of the axum REST surface (scheduler/src/api/routes.rs:24,
handlers.rs): scheduler state/version, executors, jobs (+cancel), per-job
stages with operator metrics, dot-format stage graphs, Prometheus metrics
passthrough, and a health endpoint. stdlib http.server — zero deps, same
routes.

GET  /api/state                 GET  /api/executors
GET  /api/jobs                  GET  /api/job/{id}
GET  /api/job/{id}/stages       GET  /api/job/{id}/dot
GET  /api/job/{id}/graph        POST /api/job/{id}/cancel
GET  /api/metrics               GET  /health
GET  / (and /ui)                — web cluster monitor (webui.py)
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from ballista_tpu.scheduler.server import SchedulerServer
from ballista_tpu.version import BALLISTA_VERSION


def _metric_percentiles(raw: list[dict]) -> list[dict]:
    """Per-operator percentile summary across a stage's task metrics
    (reference: api/handlers.rs:191,200 metric percentiles)."""
    by_op: dict[tuple, list[dict]] = {}
    for m in raw:
        by_op.setdefault((int(m.get("depth", 0)), str(m.get("name", ""))), []).append(m)

    def pct(sorted_vals, p):
        if not sorted_vals:
            return 0
        i = min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    out = []
    for (depth, name), ms in sorted(by_op.items()):
        elapsed = sorted(int(m.get("elapsed_ns", 0)) for m in ms)
        rows = sorted(int(m.get("output_rows", 0)) for m in ms)
        out.append({
            "depth": depth, "name": name, "tasks": len(ms),
            "output_rows_total": sum(rows),
            "elapsed_ms_p50": pct(elapsed, 50) / 1e6,
            "elapsed_ms_p90": pct(elapsed, 90) / 1e6,
            "elapsed_ms_p99": pct(elapsed, 99) / 1e6,
            "output_rows_p50": pct(rows, 50),
            "output_rows_p99": pct(rows, 99),
        })
    return out


def start_rest_api(scheduler: SchedulerServer, metrics: InMemoryMetricsCollector,
                   host: str = "0.0.0.0", port: int = 0):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def _send(self, code: int, body: str, ctype: str = "application/json"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _json(self, obj, code: int = 200):
            self._send(code, json.dumps(obj, default=str, indent=1))

        def do_GET(self):  # noqa: N802
            p = self.path.rstrip("/")
            if p in ("", "/ui"):
                from ballista_tpu.scheduler.api.webui import WEBUI_HTML

                return self._send(200, WEBUI_HTML, "text/html; charset=utf-8")
            if p == "/health":
                return self._json({"status": "healthy"})
            if p == "/api/state":
                with scheduler._jobs_lock:
                    jobs = len(scheduler.jobs)
                return self._json({
                    "version": BALLISTA_VERSION,
                    "scheduler_id": scheduler.scheduler_id,
                    "executors": len(scheduler.executors.alive_executors()),
                    "quarantined_executors": scheduler.executors.quarantined_count(),
                    "jobs": jobs,
                    "flight_proxy_port": getattr(scheduler, "flight_proxy_port", 0),
                    # overload posture: state machine + admission gauges
                    # (per-lane inflight/shed counts live under "lanes")
                    "overload": scheduler.admission.snapshot(),
                    "aggregate_memory_pressure": round(
                        scheduler.executors.aggregate_pressure(), 4),
                    # serving tier: plan/result cache hit rates + fast lane
                    "serving": scheduler.serving.snapshot(),
                    # append ingestion: retained delta versions/bytes,
                    # compaction counters; continuous-query subscriptions
                    "ingest": scheduler.ingest.snapshot(),
                    "subscriptions": scheduler.subscriptions.snapshot(),
                    # scheduler scale-out: per-shard queue depth/lag/job
                    # counts, direct-dispatch lease ledger, heartbeat fan-in
                    "shards": scheduler.shards_snapshot(),
                    "leases": scheduler.leases.snapshot(),
                    "fanin": dict(scheduler._fanin),
                    # executor lifecycle (docs/lifecycle.md): drain/migration
                    # counters + the terminal drained-executor ledger
                    "lifecycle": {
                        **scheduler.lifecycle_stats,
                        "drained_executors": scheduler.executors.drained_snapshot(),
                    },
                })
            if p == "/api/executors":
                out = []
                health = scheduler.executors.health_snapshot()
                for e in scheduler.executors.alive_executors():
                    out.append({
                        "id": e.metadata.id, "host": e.metadata.host,
                        "grpc_port": e.metadata.grpc_port, "flight_port": e.metadata.flight_port,
                        "total_slots": e.total_slots, "free_slots": e.free_slots,
                        "last_seen": e.last_seen,
                        "device_ordinal": e.metadata.device_ordinal,
                        **health.get(e.metadata.id, {}),
                    })
                return self._json(out)
            if p == "/api/jobs":
                with scheduler._jobs_lock:
                    out = [g.job_status() for g in scheduler.jobs.values()]
                for o in out:
                    o.pop("partitions", None)
                    o.pop("schema", None)
                    o.pop("inline_result", None)  # pa.Table; not JSON
                return self._json(out)
            m = re.match(r"^/api/job/([^/]+)$", p)
            if m:
                st = scheduler.job_status(m.group(1))
                if st is None:
                    return self._json({"error": "not found"}, 404)
                st.pop("partitions", None)
                st.pop("schema", None)
                st.pop("inline_result", None)
                return self._json(st)
            m = re.match(r"^/api/job/([^/]+)/stages$", p)
            if m:
                with scheduler._jobs_lock:
                    g = scheduler.jobs.get(m.group(1))
                if g is None:
                    return self._json({"error": "not found"}, 404)
                stages = []
                for sid in sorted(g.stages):
                    s = g.stages[sid]
                    raw = g.stage_metrics.get(sid, [])
                    stages.append({
                        "stage_id": sid, "state": s.state.value, "attempt": s.attempt,
                        "partitions": s.spec.partitions,
                        "output_partitions": s.spec.output_partitions,
                        "pending": len(s.pending), "running": len(s.running),
                        "completed": len(s.completed),
                        "plan": s.spec.plan.display(),
                        "metrics": raw[:200],
                        "metric_percentiles": _metric_percentiles(raw),
                    })
                return self._json(stages)
            m = re.match(r"^/api/job/([^/]+)/graph$", p)
            if m:
                # stage DAG as JSON (the web monitor's client-side renderer;
                # the dot endpoint below stays for graphviz tooling)
                with scheduler._jobs_lock:
                    g = scheduler.jobs.get(m.group(1))
                if g is None:
                    return self._json({"error": "not found"}, 404)
                stages = []
                for sid in sorted(g.stages):
                    s = g.stages[sid]
                    stages.append({
                        "stage_id": sid, "state": s.state.value,
                        "attempt": s.attempt,
                        "partitions": s.spec.partitions,
                        "completed": len(s.completed),
                        "summary": s.spec.plan.node_str(),
                        "metric_percentiles": _metric_percentiles(
                            g.stage_metrics.get(sid, [])),
                    })
                edges = [[sid, o] for sid, outs in g.output_links.items()
                         for o in outs]
                return self._json({
                    "job_id": g.job_id, "status": g.status.value,
                    "stages": stages, "edges": edges,
                })
            m = re.match(r"^/api/job/([^/]+)/dot$", p)
            if m:
                with scheduler._jobs_lock:
                    g = scheduler.jobs.get(m.group(1))
                if g is None:
                    return self._json({"error": "not found"}, 404)
                from ballista_tpu.utils.dot import graph_to_dot

                return self._send(200, graph_to_dot(g), "text/vnd.graphviz")
            if p == "/api/metrics":
                return self._send(200, metrics.render_prometheus(), "text/plain; version=0.0.4")
            if p == "/api/config":
                # scheduler runtime settings + the typed session-config
                # registry (reference: the TUI's scheduler-config screen);
                # restricted keys are scrubbed like the session KV transport
                from ballista_tpu.config import RESTRICTED_KEYS, VALID_ENTRIES

                entries = [{
                    "name": e.name, "type": e.ty.__name__,
                    "default": e.default, "description": e.description,
                    **({"choices": list(e.choices)} if e.choices else {}),
                } for e in VALID_ENTRIES.values() if e.name not in RESTRICTED_KEYS]
                return self._json({
                    "scheduler_id": scheduler.scheduler_id,
                    "version": BALLISTA_VERSION,
                    "task_distribution": scheduler.executors.task_distribution,
                    "executor_timeout_s": scheduler.executors.timeout_s,
                    "job_state_backend": type(scheduler.job_state).__name__,
                    "flight_proxy_port": getattr(scheduler, "flight_proxy_port", 0),
                    "session_config_entries": sorted(entries, key=lambda e: e["name"]),
                })
            return self._json({"error": "not found"}, 404)

        def do_POST(self):  # noqa: N802
            m = re.match(r"^/api/job/([^/]+)/cancel$", self.path.rstrip("/"))
            if m:
                scheduler.cancel_job(m.group(1))
                return self._json({"cancelled": m.group(1)})
            m = re.match(r"^/api/table/([^/]+)/append$", self.path.rstrip("/"))
            if m:
                # body: one Arrow IPC stream of appended rows
                import pyarrow as pa

                length = int(self.headers.get("Content-Length", 0))
                if length <= 0:
                    return self._json({"error": "empty body"}, 400)
                try:
                    reader = pa.ipc.open_stream(self.rfile.read(length))
                    batches = [b for b in reader if b.num_rows]
                    out = scheduler.append_data(m.group(1), batches)
                except Exception as e:  # noqa: BLE001 — malformed IPC → client error
                    return self._json({"error": str(e)}, 400)
                return self._json(out)
            return self._json({"error": "not found"}, 404)

    server = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True, name="rest-api")
    t.start()
    return server, server.server_port
