"""Web cluster monitor served by the scheduler REST API.

Rebuild of the reference's web TUI (`ballista-cli` ratatui monitor + its
Trunk/wasm build, ballista-cli/src/tui/): live jobs / executors / metrics
tables over the same REST endpoints, per-job stage DAG and operator metric
percentiles, job cancel, and client-side search — one static page, zero
external assets (the wasm build's role here is plain JS polling the JSON
API, which is the TPU build's equivalent of the hexagonal ui/http_client
split).
"""

WEBUI_HTML = r"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ballista-tpu cluster monitor</title>
<style>
:root {
  --bg: #11151a; --panel: #1a2027; --line: #2a323c; --fg: #d7dde4;
  --dim: #8a96a3; --acc: #5aa9e6; --ok: #69c98f; --warn: #e6c85a;
  --err: #e66a6a; --run: #5aa9e6;
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--bg); color: var(--fg);
       font: 13px/1.45 ui-monospace, SFMono-Regular, Menlo, Consolas, monospace; }
header { display: flex; gap: 18px; align-items: baseline; padding: 10px 16px;
         border-bottom: 1px solid var(--line); background: var(--panel);
         position: sticky; top: 0; }
header h1 { font-size: 15px; margin: 0; color: var(--acc); }
header .kv { color: var(--dim); }
header .kv b { color: var(--fg); font-weight: 600; }
main { display: grid; grid-template-columns: minmax(420px, 1fr) 2fr;
       gap: 12px; padding: 12px 16px; }
section { background: var(--panel); border: 1px solid var(--line);
          border-radius: 6px; padding: 10px 12px; min-width: 0; }
section h2 { font-size: 12px; margin: 0 0 8px; color: var(--dim);
             text-transform: uppercase; letter-spacing: .08em; }
table { border-collapse: collapse; width: 100%; }
th, td { text-align: left; padding: 3px 8px; border-bottom: 1px solid var(--line);
         white-space: nowrap; overflow: hidden; text-overflow: ellipsis; }
th { color: var(--dim); font-weight: 600; }
tr.sel td { background: #233040; }
tbody tr:hover td { background: #202833; cursor: pointer; }
.st { padding: 0 6px; border-radius: 3px; font-size: 11px; }
.st.successful, .st.completed { color: var(--ok); }
.st.running { color: var(--run); }
.st.failed, .st.cancelled { color: var(--err); }
.st.queued, .st.resolved, .st.unresolved, .st.pending { color: var(--warn); }
input[type=text] { background: var(--bg); color: var(--fg); border: 1px solid var(--line);
        border-radius: 4px; padding: 3px 8px; width: 180px; }
button { background: #2a3340; color: var(--fg); border: 1px solid var(--line);
         border-radius: 4px; padding: 2px 10px; cursor: pointer; font: inherit; }
button:hover { border-color: var(--acc); }
button.danger:hover { border-color: var(--err); color: var(--err); }
#dag { width: 100%; min-height: 120px; }
#dag .node rect { fill: #202a36; stroke: var(--line); rx: 4; }
#dag .node.successful rect { stroke: var(--ok); }
#dag .node.running rect { stroke: var(--run); }
#dag .node.failed rect { stroke: var(--err); }
#dag .node.resolved rect, #dag .node.unresolved rect { stroke: var(--warn); }
#dag text { fill: var(--fg); font-size: 11px; }
#dag text.sub { fill: var(--dim); font-size: 10px; }
#dag line { stroke: var(--dim); stroke-width: 1.2; marker-end: url(#arr); }
.bar { background: #2a323c; height: 6px; border-radius: 3px; min-width: 60px; }
.bar i { display: block; height: 6px; border-radius: 3px; background: var(--acc); }
pre { white-space: pre-wrap; color: var(--dim); margin: 4px 0 0; font-size: 11px; }
.muted { color: var(--dim); }
#detail, #config { grid-column: 1 / -1; }
.spark { font-weight: 400; letter-spacing: -1px; color: var(--acc); }
.row { display: flex; gap: 10px; align-items: center; margin-bottom: 8px; }
</style>
</head>
<body>
<header>
  <h1>ballista-tpu</h1>
  <span class="kv">scheduler <b id="h-id">–</b></span>
  <span class="kv">version <b id="h-ver">–</b></span>
  <span class="kv">executors <b id="h-ex">–</b></span>
  <span class="kv">jobs <b id="h-jobs">–</b></span>
  <span class="kv">act <b id="spark-act" class="spark">–</b></span>
  <span class="kv">slots <b id="spark-slots" class="spark">–</b></span>
  <span class="kv"><button id="pause">pause</button></span>
  <span class="kv"><button id="cfg-btn">config</button></span>
  <span class="kv muted" id="h-upd"></span>
</header>
<main>
  <section>
    <div class="row"><h2 style="margin:0">Jobs</h2>
      <input type="text" id="q" placeholder="filter id / status / sql"></div>
    <table id="jobs"><thead><tr>
      <th>job</th><th>status</th><th>stages</th><th>progress</th><th>sec</th><th></th>
    </tr></thead><tbody></tbody></table>
  </section>
  <section>
    <h2>Executors</h2>
    <table id="execs"><thead><tr>
      <th>id</th><th>host</th><th>grpc</th><th>flight</th><th>slots</th><th>dev</th><th>seen</th>
    </tr></thead><tbody></tbody></table>
    <h2 style="margin-top:14px">Scheduler metrics</h2>
    <pre id="prom" class="muted"></pre>
  </section>
  <section id="config" hidden>
    <h2>Scheduler config</h2>
    <div class="muted" id="cfg-head"></div>
    <table id="cfg-table"><thead><tr>
      <th>session config key</th><th>type</th><th>default</th><th>description</th>
    </tr></thead><tbody></tbody></table>
  </section>
  <section id="detail" hidden>
    <div class="row"><h2 style="margin:0" id="d-title">Job</h2>
      <span class="st" id="d-status"></span></div>
    <svg id="dag"></svg>
    <table id="stages"><thead><tr>
      <th>stage</th><th>state</th><th>attempt</th><th>parts</th><th>done</th><th>top operators (p50 / p99 ms · rows)</th>
    </tr></thead><tbody></tbody></table>
  </section>
</main>
<script>
"use strict";
let paused = false, selected = null, cachedJobs = [];
const $ = (s) => document.querySelector(s);
const esc = (s) => String(s).replace(/[&<>"]/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const J = (u) => fetch(u).then(r => { if (!r.ok) throw new Error(u + ": " + r.status); return r.json(); });

function stBadge(s) { return `<span class="st ${esc(s)}">${esc(s)}</span>`; }

// cluster-history sparklines (the ratatui Sparkline widget analog)
const SPARK = " ▁▂▃▄▅▆▇█", HWIN = 40, hist = { act: [], slots: [] };
function sparkline(vals) {
  const v = vals.slice(-HWIN), hi = Math.max(1, ...v);
  return v.map(x => SPARK[Math.min(8, 1 + Math.round(x / hi * 7))]).join("");
}
function sample(jobs, execs) {
  hist.act.push(jobs.filter(j => ["running", "queued"].includes(j.state)).length);
  hist.slots.push(execs.reduce((a, e) => a + (e.total_slots - e.free_slots), 0));
  for (const k in hist) if (hist[k].length > HWIN) hist[k].shift();
  $("#spark-act").textContent = sparkline(hist.act) || "–";
  $("#spark-slots").textContent = sparkline(hist.slots) || "–";
}

let cfgShown = false;
async function toggleConfig() {
  cfgShown = !cfgShown;
  const el = $("#config");
  el.hidden = !cfgShown;
  if (!cfgShown || el.dataset.loaded) return;
  el.dataset.loaded = "1";  // set BEFORE awaiting: no duplicate fetch/rows
  let c;
  try { c = await J("/api/config"); }  // static payload: fetched once
  catch (e) { delete el.dataset.loaded; $("#cfg-head").textContent = "config fetch failed: " + e.message; return; }
  $("#cfg-head").textContent =
    `task-distribution=${c.task_distribution} · executor-timeout=${c.executor_timeout_s}s · ` +
    `job-state=${c.job_state_backend}`;
  const tb = $("#cfg-table tbody");
  for (const e of c.session_config_entries || []) {
    const tr = document.createElement("tr");
    tr.innerHTML = `<td>${esc(e.name)}</td><td>${esc(e.type)}</td>` +
      `<td>${esc(String(e.default))}</td><td class="muted">${esc(e.description)}</td>`;
    tb.appendChild(tr);
  }
}

let busy = false;
async function refresh() {
  if (paused || busy) return;
  busy = true;
  try {
    const [state, jobs, execs] = await Promise.all([
      J("/api/state"), J("/api/jobs"), J("/api/executors")]);
    $("#h-id").textContent = state.scheduler_id || "–";
    $("#h-ver").textContent = state.version || "–";
    $("#h-ex").textContent = state.executors;
    $("#h-jobs").textContent = state.jobs;
    $("#h-upd").textContent = "updated " + new Date().toLocaleTimeString();
    cachedJobs = jobs;
    sample(jobs, execs);
    renderJobs(jobs);
    renderExecs(execs);
    await renderProm();
    if (selected) await renderDetail(selected);
  } catch (e) { $("#h-upd").textContent = "refresh failed: " + e.message; }
  finally { busy = false; }
}

function renderJobs(jobs) {
  const q = $("#q").value.trim().toLowerCase();
  const tb = $("#jobs tbody");
  tb.innerHTML = "";
  for (const j of jobs.slice().reverse()) {
    const hay = (j.job_id + " " + j.state + " " + (j.job_name || "")).toLowerCase();
    if (q && !hay.includes(q)) continue;
    const total = j.total_stages || 0;
    const done = j.completed_stages || 0;
    const pct = total ? Math.round(100 * done / total) : (j.state === "successful" ? 100 : 0);
    const sec = j.ended_at && j.queued_at ? (j.ended_at - j.queued_at).toFixed(2)
              : j.queued_at ? ((Date.now() / 1e3) - j.queued_at).toFixed(1) : "";
    const tr = document.createElement("tr");
    if (j.job_id === selected) tr.classList.add("sel");
    tr.innerHTML = `<td title="${esc(j.job_name || "")}">${esc(j.job_id)}</td>` +
      `<td>${stBadge(j.state)}</td><td>${done}/${total}</td>` +
      `<td><div class="bar"><i style="width:${pct}%"></i></div></td>` +
      `<td>${sec}</td>` +
      `<td>${["queued","running"].includes(j.state) ? '<button class="danger" data-cancel="' + esc(j.job_id) + '">cancel</button>' : ""}</td>`;
    tr.addEventListener("click", (ev) => {
      if (ev.target.dataset.cancel) return;
      selected = j.job_id;
      if (paused || busy) renderDetail(selected); else refresh();
    });
    tb.appendChild(tr);
  }
  tb.querySelectorAll("[data-cancel]").forEach(b => b.addEventListener("click", async () => {
    await fetch("/api/job/" + b.dataset.cancel + "/cancel", { method: "POST" });
    refresh();
  }));
}

function renderExecs(execs) {
  const tb = $("#execs tbody");
  tb.innerHTML = "";
  for (const e of execs) {
    const seen = e.last_seen ? Math.max(0, Date.now() / 1e3 - e.last_seen).toFixed(0) + "s ago" : "";
    const tr = document.createElement("tr");
    const dev = (e.device_ordinal == null || e.device_ordinal < 0) ? "–" : e.device_ordinal;
    tr.innerHTML = `<td>${esc(e.id)}</td><td>${esc(e.host)}</td><td>${e.grpc_port}</td>` +
      `<td>${e.flight_port}</td><td>${e.total_slots - e.free_slots}/${e.total_slots}</td>` +
      `<td>${dev}</td><td>${seen}</td>`;
    tb.appendChild(tr);
  }
}

async function renderProm() {
  const text = await fetch("/api/metrics").then(r => r.text());
  const keep = text.split("\n").filter(l => l && !l.startsWith("#")).slice(0, 12);
  $("#prom").textContent = keep.join("\n");
}

async function renderDetail(jobId) {
  // ONE lean request: /graph carries everything the detail pane shows
  // (the /stages endpoint with full plans + raw task metrics stays for
  // API tooling, but polling it per tab would re-ship hundreds of KB)
  let g;
  try { g = await J("/api/job/" + jobId + "/graph"); }
  catch { $("#detail").hidden = true; return; }
  $("#detail").hidden = false;
  $("#d-title").textContent = "Job " + jobId;
  $("#d-status").textContent = g.status;
  $("#d-status").className = "st " + g.status;
  drawDag(g);
  const tb = $("#stages tbody");
  tb.innerHTML = "";
  for (const s of g.stages) {
    const ops = (s.metric_percentiles || []).slice()
      .sort((a, b) => b.elapsed_ms_p50 - a.elapsed_ms_p50).slice(0, 3)
      .map(p => `${esc(p.name)} ${p.elapsed_ms_p50.toFixed(1)}/${p.elapsed_ms_p99.toFixed(1)}ms · ${p.output_rows_total} rows`)
      .join("  |  ");
    const tr = document.createElement("tr");
    tr.innerHTML = `<td>${s.stage_id}</td><td>${stBadge(s.state)}</td><td>${s.attempt}</td>` +
      `<td>${s.partitions}</td><td>${s.completed}</td>` +
      `<td title="${esc(s.summary)}">${ops || '<span class="muted">–</span>'}</td>`;
    tb.appendChild(tr);
  }
}

function drawDag(g) {
  // layer by longest path from sources (edges run upstream → downstream)
  const ids = g.stages.map(s => s.stage_id);
  const depth = Object.fromEntries(ids.map(i => [i, 0]));
  for (let pass = 0; pass < ids.length; pass++)
    for (const [a, b] of g.edges)
      if (depth[b] < depth[a] + 1) depth[b] = depth[a] + 1;
  const cols = {};
  for (const s of g.stages) (cols[depth[s.stage_id]] ||= []).push(s);
  const W = 170, H = 54, GX = 60, GY = 16;
  const maxRows = Math.max(1, ...Object.values(cols).map(c => c.length));
  const nCols = Object.keys(cols).length;
  const width = nCols * (W + GX), height = maxRows * (H + GY) + 20;
  const pos = {};
  let svg = `<defs><marker id="arr" markerWidth="7" markerHeight="7" refX="6" refY="3" orient="auto">` +
            `<path d="M0,0 L7,3 L0,6 z" fill="#8a96a3"/></marker></defs>`;
  Object.keys(cols).sort((a, b) => a - b).forEach((d, ci) => {
    cols[d].sort((a, b) => a.stage_id - b.stage_id).forEach((s, ri) => {
      const x = 10 + ci * (W + GX), y = 10 + ri * (H + GY);
      pos[s.stage_id] = [x, y];
      svg += `<g class="node ${esc(s.state)}"><rect x="${x}" y="${y}" width="${W}" height="${H}"/>` +
        `<text x="${x + 8}" y="${y + 18}">stage ${s.stage_id} · ${esc(s.state)}</text>` +
        `<text class="sub" x="${x + 8}" y="${y + 33}">${esc(String(s.summary).slice(0, 26))}</text>` +
        `<text class="sub" x="${x + 8}" y="${y + 47}">${s.completed}/${s.partitions} parts</text></g>`;
    });
  });
  let edges = "";
  for (const [a, b] of g.edges) {
    const [ax, ay] = pos[a], [bx, by] = pos[b];
    edges += `<line x1="${ax + W}" y1="${ay + H / 2}" x2="${bx - 4}" y2="${by + H / 2}"/>`;
  }
  const el = $("#dag");
  el.setAttribute("viewBox", `0 0 ${width} ${height}`);
  el.style.height = Math.min(300, height) + "px";
  el.innerHTML = svg + edges;
}

$("#pause").addEventListener("click", () => {
  paused = !paused;
  $("#pause").textContent = paused ? "resume" : "pause";
});
$("#cfg-btn").addEventListener("click", toggleConfig);
$("#q").addEventListener("input", () => renderJobs(cachedJobs));
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
