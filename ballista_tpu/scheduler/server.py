"""Scheduler server core: job state machine + event loop + task binding.

Rebuild of SchedulerServer / QueryStageScheduler / SchedulerState
(scheduler/src/scheduler_server/mod.rs:75, query_stage_scheduler.rs:96,
state/mod.rs:98):

- events (JobQueued, JobSubmitted, TaskUpdating, ReviveOffers,
  ExecutorLost, JobFinished/Failed, CancelJob) flow through a single
  bounded event loop; PLANNING runs on a spawned thread so the loop never
  blocks (query_stage_scheduler.rs:372);
- ReviveOffers: reserve executor slots → pop runnable tasks from job
  graphs → hand to the TaskLauncher (push mode); pull-mode executors call
  `poll_work` which pops directly from the same state;
- the TaskLauncher seam is what the virtual-cluster test harness fakes
  (reference: VirtualTaskLauncher, test_utils.rs:349).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ballista_tpu.config import (
    SERVING_FAST_LANE,
    SERVING_FAST_LANE_TIMEOUT_S,
    SERVING_INCREMENTAL,
    SERVING_PLAN_CACHE,
    SERVING_RESULT_CACHE,
    SERVING_SUBSCRIPTION_QUEUE,
    BallistaConfig,
)
from ballista_tpu.errors import BallistaError, ClusterOverloaded, PlanningError
from ballista_tpu.executor.executor import ExecutorMetadata, TaskResult
from ballista_tpu.ids import JobId, new_job_id
from ballista_tpu.scheduler.admission import LANE_BATCH, LANE_INTERACTIVE, AdmissionController
from ballista_tpu.scheduler.metrics import NoopMetricsCollector, SchedulerMetricsCollector
from ballista_tpu.scheduler.planner import DistributedPlanner
from ballista_tpu.scheduler.shard import SchedulerShard, shard_of
from ballista_tpu.scheduler.state.execution_graph import (
    ExecutionGraph,
    JobState,
    TaskDescription,
)
from ballista_tpu.scheduler.state.executor_manager import ExecutorManager
from ballista_tpu.scheduler.state.session_manager import SessionManager
from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE, FastJob
from ballista_tpu.serving.incremental import (
    DeltaRegistry,
    SubscriptionRegistry,
    build_maintain_plan,
    decide,
    graft_append_scans,
    graft_delta_scan,
    render_finisher,
    split_finisher,
)
from ballista_tpu.serving.lease import (
    DEFAULT_LEASE_SLOTS, DEFAULT_LEASE_TTL_S, ExecutorLease, LeaseRegistry)
from ballista_tpu.serving.normalize import (
    bind_logical,
    bind_physical,
    collect_physical_params,
    config_fingerprint,
    lift_parameters,
)
from ballista_tpu.serving.tier import (
    PlanTemplate,
    PreparedStatement,
    ServingTier,
    StateEntry,
)

log = logging.getLogger(__name__)


class TaskLauncher:
    """Seam for pushing bound tasks to executors."""

    def launch(self, executor_id: str, tasks: list[TaskDescription], server: "SchedulerServer") -> None:
        raise NotImplementedError

    def cancel_tasks(self, executor_id: str, job_id: str,
                     items: list[tuple[int, int]], server: "SchedulerServer") -> None:
        """Best-effort CancelTasks push: items = [(task_id, stage_id)].
        In-process/virtual launchers may ignore it (their tasks either
        finish instantly or are synthetic)."""
        return

    def remove_job_data(self, executor_id: str, job_id: str,
                        server: "SchedulerServer") -> None:
        """Best-effort shuffle-GC push for a finished/cleaned job."""
        return

    def grant_lease(self, executor_id: str, lease, server: "SchedulerServer") -> None:
        """Push a freshly minted direct-dispatch lease to the executor's
        lease table (in-process launchers set it directly; gRPC/Flight
        launchers ship the wire form)."""
        return

    def migrate_partitions(self, src_executor_id: str, dest_executor_id: str,
                           locations: list, server: "SchedulerServer") -> tuple[int, int]:
        """Drain handoff (docs/lifecycle.md): move `locations` (shuffle map
        outputs held by the draining source) to the destination executor
        and rewrite each PartitionLocation in place. Returns
        (migrated_count, migrated_bytes). The default launcher migrates
        nothing — the drain then falls back to the recompute path exactly
        like an executor loss."""
        return 0, 0

    def revoke_lease(self, executor_id: str, lease_id: str,
                     server: "SchedulerServer") -> None:
        """Best-effort revocation push; the executor-side expiry check is
        the backstop when this never arrives."""
        return


@dataclass
class Event:
    kind: str  # job_queued | revive | task_update | executor_lost | cancel | shutdown
    payload: object = None
    # stamped at post time; dequeue-time minus this is the event-loop lag
    # that feeds the overload state machine
    posted_at: float = field(default_factory=time.monotonic)


@dataclass
class _RcFill:
    """What to do with a dispatched job's output before serving it.

    kind "plain": the output IS the result — store under `rkey`.
    kind "state": the output is accumulator state (the plan was truncated
    at the final aggregate) — persist it as a StateEntry, render the
    finisher chain over it, and serve/store the rendered table. The job
    must not look successful until the render lands (`_rc_render_pending`
    masks `job_status`), or clients would fetch raw accumulators.
    kind "append": the output is the delta rows of an append-maintained
    plan — concatenate onto `base` (the cached prior result), persist,
    serve.
    """

    rkey: tuple
    kind: str = "plain"  # plain | state | append
    template_key: str = ""
    values: tuple = ()
    vector: tuple = ()  # table-version vector snapshotted at submit
    finisher: list = field(default_factory=list)
    final: object = None  # the final HashAggregateExec (kind "state")
    base: object = None  # prior result table (kind "append" maintain)
    mode: str = ""  # maintained | bootstrap
    inline_result: object = None  # set when no job needs dispatching


class SchedulerServer:
    def __init__(self, launcher: TaskLauncher | None = None,
                 metrics: SchedulerMetricsCollector | None = None,
                 task_distribution: str = "bias",
                 executor_timeout_s: float = 180.0,
                 scheduler_id: str = "scheduler-0",
                 job_state=None,
                 quarantine_threshold: float = 0.5,
                 quarantine_min_events: float = 4.0,
                 health_half_life_s: float = 60.0,
                 probe_backoff_s: float = 10.0,
                 sweep_interval_s: float = 0.5,
                 admission: AdmissionController | None = None,
                 shards: int = 1):
        from ballista_tpu.scheduler.state.job_state import InMemoryJobState

        self.scheduler_id = scheduler_id
        self.executors = ExecutorManager(
            task_distribution, executor_timeout_s,
            quarantine_threshold=quarantine_threshold,
            quarantine_min_events=quarantine_min_events,
            health_half_life_s=health_half_life_s,
            probe_backoff_s=probe_backoff_s,
        )
        self.sweep_interval_s = sweep_interval_s
        self.sessions = SessionManager()
        self.jobs: dict[str, ExecutionGraph] = {}
        self.job_state = job_state or InMemoryJobState()
        self.launcher = launcher
        self.metrics = metrics or NoopMetricsCollector()
        self.admission = admission or AdmissionController()
        # sharded event loops: job ownership partitions by
        # shard_of(job_id) % num_shards; each shard has its own bounded
        # queue and lag EWMA (fleet lag = max over shards)
        self.num_shards = max(1, int(shards))
        self._shards = [SchedulerShard(self, i) for i in range(self.num_shards)]
        # heartbeat fan-in accounting: executor signals arrive ONCE and
        # fleet-scoped events multicast to the shards owning work
        self._fanin = {"heartbeats": 0, "events_multicast": 0}
        # direct-dispatch lease ledger (capacity slices on warm executors)
        self.leases = LeaseRegistry()
        self._jobs_lock = threading.RLock()
        self._job_rr = 0  # round-robin offer fairness across jobs
        self._running = False
        self._watchers: dict[str, list[threading.Event]] = {}
        # serving tier: plan/result caches + fast-lane jobs executing
        # outside the execution-graph machinery (keyed by job_id)
        self.serving = ServingTier()
        self._fast_jobs: dict[str, FastJob] = {}
        # graph jobs whose results should fill a result-cache slot on finish
        self._rc_pending: dict[str, _RcFill] = {}
        # jobs whose terminal transition is owned by the post-finish render
        # (incremental state/append fills): job_status masks success until
        # the rendered result is attached
        self._rc_render_pending: set[str] = set()
        # streaming ingestion: retained append deltas + continuous queries
        self.ingest = DeltaRegistry()
        self.subscriptions = SubscriptionRegistry()
        # lifecycle (docs/lifecycle.md): drains in flight (guards against
        # duplicate heartbeat triggers) + fleet drain/GC counters surfaced
        # on /api/state
        self._drains_inflight: set[str] = set()
        self._drain_lock = threading.Lock()
        self.lifecycle_stats = {"drains": 0, "drain_kills": 0,
                                "migrated_partitions": 0, "migrated_bytes": 0,
                                "gc_swept_jobs": 0}
        # catalog changes orphan the table's cached results AND its
        # retained deltas (new lineage), and wake continuous queries
        self.sessions.on_catalog_change = self._on_catalog_change

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        for sh in self._shards:
            sh.start()
        if self.sweep_interval_s > 0:
            threading.Thread(target=self._sweep_timer, daemon=True, name="straggler-sweep").start()

    def _sweep_timer(self) -> None:
        """Periodic straggler sweep: deadline expiry, speculative launches,
        quarantine probes. Posted as an event so all graph mutation stays on
        the single event loop."""
        while self._running:
            time.sleep(self.sweep_interval_s)
            if not self._running:
                return
            self.post(Event("sweep"))

    def stop(self) -> None:
        self._running = False
        for sh in self._shards:
            sh.post(Event("shutdown"))
        for sh in self._shards:
            sh.join(timeout=5)

    @property
    def _loop_lag_s(self) -> float:
        """Fleet admission-lag signal: the WORST shard's EWMA (one wedged
        shard must still trip the overload state machine)."""
        return max(sh.loop_lag_s for sh in self._shards)

    def _shard_for(self, job_id: str) -> SchedulerShard:
        return self._shards[shard_of(job_id, self.num_shards)]

    def post(self, ev: Event) -> None:
        """Route an event to its owning shard. Job-scoped events go to
        hash(job_id) % N; fleet-scoped events (revive / sweep /
        executor_lost / shutdown) fan in once here and multicast."""
        if self.num_shards == 1:
            self._shards[0].post(ev)
            return
        if ev.kind == "job_queued":
            self._shard_for(ev.payload[0]).post(ev)
        elif ev.kind == "cancel":
            self._shard_for(ev.payload).post(ev)
        elif ev.kind == "revive" and ev.payload is not None:
            # job-scoped revive (a specific job became runnable): only its
            # owning shard can offer it; multicasting would make every
            # dispatch cost N offer scans
            self._shard_for(ev.payload).post(ev)
        elif ev.kind == "task_update":
            executor_id, results = ev.payload
            by_shard: dict[int, list] = {}
            for r in results:
                by_shard.setdefault(shard_of(r.job_id, self.num_shards), []).append(r)
            for idx, rs in by_shard.items():
                self._shards[idx].post(
                    Event("task_update", (executor_id, rs), posted_at=ev.posted_at))
        else:
            self._fanin["events_multicast"] += 1
            for sh in self._shards:
                sh.post(Event(ev.kind, ev.payload, posted_at=ev.posted_at))

    def _handle(self, ev: Event, shard: SchedulerShard | None = None) -> None:
        """Per-event dispatch, scoped to `shard`'s slice of the jobs dict
        (None = unsharded view, e.g. direct calls from tests)."""
        if ev.kind == "shutdown":
            return
        if ev.kind == "job_queued":
            # planning off the event loop (query_stage_scheduler.rs:372)
            threading.Thread(target=self._plan_job, args=(ev.payload,), daemon=True).start()
        elif ev.kind == "revive":
            self._offer_reservation(shard)
        elif ev.kind == "task_update":
            executor_id, results = ev.payload
            self._apply_task_updates(executor_id, results)
            self._offer_reservation(shard)
            # the completions above freed slots OTHER shards' starved jobs
            # may be waiting on, and those shards see no event for it.
            # Nudge idle peers ONLY while slots stay free after our own
            # offer: under saturation the gate stays shut, so the nudge
            # never turns one completion into N offer scans
            if (shard is not None and self.num_shards > 1
                    and self.executors.free_slot_count() > 0):
                for sh in self._shards:
                    if sh.shard_id != shard.shard_id and sh.queue_depth() == 0:
                        sh.post(Event("revive"))
        elif ev.kind == "executor_lost":
            self._on_executor_lost(ev.payload, shard)
            self._offer_reservation(shard)
        elif ev.kind == "cancel":
            self._cancel_job(ev.payload)
        elif ev.kind == "sweep":
            self._sweep_stragglers(shard)

    def shards_snapshot(self) -> list[dict]:
        """Per-shard queue depth / lag / owned-job counts (REST + KEDA)."""
        counts: dict[int, int] = {}
        with self._jobs_lock:
            for job_id in self.jobs:
                idx = shard_of(job_id, self.num_shards)
                counts[idx] = counts.get(idx, 0) + 1
        return [{
            "shard": sh.shard_id,
            "queue_depth": sh.queue_depth(),
            "loop_lag_s": round(sh.loop_lag_s, 4),
            "handled": sh.handled,
            "jobs": counts.get(sh.shard_id, 0),
        } for sh in self._shards]

    # -- job submission --------------------------------------------------------

    def _admit_or_shed(self, session_id: str, job_id: str, lane: str = LANE_BATCH) -> None:
        """Admission gate in front of every submit path. A rejection
        happens BEFORE any job state exists, so a shed submission costs
        one dict lookup — the whole point of admission control. Lanes shed
        independently: interactive (fast-lane) traffic has its own cap and
        keeps flowing while batch drains, and vice versa."""
        try:
            self.admission.admit(session_id, job_id, lane=lane)
        except ClusterOverloaded as e:
            self.metrics.record_job_rejected(e.reason, lane=lane)
            log.warning("shed %s-lane job %s from session %s (%s, retry_after=%dms)",
                        lane, job_id, session_id, e.reason, e.retry_after_ms)
            raise
        self.metrics.record_lane_admitted(lane)

    def submit_sql(self, sql: str, session_id: str, job_name: str = "",
                   inline_results: bool = False) -> str:
        """SQL entry point. With the serving tier enabled, planning runs
        synchronously on the submit thread through the plan cache (a hit
        skips parse+optimize+physical planning entirely); single-stage
        plans then dispatch on the fast lane without ever touching the
        event loop. `inline_results` marks an in-process caller that can
        accept a result table in the status dict (result-cache hits)."""
        cfg = self.sessions.get(session_id) or BallistaConfig()
        if not bool(cfg.get(SERVING_PLAN_CACHE)):
            job_id = str(new_job_id())
            self._admit_or_shed(session_id, job_id)
            with self._jobs_lock:
                self.jobs[job_id] = ExecutionGraph(job_id, job_name, session_id, [],
                                                   self.sessions.get(session_id))
                self.jobs[job_id].status = JobState.QUEUED
            self.metrics.record_submitted(job_id)
            self.post(Event("job_queued", (job_id, "sql", sql, session_id)))
            return job_id
        return self._submit_serving(sql, session_id, job_name, cfg, inline_results)

    def _enqueue_legacy_sql(self, job_id: str, sql: str, session_id: str,
                            job_name: str) -> str:
        with self._jobs_lock:
            self.jobs[job_id] = ExecutionGraph(job_id, job_name, session_id, [],
                                               self.sessions.get(session_id))
            self.jobs[job_id].status = JobState.QUEUED
        self.post(Event("job_queued", (job_id, "sql", sql, session_id)))
        return job_id

    def _submit_serving(self, sql: str, session_id: str, job_name: str,
                        cfg: BallistaConfig, inline_results: bool) -> str:
        from ballista_tpu.engine.physical_planner import PhysicalPlanner
        from ballista_tpu.sql.ast import CreateExternalTable, DropTable, SelectStmt
        from ballista_tpu.sql.optimizer import optimize
        from ballista_tpu.sql.parser import parse_sql
        from ballista_tpu.sql.planner import SqlPlanner

        cfg_fp = config_fingerprint(cfg)
        hit = self.serving.lookup_text(sql, cfg_fp)
        job_id = str(new_job_id())
        # lane choice must precede admission; only a cache hit knows the
        # stage count up front, so first-time shapes ride the batch lane
        lane = LANE_BATCH
        if hit is not None and hit[2].single_stage:
            lane = LANE_INTERACTIVE
        self._admit_or_shed(session_id, job_id, lane=lane)
        self.metrics.record_submitted(job_id)
        t0 = time.time()
        try:
            if hit is not None:
                key, values, template = hit
                self.metrics.record_plan_cache(True)
                template.hits += 1
            else:
                stmt = parse_sql(sql)
                if not isinstance(stmt, SelectStmt):
                    # DDL / utility statements take the legacy queued path
                    # (the planning context handles them); catalog-visible
                    # DDL orphans the table's cached results
                    if isinstance(stmt, (CreateExternalTable, DropTable)):
                        self._on_catalog_change(stmt.name.lower())
                    return self._enqueue_legacy_sql(job_id, sql, session_id, job_name)
                ctx = self.sessions.create_planning_context(session_id)
                optimized = optimize(SqlPlanner(ctx.catalog).plan_query(stmt))
                lift = lift_parameters(optimized)
                if not lift.cacheable:
                    self.serving.note_uncacheable()
                    log.debug("job %s uncacheable (%s); planning directly", job_id, lift.reason)
                    physical = PhysicalPlanner(cfg).plan(optimized)
                    self.metrics.record_planning_ms(job_id, (time.time() - t0) * 1000)
                    return self._dispatch_serving(job_id, job_name, session_id, cfg,
                                                  physical, None, (), inline_results)
                key = f"{lift.key}:{cfg_fp}"
                values = lift.values
                template = self.serving.lookup_template(key, values)
                self.metrics.record_plan_cache(template is not None)
                if template is None:
                    tagged_physical = PhysicalPlanner(cfg).plan(lift.tagged)
                    bindable = set(range(len(values))) <= collect_physical_params(tagged_physical)
                    template = PlanTemplate(key=key, physical=tagged_physical,
                                            type_tags=lift.type_tags, values=values,
                                            tables=lift.tables, bindable=bindable)
                    self.serving.store_template(template)
                self.serving.remember_text(sql, cfg_fp, key, values)
            if (bool(cfg.get(SERVING_RESULT_CACHE)) and inline_results):
                rkey = self.serving.result_key(template.key, values, template.tables)
                cached = self.serving.lookup_result(rkey)
                self.metrics.record_result_cache(cached is not None)
                if cached is not None:
                    job = FastJob(job_id, job_name, session_id, cfg, inline_result=cached)
                    with self._jobs_lock:
                        self.jobs[job_id] = job
                    self.metrics.record_completed(job_id, 0.0)
                    self._notify(job_id)
                    return job_id
            else:
                rkey = None
            bound = bind_physical(template.physical, values)
            physical, fill = self._incremental_or_plain(template, values, bound,
                                                        rkey, cfg)
            self.metrics.record_planning_ms(job_id, (time.time() - t0) * 1000)
            if physical is None:
                # cached state already covers the current versions
                self.serving.store_result(rkey, fill.inline_result)
                return self._serve_inline(job_id, job_name, session_id, cfg,
                                          fill.inline_result)
            return self._dispatch_serving(job_id, job_name, session_id, cfg,
                                          physical, template, values,
                                          inline_results, fill=fill)
        except BaseException as e:  # noqa: BLE001 — same contract as _plan_job
            log.warning("serving submit failed for %s: %s", job_id, e, exc_info=True)
            with self._jobs_lock:
                g = ExecutionGraph(job_id, job_name, session_id, [], cfg)
                g.status = JobState.FAILED
                g.error = f"planning failed: {e}"
                g.ended_at = time.time()
                self.jobs[job_id] = g
            self.metrics.record_failed(job_id)
            self._notify(job_id)
            return job_id

    def _dispatch_serving(self, job_id: str, job_name: str, session_id: str,
                          cfg: BallistaConfig, physical, template, values,
                          inline_results: bool, fill: _RcFill | None = None) -> str:
        """Stage the bound plan and dispatch: fast lane for single-stage
        plans with slots available, the ordinary execution graph otherwise."""
        from ballista_tpu.scheduler.planner import merge_mesh_stages

        physical = self._graft_deltas(physical)
        stages = merge_mesh_stages(DistributedPlanner(job_id).plan_query_stages(physical), cfg)
        self._maybe_verify_stages(stages, cfg, job_id)
        if template is not None and template.single_stage is None:
            template.single_stage = len(stages) == 1
        if (len(stages) == 1 and self.launcher is not None
                and bool(cfg.get(SERVING_FAST_LANE))
                and self._try_fast_lane(job_id, job_name, session_id, cfg, stages, fill)):
            return job_id
        graph = ExecutionGraph(job_id, job_name, session_id, stages, cfg)
        with self._jobs_lock:
            self.jobs[job_id] = graph
            if fill is not None:
                self._rc_pending[job_id] = fill
                if fill.kind != "plain":
                    self._rc_render_pending.add(job_id)
        if self.job_state.acquire(job_id, self.scheduler_id):
            self.job_state.save_graph(graph)
        self.post(Event("revive", job_id))
        return job_id

    @staticmethod
    def _maybe_verify_stages(stages, cfg: BallistaConfig, job_id: str) -> None:
        """Static plan verification behind ballista.debug.plan.verify: a
        violated DAG invariant fails the job at submit time (the raise
        propagates into the planning-failure path) instead of executing a
        corrupt plan. Off by default — the golden plan-stability tests run
        the same checks unconditionally."""
        from ballista_tpu.config import DEBUG_PLAN_VERIFY

        if cfg is not None and bool(cfg.get(DEBUG_PLAN_VERIFY)):
            from ballista_tpu.analysis.plan_check import check_stages

            log.debug("plan verify: %d stages of %s", len(stages), job_id)
            check_stages(stages)

    def _try_fast_lane(self, job_id: str, job_name: str, session_id: str,
                       cfg: BallistaConfig, stages, fill) -> bool:
        """Dispatch a single-stage job straight to warm executors from the
        submit thread — no graph, no event-loop round trip. Declines (and
        the caller falls back to the graph) unless every partition gets a
        slot NOW: a partially-dispatched fast job would just be a worse
        execution graph."""
        stage = stages[0]
        n = stage.partitions
        reservations = self.executors.reserve_slots(n)
        granted = sum(c for _, c in reservations)
        if granted < n:
            for executor_id, count in reservations:
                self.executors.free_slot(executor_id, count)
            return False
        job = FastJob(job_id, job_name, session_id, cfg, stages=stages, rc_key=fill)
        with self._jobs_lock:
            self.jobs[job_id] = job
            self._fast_jobs[job_id] = job
        parts = list(range(n))
        i = 0
        for executor_id, count in reservations:
            chunk, i = parts[i:i + count], i + count
            tasks = [TaskDescription(
                job_id=job_id, stage_id=stage.stage_id, stage_attempt=0,
                task_id=FAST_TASK_ID_BASE + p, partitions=[p], plan=stage.plan,
                session_id=session_id, fast_lane=True,
            ) for p in chunk]
            if tasks:
                self._spawn_launch(executor_id, tasks)
        self.serving.note_fast_lane("executed")
        self.metrics.record_fast_lane("executed")
        return True

    # -- streaming ingestion + incremental maintenance ------------------------

    def _on_catalog_change(self, table: str) -> None:
        self.serving.table_versions.bump(table)
        self.ingest.reset(table)
        self._notify_subscriptions(table)

    def append_data(self, table: str, batches, session_id: str = "") -> dict:
        """Append-oriented ingestion: bump the table's version AND retain
        the delta batches under the new version, so eligible cached
        results maintain instead of recomputing. Every read path sees the
        appended rows immediately via the dispatch-time scan graft."""
        table = str(table).lower()
        rows = int(sum(b.num_rows for b in batches))
        cfg = self.sessions.get(session_id)
        if cfg is not None:
            self.ingest.configure(cfg)
        version = self.serving.table_versions.bump(table)
        self.ingest.append(table, version, list(batches))
        self.serving.note_append(rows)
        self.metrics.record_append(rows)
        self._notify_subscriptions(table)
        log.debug("append %d rows to %s -> version %d", rows, table, version)
        return {"table": table, "version": version, "rows": rows}

    def _graft_deltas(self, physical):
        """Bind-time delta stamping: planning contexts and cached templates
        stay base-only; every dispatch path unions named scans with the
        ingest registry's folded parts + retained appends. Stage planning
        runs AFTER the graft, so AQE and plan verification see the real
        DAG."""
        if self.ingest.empty():
            return physical
        return graft_append_scans(physical, self.ingest.view())

    def _serve_inline(self, job_id: str, job_name: str, session_id: str,
                      cfg: BallistaConfig, result) -> str:
        """Finish a submission whose result exists without dispatching."""
        job = FastJob(job_id, job_name, session_id, cfg, inline_result=result)
        with self._jobs_lock:
            self.jobs[job_id] = job
        self.metrics.record_completed(job_id, 0.0)
        self._notify(job_id)
        return job_id

    def _incremental_or_plain(self, template: PlanTemplate, values: tuple,
                              bound, rkey, cfg: BallistaConfig):
        """The maintain-on-bump ladder for a result-cache miss. Returns
        (physical_to_dispatch, fill); physical is None when the cached
        state already covers the current versions (fill.inline_result is
        the rendered answer, no job needed)."""
        if rkey is None:
            return bound, None
        fill = _RcFill(rkey=rkey)
        if not bool(cfg.get(SERVING_INCREMENTAL)):
            return bound, fill
        decision = decide(template)
        if decision.mode == "none":
            self.serving.note_incremental("recompute", decision.reason)
            self.metrics.record_incremental("recompute")
            return bound, fill
        vector = rkey[2]  # version vector snapshotted into the result key
        fill.template_key, fill.values, fill.vector = template.key, values, vector
        entry = self.serving.lookup_state(template.key, values)
        stale = entry if (entry is not None and entry.kind != decision.mode) else None
        if stale is not None:
            entry = None  # template re-analyzed differently; state unusable
        changed = None
        if entry is not None and len(entry.vector) == len(vector):
            changed = [(t, old, new) for (t, old), (_, new)
                       in zip(entry.vector, vector) if new != old]
        if decision.mode == "aggregate":
            final, chain = split_finisher(bound)
            fill.kind, fill.final, fill.finisher = "state", final, chain
            if changed is not None and not changed:
                # result cache evicted but state is current: render only
                result = render_finisher(chain, final, entry.table.to_batches(), cfg)
                self.serving.note_incremental("state_render")
                self.metrics.record_incremental("state_render")
                fill.inline_result = result
                return None, fill
            if changed is not None and len(changed) == 1 and changed[0][2] > changed[0][1]:
                t, old, new = changed[0]
                deltas, why = self.ingest.range(t, old, new)
                if deltas is not None:
                    plan = build_maintain_plan(bound, t, deltas,
                                               entry.table.to_batches())
                    fill.mode = "maintained"
                    self.serving.note_incremental("maintained")
                    self.metrics.record_incremental("maintained")
                    return plan, fill
                self.serving.note_incremental("recompute", why)
                self.metrics.record_incremental("recompute")
            elif changed is not None:
                reason = ("multi-table-append" if len(changed) > 1
                          else "version-regressed")
                self.serving.note_incremental("recompute", reason)
                self.metrics.record_incremental("recompute")
            # bootstrap: run the state computation once so the NEXT bump
            # maintains; the finisher renders scheduler-side either way
            fill.mode = "bootstrap"
            if changed is None:  # fallbacks above already counted recompute
                self.serving.note_incremental("bootstrap")
                self.metrics.record_incremental("bootstrap")
            return final, fill
        # decision.mode == "append": stateless plans maintain by
        # concatenating the delta query's rows onto the cached result
        fill.kind = "append"
        if changed is not None and not changed:
            self.serving.note_incremental("state_render")
            self.metrics.record_incremental("state_render")
            fill.inline_result = entry.table
            return None, fill
        if changed is not None and len(changed) == 1 and changed[0][2] > changed[0][1]:
            t, old, new = changed[0]
            deltas, why = self.ingest.range(t, old, new)
            if deltas is not None:
                fill.base, fill.mode = entry.table, "maintained"
                self.serving.note_incremental("maintained")
                self.metrics.record_incremental("maintained")
                return graft_delta_scan(bound, t, deltas), fill
            self.serving.note_incremental("recompute", why)
            self.metrics.record_incremental("recompute")
        elif changed is not None:
            self.serving.note_incremental("recompute", "multi-table-append")
            self.metrics.record_incremental("recompute")
        fill.mode = "bootstrap"
        if changed is None:
            self.serving.note_incremental("bootstrap")
            self.metrics.record_incremental("bootstrap")
        return bound, fill

    def _finish_fill(self, fill: _RcFill, tbl, cfg) -> object:
        """Turn a finished job's fetched output into the served result per
        the fill kind, persisting maintenance state for the next bump."""
        if fill.kind == "state":
            result = render_finisher(fill.finisher, fill.final,
                                     tbl.to_batches(), cfg)
            self.serving.store_state(fill.template_key, fill.values,
                                     StateEntry(fill.vector, tbl, "aggregate"))
            self.serving.store_result(fill.rkey, result)
            return result
        if fill.kind == "append":
            import pyarrow as pa

            if fill.base is not None:
                result = pa.concat_tables(
                    [fill.base, tbl.cast(fill.base.schema)]).combine_chunks()
            else:
                result = tbl
            self.serving.store_state(fill.template_key, fill.values,
                                     StateEntry(fill.vector, result, "append"))
            self.serving.store_result(fill.rkey, result)
            return result
        self.serving.store_result(fill.rkey, tbl)
        return tbl

    # -- continuous queries ----------------------------------------------------

    def subscribe_statement(self, statement_id: str, params=None,
                            session_id: str = "",
                            inline_results: bool = True):
        """Continuous-query mode: re-execute a prepared statement
        (incrementally when eligible) on every bump of its tables, pushing
        fresh results into the subscription's queue. Returns the
        Subscription; the gRPC push stream drains its queue."""
        stmt = self.serving.get_prepared(statement_id)
        if stmt is None:
            raise BallistaError(f"unknown prepared statement {statement_id}")
        sid = session_id or stmt.session_id
        cfg = self.sessions.get(sid) or BallistaConfig()
        template = self.serving.plan_cache.get(stmt.key)
        tables = template.tables if template is not None else ()
        sub = self.subscriptions.register(
            statement_id, tuple(params) if params is not None else None,
            sid, tables, int(cfg.get(SERVING_SUBSCRIPTION_QUEUE)),
            inline_results)
        # push the current result immediately so subscribers start warm
        self._spawn_subscription_refresh(sub)
        return sub

    def unsubscribe(self, sub_id: str) -> None:
        self.subscriptions.remove(sub_id)

    def _notify_subscriptions(self, table: str) -> None:
        for sub in self.subscriptions.for_table(table):
            self._spawn_subscription_refresh(sub)

    def _spawn_subscription_refresh(self, sub) -> None:
        if not sub.begin_refresh():
            return  # in-flight refresh absorbs the bump (dirty mark)

        def run():
            while True:
                try:
                    job_id = self.execute_prepared(
                        sub.statement_id, sub.params, session_id=sub.session_id,
                        inline_results=sub.inline)
                    st = self.wait_for_job(job_id, timeout=300.0)
                    st = dict(st)
                    st["subscription_id"] = sub.sub_id
                    sub.offer(st)
                    if not sub.tables:
                        stmt = self.serving.get_prepared(sub.statement_id)
                        peek = (self.serving.plan_cache.get(stmt.key)
                                if stmt is not None else None)
                        if peek is not None and peek.tables:
                            self.subscriptions.bind_tables(sub, peek.tables)
                except BaseException as e:  # noqa: BLE001 — push the error, keep the stream
                    log.warning("subscription %s refresh failed: %s",
                                sub.sub_id, e)
                    sub.note_error(str(e))
                if not sub.end_refresh():
                    return

        threading.Thread(target=run, daemon=True,
                         name=f"subscription-{sub.sub_id}").start()

    # -- prepared statements ---------------------------------------------------

    def prepare_statement(self, sql: str, session_id: str) -> dict:
        """Parse + optimize + physical-plan ONCE; later execute() calls
        bind new parameter values into the cached template. Returns the
        statement id and the slot signature (count + arrow types)."""
        from ballista_tpu.engine.physical_planner import PhysicalPlanner
        from ballista_tpu.sql.ast import SelectStmt
        from ballista_tpu.sql.optimizer import optimize
        from ballista_tpu.sql.parser import parse_sql
        from ballista_tpu.sql.planner import SqlPlanner

        cfg = self.sessions.get(session_id) or BallistaConfig()
        stmt = parse_sql(sql)
        if not isinstance(stmt, SelectStmt):
            raise PlanningError("only SELECT statements can be prepared")
        ctx = self.sessions.create_planning_context(session_id)
        lift = lift_parameters(optimize(SqlPlanner(ctx.catalog).plan_query(stmt)))
        if not lift.cacheable:
            raise PlanningError(f"statement cannot be parameterized: {lift.reason}")
        key = f"{lift.key}:{config_fingerprint(cfg)}"
        if self.serving.plan_cache.get(key) is None:
            physical = PhysicalPlanner(cfg).plan(lift.tagged)
            bindable = set(range(len(lift.values))) <= collect_physical_params(physical)
            self.serving.store_template(PlanTemplate(
                key=key, physical=physical, type_tags=lift.type_tags,
                values=lift.values, tables=lift.tables, bindable=bindable))
        statement_id = f"stmt-{new_job_id()}"
        self.serving.register_prepared(PreparedStatement(
            statement_id, sql, session_id, key, lift.type_tags, lift.values))
        return {"statement_id": statement_id,
                "num_params": len(lift.values),
                "type_tags": list(lift.type_tags)}

    def execute_prepared(self, statement_id: str, params=None, session_id: str = "",
                         job_name: str = "", inline_results: bool = False) -> str:
        """Bind params into a prepared statement's template and dispatch.
        Survives template eviction (re-plans from the retained SQL) and
        non-bindable templates (binds at the logical level instead)."""
        from ballista_tpu.engine.physical_planner import PhysicalPlanner
        from ballista_tpu.sql.optimizer import optimize
        from ballista_tpu.sql.parser import parse_sql
        from ballista_tpu.sql.planner import SqlPlanner

        stmt = self.serving.get_prepared(statement_id)
        if stmt is None:
            raise BallistaError(f"unknown prepared statement {statement_id}")
        sid = session_id or stmt.session_id
        cfg = self.sessions.get(sid) or BallistaConfig()
        values = tuple(params) if params is not None else stmt.default_values
        if len(values) != len(stmt.type_tags):
            raise PlanningError(
                f"statement {statement_id} takes {len(stmt.type_tags)} "
                f"parameters, got {len(values)}")
        job_id = str(new_job_id())
        peek = self.serving.plan_cache.get(stmt.key)
        lane = LANE_INTERACTIVE if (peek is not None and peek.single_stage) else LANE_BATCH
        self._admit_or_shed(sid, job_id, lane=lane)
        self.metrics.record_submitted(job_id)
        t0 = time.time()
        try:
            template = self.serving.lookup_template(stmt.key, values)
            self.metrics.record_plan_cache(template is not None)
            if (bool(cfg.get(SERVING_RESULT_CACHE)) and inline_results
                    and template is not None):
                rkey = self.serving.result_key(stmt.key, values, template.tables)
                cached = self.serving.lookup_result(rkey)
                self.metrics.record_result_cache(cached is not None)
                if cached is not None:
                    job = FastJob(job_id, job_name, sid, cfg, inline_result=cached)
                    with self._jobs_lock:
                        self.jobs[job_id] = job
                    self.metrics.record_completed(job_id, 0.0)
                    self._notify(job_id)
                    return job_id
            else:
                rkey = None
            if template is not None:
                bound = bind_physical(template.physical, values)
            else:
                # evicted, or non-bindable with new values: re-lift from
                # the retained SQL and bind at the logical level
                ctx = self.sessions.create_planning_context(sid)
                lift = lift_parameters(optimize(
                    SqlPlanner(ctx.catalog).plan_query(parse_sql(stmt.sql))))
                if not lift.cacheable or len(lift.values) != len(values):
                    raise PlanningError(
                        f"statement {statement_id} no longer parameterizes "
                        f"the same way ({lift.reason or 'slot count changed'})")
                bound = PhysicalPlanner(cfg).plan(bind_logical(lift.tagged, values))
                physical = PhysicalPlanner(cfg).plan(lift.tagged)
                bindable = set(range(len(values))) <= collect_physical_params(physical)
                template = PlanTemplate(
                    key=stmt.key, physical=physical, type_tags=lift.type_tags,
                    values=lift.values, tables=lift.tables, bindable=bindable)
                self.serving.store_template(template)
            physical, fill = self._incremental_or_plain(template, values, bound,
                                                        rkey, cfg)
            self.metrics.record_planning_ms(job_id, (time.time() - t0) * 1000)
            if physical is None:
                self.serving.store_result(rkey, fill.inline_result)
                return self._serve_inline(job_id, job_name, sid, cfg,
                                          fill.inline_result)
            return self._dispatch_serving(job_id, job_name, sid, cfg, physical,
                                          template, values, inline_results,
                                          fill=fill)
        except BaseException as e:  # noqa: BLE001 — same contract as _plan_job
            log.warning("execute_prepared failed for %s: %s", job_id, e, exc_info=True)
            with self._jobs_lock:
                g = ExecutionGraph(job_id, job_name, sid, [], cfg)
                g.status = JobState.FAILED
                g.error = f"planning failed: {e}"
                g.ended_at = time.time()
                self.jobs[job_id] = g
            self.metrics.record_failed(job_id)
            self._notify(job_id)
            return job_id

    def close_prepared(self, statement_id: str) -> None:
        self.serving.close_prepared(statement_id)

    def submit_physical_plan(self, plan, session_id: str, job_name: str = "") -> str:
        job_id = str(new_job_id())
        self._admit_or_shed(session_id, job_id)
        with self._jobs_lock:
            self.jobs[job_id] = ExecutionGraph(job_id, job_name, session_id, [],
                                               self.sessions.get(session_id))
            self.jobs[job_id].status = JobState.QUEUED
        self.metrics.record_submitted(job_id)
        self.post(Event("job_queued", (job_id, "physical", plan, session_id)))
        return job_id

    def _plan_job(self, payload) -> None:
        job_id, kind, body, session_id = payload
        t0 = time.time()
        try:
            ctx = self.sessions.create_planning_context(session_id)
            if kind == "sql":
                df = ctx.sql(body)
                physical = ctx.create_physical_plan(df.plan)
            else:
                physical = body
            physical = self._graft_deltas(physical)
            stages = DistributedPlanner(job_id).plan_query_stages(physical)
            cfg = self.sessions.get(session_id) or BallistaConfig()
            from ballista_tpu.scheduler.planner import merge_mesh_stages

            stages = merge_mesh_stages(stages, cfg)
            self._maybe_verify_stages(stages, cfg, job_id)
            old = self.jobs.get(job_id)
            graph = ExecutionGraph(job_id, old.job_name if old else "", session_id, stages, cfg)
            with self._jobs_lock:
                self.jobs[job_id] = graph
            if self.job_state.acquire(job_id, self.scheduler_id):
                self.job_state.save_graph(graph)
            else:
                # never clobber a peer's checkpoint on an id collision
                log.warning("job %s is owned by another scheduler; not persisting", job_id)
            self.metrics.record_planning_ms(job_id, (time.time() - t0) * 1000)
            self.post(Event("revive", job_id))
        except BaseException as e:  # noqa: BLE001
            log.warning("planning failed for %s: %s", job_id, e, exc_info=True)
            with self._jobs_lock:
                g = self.jobs.get(job_id)
                if g is not None:
                    g.status = JobState.FAILED
                    g.error = f"planning failed: {e}"
                    g.ended_at = time.time()
            self.metrics.record_failed(job_id)
            self._notify(job_id)

    # -- scheduling (push mode) -------------------------------------------------

    def _running_jobs_rotated(self, shard: SchedulerShard | None = None) -> list:
        """Round-robin fairness across jobs: each offer starts at a rotating
        position, so a long job can no longer starve later submissions
        (the reference round-robins offers across jobs). With a shard scope,
        only that shard's slice is enumerated — the offer scan is O(jobs/N)
        per event instead of O(jobs)."""
        with self._jobs_lock:
            running = [g for g in self.jobs.values() if g.status is JobState.RUNNING]
            if shard is not None and self.num_shards > 1:
                running = [g for g in running if shard.owns(g.job_id)]
            if len(running) > 1:
                off = self._job_rr % len(running)
                self._job_rr += 1
                running = running[off:] + running[:off]
        return running

    def _offer_reservation(self, shard: SchedulerShard | None = None) -> None:
        """Bind runnable tasks to free executor slots and launch them
        (state/mod.rs:181-221: offer → bind → launch → unbind leftovers).
        Launches leave the event loop immediately: one slow executor's gRPC
        round trip must never stall scheduling for the rest of the cluster
        (the reference spawns launch_tasks). The slot ledger is shared, so
        concurrent shard offers stay safe."""
        if self.launcher is None:
            return
        running = self._running_jobs_rotated(shard)
        demand = sum(g.available_task_count() for g in running)
        if demand == 0:
            return
        self._offer_probes(running)
        if self.executors.task_distribution == "consistent-hash":
            self._offer_consistent(running)
            return
        reservations = self.executors.reserve_slots(demand)
        for executor_id, count in reservations:
            tasks: list[TaskDescription] = []
            for g in running:
                while len(tasks) < count:
                    t = g.pop_next_task(executor_id)
                    if t is None:
                        break
                    tasks.append(t)
                if len(tasks) >= count:
                    break
            unused = count - len(tasks)
            if unused:
                self.executors.free_slot(executor_id, unused)
            if tasks:
                self._spawn_launch(executor_id, tasks)

    def _offer_probes(self, running: list) -> None:
        """Bind ONE real task to each quarantined executor whose probe
        backoff elapsed; its outcome decides re-admission vs re-quarantine."""
        for executor_id, _count in self.executors.probe_reservations():
            probe: list[TaskDescription] = []
            for g in running:
                t = g.pop_next_task(executor_id)
                if t is not None:
                    probe.append(t)
                    break
            if probe:
                log.info("probing quarantined executor %s with task %d", executor_id, probe[0].task_id)
                self._spawn_launch(executor_id, probe)
            else:
                # nothing to bind: cancel_probe returns the slot itself
                self.executors.cancel_probe(executor_id)

    def _offer_consistent(self, running: list) -> None:
        """Consistent-hash binding: each task's (job, stage, partition)
        identity picks its executor on the ring — sticky placement."""
        by_exec: dict[str, list[TaskDescription]] = {}
        for g in running:
            while True:
                peek = g.pop_next_task("")  # bound to a concrete executor below
                if peek is None:
                    break
                key = f"{peek.job_id}/{peek.stage_id}/{peek.partitions[0] if peek.partitions else 0}"
                executor_id = self.executors.pick_consistent(key)
                if executor_id is None:
                    # no free slot anywhere: return the work and stop
                    g.return_task(peek)
                    break
                g.reassign_running(peek.task_id, peek.stage_id, executor_id)
                by_exec.setdefault(executor_id, []).append(peek)
        for executor_id, tasks in by_exec.items():
            self._spawn_launch(executor_id, tasks)

    def _spawn_launch(self, executor_id: str, tasks: list[TaskDescription]) -> None:
        def run():
            try:
                self.launcher.launch(executor_id, tasks, self)
            except Exception as e:  # noqa: BLE001
                log.warning("launch to %s failed: %s", executor_id, e)
                self.post(Event("executor_lost", executor_id))

        threading.Thread(target=run, daemon=True, name=f"launch-{executor_id}").start()

    # -- pull mode ---------------------------------------------------------------

    def poll_work(self, metadata: ExecutorMetadata, can_accept: bool, free_slots: int,
                  results: list[TaskResult]) -> list[TaskDescription]:
        """PollWork doubles as heartbeat + status sink + task source
        (scheduler_server/grpc.rs:92)."""
        if not self.executors.heartbeat(metadata.id):
            self.executors.register(metadata)
        if results:
            fast, results = self._split_fast(results)
            if fast:
                self._fast_update(metadata.id, fast)
        if results:
            # frees the ledger slots taken at handout below
            self._apply_task_updates(metadata.id, results, free_slots_managed=True)
        out: list[TaskDescription] = []
        if can_accept:
            # debit the SHARED slot ledger for pull handouts, or a mixed
            # push+pull cluster double-books the same vcores
            granted = self.executors.take_slots(metadata.id, free_slots)
            running = self._running_jobs_rotated()
            for g in running:
                while len(out) < granted:
                    t = g.pop_next_task(metadata.id)
                    if t is None:
                        break
                    out.append(t)
                if len(out) >= granted:
                    break
            if granted > len(out):
                self.executors.free_slot(metadata.id, granted - len(out))
        return out

    # -- status ingestion ----------------------------------------------------------

    def update_task_status(self, executor_id: str, results: list[TaskResult]) -> None:
        fast, rest = self._split_fast(results)
        if fast:
            # fast-lane results complete on the reporting thread: the whole
            # point of the lane is that short queries never wait behind the
            # event-loop queue
            self._fast_update(executor_id, fast)
        if rest:
            self.post(Event("task_update", (executor_id, rest)))

    def _split_fast(self, results: list[TaskResult]) -> tuple[list, list]:
        with self._jobs_lock:
            fast_ids = set(self._fast_jobs)
        fast = [r for r in results if r.job_id in fast_ids]
        rest = [r for r in results if r.job_id not in fast_ids]
        return fast, rest

    def _fast_update(self, executor_id: str, results: list[TaskResult]) -> None:
        for r in results:
            self.executors.free_slot(executor_id, 1)
            if r.state in ("success", "failed"):
                transition = self.executors.record_task_result(
                    executor_id, ok=(r.state == "success"),
                    timed_out=bool(getattr(r, "timed_out", False)))
                if transition is not None:
                    self.metrics.set_quarantined_executors(self.executors.quarantined_count())
            with self._jobs_lock:
                job = self._fast_jobs.get(r.job_id)
            if job is None:
                continue
            outcome = job.on_result(r)
            if outcome == "finished":
                with self._jobs_lock:
                    self._fast_jobs.pop(r.job_id, None)
                self.metrics.record_completed(job.job_id, time.time() - job.queued_at)
                self._maybe_cache_result(job)
                self._notify(job.job_id)
            elif outcome == "failed":
                self._fast_fallback(job, job.error)
        self.post(Event("revive"))  # freed slots may unblock queued graph work

    def _fast_fallback(self, job: FastJob, reason: str) -> None:
        """Demote a failed/timed-out fast job to an ordinary execution
        graph built from the same stages — it gets retries, speculation,
        and deadline sweeps like any other job. Idempotent per job."""
        with self._jobs_lock:
            if self._fast_jobs.pop(job.job_id, None) is None:
                return  # raced another fallback / completion
            graph = ExecutionGraph(job.job_id, job.job_name, job.session_id,
                                   job.demote(), job.config)
            self.jobs[job.job_id] = graph
        self.serving.note_fast_lane("fallback")
        self.metrics.record_fast_lane("fallback")
        log.warning("fast lane fell back to full DAG for %s: %s",
                    job.job_id, reason.splitlines()[0][:200] if reason else "timeout")
        self.post(Event("revive", job.job_id))

    def _maybe_cache_result(self, job: FastJob) -> None:
        """Fetch a finished fast job's partitions and finish its fill
        (cache store + any incremental render), also serving THIS
        submission inline (the fetch already ran). Runs before the
        terminal notify, so incremental outputs never leak raw."""
        fill = job.rc_key
        if fill is None:
            return
        try:
            from ballista_tpu.client.context import fetch_job_results

            tbl = fetch_job_results(job.job_status(), job.config)
            job.inline_result = self._finish_fill(fill, tbl, job.config)
        except Exception as e:  # noqa: BLE001 — plain cache fill is best-effort
            if fill.kind != "plain":
                # the fetched bytes are accumulator state / delta rows,
                # not the answer: fail rather than serve them
                job.status = JobState.FAILED
                job.error = f"incremental render failed: {e}"
                log.warning("incremental render for %s failed: %s", job.job_id, e)
            else:
                log.debug("result-cache fill for %s failed: %s", job.job_id, e)

    def _fill_result_cache_from_graph(self, g) -> bool:
        """Graph-path fill: on job_finished, fetch the final partitions off
        the event loop and finish the fill. Returns True when the job's
        terminal notify is DEFERRED to the fill thread — incremental
        state/append outputs must render into `g.inline_result` before
        clients observe success (`job_status` masks until then)."""
        with self._jobs_lock:
            fill = self._rc_pending.pop(g.job_id, None)
        if fill is None:
            return False
        deferred = fill.kind != "plain"

        def run():
            try:
                from ballista_tpu.client.context import fetch_job_results

                tbl = fetch_job_results(g.job_status(), g.config)
                result = self._finish_fill(fill, tbl, g.config)
                if deferred:
                    g.inline_result = result
            except Exception as e:  # noqa: BLE001
                if deferred:
                    g.status = JobState.FAILED
                    g.error = f"incremental render failed: {e}"
                    log.warning("incremental render for %s failed: %s", g.job_id, e)
                else:
                    log.debug("result-cache fill for %s failed: %s", g.job_id, e)
            finally:
                if deferred:
                    with self._jobs_lock:
                        self._rc_render_pending.discard(g.job_id)
                    self._notify(g.job_id)

        threading.Thread(target=run, daemon=True, name="result-cache-fill").start()
        return deferred

    def _apply_task_updates(self, executor_id: str, results: list[TaskResult],
                            free_slots_managed: bool = True) -> None:
        for r in results:
            if free_slots_managed:
                self.executors.free_slot(executor_id, 1)
            timed_out = bool(getattr(r, "timed_out", False))
            # cancelled tasks say nothing about executor health; success and
            # failure (incl. timeout) feed the decayed quarantine score
            if r.state in ("success", "failed"):
                if timed_out:
                    self.metrics.record_task_timeout(executor_id)
                transition = self.executors.record_task_result(
                    executor_id, ok=(r.state == "success"), timed_out=timed_out)
                if transition is not None:
                    log.warning("executor %s %s (failure_rate over window: %s)",
                                executor_id, transition,
                                self.executors.health_snapshot().get(executor_id, {}).get("failure_rate"))
                    self.metrics.set_quarantined_executors(self.executors.quarantined_count())
            fetch_cause = str(getattr(r, "fetch_failed_cause", "") or "")
            if fetch_cause == "corruption" and r.fetch_failed_executor_id:
                # blame the SERVING executor, not the fetcher: its stored
                # bytes failed verification twice. Repeated strikes push it
                # through the same quarantine machinery as task failures.
                transition = self.executors.record_corruption_strike(
                    r.fetch_failed_executor_id)
                log.warning(
                    "corruption strike against executor %s (reported by %s, "
                    "%s/%s)%s", r.fetch_failed_executor_id, executor_id,
                    r.job_id, r.fetch_failed_stage_id,
                    f" — {transition}" if transition else "")
                if transition is not None:
                    self.metrics.set_quarantined_executors(
                        self.executors.quarantined_count())
            with self._jobs_lock:
                g = self.jobs.get(r.job_id)
            if g is None:
                continue
            events = g.update_task_status(
                r.task_id, r.stage_id, r.stage_attempt, r.state, r.partitions,
                r.locations, r.error, r.retryable, r.metrics,
                r.fetch_failed_executor_id, r.fetch_failed_stage_id,
                timed_out=timed_out, fetch_failed_cause=fetch_cause,
            )
            if events:
                # checkpoint the graph at every stage/terminal transition:
                # the durable unit is the materialized shuffle output, so a
                # recovering scheduler resumes from the last finished stage
                self.job_state.save_graph(g)
            for ev in events:
                if ev == "job_finished":
                    self.metrics.record_completed(g.job_id, time.time() - g.queued_at)
                    if not self._fill_result_cache_from_graph(g):
                        self._notify(g.job_id)
                elif ev == "job_failed":
                    self.metrics.record_failed(g.job_id)
                    self._notify(g.job_id)
            self._push_cancellations(g)

    def _push_cancellations(self, g) -> None:
        """Fan CancelTasks out to the executors running tasks that
        incremental replanning (or a job cancel) obsoleted. Off the event
        loop: a dead executor's rpc timeout must not stall scheduling."""
        doomed = g.drain_cancelled_tasks()
        if not doomed or self.launcher is None:
            return
        by_exec: dict[str, list[tuple[int, int]]] = {}
        for executor_id, task_id, stage_id in doomed:
            by_exec.setdefault(executor_id, []).append((task_id, stage_id))

        def run():
            for executor_id, items in by_exec.items():
                try:
                    self.launcher.cancel_tasks(executor_id, g.job_id, items, self)
                except Exception as e:  # noqa: BLE001 — best-effort; expiry sweeps catch leaks
                    log.warning("CancelTasks to %s failed: %s", executor_id, e)

        threading.Thread(target=run, daemon=True, name="cancel-push").start()

    # -- straggler defense -------------------------------------------------------------

    def _sweep_stragglers(self, shard: SchedulerShard | None = None) -> None:
        """Event-loop sweep: (1) expire tasks past deadline+grace (backstop
        for executors too wedged to self-report the timeout), (2) launch
        speculative duplicates of a nearly-done stage's slowest tasks on a
        DIFFERENT executor, (3) re-offer when quarantine probes come due.
        Each shard sweeps only the jobs it owns; fleet-scoped work (lease
        expiry, admission update) runs once, on shard 0."""
        now = time.time()
        scoped = shard is not None and self.num_shards > 1
        with self._jobs_lock:
            fast = list(self._fast_jobs.values())
            running = [g for g in self.jobs.values()
                       if g.status is JobState.RUNNING and not isinstance(g, FastJob)]
            if scoped:
                fast = [j for j in fast if shard.owns(j.job_id)]
                running = [g for g in running if shard.owns(g.job_id)]
        for job in fast:
            # backstop for fast jobs whose executor died or wedged: demote
            # to a full graph, which has retries and deadline machinery
            if job.expired(now, float(job.config.get(SERVING_FAST_LANE_TIMEOUT_S))):
                self._fast_fallback(job, "fast-lane timeout")
        for g in running:
            expired, job_failed = g.expire_overdue_tasks(now)
            if expired:
                for executor_id, task_id, stage_id in expired:
                    log.warning("task %d of %s/%d on %s expired past deadline",
                                task_id, g.job_id, stage_id, executor_id)
                    self.executors.free_slot(executor_id, 1)
                    self.metrics.record_task_timeout(executor_id)
                    self.executors.record_task_result(executor_id, ok=False, timed_out=True)
                self._push_cancellations(g)
                if job_failed:
                    self.job_state.save_graph(g)
                    self.metrics.record_failed(g.job_id)
                    self._notify(g.job_id)
                else:
                    # expired partitions re-pended on this specific graph
                    self.post(Event("revive", g.job_id))
            if self.launcher is None:
                continue  # speculation is push-only; pull executors can't be targeted
            for stage_id, task_id, victim in g.speculation_candidates(now):
                executor_id = self.executors.reserve_one_avoiding({victim})
                if executor_id is None:
                    break  # no healthy slot anywhere else; retry next sweep
                task = g.register_speculative(stage_id, task_id, executor_id)
                if task is None:
                    self.executors.free_slot(executor_id, 1)
                    continue
                log.info("speculative attempt %d of %s/%d task %d → %s (straggling on %s)",
                         task.task_id, g.job_id, stage_id, task_id, executor_id, victim)
                self.metrics.record_speculative_launched(g.job_id, stage_id)
                self._spawn_launch(executor_id, [task])
        # cross-shard slot-release backstop: slots freed by another shard's
        # completions (or by lease expiry) generate no event on this shard,
        # so every sweep re-offers this shard's slice; zero demand exits in
        # one pass over the scoped jobs
        if scoped:
            self._offer_reservation(shard)
        if shard is not None and shard.shard_id != 0:
            return  # fleet-scoped sweep work below runs once per round
        if self.executors.probes_due():
            self._offer_reservation(shard)
        self._sweep_leases(now)
        self._sweep_job_data_ttl(now)
        self.metrics.set_quarantined_executors(self.executors.quarantined_count())
        pressure = self.executors.aggregate_pressure()
        transition = self.admission.update(self._loop_lag_s, pressure)
        if transition is not None:
            log.warning("overload state -> %s (inflight=%d, loop_lag=%.2fs, memory_pressure=%.2f)",
                        transition, self.admission.depth(), self._loop_lag_s, pressure)
            self.metrics.set_overload_state(transition)
            if transition in ("shedding", "draining"):
                # give the shed its headroom: drop the serving caches so
                # memory-pressure recovery isn't fighting cached results
                self.serving.clear()

    def _sweep_job_data_ttl(self, now: float) -> None:
        """Orphaned-data GC, scheduler-driven half (docs/lifecycle.md#gc):
        terminal jobs past their `ballista.executor.data.ttl.seconds` get
        their scheduler state dropped and a shuffle-GC RPC fanned out over
        the existing remove_job_data seam. Per-job TTL (it is a session
        knob); 0 disables. Bounded work: clean_job_data fans the executor
        RPCs off-thread, so the sweep itself never blocks the loop."""
        from ballista_tpu.config import EXECUTOR_DATA_TTL_S

        with self._jobs_lock:
            terminal = [g for g in self.jobs.values()
                        if g.status in (JobState.SUCCESSFUL, JobState.FAILED,
                                        JobState.CANCELLED)
                        and not isinstance(g, FastJob)]
        for g in terminal:
            try:
                ttl = float(g.config.get(EXECUTOR_DATA_TTL_S))
            except Exception:  # noqa: BLE001 — a broken config must not kill the sweep
                continue
            ended = float(g.ended_at or 0.0)
            if ttl <= 0 or not ended or now - ended < ttl:
                continue
            log.info("job %s terminal for %.0fs (ttl %.0fs): sweeping its data",
                     g.job_id, now - ended, ttl)
            self.lifecycle_stats["gc_swept_jobs"] += 1
            self.clean_job_data(g.job_id)

    # -- executor lifecycle -----------------------------------------------------------

    def register_executor(self, metadata: ExecutorMetadata) -> None:
        self.executors.register(metadata)
        self.post(Event("revive"))

    def executor_heartbeat(self, executor_id: str,
                           metrics: dict[str, float] | None = None) -> bool:
        """Heartbeat + overload-signal ingestion. `metrics` is the decoded
        HeartBeatParams.metrics map (memory_pressure et al.); the
        pressure feeds the admission state machine on the next sweep.
        Fans in ONCE: shards never see heartbeats directly — executor
        state lives in the shared ExecutorManager, and only the derived
        executor_lost events multicast."""
        self._fanin["heartbeats"] += 1
        if metrics and metrics.get("pressure_rejections"):
            # gauge, not delta: only count growth over the last report
            prev = self.executors.get(executor_id)
            prev_n = prev.pressure_rejections if prev is not None else 0.0
            grown = int(metrics["pressure_rejections"] - prev_n)
            for _ in range(max(0, grown)):
                self.metrics.record_pressure_rejection(executor_id)
        known = self.executors.heartbeat(executor_id, metrics)
        if known and metrics and float(metrics.get("lifecycle_draining", 0.0)) >= 1.0:
            # SIGTERM-initiated drain announcement: run the drain state
            # machine off-thread (it waits on running tasks and migrates
            # files — never on a caller's RPC thread or the event loop)
            self._spawn_drain(executor_id)
        return known

    # -- drain state machine (docs/lifecycle.md#drain-protocol) ---------------

    def _spawn_drain(self, executor_id: str) -> None:
        with self._drain_lock:
            if executor_id in self._drains_inflight:
                return
            self._drains_inflight.add(executor_id)

        def run():
            try:
                self.drain_executor(executor_id)
            except Exception:  # noqa: BLE001 — a died drain must not leak the guard
                log.exception("drain of %s failed", executor_id)
            finally:
                with self._drain_lock:
                    self._drains_inflight.discard(executor_id)

        threading.Thread(target=run, daemon=True, name=f"drain-{executor_id}").start()

    def _executor_has_running(self, executor_id: str) -> bool:
        with self._jobs_lock:
            graphs = [g for g in self.jobs.values()
                      if g.status is JobState.RUNNING and not isinstance(g, FastJob)]
        for g in graphs:
            with g._lock:
                for s in g.stages.values():
                    if any(t.executor_id == executor_id for t in s.running.values()):
                        return True
        return False

    def _locations_on(self, executor_id: str) -> list:
        """Every completed PartitionLocation a draining executor still
        holds — across RUNNING graphs (partial stage outputs included: a
        running stage's finished map tasks are exactly what downstream
        readers will fetch) and SUCCESSFUL ones (clients fetch final-stage
        partitions after the job ends)."""
        out = []
        with self._jobs_lock:
            graphs = [g for g in self.jobs.values()
                      if g.status in (JobState.RUNNING, JobState.SUCCESSFUL)
                      and not isinstance(g, FastJob)]
        for g in graphs:
            with g._lock:
                for s in g.stages.values():
                    for locs in s.completed.values():
                        out.extend(l for l in locs if l.executor_id == executor_id)
        return out

    def drain_executor(self, executor_id: str, timeout_s: float | None = None) -> dict:
        """Graceful decommission (docs/lifecycle.md): stop offering to the
        executor, revoke its direct-dispatch leases, wait (bounded) for its
        running tasks, hand its map outputs off to a survivor, then retire
        it with a `drained` ledger entry. The closing `executor_lost` event
        is the safety net: fully migrated locations no longer name the
        executor (zero stage reruns), while anything left behind — hard
        kill mid-migration, no survivor, launcher without a migration
        path — recomputes through today's recovery machinery, byte-
        identical. MUST run off the event loop (it sleeps)."""
        slot = self.executors.get(executor_id)
        if slot is None or not self.executors.begin_drain(executor_id):
            return {"executor_id": executor_id, "status": "unknown"}
        log.info("draining executor %s", executor_id)
        self.lifecycle_stats["drains"] += 1
        for lease in [l for l in self.leases.active() if l.executor_id == executor_id]:
            self.revoke_executor_lease(lease.lease_id)
        if timeout_s is None:
            from ballista_tpu.config import EXECUTOR_DRAIN_TIMEOUT_S

            timeout_s = float(BallistaConfig().get(EXECUTOR_DRAIN_TIMEOUT_S))
        deadline = time.time() + max(0.0, timeout_s)
        while time.time() < deadline and self._executor_has_running(executor_id):
            time.sleep(0.05)
        locations = self._locations_on(executor_id)
        migrated = migrated_bytes = 0
        status = "drained"
        if locations and self.launcher is not None:
            survivors = [e for e in self.executors.alive_executors()
                         if e.schedulable and e.metadata.id != executor_id]
            if survivors:
                dest = max(survivors, key=lambda e: e.free_slots)
                try:
                    migrated, migrated_bytes = self.launcher.migrate_partitions(
                        executor_id, dest.metadata.id, locations, self)
                except Exception as e:  # noqa: BLE001 — hard-kill fallback is the contract
                    status = "drain-killed"
                    self.lifecycle_stats["drain_kills"] += 1
                    log.warning("drain of %s died mid-migration (%s); unmigrated "
                                "outputs fall back to recompute", executor_id, e)
            else:
                log.warning("drain of %s found no survivor; %d locations fall "
                            "back to recompute", executor_id, len(locations))
        if migrated:
            log.info("drain of %s migrated %d/%d locations (%d bytes)",
                     executor_id, migrated, len(locations), migrated_bytes)
        self.lifecycle_stats["migrated_partitions"] += migrated
        self.lifecycle_stats["migrated_bytes"] += migrated_bytes
        self.executors.mark_drained(executor_id, migrated, migrated_bytes, reason=status)
        # safety net + remainder recovery: locations rewritten by the
        # migration no longer match the lost executor id
        self.post(Event("executor_lost", executor_id))
        return {"executor_id": executor_id, "status": status,
                "locations": len(locations), "migrated_partitions": migrated,
                "migrated_bytes": migrated_bytes}

    def _on_executor_lost(self, executor_id: str,
                          shard: SchedulerShard | None = None) -> None:
        # deregister is idempotent: the event multicasts, every shard rolls
        # back only its own jobs' stages
        self.executors.deregister(executor_id)
        with self._jobs_lock:
            graphs = list(self.jobs.values())
            if shard is not None and self.num_shards > 1:
                graphs = [g for g in graphs if shard.owns(g.job_id)]
        for g in graphs:
            n = g.reset_stages_on_lost_executor(executor_id)
            if n:
                log.info("rolled back %d task/stage units of %s after losing %s", n, g.job_id, executor_id)

    def check_expired_executors(self) -> None:
        for eid in self.executors.expire_dead():
            log.warning("executor %s expired (no heartbeat)", eid)
            self.post(Event("executor_lost", eid))

    def resubmit_stuck_jobs(self) -> None:
        """ballista.scheduler.job.resubmit.interval.ms: periodically re-offer
        jobs holding runnable-but-unscheduled tasks (missed offers, executors
        that freed slots without an event, scale-out while idle) — the
        reference's job-resubmit behavior for jobs that couldn't schedule.
        In a multi-scheduler deployment this is also the orphan reviver:
        jobs whose owner died mid-flight sit in the shared store with a
        stale lease until a live peer's sweep adopts them here."""
        from ballista_tpu.config import JOB_RESUBMIT_INTERVAL_MS

        try:
            orphans = self.recover_jobs(only_active=True)
        except Exception:  # noqa: BLE001 — a wedged store must not kill the sweep
            log.exception("orphan recovery sweep failed")
            orphans = []
        for job_id in orphans:
            log.warning("adopted orphaned job %s (owner lease expired)", job_id)
        with self._jobs_lock:
            running = [g for g in self.jobs.values() if g.status is JobState.RUNNING]
        stuck = []
        for g in running:
            interval = int(g.config.get(JOB_RESUBMIT_INTERVAL_MS))
            if interval > 0 and g.available_task_count() > 0:
                stuck.append(g)
        if not stuck:
            return
        # diagnose WHY work sat unscheduled, so an overload incident is
        # readable from logs alone: every slot busy (no-capacity) vs slots
        # exist but their executors are quarantined (quarantine-starved)
        alive = self.executors.alive_executors()
        free_any = sum(e.free_slots for e in alive)
        free_healthy = sum(e.free_slots for e in alive if e.schedulable)
        if free_any == 0:
            reason = "no-capacity"
        elif free_healthy == 0:
            reason = "quarantine-starved"
        else:
            reason = "missed-offer"
        for g in stuck:
            log.info("resubmitting stuck job %s (%d runnable tasks, cause: %s)",
                     g.job_id, g.available_task_count(), reason)
        self.post(Event("revive"))

    # -- direct-dispatch leases ----------------------------------------------------------

    def mint_executor_lease(self, session_id: str, slots: int | None = None,
                            ttl_s: float | None = None,
                            band_size: int | None = None) -> "ExecutorLease | None":
        """Mint a revocable direct-dispatch lease on ONE warm executor: a
        capacity slice (slots), an expiry, and a reserved task-id band.
        Slots come out of the shared ledger up front, so graph scheduling
        and direct dispatch can never oversubscribe the same executor.
        Returns None (and counts a denial) when no single executor has
        the headroom — callers fall back to the scheduled path."""
        want = DEFAULT_LEASE_SLOTS if slots is None else max(1, int(slots))
        ttl = DEFAULT_LEASE_TTL_S if ttl_s is None else float(ttl_s)
        candidates = [e for e in self.executors.alive_executors()
                      if e.schedulable and e.free_slots >= want]
        if not candidates:
            self.leases.denied += 1
            return None
        best = max(candidates, key=lambda e: e.free_slots)
        eid = best.metadata.id
        if self.executors.take_slots(eid, want) < want:
            self.leases.denied += 1
            return None
        lease = self.leases.mint(
            executor_id=eid, host=best.metadata.host,
            flight_port=best.metadata.flight_port, session_id=session_id,
            slots=want, ttl_s=ttl, band_size=band_size)
        self.metrics.record_lease("minted")
        if self.launcher is not None:
            try:
                self.launcher.grant_lease(eid, lease, self)
            except Exception as e:  # noqa: BLE001 — executor admits nothing it wasn't granted
                log.warning("lease grant push to %s failed: %s", eid, e)
                self.executors.free_slot(eid, want)
                self.leases.revoke(lease.lease_id)
                self.leases.denied += 1
                return None
        return lease

    def revoke_executor_lease(self, lease_id: str) -> bool:
        """Revoke a lease: return its slots to the ledger and push the
        revocation to the executor off-thread (best effort — the
        executor-side expiry check is the backstop)."""
        lease = self.leases.revoke(lease_id)
        if lease is None:
            return False
        self.executors.free_slot(lease.executor_id, lease.slots)
        self.metrics.record_lease("revoked")
        self._push_lease_revocations([lease])
        return True

    def _sweep_leases(self, now: float) -> None:
        """Sweep-time backstop: expired leases return their slots and get a
        best-effort revocation push (clients normally stop first — the
        token itself rejects past expiry)."""
        expired = self.leases.expire(now)
        for lease in expired:
            self.executors.free_slot(lease.executor_id, lease.slots)
            self.metrics.record_lease("expired")
        if expired:
            self._push_lease_revocations(expired)

    def _push_lease_revocations(self, leases: list) -> None:
        if self.launcher is None:
            return

        def run():
            for lease in leases:
                try:
                    self.launcher.revoke_lease(lease.executor_id, lease.lease_id, self)
                except Exception as e:  # noqa: BLE001 — expiry at the executor is the backstop
                    log.debug("lease revoke push to %s failed: %s", lease.executor_id, e)

        threading.Thread(target=run, daemon=True, name="lease-revoke-push").start()

    def reconcile_direct_dispatch(self, record: dict) -> None:
        """Asynchronous reconciliation: the client already has its bytes;
        the scheduler just folds the completed direct-dispatch work into
        its ledgers (job accounting, KEDA counters) after the fact."""
        tasks = int(record.get("tasks", 1))
        self.leases.note_reconciled(record.get("lease_id"), tasks)
        self.metrics.record_direct_dispatch("reconciled")

    # -- job control ---------------------------------------------------------------------

    def _cancel_job(self, job_id: str) -> None:
        with self._jobs_lock:
            g = self.jobs.get(job_id)
        if g is not None:
            g.cancel()
            self._push_cancellations(g)
            self.job_state.save_graph(g)  # terminal transition: checkpoint
            self.metrics.record_cancelled(job_id)
            self._notify(job_id)

    def cancel_job(self, job_id: str) -> None:
        self.post(Event("cancel", job_id))

    def job_status(self, job_id: str) -> dict | None:
        with self._jobs_lock:
            g = self.jobs.get(job_id)
            pending_render = job_id in self._rc_render_pending
        if g is None:
            return None
        st = g.job_status()
        if pending_render and st.get("state") == "successful":
            # an incremental fill owns the terminal transition: the stage
            # partitions hold raw accumulator state, not the result —
            # clients must keep polling until the render attaches it
            st = dict(st)
            st["state"] = "running"
            st.pop("partitions", None)
        return st

    def wait_for_job(self, job_id: str, timeout: float = 300.0) -> dict:
        ev = threading.Event()
        with self._jobs_lock:
            self._watchers.setdefault(job_id, []).append(ev)
        st = self.job_status(job_id)
        if st is not None and st["state"] in ("successful", "failed", "cancelled"):
            return st
        deadline = time.time() + timeout
        while time.time() < deadline:
            if ev.wait(timeout=0.5):
                break
            st = self.job_status(job_id)
            if st and st["state"] in ("successful", "failed", "cancelled"):
                break
        st = self.job_status(job_id)
        if st is None:
            raise BallistaError(f"unknown job {job_id}")
        return st

    def _notify(self, job_id: str) -> None:
        # _notify fires on every terminal transition (finished / failed /
        # cancelled / planning error), so it doubles as the single release
        # point for the job's admission slot; finish() is idempotent.
        self.admission.finish(job_id)
        with self._jobs_lock:
            for ev in self._watchers.pop(job_id, []):
                ev.set()

    def clean_job_data(self, job_id: str) -> None:
        """Drop scheduler-side job state AND fan a shuffle-GC rpc out to
        every live executor (reference: ExecutorManager::clean_up_job_data,
        state/executor_manager.rs — otherwise shuffle files linger until
        the work-dir TTL sweep)."""
        with self._jobs_lock:
            self.jobs.pop(job_id, None)
            self._fast_jobs.pop(job_id, None)
            self._rc_pending.pop(job_id, None)
            self._rc_render_pending.discard(job_id)
        self.admission.finish(job_id)  # backstop; no-op if already released
        self.job_state.remove_job(job_id)
        if self.launcher is None:
            return
        executors = [e.metadata.id for e in self.executors.alive_executors()]

        def run():
            for executor_id in executors:
                try:
                    self.launcher.remove_job_data(executor_id, job_id, self)
                except Exception as e:  # noqa: BLE001 — TTL sweep catches leftovers
                    log.debug("RemoveJobData to %s failed: %s", executor_id, e)

        threading.Thread(target=run, daemon=True, name="job-gc").start()

    # -- fail-over recovery ------------------------------------------------

    def recover_jobs(self, force: bool = False,
                     only_active: bool = False) -> list[str]:
        """Adopt persisted job graphs (scheduler restart / standby takeover).
        Successful stages resume from their materialized shuffle outputs;
        mid-flight work recomputes. Jobs owned by a LIVE peer are skipped
        unless force (the reference's JobAcquired/JobReleased arbitration,
        cluster/mod.rs:221). `only_active` is the periodic orphan sweep in
        a multi-scheduler deployment: adopt only non-terminal jobs whose
        owner's lease went stale (a peer died mid-job), and release
        terminal graphs back rather than hoarding them."""
        recovered = []
        for job_id in self.job_state.list_jobs():
            with self._jobs_lock:
                if job_id in self.jobs:
                    continue
            if not self.job_state.acquire(job_id, self.scheduler_id, force=force):
                if not only_active:
                    log.info("job %s owned by another scheduler; skipping", job_id)
                continue
            g = self.job_state.load_graph(job_id)
            if g is None:
                continue
            if only_active and g.status in (
                    JobState.SUCCESSFUL, JobState.FAILED, JobState.CANCELLED):
                self.job_state.release(job_id, self.scheduler_id)
                continue
            with self._jobs_lock:
                self.jobs[job_id] = g
            # re-register the session so later planning/launches see the
            # job's settings (the graph proto carries the config snapshot)
            self.sessions.create_or_update(g.config.to_key_value_pairs(), g.session_id)
            recovered.append(job_id)
            log.info("recovered job %s (status=%s)", job_id, g.status.value)
        if recovered:
            self.post(Event("revive"))
        return recovered
