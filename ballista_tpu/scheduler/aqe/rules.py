"""Adaptive query execution: replan stages with runtime statistics.

Rebuild of the reference's AQE subsystem (scheduler/src/state/aqe/), scoped
to its three headline optimizations, applied when a stage RESOLVES (all
inputs finished, actual per-partition stats in hand):

- PropagateEmptyExecRule: an inner join whose build or probe input produced
  ZERO rows collapses to an EmptyExec subtree (semi joins likewise; anti
  joins with an empty right side collapse to their left input).
- CoalescePartitionsRule: post-shuffle reduce partitions are bin-packed to
  `ballista.planner.adaptive.coalesce.target.bytes` — ONE group assignment
  per stage (computed over the summed sizes of every hash input) so
  co-partitioned join sides stay aligned (coalesce/algorithm.rs).
- SelectJoinRule (dynamic join selection): a partitioned inner join whose
  build side turned out tiny is rewritten to CollectLeft with a broadcast
  reader, skipping the per-partition build (join swap by ACTUAL sizes, not
  estimates). Build-side-emitting join types keep partitioned mode — the
  correctness constraint from the physical planner applies at runtime too.
- SkewSplitRule (docs/aqe.md): a reduce partition whose observed bytes
  exceed `median × ballista.aqe.skew.factor` (median via a T-Digest over
  the per-bucket histogram) and the `ballista.aqe.skew.min.bytes` floor is
  split into K partition-SLICE tasks. Each slice's reader consumes a
  distinct contiguous sub-range of the hot partition's map outputs
  (shuffle.reader.split_location_ranges), so concatenating the slices in
  partition order is byte-identical to the unsplit read; a join's build
  side is DUPLICATED into every slice instead. plan_check's skew rule
  verifies cover / no-overlap / order from the SkewSplitReport before the
  replanned DAG runs.
- Mesh composition: mesh-fused stages no longer disable AQE wholesale
  (the PR 7 blanket skip). A hot key demotes the fused edge to the host
  split with `mesh_mode_reason="demoted:aqe:skew"`; otherwise, when the
  observed input volume warrants far fewer device buckets, the exchange
  is rebuilt at the smaller count and the stage's task span shrinks with
  it.

The reference plans stages incrementally (AdaptivePlanner::replan_stages);
this build plans statically and rewrites at resolution — same signals,
same rewrites, one fewer moving part. Incremental planning is the round-2
item that also unlocks probe-side-shuffle elision.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from ballista_tpu.config import (
    AQE_COALESCE_MERGED_FACTOR,
    AQE_DYNAMIC_JOIN_SELECTION,
    AQE_EMPTY_PROPAGATION,
    AQE_MIN_PARTITION_BYTES,
    AQE_SKEW_ENABLED,
    AQE_SKEW_FACTOR,
    AQE_SKEW_MAX_SLICES,
    AQE_SKEW_MIN_BYTES,
    AQE_TARGET_PARTITION_BYTES,
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
    PLANNER_ADAPTIVE_ENABLED,
    BallistaConfig,
)
from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    EmptyExec,
    ExecutionPlan,
    FilterExec,
    HashJoinExec,
    ProjectionExec,
)
from ballista_tpu.shuffle.reader import ShuffleReaderExec, split_location_ranges
from ballista_tpu.shuffle.writer import ShuffleWriterExec
from ballista_tpu.utils.tdigest import TDigest

log = logging.getLogger(__name__)


def coalesce_groups(sizes: list[int], target: int, min_bytes: int, merged_factor: float) -> list[list[int]]:
    """Bin-pack contiguous reduce partitions by byte size.

    Greedy sequential packing to `target` bytes with a slack factor; a
    trailing small group merges backwards (the reference's merged-factor +
    small-tail refinements, aqe/coalesce/algorithm.rs)."""
    if not sizes:
        return []
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, s in enumerate(sizes):
        if cur and cur_bytes + s > target * merged_factor:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += s
    if cur:
        tail_bytes = sum(sizes[i] for i in cur)
        if groups and tail_bytes < min_bytes:
            groups[-1].extend(cur)
        else:
            groups.append(cur)
    return groups


@dataclass
class InputStageStats:
    stage_id: int
    total_rows: int
    total_bytes: int
    bucket_bytes: list[int]  # per output partition
    broadcast: bool
    # T-Digest over this input's per-bucket byte histogram; the skew rule's
    # robust-median threshold merges these across hash inputs
    bytes_digest: "TDigest | None" = None


@dataclass
class SkewSplit:
    """One hot reduce partition split into slice tasks."""

    bucket: int            # original output-partition index that ran hot
    partitions: list[int]  # stage partition indices now holding the slices
    bytes: int             # observed combined bytes of the hot bucket


@dataclass
class SkewSplitReport:
    """Resolution-time record of skew splits on a stage, consumed by
    plan_check's skew rule (cover / no-overlap / order over the slice
    readers' location lists) and by the aqe-grew partition accounting."""

    splits: list[SkewSplit] = field(default_factory=list)
    extra_partitions: int = 0


# join types whose output is a pure function of (full build side, probe
# rows): slicing the probe and concatenating slice outputs in probe order
# reproduces the unsplit join. Build-emitting types (left/full/anti-left)
# would emit their unmatched-build rows once PER slice — never split those.
_SPLIT_SAFE_JOINS = ("inner", "right", "right_semi", "right_anti")


def apply_aqe(plan: ExecutionPlan, input_stats: dict[int, InputStageStats],
              config: BallistaConfig,
              stage_partitions: int | None = None,
              stage_unconsumed: bool = False,
              ) -> tuple[ExecutionPlan, int | None, SkewSplitReport | None]:
    """Rewrite a freshly-resolved stage plan using actual input statistics.

    `plan` has concrete ShuffleReaderExec leaves tagged with their source
    stage id (set by the graph at resolution). `stage_unconsumed` marks a
    stage with no downstream consumers (results are collected, not read by
    another stage) — a passthrough-rooted stage may only change its task
    count then, because passthrough outputs are indexed by map partition.
    Returns (new_plan, new_partition_count or None, SkewSplitReport or
    None); a non-None count replaces the stage's pending/effective
    partitions — it may exceed the planned count when a skew split grew
    the stage.
    """
    if not bool(config.get(PLANNER_ADAPTIVE_ENABLED)):
        return plan, None, None

    if bool(config.get(AQE_EMPTY_PROPAGATION)):
        plan = _propagate_empty(plan, input_stats)

    if bool(config.get(AQE_DYNAMIC_JOIN_SELECTION)):
        plan = _select_joins(plan, input_stats, config)

    # a stage whose root writer hash-routes (output_partitions > 0) can take
    # any task count — every task feeds the same K output buckets. A
    # passthrough root writes one output PER map partition, so its task
    # count is only negotiable when nothing downstream indexes those outputs
    repartitionable = isinstance(plan, ShuffleWriterExec) and (
        plan.output_partitions > 0 or stage_unconsumed
    )

    mesh_nodes = _mesh_nodes(plan)
    if mesh_nodes:
        return _mesh_aqe(plan, mesh_nodes, input_stats, config, repartitionable)

    target = int(config.get(AQE_TARGET_PARTITION_BYTES))
    min_b = int(config.get(AQE_MIN_PARTITION_BYTES))
    factor = float(config.get(AQE_COALESCE_MERGED_FACTOR))
    hash_inputs = [
        s for s in input_stats.values() if not s.broadcast and len(s.bucket_bytes) > 1
    ]
    readers = _hash_readers(plan)
    k_in = len(hash_inputs[0].bucket_bytes) if hash_inputs else 0
    # regrouping replaces reader partition lists IN PLACE of the stage's
    # partition indexing — only sound when the stage's partitions ARE the
    # readers' (a Union stage concatenates branch partition ranges, so its
    # indexing is not reader-aligned; never regroup it)
    aligned = stage_partitions is None or stage_partitions == k_in
    if not (hash_inputs and readers and aligned and all(
        len(r.partition_locations) == k_in for r in readers
    )):
        return plan, None, None

    k = k_in
    combined = _combined_bucket_bytes(input_stats)

    # -- skew detection: which buckets split, into how many slices ---------
    splits: dict[int, int] = {}
    dup_ids: set[int] = set()
    if repartitionable and bool(config.get(AQE_SKEW_ENABLED)):
        safe, sliced_ids, dup_ids = _classify_split_readers(plan)
        if safe:
            sliced = [r for r in readers if id(r) in sliced_ids]

            def min_locs(b: int) -> int:
                return min((len(r.partition_locations[b]) for r in sliced), default=0)

            splits = _plan_splits(combined, config, min_locs)

    # -- unit construction: slices for hot buckets, coalesce groups for the
    #    cold segments between them ----------------------------------------
    report = None
    if not splits:
        groups = coalesce_groups(combined, target, min_b, factor)
        if not (0 < len(groups) < k):
            return plan, None, None
        units: list[tuple] = [("group", g) for g in groups]
        log.info("AQE coalesced %d reduce partitions into %d groups", k, len(groups))
    else:
        units = []
        rsplits: list[SkewSplit] = []
        seg: list[int] = []

        def flush_segment() -> None:
            if not seg:
                return
            for g in coalesce_groups([combined[i] for i in seg], target, min_b, factor):
                units.append(("group", [seg[x] for x in g]))
            seg.clear()

        for b in range(k):
            if b in splits:
                flush_segment()
                n = splits[b]
                rsplits.append(SkewSplit(
                    bucket=b,
                    partitions=list(range(len(units), len(units) + n)),
                    bytes=combined[b],
                ))
                for j in range(n):
                    units.append(("slice", b, j, n))
            else:
                seg.append(b)
        flush_segment()
        report = SkewSplitReport(
            splits=rsplits,
            extra_partitions=sum(len(s.partitions) - 1 for s in rsplits),
        )
        log.info(
            "AQE skew split: buckets %s → %d slices each (%d stage partitions total)",
            sorted(splits), max(splits.values()), len(units),
        )

    # -- rebuild readers over the unit layout. FRESH readers rather than
    #    mutating shared ones in place: a reader aliased by a replayed or
    #    retried resolution must never see half-regrouped location lists
    #    (the stale-alias class of bug this codebase hit once already) ------
    replacements: dict[int, ShuffleReaderExec] = {}
    for r in readers:
        dup = id(r) in dup_ids
        ranges: dict[int, list[list]] = {}
        lists: list[list] = []
        for u in units:
            if u[0] == "group":
                lists.append([loc for i in u[1] for loc in r.partition_locations[i]])
            else:
                _, b, j, n = u
                if dup:
                    # a join's build side sees the WHOLE hot bucket in every
                    # slice — each slice re-builds the full hash table and
                    # probes its own sub-range
                    lists.append(list(r.partition_locations[b]))
                else:
                    if b not in ranges:
                        ranges[b] = split_location_ranges(r.partition_locations[b], n)
                    lists.append(ranges[b][j])
        nr = ShuffleReaderExec(r.df_schema, lists, r.broadcast)
        nr.source_stage_id = getattr(r, "source_stage_id", None)
        replacements[id(r)] = nr
    plan = _replace_readers(plan, replacements)
    new_parts = len(units)
    from ballista_tpu.ops.cpu.range_repartition import retarget_routers

    plan = retarget_routers(plan, new_parts)

    from ballista_tpu.ops.tpu import aqe_stats

    if splits:
        aqe_stats.note_skew_splits(len(splits))
    coalesced_away = (k - len(splits)) - sum(1 for u in units if u[0] == "group")
    aqe_stats.note_coalesced_partitions(coalesced_away)
    return plan, new_parts, report


def _combined_bucket_bytes(input_stats: dict[int, InputStageStats]) -> list[int]:
    """Per-reduce-partition bytes summed over every hash input (the joint
    histogram the coalesce and skew thresholds both read)."""
    hash_inputs = [
        s for s in input_stats.values() if not s.broadcast and len(s.bucket_bytes) > 1
    ]
    if not hash_inputs:
        return []
    k = len(hash_inputs[0].bucket_bytes)
    combined = [0] * k
    for s in hash_inputs:
        if len(s.bucket_bytes) == k:
            for i, b in enumerate(s.bucket_bytes):
                combined[i] += b
    return combined


def _hot_buckets(combined: list[int], config: BallistaConfig) -> list[int]:
    """Buckets exceeding `median × skew.factor` AND the skew bytes floor.
    The median comes from a T-Digest over the bucket histogram — the same
    sketch the runtime range repartitioner uses, robust to the hot bucket
    dragging a plain mean."""
    factor = float(config.get(AQE_SKEW_FACTOR))
    floor = int(config.get(AQE_SKEW_MIN_BYTES))
    if factor <= 0 or len(combined) < 2:
        return []
    digest = TDigest()
    digest.add_array(np.asarray(combined, dtype=np.float64))
    med = digest.quantile(0.5)
    if med != med:  # empty digest
        return []
    threshold = max(med * factor, float(floor))
    return [i for i, v in enumerate(combined) if v > threshold]


def _plan_splits(combined: list[int], config: BallistaConfig,
                 min_locs) -> dict[int, int]:
    """bucket → slice count for every splittable hot bucket. The count
    aims each slice at the coalesce target, capped by skew.max.slices and
    by the bucket's map-output count (`min_locs`) — a single map output is
    never subdivided, so fewer than 2 available locations means no split."""
    hot = _hot_buckets(combined, config)
    if not hot:
        return {}
    target = max(1, int(config.get(AQE_TARGET_PARTITION_BYTES)))
    max_slices = int(config.get(AQE_SKEW_MAX_SLICES))
    out: dict[int, int] = {}
    for b in hot:
        n = max(2, min(max_slices, -(-combined[b] // target)))
        n = min(n, min_locs(b))
        if n >= 2:
            out[b] = n
    return out


def _classify_split_readers(plan: ExecutionPlan) -> tuple[bool, set[int], set[int]]:
    """Can this stage tolerate splitting one reduce partition into slices,
    and how does each hash reader participate?

    Walks from the root writer through partition-wise operators. Filter /
    projection / batch-coalescing are transparent (row-wise, order
    preserving). A join whose type is in _SPLIT_SAFE_JOINS contributes its
    LEFT (build) subtree's readers as duplicates — the full build executes
    per slice — and recurses down the probe side; any other operator
    (sorts, aggregates, unions, build-emitting joins) makes the plan
    unsplittable. Returns (safe, sliced_reader_ids, dup_reader_ids)."""
    sliced: set[int] = set()
    dup: set[int] = set()
    ok = True

    def collect(n: ExecutionPlan) -> None:
        if isinstance(n, ShuffleReaderExec):
            if not n.broadcast:
                dup.add(id(n))
            return
        for c in n.children():
            collect(c)

    def walk(n: ExecutionPlan) -> None:
        nonlocal ok
        if not ok:
            return
        if isinstance(n, ShuffleReaderExec):
            if not n.broadcast:
                sliced.add(id(n))
            return
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            if n.join_type not in _SPLIT_SAFE_JOINS:
                ok = False
                return
            collect(n.left)
            walk(n.right)
            return
        if isinstance(n, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
            walk(n.children()[0])
            return
        ok = False

    if isinstance(plan, ShuffleWriterExec):
        walk(plan.input)
    else:
        ok = False
    return ok and bool(sliced), sliced, dup


def _mesh_aqe(plan: ExecutionPlan, mesh_nodes: list,
              input_stats: dict[int, InputStageStats], config: BallistaConfig,
              repartitionable: bool,
              ) -> tuple[ExecutionPlan, int | None, SkewSplitReport | None]:
    """AQE over a mesh-fused stage — composition, not mutual exclusion.

    Partition-slicing and reader regrouping cannot apply (the exchange
    stands where the readers stood), but the runtime stats still drive two
    decisions:

    1. **skew demotion**: a hot bucket in the input histogram means the
       fixed-capacity collective would see one device's receive lane blow
       past its peers — demote the fused edge to the host split up front,
       with `mesh_mode_reason="demoted:aqe:skew"` on record.
    2. **bucket replan**: when the observed input volume wants far fewer
       buckets than planned (the coalescing signal), REBUILD the exchange
       at the smaller count — hash routing is bucket-count-parametric
       (`h % K` on both the device and host paths), so any K is valid —
       and shrink the stage's task span to match.
    """
    from ballista_tpu.ops.tpu import aqe_stats

    _demote_oversized_mesh(mesh_nodes, input_stats, config)

    combined = _combined_bucket_bytes(input_stats)
    if bool(config.get(AQE_SKEW_ENABLED)) and combined and _hot_buckets(combined, config):
        demoted = False
        for n in mesh_nodes:
            if not n.demote_reason:
                n.demote_reason = "aqe:skew"
                demoted = True
        if demoted:
            aqe_stats.note_mesh_replan()
            log.info("AQE demoted mesh exchange: hot reduce bucket detected "
                     "(mesh_mode_reason=demoted:aqe:skew)")
        return plan, None, None

    if not repartitionable or len(mesh_nodes) != 1:
        return plan, None, None
    ex = mesh_nodes[0]
    if ex.demote_reason:
        return plan, None, None
    target = int(config.get(AQE_TARGET_PARTITION_BYTES))
    total = sum(s.total_bytes for s in input_stats.values() if not s.broadcast)
    if target <= 0 or total <= 0:
        return plan, None, None
    k = ex.file_partitions
    new_k = max(1, -(-total // target))
    # same hysteresis as the fan-out rule: only replan on a big win, the
    # device dispatch amortizes small imbalances anyway
    if new_k > k // 2 or new_k >= k:
        return plan, None, None
    plan = _replace_readers(plan, {id(ex): ex.with_file_partitions(new_k)})
    from ballista_tpu.ops.cpu.range_repartition import retarget_routers

    plan = retarget_routers(plan, new_k)
    aqe_stats.note_mesh_replan()
    log.info("AQE replanned mesh exchange: %d → %d device buckets "
             "(%d observed input bytes)", k, new_k, total)
    return plan, new_k, None


def _mesh_nodes(plan: ExecutionPlan) -> list:
    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec

    out = []

    def walk(n):
        if isinstance(n, MeshExchangeExec):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def _demote_oversized_mesh(nodes: list, input_stats: dict[int, InputStageStats],
                           config: BallistaConfig) -> None:
    from ballista_tpu.config import TPU_MESH_MAX_INPUT_BYTES

    limit = int(config.get(TPU_MESH_MAX_INPUT_BYTES))
    if limit <= 0:
        return
    total = sum(s.total_bytes for s in input_stats.values() if not s.broadcast)
    if total <= limit:
        return
    reason = f"aqe:input-bytes({total}>{limit})"
    for n in nodes:
        n.demote_reason = reason
    log.info("AQE demoted mesh exchange to the per-partition path: %s", reason)


def _replace_readers(plan: ExecutionPlan, replacements: dict[int, ShuffleReaderExec]) -> ExecutionPlan:
    hit = replacements.get(id(plan))
    if hit is not None:
        return hit
    kids = plan.children()
    if not kids:
        return plan
    new_kids = [_replace_readers(c, replacements) for c in kids]
    if all(a is b for a, b in zip(new_kids, kids)):
        return plan
    return plan.with_children(new_kids)


def _hash_readers(plan: ExecutionPlan) -> list[ShuffleReaderExec]:
    out = []

    def walk(n, under_collect_build=False):
        if isinstance(n, ShuffleReaderExec) and not n.broadcast and not under_collect_build:
            out.append(n)
        if isinstance(n, HashJoinExec) and n.mode == "collect_left":
            walk(n.left, True)
            walk(n.right, under_collect_build)
            return
        for c in n.children():
            walk(c, under_collect_build)

    walk(plan)
    return out


def _stats_of(reader: ShuffleReaderExec, input_stats: dict[int, InputStageStats]):
    sid = getattr(reader, "source_stage_id", None)
    return input_stats.get(sid) if sid is not None else None


def _propagate_empty(plan: ExecutionPlan, input_stats) -> ExecutionPlan:
    def is_empty(n: ExecutionPlan) -> bool:
        if isinstance(n, ShuffleReaderExec):
            s = _stats_of(n, input_stats)
            return s is not None and s.total_rows == 0
        if isinstance(n, EmptyExec):
            return not n.produce_one_row
        return False

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            n = n.with_children([walk(c) for c in kids])
        # the deferred-decision node collapses under the same rules as a
        # concrete hash join: emptiness does not depend on build strategy
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            l_empty, r_empty = is_empty(n.left), is_empty(n.right)
            jt = n.join_type
            if jt == "inner" and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt in ("left_semi", "right_semi") and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt == "left_anti" and r_empty:
                return n.left  # nothing to subtract: pass the build side through
            if jt == "right_anti" and l_empty:
                return n.right
        return n

    return walk(plan)


def _broadcast_build_reader(resolved: ExecutionPlan) -> ExecutionPlan:
    """A collect_left build over a plain partitioned reader collects its
    partitions one sequential execute(p) at a time; the broadcast reader
    flattens every location into ONE concurrently governed fetch. Rebuild
    the build-side reader accordingly (the resolved node may sit under the
    swap-restoring projection)."""
    join = resolved
    if not isinstance(join, HashJoinExec):
        kids = join.children()
        if len(kids) != 1 or not isinstance(kids[0], HashJoinExec):
            return resolved
        join = kids[0]
    if join.mode != "collect_left" or not isinstance(join.left, ShuffleReaderExec) \
            or join.left.broadcast:
        return resolved
    bcast = ShuffleReaderExec(join.left.df_schema, join.left.partition_locations,
                              broadcast=True)
    bcast.source_stage_id = getattr(join.left, "source_stage_id", None)
    new_join = join.with_children([bcast, join.right])
    if join is resolved:
        return new_join
    return resolved.with_children([new_join])


def _select_joins(plan: ExecutionPlan, input_stats, config: BallistaConfig) -> ExecutionPlan:
    rows_threshold = int(config.get(BROADCAST_JOIN_ROWS_THRESHOLD))
    byte_threshold = int(config.get(BROADCAST_JOIN_THRESHOLD))

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            n = n.with_children([walk(c) for c in kids])
        if isinstance(n, DynamicJoinSelectionExec):
            # the planner's deferred decision, resolved here when BOTH input
            # stages finished with known sizes (the reference's optimizer-
            # rule replacement of dynamic_join.rs); otherwise the node stays
            # and decides mid-stage at first-batch time
            ls = _stats_of(n.left, input_stats) if isinstance(n.left, ShuffleReaderExec) else None
            rs = _stats_of(n.right, input_stats) if isinstance(n.right, ShuffleReaderExec) else None
            if ls is not None and rs is not None:
                resolved = n.resolve_with_stats(
                    ls.total_bytes, ls.total_rows, rs.total_bytes, rs.total_rows,
                    byte_threshold, rows_threshold,
                )
                resolved = _broadcast_build_reader(resolved)
                log.info(
                    "AQE dynamic join resolved at stage resolution: %s "
                    "(left %d B/%d rows, right %d B/%d rows)", n.decision,
                    ls.total_bytes, ls.total_rows, rs.total_bytes, rs.total_rows,
                )
                return resolved
            return n
        if (
            isinstance(n, HashJoinExec)
            and n.mode == "partitioned"
            and n.join_type in ("inner", "right", "right_semi", "right_anti")
            and isinstance(n.left, ShuffleReaderExec)
        ):
            s = _stats_of(n.left, input_stats)
            # promotion is byte-aware as well as row-aware: a build whose
            # rows squeak under the budget but whose BYTES are broadcast-
            # hostile (wide payloads) stays partitioned
            if (s is not None and s.total_rows <= rows_threshold // 8
                    and 0 < s.total_bytes <= byte_threshold // 8):
                bcast = ShuffleReaderExec(n.left.df_schema, n.left.partition_locations, broadcast=True)
                bcast.source_stage_id = getattr(n.left, "source_stage_id", None)
                log.info(
                    "AQE join selection: build side has %d rows / %d bytes → "
                    "CollectLeft broadcast", s.total_rows, s.total_bytes,
                )
                from ballista_tpu.ops.tpu import aqe_stats

                aqe_stats.note_broadcast_promotion()
                return HashJoinExec(
                    bcast, n.right, n.on, n.join_type, n.filter, "collect_left", n.df_schema
                )
        return n

    return walk(plan)


# -- incremental replanning over UNRESOLVED stage specs ----------------------


def propagate_empty_unresolved(plan: ExecutionPlan, empty_ids: set[int]) -> ExecutionPlan:
    """The incremental form of PropagateEmptyExecRule: operates on a NOT yet
    resolved stage spec whose leaves are UnresolvedShuffleExec placeholders.
    A placeholder whose source stage finished with ZERO rows is a proven-
    empty leaf — join shapes collapse immediately, before the stage ever
    resolves or schedules (reference: aqe/optimizer_rule/propagate_empty
    over the remaining plan, state/aqe/planner.rs:304)."""
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    def is_empty(n: ExecutionPlan) -> bool:
        if isinstance(n, UnresolvedShuffleExec):
            return n.stage_id in empty_ids
        if isinstance(n, EmptyExec):
            return not n.produce_one_row
        return False

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            new_kids = [walk(c) for c in kids]
            if any(a is not b for a, b in zip(new_kids, kids)):
                n = n.with_children(new_kids)
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            l_empty, r_empty = is_empty(n.left), is_empty(n.right)
            jt = n.join_type
            if jt == "inner" and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt in ("left_semi", "right_semi") and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt == "left_anti" and r_empty:
                return n.left
            if jt == "right_anti" and l_empty:
                return n.right
            if jt in ("left", "right", "full"):
                # outer joins: an empty probe/emitting side empties the join
                if (jt == "right" and r_empty) or (jt == "left" and l_empty):
                    return EmptyExec(n.df_schema, False)
        return n

    return walk(plan)


def provably_empty(plan: ExecutionPlan) -> bool:
    """True iff the plan yields ZERO rows given its EmptyExec leaves — the
    gate for SKIPPING a stage outright. Conservative: only operators that
    provably preserve emptiness qualify (a group-less aggregate emits one
    row from empty input, so it never qualifies)."""
    from ballista_tpu.plan.physical import (
        CoalesceBatchesExec,
        CoalescePartitionsExec,
        FilterExec,
        GlobalLimitExec,
        HashAggregateExec,
        LocalLimitExec,
        ProjectionExec,
        SortExec,
        SortPreservingMergeExec,
        UnionExec,
        WindowExec,
    )

    if isinstance(plan, EmptyExec):
        return not plan.produce_one_row
    if isinstance(plan, (FilterExec, ProjectionExec, CoalesceBatchesExec,
                         LocalLimitExec, GlobalLimitExec, SortExec,
                         SortPreservingMergeExec, CoalescePartitionsExec,
                         WindowExec)):
        return provably_empty(plan.children()[0])
    if isinstance(plan, HashAggregateExec):
        return bool(plan.group_exprs) and provably_empty(plan.children()[0])
    if isinstance(plan, UnionExec):
        return all(provably_empty(c) for c in plan.children())
    if isinstance(plan, (HashJoinExec, DynamicJoinSelectionExec)):
        jt = plan.join_type
        if jt in ("inner", "left_semi", "right_semi"):
            return provably_empty(plan.left) or provably_empty(plan.right)
        if jt == "full":
            return provably_empty(plan.left) and provably_empty(plan.right)
        emit = plan.left if jt in ("left", "left_anti") else plan.right
        return provably_empty(emit)
    return False
