"""Adaptive query execution: replan stages with runtime statistics.

Rebuild of the reference's AQE subsystem (scheduler/src/state/aqe/), scoped
to its three headline optimizations, applied when a stage RESOLVES (all
inputs finished, actual per-partition stats in hand):

- PropagateEmptyExecRule: an inner join whose build or probe input produced
  ZERO rows collapses to an EmptyExec subtree (semi joins likewise; anti
  joins with an empty right side collapse to their left input).
- CoalescePartitionsRule: post-shuffle reduce partitions are bin-packed to
  `ballista.planner.adaptive.coalesce.target.bytes` — ONE group assignment
  per stage (computed over the summed sizes of every hash input) so
  co-partitioned join sides stay aligned (coalesce/algorithm.rs).
- SelectJoinRule (dynamic join selection): a partitioned inner join whose
  build side turned out tiny is rewritten to CollectLeft with a broadcast
  reader, skipping the per-partition build (join swap by ACTUAL sizes, not
  estimates). Build-side-emitting join types keep partitioned mode — the
  correctness constraint from the physical planner applies at runtime too.

The reference plans stages incrementally (AdaptivePlanner::replan_stages);
this build plans statically and rewrites at resolution — same signals,
same rewrites, one fewer moving part. Incremental planning is the round-2
item that also unlocks probe-side-shuffle elision.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from ballista_tpu.config import (
    AQE_COALESCE_MERGED_FACTOR,
    AQE_DYNAMIC_JOIN_SELECTION,
    AQE_EMPTY_PROPAGATION,
    AQE_MIN_PARTITION_BYTES,
    AQE_TARGET_PARTITION_BYTES,
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
    PLANNER_ADAPTIVE_ENABLED,
    BallistaConfig,
)
from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
from ballista_tpu.plan.physical import EmptyExec, ExecutionPlan, HashJoinExec
from ballista_tpu.shuffle.reader import ShuffleReaderExec

log = logging.getLogger(__name__)


def coalesce_groups(sizes: list[int], target: int, min_bytes: int, merged_factor: float) -> list[list[int]]:
    """Bin-pack contiguous reduce partitions by byte size.

    Greedy sequential packing to `target` bytes with a slack factor; a
    trailing small group merges backwards (the reference's merged-factor +
    small-tail refinements, aqe/coalesce/algorithm.rs)."""
    if not sizes:
        return []
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, s in enumerate(sizes):
        if cur and cur_bytes + s > target * merged_factor:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += s
    if cur:
        tail_bytes = sum(sizes[i] for i in cur)
        if groups and tail_bytes < min_bytes:
            groups[-1].extend(cur)
        else:
            groups.append(cur)
    return groups


@dataclass
class InputStageStats:
    stage_id: int
    total_rows: int
    total_bytes: int
    bucket_bytes: list[int]  # per output partition
    broadcast: bool


def apply_aqe(plan: ExecutionPlan, input_stats: dict[int, InputStageStats],
              config: BallistaConfig,
              stage_partitions: int | None = None) -> tuple[ExecutionPlan, int | None]:
    """Rewrite a freshly-resolved stage plan using actual input statistics.

    `plan` has concrete ShuffleReaderExec leaves tagged with their source
    stage id (set by the graph at resolution). Returns (new_plan,
    coalesced_partition_count or None).
    """
    if not bool(config.get(PLANNER_ADAPTIVE_ENABLED)):
        return plan, None

    if bool(config.get(AQE_EMPTY_PROPAGATION)):
        plan = _propagate_empty(plan, input_stats)

    if bool(config.get(AQE_DYNAMIC_JOIN_SELECTION)):
        plan = _select_joins(plan, input_stats, config)

    # mesh-wide stages: the fused exchange's bucket count is a fixed K baked
    # into MeshExchangeExec — coalescing this stage's partitions below K
    # would orphan every bucket >= the coalesced count (silent data loss),
    # so the coalescing rule never applies here. AQE's contribution instead
    # is the input-bytes demotion guard: a mesh exchange whose observed
    # input stages exceed `ballista.tpu.mesh.max.input.bytes` would blow the
    # fixed-capacity collective anyway — demote it before the wasted
    # dispatch, with the reason on record.
    mesh_nodes = _mesh_nodes(plan)
    if mesh_nodes:
        _demote_oversized_mesh(mesh_nodes, input_stats, config)
        return plan, None

    new_parts = None
    target = int(config.get(AQE_TARGET_PARTITION_BYTES))
    min_b = int(config.get(AQE_MIN_PARTITION_BYTES))
    factor = float(config.get(AQE_COALESCE_MERGED_FACTOR))
    hash_inputs = [
        s for s in input_stats.values() if not s.broadcast and len(s.bucket_bytes) > 1
    ]
    readers = _hash_readers(plan)
    k_in = len(hash_inputs[0].bucket_bytes) if hash_inputs else 0
    # coalescing regroups reader partition lists IN PLACE of the stage's
    # partition indexing — only sound when the stage's partitions ARE the
    # readers' (a Union stage concatenates branch partition ranges, so its
    # indexing is not reader-aligned; never coalesce it)
    aligned = stage_partitions is None or stage_partitions == k_in
    if hash_inputs and readers and aligned and all(
        len(r.partition_locations) == k_in for r in readers
    ):
        k = len(hash_inputs[0].bucket_bytes)
        combined = [0] * k
        for s in hash_inputs:
            if len(s.bucket_bytes) == k:
                for i, b in enumerate(s.bucket_bytes):
                    combined[i] += b
        groups = coalesce_groups(combined, target, min_b, factor)
        if 0 < len(groups) < k:
            # build FRESH readers rather than mutating shared ones in place:
            # a reader aliased by a replayed/retried resolution must never
            # see half-regrouped location lists (the stale-alias class of
            # bug this codebase hit once already)
            replacements: dict[int, ShuffleReaderExec] = {}
            for r in readers:
                nr = ShuffleReaderExec(
                    r.df_schema,
                    [[loc for i in g for loc in r.partition_locations[i]] for g in groups],
                    r.broadcast,
                )
                nr.source_stage_id = getattr(r, "source_stage_id", None)
                replacements[id(r)] = nr
            plan = _replace_readers(plan, replacements)
            new_parts = len(groups)
            from ballista_tpu.ops.cpu.range_repartition import retarget_routers

            plan = retarget_routers(plan, new_parts)
            log.info("AQE coalesced %d reduce partitions into %d groups", k, len(groups))
    return plan, new_parts


def _mesh_nodes(plan: ExecutionPlan) -> list:
    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec

    out = []

    def walk(n):
        if isinstance(n, MeshExchangeExec):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def _demote_oversized_mesh(nodes: list, input_stats: dict[int, InputStageStats],
                           config: BallistaConfig) -> None:
    from ballista_tpu.config import TPU_MESH_MAX_INPUT_BYTES

    limit = int(config.get(TPU_MESH_MAX_INPUT_BYTES))
    if limit <= 0:
        return
    total = sum(s.total_bytes for s in input_stats.values() if not s.broadcast)
    if total <= limit:
        return
    reason = f"aqe:input-bytes({total}>{limit})"
    for n in nodes:
        n.demote_reason = reason
    log.info("AQE demoted mesh exchange to the per-partition path: %s", reason)


def _replace_readers(plan: ExecutionPlan, replacements: dict[int, ShuffleReaderExec]) -> ExecutionPlan:
    hit = replacements.get(id(plan))
    if hit is not None:
        return hit
    kids = plan.children()
    if not kids:
        return plan
    new_kids = [_replace_readers(c, replacements) for c in kids]
    if all(a is b for a, b in zip(new_kids, kids)):
        return plan
    return plan.with_children(new_kids)


def _hash_readers(plan: ExecutionPlan) -> list[ShuffleReaderExec]:
    out = []

    def walk(n, under_collect_build=False):
        if isinstance(n, ShuffleReaderExec) and not n.broadcast and not under_collect_build:
            out.append(n)
        if isinstance(n, HashJoinExec) and n.mode == "collect_left":
            walk(n.left, True)
            walk(n.right, under_collect_build)
            return
        for c in n.children():
            walk(c, under_collect_build)

    walk(plan)
    return out


def _stats_of(reader: ShuffleReaderExec, input_stats: dict[int, InputStageStats]):
    sid = getattr(reader, "source_stage_id", None)
    return input_stats.get(sid) if sid is not None else None


def _propagate_empty(plan: ExecutionPlan, input_stats) -> ExecutionPlan:
    def is_empty(n: ExecutionPlan) -> bool:
        if isinstance(n, ShuffleReaderExec):
            s = _stats_of(n, input_stats)
            return s is not None and s.total_rows == 0
        if isinstance(n, EmptyExec):
            return not n.produce_one_row
        return False

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            n = n.with_children([walk(c) for c in kids])
        # the deferred-decision node collapses under the same rules as a
        # concrete hash join: emptiness does not depend on build strategy
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            l_empty, r_empty = is_empty(n.left), is_empty(n.right)
            jt = n.join_type
            if jt == "inner" and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt in ("left_semi", "right_semi") and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt == "left_anti" and r_empty:
                return n.left  # nothing to subtract: pass the build side through
            if jt == "right_anti" and l_empty:
                return n.right
        return n

    return walk(plan)


def _broadcast_build_reader(resolved: ExecutionPlan) -> ExecutionPlan:
    """A collect_left build over a plain partitioned reader collects its
    partitions one sequential execute(p) at a time; the broadcast reader
    flattens every location into ONE concurrently governed fetch. Rebuild
    the build-side reader accordingly (the resolved node may sit under the
    swap-restoring projection)."""
    join = resolved
    if not isinstance(join, HashJoinExec):
        kids = join.children()
        if len(kids) != 1 or not isinstance(kids[0], HashJoinExec):
            return resolved
        join = kids[0]
    if join.mode != "collect_left" or not isinstance(join.left, ShuffleReaderExec) \
            or join.left.broadcast:
        return resolved
    bcast = ShuffleReaderExec(join.left.df_schema, join.left.partition_locations,
                              broadcast=True)
    bcast.source_stage_id = getattr(join.left, "source_stage_id", None)
    new_join = join.with_children([bcast, join.right])
    if join is resolved:
        return new_join
    return resolved.with_children([new_join])


def _select_joins(plan: ExecutionPlan, input_stats, config: BallistaConfig) -> ExecutionPlan:
    rows_threshold = int(config.get(BROADCAST_JOIN_ROWS_THRESHOLD))
    byte_threshold = int(config.get(BROADCAST_JOIN_THRESHOLD))

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            n = n.with_children([walk(c) for c in kids])
        if isinstance(n, DynamicJoinSelectionExec):
            # the planner's deferred decision, resolved here when BOTH input
            # stages finished with known sizes (the reference's optimizer-
            # rule replacement of dynamic_join.rs); otherwise the node stays
            # and decides mid-stage at first-batch time
            ls = _stats_of(n.left, input_stats) if isinstance(n.left, ShuffleReaderExec) else None
            rs = _stats_of(n.right, input_stats) if isinstance(n.right, ShuffleReaderExec) else None
            if ls is not None and rs is not None:
                resolved = n.resolve_with_stats(
                    ls.total_bytes, ls.total_rows, rs.total_bytes, rs.total_rows,
                    byte_threshold, rows_threshold,
                )
                resolved = _broadcast_build_reader(resolved)
                log.info(
                    "AQE dynamic join resolved at stage resolution: %s "
                    "(left %d B/%d rows, right %d B/%d rows)", n.decision,
                    ls.total_bytes, ls.total_rows, rs.total_bytes, rs.total_rows,
                )
                return resolved
            return n
        if (
            isinstance(n, HashJoinExec)
            and n.mode == "partitioned"
            and n.join_type in ("inner", "right", "right_semi", "right_anti")
            and isinstance(n.left, ShuffleReaderExec)
        ):
            s = _stats_of(n.left, input_stats)
            if s is not None and s.total_rows <= rows_threshold // 8:
                bcast = ShuffleReaderExec(n.left.df_schema, n.left.partition_locations, broadcast=True)
                bcast.source_stage_id = getattr(n.left, "source_stage_id", None)
                log.info(
                    "AQE join selection: build side has %d rows → CollectLeft broadcast", s.total_rows
                )
                return HashJoinExec(
                    bcast, n.right, n.on, n.join_type, n.filter, "collect_left", n.df_schema
                )
        return n

    return walk(plan)


# -- incremental replanning over UNRESOLVED stage specs ----------------------


def propagate_empty_unresolved(plan: ExecutionPlan, empty_ids: set[int]) -> ExecutionPlan:
    """The incremental form of PropagateEmptyExecRule: operates on a NOT yet
    resolved stage spec whose leaves are UnresolvedShuffleExec placeholders.
    A placeholder whose source stage finished with ZERO rows is a proven-
    empty leaf — join shapes collapse immediately, before the stage ever
    resolves or schedules (reference: aqe/optimizer_rule/propagate_empty
    over the remaining plan, state/aqe/planner.rs:304)."""
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    def is_empty(n: ExecutionPlan) -> bool:
        if isinstance(n, UnresolvedShuffleExec):
            return n.stage_id in empty_ids
        if isinstance(n, EmptyExec):
            return not n.produce_one_row
        return False

    def walk(n: ExecutionPlan) -> ExecutionPlan:
        kids = n.children()
        if kids:
            new_kids = [walk(c) for c in kids]
            if any(a is not b for a, b in zip(new_kids, kids)):
                n = n.with_children(new_kids)
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            l_empty, r_empty = is_empty(n.left), is_empty(n.right)
            jt = n.join_type
            if jt == "inner" and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt in ("left_semi", "right_semi") and (l_empty or r_empty):
                return EmptyExec(n.df_schema, False)
            if jt == "left_anti" and r_empty:
                return n.left
            if jt == "right_anti" and l_empty:
                return n.right
            if jt in ("left", "right", "full"):
                # outer joins: an empty probe/emitting side empties the join
                if (jt == "right" and r_empty) or (jt == "left" and l_empty):
                    return EmptyExec(n.df_schema, False)
        return n

    return walk(plan)


def provably_empty(plan: ExecutionPlan) -> bool:
    """True iff the plan yields ZERO rows given its EmptyExec leaves — the
    gate for SKIPPING a stage outright. Conservative: only operators that
    provably preserve emptiness qualify (a group-less aggregate emits one
    row from empty input, so it never qualifies)."""
    from ballista_tpu.plan.physical import (
        CoalesceBatchesExec,
        CoalescePartitionsExec,
        FilterExec,
        GlobalLimitExec,
        HashAggregateExec,
        LocalLimitExec,
        ProjectionExec,
        SortExec,
        SortPreservingMergeExec,
        UnionExec,
        WindowExec,
    )

    if isinstance(plan, EmptyExec):
        return not plan.produce_one_row
    if isinstance(plan, (FilterExec, ProjectionExec, CoalesceBatchesExec,
                         LocalLimitExec, GlobalLimitExec, SortExec,
                         SortPreservingMergeExec, CoalescePartitionsExec,
                         WindowExec)):
        return provably_empty(plan.children()[0])
    if isinstance(plan, HashAggregateExec):
        return bool(plan.group_exprs) and provably_empty(plan.children()[0])
    if isinstance(plan, UnionExec):
        return all(provably_empty(c) for c in plan.children())
    if isinstance(plan, (HashJoinExec, DynamicJoinSelectionExec)):
        jt = plan.join_type
        if jt in ("inner", "left_semi", "right_semi"):
            return provably_empty(plan.left) or provably_empty(plan.right)
        if jt == "full":
            return provably_empty(plan.left) and provably_empty(plan.right)
        emit = plan.left if jt in ("left", "left_anti") else plan.right
        return provably_empty(emit)
    return False
