"""The adaptive replanning pass: one pipeline, run over the remaining plan.

Reference shape (scheduler/src/state/aqe/planner.rs:304): after every stage
finalizes, `replan_stages` re-runs a physical-optimizer pipeline over the
plan that has NOT yet executed, with the finished stages' actual statistics
bound; actionable outcomes become resolved stages, obsolete ones are
cancelled. Round 2 of this build carried the same behaviors as three ad-hoc
hooks inlined in ExecutionGraph; this module restructures them as rules in
an explicit pipeline so the pass composes and grows the way the
reference's does.

Two pipeline points, both invoked by ExecutionGraph under its lock:

- `replan_after_finalize` — a stage just became SUCCESSFUL. Rules walk the
  REMAINING plan (every still-unresolved stage spec, leaves =
  UnresolvedShuffleExec placeholders) to fixpoint:
    1. EmptyPropagationRule  — collapse joins against proven-empty inputs,
       complete provably-empty stages without scheduling (skip), which can
       cascade further finalizations.
    2. RuntimeJoinSelectionRule — a partitioned join whose build input
       finished tiny becomes CollectLeft over a broadcast read, and the
       not-yet-started probe stage's hash shuffle is rewritten to a
       passthrough (probe-side shuffle elision — the rewrite only an
       incremental replanner can reach).
  then obsolete stages (no remaining consumer) are cancelled.

- `replan_at_resolution` — a stage's inputs all finished; before readers
  are built:
    3. AlterFanoutRule — shrink the stage's hash fan-out K when observed
       input volume proves the planned bucket count absurd, repartitioning
       the still-unresolved consumer chain.
  Reader-level rules (resolution-time empty propagation, join selection
  with actual sizes, partition coalescing with merged-factor/small-tail
  bin-packing) then run in `aqe.rules.apply_aqe` over the resolved plan.

Exchange insertion is the one reference rule with no analog here by
design: stage boundaries are fixed at static planning time, and runtime
exchange changes are expressed as boundary REWRITES (passthrough elision,
fan-out alteration) rather than insertions.
"""

from __future__ import annotations

import logging

from ballista_tpu.config import (
    AQE_ALTER_FANOUT,
    AQE_DYNAMIC_JOIN_SELECTION,
    AQE_EMPTY_PROPAGATION,
    AQE_TARGET_PARTITION_BYTES,
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
    PLANNER_ADAPTIVE_ENABLED,
)
from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

log = logging.getLogger(__name__)

# runtime broadcast decisions apply this safety factor to the configured
# planner threshold (the elision rewrites TWO stages; fire conservatively)
ELISION_MARGIN = 8


class EmptyPropagationRule:
    """Collapse join shapes in unresolved stage specs against inputs that
    finished with ZERO rows; stages thereby proven empty complete without
    scheduling a single task (reference: PropagateEmptyExecRule over the
    remaining plan + stage skipping, state/aqe/planner.rs:349)."""

    def on_finalize(self, graph, finished, events: list[str]) -> bool:
        from ballista_tpu.scheduler.aqe.rules import (
            propagate_empty_unresolved,
            provably_empty,
        )
        from ballista_tpu.scheduler.planner import _find_input_stages
        from ballista_tpu.scheduler.state.execution_graph import JobState, StageState

        if not bool(graph.config.get(AQE_EMPTY_PROPAGATION)):
            return False

        empty_ids = {
            sid for sid, s in graph.stages.items()
            if s.state is StageState.SUCCESSFUL
            and not any(l.stats.num_rows for l in s.output_locations())
        }
        if not empty_ids:
            return False

        changed = False
        for s in graph.stages.values():
            if graph.status is not JobState.RUNNING:
                break
            if s.state is not StageState.UNRESOLVED:
                continue
            new_plan = propagate_empty_unresolved(s.spec.plan, empty_ids)
            if new_plan is s.spec.plan:
                continue
            s.spec.plan = new_plan
            s.spec.input_stage_ids = _find_input_stages(s.spec.plan)
            changed = True
            if s.stage_id != graph.final_stage_id and provably_empty(s.spec.plan.input):
                log.info(
                    "AQE replan: stage %d proven empty after stage %d finished "
                    "with 0 rows — skipped without scheduling",
                    s.stage_id, finished.stage_id,
                )
                graph.complete_stage_skipped(s, events)
            else:
                # the collapse may have removed the LAST pending input (e.g.
                # a group-less aggregate over the emptied join still has to
                # run to emit its zero-count row): nothing else will trigger
                # resolution, so try it here
                graph._try_resolve(s)
        return changed


class RuntimeJoinSelectionRule:
    """Replan partitioned joins whose BUILD input just finished tiny while
    the PROBE-side hash shuffle hasn't started: the join becomes CollectLeft
    over a broadcast build, and the probe stage's hash writer is rewritten
    to a passthrough, ELIDING the probe-side shuffle entirely. This is the
    win resolution-time rewrites cannot reach: by resolution the probe rows
    have already been hashed, bucketed, and written (reference:
    DelayJoinSelectionRule/SelectJoinRule via AdaptivePlanner::replan_stages,
    state/aqe/planner.rs:304, execution_plan/dynamic_join.rs)."""

    def on_finalize(self, graph, finished, events: list[str]) -> bool:
        from ballista_tpu.plan.physical import HashJoinExec
        from ballista_tpu.scheduler.state.execution_graph import StageState
        from ballista_tpu.shuffle.reader import UnresolvedShuffleExec
        from ballista_tpu.shuffle.writer import ShuffleWriterExec

        if not bool(graph.config.get(AQE_DYNAMIC_JOIN_SELECTION)):
            return False
        threshold = int(graph.config.get(BROADCAST_JOIN_ROWS_THRESHOLD)) // ELISION_MARGIN
        byte_limit = int(graph.config.get(BROADCAST_JOIN_THRESHOLD)) // ELISION_MARGIN

        def passthrough(writer: ShuffleWriterExec) -> ShuffleWriterExec:
            return ShuffleWriterExec(
                writer.input, graph.job_id, writer.stage_id, 0, [], sort_shuffle=False
            )

        def reader_refs(pid: int) -> int:
            """How many shuffle-reader leaves across live stage specs read
            stage `pid`. The elision rewrites the PRODUCERS (probe writer →
            passthrough, build stage → broadcast), so it is only sound when
            this join holds the sole reference — a second consumer would
            keep expecting the original hash layout (the q68 shape: one
            producer fans out to two join stages)."""
            n = 0
            for s in graph.stages.values():
                if s.state is StageState.SUCCESSFUL:
                    continue

                def walk(node):
                    nonlocal n
                    if isinstance(node, UnresolvedShuffleExec) and node.stage_id == pid:
                        n += 1
                    for c in node.children():
                        walk(c)

                walk(s.spec.plan)
            return n

        any_changed = False
        for stage in graph.stages.values():
            if stage.state is not StageState.UNRESOLVED:
                continue

            def rewrite(node):
                changed = False
                kids = node.children()
                if kids:
                    new_kids = []
                    for c in kids:
                        nc, ch = rewrite(c)
                        new_kids.append(nc)
                        changed = changed or ch
                    if changed:
                        node = node.with_children(new_kids)
                # the planner's deferred-decision node carries the same join
                # fields as a partitioned HashJoinExec; the cascade rewrite
                # concretizes either into a CollectLeft broadcast
                if (
                    isinstance(node, (HashJoinExec, DynamicJoinSelectionExec))
                    and node.mode == "partitioned"
                    and node.join_type in ("inner", "right", "right_semi", "right_anti")
                    and isinstance(node.left, UnresolvedShuffleExec)
                    and isinstance(node.right, UnresolvedShuffleExec)
                    and node.left.stage_id != node.right.stage_id
                ):
                    build = graph.stages.get(node.left.stage_id)
                    probe = graph.stages.get(node.right.stage_id)
                    if build is None or probe is None or build.state is not StageState.SUCCESSFUL:
                        return node, changed
                    if (
                        probe.running or probe.completed
                        or probe.state not in (StageState.UNRESOLVED, StageState.RESOLVED)
                        or probe.spec.plan.output_partitions <= 0
                    ):
                        return node, changed  # probe started (or already passthrough)
                    rows = sum(loc.stats.num_rows for loc in build.output_locations())
                    nbytes = sum(loc.stats.num_bytes for loc in build.output_locations())
                    # byte-aware as well as row-aware: the collected build
                    # ships to every probe task, so wide payloads that
                    # squeak under the row budget must still stay put
                    if rows > threshold or nbytes > byte_limit:
                        return node, changed
                    if reader_refs(probe.stage_id) != 1 or reader_refs(build.stage_id) != 1:
                        return node, changed
                    probe.spec.plan = passthrough(probe.spec.plan)
                    probe.spec.output_partitions = probe.spec.partitions
                    if probe.resolved_plan is not None:
                        probe.resolved_plan = passthrough(probe.resolved_plan)
                    build.spec.broadcast = True
                    new_left = UnresolvedShuffleExec(
                        build.stage_id, node.left.df_schema, node.left.output_partitions,
                        broadcast=True,
                    )
                    new_right = UnresolvedShuffleExec(
                        probe.stage_id, node.right.df_schema, probe.spec.partitions,
                        broadcast=False,
                    )
                    log.info(
                        "AQE replan: build stage %d finished with %d rows / %d "
                        "bytes → CollectLeft broadcast; probe stage %d hash "
                        "shuffle elided (passthrough, %d partitions)",
                        build.stage_id, rows, nbytes, probe.stage_id,
                        probe.spec.partitions,
                    )
                    from ballista_tpu.ops.tpu import aqe_stats

                    aqe_stats.note_broadcast_promotion()
                    return (
                        HashJoinExec(
                            new_left, new_right, node.on, node.join_type, node.filter,
                            "collect_left", node.df_schema,
                        ),
                        True,
                    )
                return node, changed

            new_plan, changed = rewrite(stage.spec.plan)
            if changed:
                stage.spec.plan = new_plan
                stage.spec.partitions = new_plan.input.output_partition_count()
                stage.pending = list(range(stage.spec.partitions))
                stage.effective_partitions = stage.spec.partitions
                any_changed = True
        return any_changed


class AlterFanoutRule:
    """Stage-alteration replanning at resolution (state/aqe/planner.rs:349,
    alter_stages analog): after this stage's inputs finished but before any
    of its tasks launch, shrink its hash fan-out K when the observed input
    volume proves the planned bucket count absurd, and patch the
    still-unresolved consumers' leaves to the new K. Read-side coalescing
    (CoalescePartitionsRule in apply_aqe) already merges tiny reduce reads;
    this removes the WRITE-side cost: K sort-shuffle buckets, K index
    entries, K files per map task.

    Guards: every transitive consumer must still be UNRESOLVED and have
    this stage as its ONLY input, so co-partitioned join alignment (two
    producers hashed to the same K) can never break."""

    def on_resolve(self, graph, stage, inputs) -> None:
        from ballista_tpu.scheduler.state.execution_graph import StageState
        from ballista_tpu.shuffle.reader import UnresolvedShuffleExec
        from ballista_tpu.shuffle.writer import ShuffleWriterExec

        if not bool(graph.config.get(AQE_ALTER_FANOUT)):
            return
        writer = stage.spec.plan
        if not isinstance(writer, ShuffleWriterExec) or writer.output_partitions <= 1:
            return
        if stage.spec.broadcast:
            return

        def leaves(node):
            kids = node.children()
            if not kids:
                yield node
            for c in kids:
                yield from leaves(c)

        # every leaf must be a shuffle input: a stage that also SCANS a
        # table (e.g. broadcast-join probe) has volume the input stats
        # cannot see
        if any(not isinstance(l, UnresolvedShuffleExec) for l in leaves(writer.input)):
            return
        # transitively collect the consumers whose task count must follow
        # the altered output count: a PASSTHROUGH consumer's own output
        # count equals its task count (one file per task), so ITS consumers
        # — e.g. a join stage left behind by broadcast elision — must be
        # repartitioned too, or they schedule tasks past the shrunken
        # reader. Abort entirely if any transitive consumer fails the
        # safety guards (unresolved + single-input): a half-patched chain
        # would execute partitions that no longer exist.
        affected: list[tuple[int, object]] = []  # (producer_id, consumer)
        bcast_readers: list[tuple[int, int]] = []  # (producer_id, consumer_id)
        seen: set[int] = set()
        frontier = [(stage.stage_id, cid) for cid in graph.output_links.get(stage.stage_id, [])]
        if not frontier:
            return
        while frontier:
            pid, cid = frontier.pop(0)
            c = graph.stages.get(cid)
            if (c is None or cid in seen
                    or c.state is not StageState.UNRESOLVED
                    or set(c.spec.input_stage_ids) != {pid}):
                return
            seen.add(cid)
            affected.append((pid, c))
            if c.spec.plan.output_partitions <= 0:
                nxt = [(cid, g) for g in graph.output_links.get(cid, [])]
                if not c.spec.broadcast:
                    frontier.extend(nxt)
                else:
                    # broadcast outputs are read whole regardless of count,
                    # so consumers past a broadcast passthrough keep their
                    # task layout — but their reader leaves still advertise
                    # the producer's count, which must follow the new K or
                    # the plan verifier sees a phantom partition mismatch
                    bcast_readers.extend(nxt)
        for _, cid in bcast_readers:
            c = graph.stages.get(cid)
            if c is None or c.state is not StageState.UNRESOLVED:
                return  # can't patch a built reader: abort before mutating
        total_bytes = sum(
            l.stats.num_bytes for inp in inputs for l in inp.output_locations()
        )
        target = max(1, int(graph.config.get(AQE_TARGET_PARTITION_BYTES)))
        # input volume bounds this stage's output for scan/filter/agg
        # pipelines; expansion joins can exceed it, so shrink only with a
        # 2x margin and only when the drop is at least 2x (mis-guessing low
        # costs read-side balance, never correctness)
        k = writer.output_partitions
        new_k = max(1, -(-2 * total_bytes // target))  # ceil(2·bytes/target)
        if new_k > k // 2:
            return
        stage.spec.plan = ShuffleWriterExec(
            writer.input, graph.job_id, writer.stage_id, new_k, writer.keys,
            writer.sort_shuffle,
        )
        stage.spec.output_partitions = new_k

        def patch(node, pid: int, count: int, bcast: bool = False):
            if (isinstance(node, UnresolvedShuffleExec)
                    and node.stage_id == pid and bool(node.broadcast) == bcast):
                return UnresolvedShuffleExec(
                    node.stage_id, node.df_schema, count, broadcast=bcast)
            kids = node.children()
            if not kids:
                return node
            new_kids = [patch(c, pid, count, bcast) for c in kids]
            if all(a is b for a, b in zip(new_kids, kids)):
                return node
            return node.with_children(new_kids)

        new_out = {stage.stage_id: new_k}
        for pid, c in affected:
            from ballista_tpu.ops.cpu.range_repartition import retarget_routers

            c.spec.plan = retarget_routers(
                patch(c.spec.plan, pid, new_out[pid]), new_out[pid])
            new_parts = c.spec.plan.input.output_partition_count()
            c.spec.partitions = new_parts
            if c.spec.plan.output_partitions <= 0:
                # passthrough writers materialize one output per task: the
                # advertised output count must follow the new task count or
                # downstream readers size against the stale K
                c.spec.output_partitions = new_parts
                new_out[c.stage_id] = new_parts
            c.pending = list(range(new_parts))
            c.effective_partitions = new_parts
        for pid, cid in bcast_readers:
            c = graph.stages[cid]
            c.spec.plan = patch(c.spec.plan, pid, new_out[pid], bcast=True)
        log.info(
            "AQE replan: stage %d inputs totalled %d bytes — hash fan-out "
            "altered %d → %d buckets (consumers repartitioned)",
            stage.stage_id, total_bytes, k, new_k,
        )


class HbmPrePlanRule:
    """Out-of-core pre-planning at resolution: once a stage's producers
    have finished, its observed input volume is ground truth — stamp it on
    the stage plan so the executor's HBM admission (ops/tpu/hbm.plan_stage)
    floors its build-size estimate with reality instead of encode-time
    guesses. This is what lets a RETRIED stage whose first attempt brushed
    the budget pre-plan a grace split up front rather than rediscover the
    overflow at dispatch. The stamp is a plain plan attribute, deliberately
    outside the proto (the ISSUE 12 serde note: grace sub-plans are
    executor-local and only stage stats ride heartbeats) — a multi-process
    cluster that drops it on the wire simply falls back to estimate-only
    admission, which is always safe.

    Runs AFTER AlterFanoutRule: fan-out alteration rebuilds the writer
    node, which would shed an earlier stamp."""

    def on_resolve(self, graph, stage, inputs) -> None:
        try:
            total = sum(
                l.stats.num_bytes for inp in inputs for l in inp.output_locations()
            )
        except Exception:  # noqa: BLE001 — a hint, never a scheduling failure
            return
        if total > 0:
            stage.spec.plan.hbm_observed_input_bytes = int(total)


class AdaptiveReplanner:
    """The pipeline driver. Owned by ExecutionGraph; every entry point runs
    under the graph lock."""

    def __init__(self):
        self.finalize_rules = [EmptyPropagationRule(), RuntimeJoinSelectionRule()]
        self.resolve_rules = [AlterFanoutRule(), HbmPrePlanRule()]

    def replan_after_finalize(self, graph, finished, events: list[str]) -> None:
        from ballista_tpu.scheduler.state.execution_graph import JobState

        if not bool(graph.config.get(PLANNER_ADAPTIVE_ENABLED)):
            return
        changed = True
        while changed and graph.status is JobState.RUNNING:
            changed = False
            for rule in self.finalize_rules:
                changed = rule.on_finalize(graph, finished, events) or changed
        graph._rebuild_output_links()
        graph._cancel_obsolete_stages(events)

    def replan_at_resolution(self, graph, stage, inputs) -> None:
        if not bool(graph.config.get(PLANNER_ADAPTIVE_ENABLED)):
            return
        for rule in self.resolve_rules:
            rule.on_resolve(graph, stage, inputs)
