"""KEDA external scaler: autoscale executors on scheduler job pressure.

Rebuild of the reference's `ExternalScaler` gRPC service
(scheduler/src/scheduler_server/external_scaler.rs, proto/keda.proto:24) —
served on the scheduler's own gRPC port so a k8s ScaledObject pointing at
`<scheduler>:<port>` scales executor replicas from pending/running job
counts. Same contract: IsActive always true (the scheduler itself stays
up), GetMetricSpec advertises `pending_jobs` with target 0, GetMetrics
reports pending_jobs and running_jobs.
"""

from __future__ import annotations

import grpc

from ballista_tpu.proto import keda_pb2 as kpb
from ballista_tpu.scheduler.server import JobState, SchedulerServer

PENDING_JOBS = "pending_jobs"
RUNNING_JOBS = "running_jobs"
SERVICE_NAME = "externalscaler.ExternalScaler"


class ExternalScalerService:
    def __init__(self, scheduler: SchedulerServer):
        self.scheduler = scheduler

    def _counts(self) -> tuple[int, int]:
        pending = running = 0
        with self.scheduler._jobs_lock:
            for g in self.scheduler.jobs.values():
                if g.status is JobState.QUEUED:
                    pending += 1
                elif g.status is JobState.RUNNING:
                    running += 1
        return pending, running

    def IsActive(self, request: kpb.ScaledObjectRef, context) -> kpb.IsActiveResponse:
        return kpb.IsActiveResponse(result=True)

    def GetMetricSpec(self, request: kpb.ScaledObjectRef, context) -> kpb.GetMetricSpecResponse:
        # target 1 = one executor replica per pending job (HPA computes
        # desired = ceil(metric / target)); the reference advertises 0
        # here, which KEDA's HPA rejects as a non-positive target — a
        # deliberate deviation, overridable per ScaledObject metadata
        target = 1
        meta = request.scalerMetadata.get("targetSize") if request.scalerMetadata else None
        if meta:
            try:
                target = max(1, int(meta))
            except ValueError:
                pass
        out = kpb.GetMetricSpecResponse()
        out.metricSpecs.append(kpb.MetricSpec(metricName=PENDING_JOBS, targetSize=target))
        return out

    def GetMetrics(self, request: kpb.GetMetricsRequest, context) -> kpb.GetMetricsResponse:
        pending, running = self._counts()
        out = kpb.GetMetricsResponse()
        out.metricValues.append(kpb.MetricValue(metricName=PENDING_JOBS, metricValue=pending))
        out.metricValues.append(kpb.MetricValue(metricName=RUNNING_JOBS, metricValue=running))
        return out


_RPCS = {
    "IsActive": kpb.ScaledObjectRef,
    "GetMetricSpec": kpb.ScaledObjectRef,
    "GetMetrics": kpb.GetMetricsRequest,
}


def add_external_scaler_service(server: grpc.Server, service: ExternalScalerService) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda resp: resp.SerializeToString(),
        )
        for name, req_t in _RPCS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


def external_scaler_stub(channel: grpc.Channel):
    """Typed callables for the scaler rpcs (test/tooling client)."""

    class Stub:
        pass

    stub = Stub()
    for name, req_t in _RPCS.items():
        resp_t = {
            "IsActive": kpb.IsActiveResponse,
            "GetMetricSpec": kpb.GetMetricSpecResponse,
            "GetMetrics": kpb.GetMetricsResponse,
        }[name]
        setattr(stub, name, channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req_t.SerializeToString,
            response_deserializer=resp_t.FromString,
        ))
    return stub
