"""KEDA external scaler: autoscale executors on scheduler job pressure.

Rebuild of the reference's `ExternalScaler` gRPC service
(scheduler/src/scheduler_server/external_scaler.rs, proto/keda.proto:24) —
served on the scheduler's own gRPC port so a k8s ScaledObject pointing at
`<scheduler>:<port>` scales executor replicas from pending/running job
counts. Same contract: IsActive always true (the scheduler itself stays
up), GetMetricSpec advertises `pending_jobs` with target 0, GetMetrics
reports pending_jobs and running_jobs.

Scheduler scale-out extends the signal set with the REAL load-shedding
inputs: per-lane admission counters (interactive/batch inflight, lifetime
sheds), the deepest shard event queue, and the count of outstanding
direct-dispatch leases — so a ScaledObject can scale on control-plane
saturation, not just job counts.
"""

from __future__ import annotations

import grpc

from ballista_tpu.proto import keda_pb2 as kpb
from ballista_tpu.scheduler.server import JobState, SchedulerServer

PENDING_JOBS = "pending_jobs"
RUNNING_JOBS = "running_jobs"
INTERACTIVE_INFLIGHT = "interactive_inflight"
BATCH_INFLIGHT = "batch_inflight"
LANE_SHED_TOTAL = "lane_shed_total"
SHARD_QUEUE_DEPTH = "shard_queue_depth"
ACTIVE_LEASES = "active_leases"
SERVICE_NAME = "externalscaler.ExternalScaler"


class ExternalScalerService:
    def __init__(self, scheduler: SchedulerServer):
        self.scheduler = scheduler

    def _counts(self) -> tuple[int, int]:
        pending = running = 0
        with self.scheduler._jobs_lock:
            for g in self.scheduler.jobs.values():
                if g.status is JobState.QUEUED:
                    pending += 1
                elif g.status is JobState.RUNNING:
                    running += 1
        return pending, running

    def IsActive(self, request: kpb.ScaledObjectRef, context) -> kpb.IsActiveResponse:
        return kpb.IsActiveResponse(result=True)

    def GetMetricSpec(self, request: kpb.ScaledObjectRef, context) -> kpb.GetMetricSpecResponse:
        # target 1 = one executor replica per pending job (HPA computes
        # desired = ceil(metric / target)); the reference advertises 0
        # here, which KEDA's HPA rejects as a non-positive target — a
        # deliberate deviation, overridable per ScaledObject metadata
        target = 1
        meta = request.scalerMetadata.get("targetSize") if request.scalerMetadata else None
        if meta:
            try:
                target = max(1, int(meta))
            except ValueError:
                pass
        out = kpb.GetMetricSpecResponse()
        out.metricSpecs.append(kpb.MetricSpec(metricName=PENDING_JOBS, targetSize=target))
        # shard queue depth scales SCHEDULER replicas, not executors: a
        # ScaledObject selecting it targets the scheduler deployment
        out.metricSpecs.append(kpb.MetricSpec(metricName=SHARD_QUEUE_DEPTH, targetSize=target))
        return out

    def GetMetrics(self, request: kpb.GetMetricsRequest, context) -> kpb.GetMetricsResponse:
        pending, running = self._counts()
        out = kpb.GetMetricsResponse()
        out.metricValues.append(kpb.MetricValue(metricName=PENDING_JOBS, metricValue=pending))
        out.metricValues.append(kpb.MetricValue(metricName=RUNNING_JOBS, metricValue=running))
        # per-lane admission pressure straight off the controller snapshot
        lanes = self.scheduler.admission.snapshot().get("lanes", {})
        out.metricValues.append(kpb.MetricValue(
            metricName=INTERACTIVE_INFLIGHT,
            metricValue=int(lanes.get("interactive", {}).get("inflight", 0))))
        out.metricValues.append(kpb.MetricValue(
            metricName=BATCH_INFLIGHT,
            metricValue=int(lanes.get("batch", {}).get("inflight", 0))))
        out.metricValues.append(kpb.MetricValue(
            metricName=LANE_SHED_TOTAL,
            metricValue=sum(int(l.get("shed_total", 0)) for l in lanes.values())))
        # deepest shard event queue: the control-plane saturation signal
        shards = self.scheduler.shards_snapshot()
        out.metricValues.append(kpb.MetricValue(
            metricName=SHARD_QUEUE_DEPTH,
            metricValue=max((s["queue_depth"] for s in shards), default=0)))
        out.metricValues.append(kpb.MetricValue(
            metricName=ACTIVE_LEASES,
            metricValue=self.scheduler.leases.active_count()))
        return out


_RPCS = {
    "IsActive": kpb.ScaledObjectRef,
    "GetMetricSpec": kpb.ScaledObjectRef,
    "GetMetrics": kpb.GetMetricsRequest,
}


def add_external_scaler_service(server: grpc.Server, service: ExternalScalerService) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda resp: resp.SerializeToString(),
        )
        for name, req_t in _RPCS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


def external_scaler_stub(channel: grpc.Channel):
    """Typed callables for the scaler rpcs (test/tooling client)."""

    class Stub:
        pass

    stub = Stub()
    for name, req_t in _RPCS.items():
        resp_t = {
            "IsActive": kpb.IsActiveResponse,
            "GetMetricSpec": kpb.GetMetricSpecResponse,
            "GetMetrics": kpb.GetMetricsResponse,
        }[name]
        setattr(stub, name, channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req_t.SerializeToString,
            response_deserializer=resp_t.FromString,
        ))
    return stub
