"""Job-level admission control and overload state machine.

The scheduler's `jobs` dict and event queue were unbounded: one burst of
submissions (or one runaway client loop) grew control-plane state without
limit and degraded every tenant. This module puts a bounded admission
gate in front of `submit_sql`/`submit_physical_plan`:

- a cluster-wide cap on in-flight (queued or running) jobs
  (`ballista.admission.max.pending.jobs`);
- a per-session in-flight quota
  (`ballista.admission.max.inflight.per.session`) so one tenant cannot
  consume the whole admission budget;
- an overload state machine `normal → shedding → draining` driven by
  three pressure signals: admission depth, scheduler event-loop lag, and
  the aggregate memory-pressure score executors piggyback on heartbeats.
  Shedding halves every session quota; draining rejects all new work
  until depth falls back under the drain threshold.

Rejections are typed (`ClusterOverloaded`) and carry a `retry_after_ms`
hint computed from the observed drain rate: if the cluster has been
finishing `r` jobs/second and the caller is `k` jobs over budget, the
hint is ~`k / r` seconds — enough for the backlog the caller would have
joined to clear. Clients (see `client/remote.py`) honor the hint with
jittered exponential backoff, which turns a thundering herd into a
paced trickle.

State here is intentionally scheduler-local (like the slot ledger in
`ExecutorManager`): admission is advisory flow control, not a durable
ledger, so a scheduler failover simply starts with a fresh gate.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ballista_tpu.config import (
    ADMISSION_DRAIN_DEPTH,
    ADMISSION_ENABLED,
    ADMISSION_INTERACTIVE_MAX_PENDING,
    ADMISSION_MAX_INFLIGHT_PER_SESSION,
    ADMISSION_MAX_PENDING_JOBS,
    ADMISSION_MIN_RETRY_AFTER_MS,
    ADMISSION_SHED_DEPTH,
    ADMISSION_SHED_LOOP_LAG_S,
    ADMISSION_SHED_MEMORY_PRESSURE,
    BallistaConfig,
)
from ballista_tpu.errors import ClusterOverloaded

NORMAL = "normal"
SHEDDING = "shedding"
DRAINING = "draining"

# admission lanes (serving tier): interactive = known-short repeat work
# (single-stage plan-cache hits, prepared executions); batch = everything
# else. Overload postures degrade the batch lane first.
LANE_BATCH = "batch"
LANE_INTERACTIVE = "interactive"

# drain-rate estimation window: recent finishes only, so the hint tracks
# the cluster's *current* throughput, not its lifetime average
_DRAIN_WINDOW_S = 30.0
_DRAIN_SAMPLES = 256


class AdmissionController:
    """Bounded admission gate + overload posture for one scheduler.

    Thread-safe: `admit` runs on gRPC/REST handler threads, `finish` and
    `update` on the scheduler event loop.
    """

    def __init__(self,
                 enabled: bool | None = None,
                 max_pending: int | None = None,
                 per_session_quota: int | None = None,
                 shed_depth: int | None = None,
                 drain_depth: int | None = None,
                 shed_loop_lag_s: float | None = None,
                 shed_memory_pressure: float | None = None,
                 min_retry_after_ms: int | None = None,
                 interactive_max_pending: int | None = None):
        defaults = BallistaConfig()
        self.enabled = bool(defaults.get(ADMISSION_ENABLED)) if enabled is None else enabled
        self.max_pending = int(defaults.get(ADMISSION_MAX_PENDING_JOBS)) if max_pending is None else max_pending
        self.per_session_quota = (int(defaults.get(ADMISSION_MAX_INFLIGHT_PER_SESSION))
                                  if per_session_quota is None else per_session_quota)
        self.shed_depth = int(defaults.get(ADMISSION_SHED_DEPTH)) if shed_depth is None else shed_depth
        self.drain_depth = int(defaults.get(ADMISSION_DRAIN_DEPTH)) if drain_depth is None else drain_depth
        self.shed_loop_lag_s = (float(defaults.get(ADMISSION_SHED_LOOP_LAG_S))
                                if shed_loop_lag_s is None else shed_loop_lag_s)
        self.shed_memory_pressure = (float(defaults.get(ADMISSION_SHED_MEMORY_PRESSURE))
                                     if shed_memory_pressure is None else shed_memory_pressure)
        self.min_retry_after_ms = (int(defaults.get(ADMISSION_MIN_RETRY_AFTER_MS))
                                   if min_retry_after_ms is None else min_retry_after_ms)
        self.interactive_max_pending = (int(defaults.get(ADMISSION_INTERACTIVE_MAX_PENDING))
                                        if interactive_max_pending is None
                                        else interactive_max_pending)
        self._lock = threading.Lock()
        self._inflight: dict[str, str] = {}  # job_id -> session_id
        self._per_session: dict[str, int] = {}
        # per-lane bookkeeping (serving tier): shedding and draining are
        # evaluated per lane so interactive traffic survives batch overload
        self._job_lane: dict[str, str] = {}  # job_id -> lane
        self._lane_inflight: dict[str, int] = {}
        self._lane_admitted: dict[str, int] = {}
        self._lane_shed: dict[str, int] = {}
        self._finishes: deque[float] = deque(maxlen=_DRAIN_SAMPLES)
        self._state = NORMAL
        self._rejected = 0
        # last pressure signals, for the REST /state posture snapshot
        self._last_loop_lag_s = 0.0
        self._last_memory_pressure = 0.0

    # -- admission ---------------------------------------------------------

    def admit(self, session_id: str, job_id: str, lane: str = LANE_BATCH) -> None:
        """Claim an admission slot for `job_id` or raise ClusterOverloaded.

        Raising means NO state was recorded: the caller must not create
        the job. Shedding is per lane: the batch lane carries the original
        posture semantics untouched, while the interactive lane (serving
        tier: known-short repeat queries) only sheds against its own depth
        cap — halved while draining — so short queries keep flowing when a
        batch backlog trips the state machine."""
        if not self.enabled:
            with self._lock:
                self._record_admit_locked(session_id, job_id, lane)
            return
        with self._lock:
            depth = len(self._inflight)
            state = self._state
            used = self._per_session.get(session_id, 0)
            if lane == LANE_INTERACTIVE:
                cap = self.interactive_max_pending
                if state == DRAINING:
                    cap = max(1, cap // 2)
                lane_depth = self._lane_inflight.get(lane, 0)
                if lane_depth >= cap:
                    self._shed_locked(
                        lane, "draining" if state == DRAINING else "depth",
                        f"interactive lane has {lane_depth} jobs in flight "
                        f"(cap {cap}{' while draining' if state == DRAINING else ''})",
                        lane_depth - cap + 1)
                if used >= self.per_session_quota:
                    self._shed_locked(
                        lane, "quota",
                        f"session {session_id} has {used} jobs in flight "
                        f"(quota {self.per_session_quota})",
                        used - self.per_session_quota + 1)
                self._record_admit_locked(session_id, job_id, lane)
                return
            if state == DRAINING:
                self._shed_locked(
                    lane, "draining",
                    f"cluster is draining (depth={depth} >= {self.drain_depth}); "
                    "rejecting all new work until the backlog clears",
                    max(1, depth - self.shed_depth))
            quota = self.per_session_quota
            if state == SHEDDING:
                # graceful degradation: shedding halves every tenant's quota
                # instead of rejecting everyone outright
                quota = max(1, quota // 2)
            if used >= quota:
                self._shed_locked(
                    lane, "shedding" if state == SHEDDING else "quota",
                    f"session {session_id} has {used} jobs in flight "
                    f"(quota {quota}{' while shedding' if state == SHEDDING else ''})",
                    used - quota + 1)
            if depth >= self.max_pending:
                self._shed_locked(
                    lane, "depth",
                    f"cluster has {depth} jobs in flight (max pending {self.max_pending})",
                    depth - self.max_pending + 1)
            self._record_admit_locked(session_id, job_id, lane)

    def _record_admit_locked(self, session_id: str, job_id: str, lane: str) -> None:
        self._inflight[job_id] = session_id
        self._per_session[session_id] = self._per_session.get(session_id, 0) + 1
        self._job_lane[job_id] = lane
        self._lane_inflight[lane] = self._lane_inflight.get(lane, 0) + 1
        self._lane_admitted[lane] = self._lane_admitted.get(lane, 0) + 1

    def _shed_locked(self, lane: str, reason: str, msg: str, excess: int) -> None:
        self._rejected += 1
        self._lane_shed[lane] = self._lane_shed.get(lane, 0) + 1
        raise ClusterOverloaded(
            msg,
            retry_after_ms=self._retry_after_ms_locked(max(1, excess)),
            reason=reason,
        )

    def lane_of(self, job_id: str) -> str | None:
        with self._lock:
            return self._job_lane.get(job_id)

    def finish(self, job_id: str) -> None:
        """Release `job_id`'s admission slot (idempotent — terminal events
        can reach the gate through more than one path)."""
        with self._lock:
            session_id = self._inflight.pop(job_id, None)
            if session_id is None:
                return
            n = self._per_session.get(session_id, 0) - 1
            if n <= 0:
                self._per_session.pop(session_id, None)
            else:
                self._per_session[session_id] = n
            lane = self._job_lane.pop(job_id, LANE_BATCH)
            ln = self._lane_inflight.get(lane, 0) - 1
            if ln <= 0:
                self._lane_inflight.pop(lane, None)
            else:
                self._lane_inflight[lane] = ln
            self._finishes.append(time.monotonic())

    # -- overload state machine --------------------------------------------

    def update(self, loop_lag_s: float, memory_pressure: float) -> str | None:
        """Re-evaluate the overload posture from the three pressure signals.
        Returns the new state if it changed, else None. Called from the
        scheduler event loop (sweep cadence)."""
        with self._lock:
            depth = len(self._inflight)
            self._last_loop_lag_s = loop_lag_s
            self._last_memory_pressure = memory_pressure
            old = self._state
            pressured = (loop_lag_s >= self.shed_loop_lag_s
                         or memory_pressure >= self.shed_memory_pressure)
            if depth >= self.drain_depth:
                new = DRAINING
            elif depth >= self.shed_depth or pressured:
                new = SHEDDING
            elif old != NORMAL:
                # hysteresis: leave shedding/draining only once depth falls
                # to half the shed threshold AND lag/pressure recovered —
                # no flapping at the boundary
                if depth <= self.shed_depth // 2 and not pressured:
                    new = NORMAL
                elif old == DRAINING and depth < self.drain_depth:
                    new = SHEDDING  # step down through shedding, never jump
                else:
                    new = old
            else:
                new = NORMAL
            if new != old:
                self._state = new
                return new
            return None

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def depth(self) -> int:
        with self._lock:
            return len(self._inflight)

    def retry_after_ms(self, excess: int = 1) -> int:
        with self._lock:
            return self._retry_after_ms_locked(excess)

    def _retry_after_ms_locked(self, excess: int) -> int:
        """Backoff hint from the observed drain rate: with `r` jobs/s
        finishing, `excess` jobs over budget clear in ~excess/r seconds."""
        now = time.monotonic()
        recent = [t for t in self._finishes if now - t <= _DRAIN_WINDOW_S]
        if len(recent) >= 2:
            span = max(now - recent[0], 0.001)
            rate = len(recent) / span  # jobs per second
            hint_ms = int(max(1, excess) / rate * 1000.0)
        else:
            # no drain signal yet: fall back to a fixed second
            hint_ms = 1000
        return max(self.min_retry_after_ms, min(hint_ms, 60_000))

    def snapshot(self) -> dict:
        """Overload posture for REST /api/state and push-stream events."""
        with self._lock:
            return {
                "state": self._state,
                "enabled": self.enabled,
                "inflight_jobs": len(self._inflight),
                "max_pending_jobs": self.max_pending,
                "per_session_quota": self.per_session_quota,
                "sessions_with_inflight": len(self._per_session),
                "rejected_total": self._rejected,
                "loop_lag_s": round(self._last_loop_lag_s, 3),
                "memory_pressure": round(self._last_memory_pressure, 3),
                "retry_after_ms": self._retry_after_ms_locked(1),
                "lanes": {
                    lane: {
                        "inflight": self._lane_inflight.get(lane, 0),
                        "admitted_total": self._lane_admitted.get(lane, 0),
                        "shed_total": self._lane_shed.get(lane, 0),
                        "cap": (self.interactive_max_pending
                                if lane == LANE_INTERACTIVE else self.max_pending),
                    }
                    for lane in (LANE_BATCH, LANE_INTERACTIVE)
                },
            }
