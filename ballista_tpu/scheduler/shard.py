"""Per-shard scheduler event loops (horizontal scheduler capacity).

One `SchedulerServer._event_loop` thread owning every job is the
control-plane ceiling: added executors raise compute throughput while
submit/heartbeat/state-transition work still serializes through a single
queue. Sharding partitions job ownership by `shard_of(job_id) % N`: each
shard runs its own bounded event loop and admission-lag EWMA, so one hot
job's checkpoint and offer traffic no longer queues behind every other
job's. Fleet-scoped events (revive, sweep, executor_lost) are fanned in
once at `SchedulerServer.post` and multicast to the shards that own work.

`shard_of` uses CRC32, not the builtin `hash` — the builtin is salted
per process, and job→shard agreement must survive restarts and hold
across the scheduler instances of a multi-scheduler deployment.

Event-loop hygiene: `SchedulerShard._handle` forwards into
`SchedulerServer._handle` with the shard as scope; the `analysis`
`event-loop` pass roots its blocking-call search at BOTH `_handle`s and
follows `self.server.*` edges, so no shard loop may reach a blocking
call either.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import zlib

log = logging.getLogger(__name__)

EVENT_QUEUE_MAXSIZE = 10_000


def shard_of(job_id: str, num_shards: int) -> int:
    """Deterministic job→shard owner; stable across processes/restarts."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(job_id.encode("utf-8", "surrogatepass")) % num_shards


class SchedulerShard:
    """One event loop + lag EWMA over the slice of jobs it owns."""

    def __init__(self, server, shard_id: int, maxsize: int = EVENT_QUEUE_MAXSIZE):
        self.server = server
        self.shard_id = shard_id
        self.events: "queue.Queue" = queue.Queue(maxsize=maxsize)
        # EWMA of post→dequeue delay; feeds the admission state machine
        # through the server's fleet-wide max
        self.loop_lag_s = 0.0
        self.handled = 0  # lifetime event count (snapshot/diagnostics)
        self._thread: threading.Thread | None = None

    def owns(self, job_id: str) -> bool:
        return shard_of(job_id, self.server.num_shards) == self.shard_id

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._event_loop, daemon=True,
            name=f"scheduler-events-{self.shard_id}")
        self._thread.start()

    def join(self, timeout: float) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def post(self, ev) -> None:
        self.events.put(ev)

    def queue_depth(self) -> int:
        return self.events.qsize()

    def _event_loop(self) -> None:
        while self.server._running:
            try:
                ev = self.events.get(timeout=0.2)
            except queue.Empty:
                # an idle loop has zero lag by definition; decay toward it
                self.loop_lag_s *= 0.5
                continue
            lag = max(0.0, time.monotonic() - ev.posted_at)
            self.loop_lag_s = 0.8 * self.loop_lag_s + 0.2 * lag
            self.handled += 1
            try:
                self._handle(ev)
            except Exception:  # noqa: BLE001
                log.exception("shard %d event loop error on %s", self.shard_id, ev.kind)

    def _handle(self, ev) -> None:
        # scoped dispatch: the server filters job enumeration to this
        # shard's slice (event-loop hygiene pass roots here too)
        self.server._handle(ev, self)
