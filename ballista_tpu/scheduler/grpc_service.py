"""SchedulerGrpc service (reference: ballista.proto:952, grpc.rs).

Hand-registered method handlers (no grpc_tools codegen in this
environment): each rpc deserializes with the generated protobuf messages.
Includes the wire-protocol version gate on registration/poll
(grpc.rs:92,200) and PollWork's heartbeat+status+handout composite.
"""

from __future__ import annotations

import logging
import threading

import grpc

from ballista_tpu.errors import BallistaError, ClusterOverloaded
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.server import SchedulerServer
from ballista_tpu.serde_control import (
    decode_executor_metadata,
    decode_task_status,
    encode_job_status,
    encode_task_definition,
)

log = logging.getLogger(__name__)

SERVICE_NAME = "ballista_tpu.SchedulerGrpc"


class _PollCoalescer:
    """Single-flight for identical in-flight job-status polls: when a herd
    of clients waits on one job, the FIRST poll in computes the status and
    every poll that arrives while it is in flight piggybacks on that
    result instead of taking the jobs lock again. Correctness is safe
    because a follower's answer is at most one leader-computation stale —
    strictly fresher than the poll interval that triggered it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict[str, tuple[threading.Event, list]] = {}
        self.computed = 0
        self.coalesced = 0

    def get(self, key: str, compute):
        with self._lock:
            entry = self._inflight.get(key)
            if entry is None:
                entry = self._inflight[key] = (threading.Event(), [])
                leader = True
                self.computed += 1
            else:
                leader = False
                self.coalesced += 1
        ev, slot = entry
        if leader:
            try:
                slot.append(compute())
            except BaseException as e:  # noqa: BLE001 — followers re-raise it
                slot.append(e)
                raise
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                ev.set()
            return slot[0]
        # follower: a missing/late leader result degrades to computing our
        # own answer — coalescing is an optimization, never a correctness gate
        if not ev.wait(timeout=5.0) or not slot:
            return compute()
        result = slot[0]
        if isinstance(result, BaseException):
            raise result
        return result


class SchedulerGrpcService:
    def __init__(self, scheduler: SchedulerServer):
        self.scheduler = scheduler
        self._poll_coalescer = _PollCoalescer()

    # -- client-facing -------------------------------------------------------

    def ExecuteQuery(self, request: pb.ExecuteQueryParams, context) -> pb.ExecuteQueryResult:
        session_id = request.session_id or self.scheduler.sessions.create_or_update(
            [(kv.key, kv.value) for kv in request.settings]
        )
        if request.settings and request.session_id:
            self.scheduler.sessions.create_or_update(
                [(kv.key, kv.value) for kv in request.settings], session_id
            )
        which = request.WhichOneof("query")
        try:
            if which == "sql":
                job_id = self.scheduler.submit_sql(request.sql, session_id, request.job_name)
            else:
                from ballista_tpu.serde import decode_plan

                plan = decode_plan(request.physical_plan)
                job_id = self.scheduler.submit_physical_plan(plan, session_id, request.job_name)
        except ClusterOverloaded as e:
            self._abort_overloaded(context, e)
        return pb.ExecuteQueryResult(job_id=job_id, session_id=session_id)

    @staticmethod
    def _abort_overloaded(context, e: ClusterOverloaded) -> None:
        """Shed submissions map to RESOURCE_EXHAUSTED with the backoff
        hint in trailing metadata (clients parse `retry-after-ms`; the
        message text carries it too for non-ballista clients)."""
        context.set_trailing_metadata((
            ("retry-after-ms", str(e.retry_after_ms)),
            ("overload-reason", e.reason),
        ))
        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                      f"{e} [retry_after_ms={e.retry_after_ms}]")

    def PrepareStatement(self, request: pb.ExecuteQueryParams, context) -> pb.ExecuteQueryResult:
        """Server-side prepare: parse/optimize/plan once, return the
        statement handle. Reuses the ExecuteQuery message pair (no protoc
        in this environment): sql carries the statement text, and the
        response's job_id field carries a JSON handle
        {statement_id, num_params, type_tags}."""
        import json

        session_id = request.session_id or self.scheduler.sessions.create_or_update(
            [(kv.key, kv.value) for kv in request.settings]
        )
        if request.settings and request.session_id:
            self.scheduler.sessions.create_or_update(
                [(kv.key, kv.value) for kv in request.settings], session_id
            )
        try:
            handle = self.scheduler.prepare_statement(request.sql, session_id)
        except BallistaError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.ExecuteQueryResult(job_id=json.dumps(handle), session_id=session_id)

    def ExecutePrepared(self, request: pb.ExecuteQueryParams, context) -> pb.ExecuteQueryResult:
        """Execute a prepared statement with bound parameters. The sql
        field carries JSON {statement_id, params} with params encoded by
        serving.encode_params (dates/decimals ride with type tags)."""
        import json

        from ballista_tpu.serving.normalize import decode_params

        body = json.loads(request.sql)
        params = decode_params(body["params"]) if body.get("params") else None
        try:
            job_id = self.scheduler.execute_prepared(
                body["statement_id"], params, request.session_id, request.job_name)
        except ClusterOverloaded as e:
            self._abort_overloaded(context, e)
        except BallistaError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.ExecuteQueryResult(job_id=job_id, session_id=request.session_id)

    def GetJobStatus(self, request: pb.GetJobStatusParams, context) -> pb.GetJobStatusResult:
        status = self._poll_coalescer.get(
            request.job_id, lambda: self.scheduler.job_status(request.job_id))
        out = pb.GetJobStatusResult()
        if status is not None:
            out.status.CopyFrom(encode_job_status(status))
        return out

    def ExecuteQueryPush(self, request: pb.ExecuteQueryParams, context):
        """Server-streaming variant (grpc.rs:419): submit, then push a
        status event on every state change until the job is terminal — no
        client polling."""
        import time as _time

        first = self.ExecuteQuery(request, context)
        yield pb.ExecuteQueryPushResult(job_id=first.job_id, session_id=first.session_id)
        last_state = None
        while context.is_active():
            status = self.scheduler.job_status(first.job_id)
            if status is None:
                return
            if status["state"] != last_state:
                last_state = status["state"]
                out = pb.ExecuteQueryPushResult(job_id=first.job_id, session_id=first.session_id)
                out.status.CopyFrom(encode_job_status(status))
                yield out
                if last_state in ("successful", "failed", "cancelled"):
                    return
            _time.sleep(0.05)

    def AppendData(self, request: pb.ExecuteQueryParams, context) -> pb.ExecuteQueryResult:
        """Append-oriented ingestion. Reuses the ExecuteQuery message pair
        (no protoc here): job_name carries the table name, physical_plan
        carries a MemoryScanExec whose IPC payload is the appended rows.
        The response's job_id field carries JSON {table, version, rows}."""
        import json

        from ballista_tpu.serde import decode_plan

        session_id = request.session_id or self.scheduler.sessions.create_or_update(
            [(kv.key, kv.value) for kv in request.settings]
        )
        if not request.job_name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "AppendData requires the table name in job_name")
        plan = decode_plan(request.physical_plan)
        batches = [b for b in getattr(plan, "batches", []) if b.num_rows]
        out = self.scheduler.append_data(request.job_name, batches, session_id)
        return pb.ExecuteQueryResult(job_id=json.dumps(out), session_id=session_id)

    def SubscribeQuery(self, request: pb.ExecuteQueryParams, context):
        """Continuous-query push stream: subscribe a prepared statement to
        its tables' versions; every append/DDL bump re-executes it
        (incrementally when eligible) and pushes the fresh terminal status.
        sql carries JSON {statement_id, params} like ExecutePrepared; the
        first frame's job_id is the subscription handle. Remote clients
        fetch each refresh's partitions like any other job."""
        import json
        import queue as _queue

        from ballista_tpu.serving.normalize import decode_params

        body = json.loads(request.sql)
        params = decode_params(body["params"]) if body.get("params") else None
        try:
            sub = self.scheduler.subscribe_statement(
                body["statement_id"], params, request.session_id,
                inline_results=False)
        except BallistaError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        yield pb.ExecuteQueryPushResult(job_id=sub.sub_id,
                                        session_id=request.session_id)
        try:
            while context.is_active():
                try:
                    st = sub.queue.get(timeout=0.25)
                except _queue.Empty:
                    continue
                out = pb.ExecuteQueryPushResult(
                    job_id=str(st.get("job_id", "")),
                    session_id=request.session_id)
                out.status.CopyFrom(encode_job_status(st))
                yield out
        finally:
            self.scheduler.unsubscribe(sub.sub_id)

    def CreateUpdateSession(self, request: pb.CreateSessionParams, context) -> pb.CreateSessionResult:
        sid = self.scheduler.sessions.create_or_update(
            [(kv.key, kv.value) for kv in request.settings], request.session_id
        )
        return pb.CreateSessionResult(session_id=sid)

    def RemoveSession(self, request: pb.RemoveSessionParams, context) -> pb.RemoveSessionResult:
        self.scheduler.sessions.remove(request.session_id)
        return pb.RemoveSessionResult()

    def CancelJob(self, request: pb.CancelJobParams, context) -> pb.CancelJobResult:
        self.scheduler.cancel_job(request.job_id)
        return pb.CancelJobResult(cancelled=True)

    def CleanJobData(self, request: pb.CleanJobDataParams, context) -> pb.CleanJobDataResult:
        self.scheduler.clean_job_data(request.job_id)
        return pb.CleanJobDataResult()

    def GetJobMetrics(self, request: pb.GetJobMetricsParams, context) -> pb.GetJobMetricsResult:
        out = pb.GetJobMetricsResult()
        with self.scheduler._jobs_lock:
            g = self.scheduler.jobs.get(request.job_id)
        if g is not None:
            for sid, metrics in sorted(g.stage_metrics.items()):
                sp = out.stages.add()
                sp.stage_id = sid
                for m in metrics:
                    sp.metrics.add(
                        name=str(m.get("name", "")), output_rows=int(m.get("output_rows", 0)),
                        elapsed_ns=int(m.get("elapsed_ns", 0)), depth=int(m.get("depth", 0)),
                    )
        return out

    # -- executor-facing -----------------------------------------------------

    def RegisterExecutor(self, request: pb.RegisterExecutorParams, context) -> pb.RegisterExecutorResult:
        try:
            self.scheduler.register_executor(decode_executor_metadata(request.metadata))
            return pb.RegisterExecutorResult(success=True)
        except BallistaError as e:
            self.scheduler.metrics.record_protocol_mismatch()
            return pb.RegisterExecutorResult(success=False, error=str(e))

    def HeartBeatFromExecutor(self, request: pb.HeartBeatParams, context) -> pb.HeartBeatResult:
        # overload signals ride the existing repeated ExecutorMetricProto
        # field — no wire change needed
        metrics = {m.name: m.value for m in request.metrics} or None
        known = self.scheduler.executor_heartbeat(request.executor_id, metrics)
        return pb.HeartBeatResult(reregister=not known)

    def UpdateTaskStatus(self, request: pb.UpdateTaskStatusParams, context) -> pb.UpdateTaskStatusResult:
        meta = self.scheduler.executors.get(request.executor_id)
        results = [
            decode_task_status(p, meta.metadata if meta else None) for p in request.task_status
        ]
        self.scheduler.update_task_status(request.executor_id, results)
        return pb.UpdateTaskStatusResult(success=True)

    def PollWork(self, request: pb.PollWorkParams, context) -> pb.PollWorkResult:
        meta = decode_executor_metadata(request.metadata)
        results = [decode_task_status(p, meta) for p in request.task_status]
        tasks = self.scheduler.poll_work(meta, request.can_accept_task, request.free_slots, results)
        out = pb.PollWorkResult()
        for t in tasks:
            out.tasks.append(
                encode_task_definition(t, self.scheduler.sessions.get(t.session_id)))
        return out

    def ExecutorStopped(self, request: pb.ExecutorStoppedParams, context) -> pb.ExecutorStoppedResult:
        from ballista_tpu.scheduler.server import Event

        self.scheduler.post(Event("executor_lost", request.executor_id))
        return pb.ExecutorStoppedResult()


_RPCS = {
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    # prepared statements reuse the ExecuteQuery message pair (no protoc
    # here): handles/params travel as JSON in the sql/job_id string fields
    "PrepareStatement": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "ExecutePrepared": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    # append ingestion rides the same pair: table in job_name, rows as a
    # MemoryScanExec in physical_plan, {table, version, rows} JSON back
    "AppendData": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    "CreateUpdateSession": (pb.CreateSessionParams, pb.CreateSessionResult),
    "RemoveSession": (pb.RemoveSessionParams, pb.RemoveSessionResult),
    "CancelJob": (pb.CancelJobParams, pb.CancelJobResult),
    "CleanJobData": (pb.CleanJobDataParams, pb.CleanJobDataResult),
    "GetJobMetrics": (pb.GetJobMetricsParams, pb.GetJobMetricsResult),
    "RegisterExecutor": (pb.RegisterExecutorParams, pb.RegisterExecutorResult),
    "HeartBeatFromExecutor": (pb.HeartBeatParams, pb.HeartBeatResult),
    "UpdateTaskStatus": (pb.UpdateTaskStatusParams, pb.UpdateTaskStatusResult),
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "ExecutorStopped": (pb.ExecutorStoppedParams, pb.ExecutorStoppedResult),
}

# server-streaming rpcs (reference: execute_query_push, grpc.rs:419)
_STREAM_RPCS = {
    "ExecuteQueryPush": (pb.ExecuteQueryParams, pb.ExecuteQueryPushResult),
    "SubscribeQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryPushResult),
}


def add_scheduler_service(server: grpc.Server, service: SchedulerGrpcService) -> None:
    handlers = {}
    for name, (req_t, _resp_t) in _RPCS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda resp: resp.SerializeToString(),
        )
    for name, (req_t, _resp_t) in _STREAM_RPCS.items():
        handlers[name] = grpc.unary_stream_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda resp: resp.SerializeToString(),
        )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


def scheduler_stub(channel: grpc.Channel):
    """Typed callables for every scheduler rpc."""

    class Stub:
        pass

    stub = Stub()
    for name, (req_t, resp_t) in _RPCS.items():
        setattr(
            stub, name,
            channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ),
        )
    for name, (req_t, resp_t) in _STREAM_RPCS.items():
        setattr(
            stub, name,
            channel.unary_stream(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ),
        )
    return stub
