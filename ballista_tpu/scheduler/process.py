"""Scheduler daemon process.

Rebuild of scheduler/src/scheduler_process.rs + bin/main.rs: gRPC server
with the full SchedulerGrpc surface, push-mode task launching over gRPC to
executors, dead-executor expiry sweep, REST API + Prometheus metrics.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time
from concurrent import futures

import grpc

from ballista_tpu.executor.executor_server import executor_stub
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.grpc_service import SchedulerGrpcService, add_scheduler_service
from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from ballista_tpu.scheduler.server import SchedulerServer, TaskLauncher
from ballista_tpu.scheduler.state.execution_graph import TaskDescription
from ballista_tpu.serde_control import encode_task_definition

log = logging.getLogger(__name__)

EXPIRY_CHECK_S = 15.0
RESUBMIT_CHECK_S = 3.0


class GrpcTaskLauncher(TaskLauncher):
    """Push mode: LaunchMultiTask to the executor's gRPC endpoint
    (reference: executor_manager.rs:406)."""

    def __init__(self, tls_config=None):
        self._stubs: dict[str, object] = {}
        self._lock = threading.Lock()
        self._tls_config = tls_config  # BallistaConfig carrying tls paths

    def _stub_for(self, addr: str):
        with self._lock:
            s = self._stubs.get(addr)
            if s is None:
                from ballista_tpu.utils.grpc_util import create_channel

                s = executor_stub(create_channel(addr, self._tls_config))
                self._stubs[addr] = s
            return s

    def launch(self, executor_id: str, tasks: list[TaskDescription], server: SchedulerServer) -> None:
        slot = server.executors.get(executor_id)
        if slot is None:
            raise RuntimeError(f"unknown executor {executor_id}")
        addr = f"{slot.metadata.host}:{slot.metadata.grpc_port}"
        req = pb.LaunchMultiTaskParams(scheduler_id=server.scheduler_id)
        for t in tasks:
            cfg = server.sessions.get(t.session_id)
            tp = encode_task_definition(t, cfg)
            if cfg is not None:
                for k, v in cfg.to_key_value_pairs():
                    tp.props.add(key=k, value=v)
            req.tasks.append(tp)
        stub = self._stub_for(addr)
        stub.LaunchMultiTask(req, timeout=30)

    def cancel_tasks(self, executor_id: str, job_id: str, items, server) -> None:
        slot = server.executors.get(executor_id)
        if slot is None:
            return
        addr = f"{slot.metadata.host}:{slot.metadata.grpc_port}"
        req = pb.CancelTasksParams()
        for task_id, stage_id in items:
            req.tasks.add(task_id=task_id, job_id=job_id, stage_id=stage_id)
        stub = self._stub_for(addr)
        stub.CancelTasks(req, timeout=10)

    def remove_job_data(self, executor_id: str, job_id: str, server) -> None:
        slot = server.executors.get(executor_id)
        if slot is None:
            return
        addr = f"{slot.metadata.host}:{slot.metadata.grpc_port}"
        stub = self._stub_for(addr)
        stub.RemoveJobData(pb.RemoveJobDataParams(job_id=job_id), timeout=10)


class SchedulerProcess:
    def __init__(self, bind_host: str = "0.0.0.0", port: int = 50050,
                 task_distribution: str = "bias", executor_timeout_s: float = 180.0,
                 rest_port: int = 0, flight_proxy_port: int = 0,
                 job_state_dir: str | None = None, scheduler_id: str = "scheduler-0",
                 force_recover: bool = False,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None,
                 quarantine_threshold: float = 0.5,
                 quarantine_min_events: float = 4.0,
                 health_half_life_s: float = 60.0,
                 probe_backoff_s: float = 10.0,
                 shards: int = 1):
        self.metrics = InMemoryMetricsCollector()
        job_state = None
        if job_state_dir:
            from ballista_tpu.scheduler.state.job_state import FileJobState

            job_state = FileJobState(job_state_dir)
        launcher_tls = None
        if tls_client_ca or tls_cert:
            from ballista_tpu.config import (
                GRPC_TLS_CA,
                GRPC_TLS_CERT,
                GRPC_TLS_KEY,
                BallistaConfig,
            )

            launcher_tls = BallistaConfig({
                GRPC_TLS_CA: tls_client_ca or "",
                GRPC_TLS_CERT: tls_cert or "",
                GRPC_TLS_KEY: tls_key or "",
            })
        self.scheduler = SchedulerServer(
            GrpcTaskLauncher(launcher_tls), self.metrics, task_distribution, executor_timeout_s,
            scheduler_id=scheduler_id, job_state=job_state,
            quarantine_threshold=quarantine_threshold,
            quarantine_min_events=quarantine_min_events,
            health_half_life_s=health_half_life_s,
            probe_backoff_s=probe_backoff_s,
            shards=shards,
        )
        from ballista_tpu.utils.grpc_util import server_options

        self.grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=32), options=server_options()
        )
        self.service = SchedulerGrpcService(self.scheduler)
        add_scheduler_service(self.grpc_server, self.service)
        from ballista_tpu.scheduler.external_scaler import (
            ExternalScalerService,
            add_external_scaler_service,
        )

        # KEDA autoscaling endpoint on the same port (external_scaler.rs)
        add_external_scaler_service(
            self.grpc_server, ExternalScalerService(self.scheduler))
        from ballista_tpu.utils.grpc_util import bind_server_port

        self.tls = (tls_cert, tls_key, tls_client_ca)
        self.port = bind_server_port(
            self.grpc_server, f"{bind_host}:{port}", tls_cert, tls_key, tls_client_ca
        )
        self._stopping = threading.Event()
        self.rest_server = None
        self.rest_port = 0
        if rest_port >= 0:
            from ballista_tpu.scheduler.api.rest import start_rest_api

            self.rest_server, self.rest_port = start_rest_api(
                self.scheduler, self.metrics, bind_host, rest_port
            )
        self.force_recover = force_recover
        self.flight_proxy = None
        self.flight_proxy_port = 0
        if flight_proxy_port >= 0:
            from ballista_tpu.flight.proxy import start_flight_proxy

            self.flight_proxy, self.flight_proxy_port = start_flight_proxy(
                bind_host, flight_proxy_port,
                tls_cert=tls_cert, tls_key=tls_key, tls_client_ca=tls_client_ca,
            )
            self.scheduler.flight_proxy_port = self.flight_proxy_port

    def start(self) -> None:
        self.scheduler.start()
        recovered = self.scheduler.recover_jobs(force=self.force_recover)
        if recovered:
            log.info("recovered %d persisted jobs: %s", len(recovered), recovered)
        self.grpc_server.start()
        threading.Thread(target=self._expiry_loop, daemon=True, name="executor-expiry").start()
        log.info("scheduler up: grpc=%d rest=%s", self.port, self.rest_port or "off")

    def _expiry_loop(self) -> None:
        ticks = 0
        while not self._stopping.wait(RESUBMIT_CHECK_S):
            ticks += 1
            self.scheduler.resubmit_stuck_jobs()
            if ticks % int(EXPIRY_CHECK_S / RESUBMIT_CHECK_S) == 0:
                self.scheduler.check_expired_executors()

    def shutdown(self) -> None:
        self._stopping.set()
        self.scheduler.stop()
        self.grpc_server.stop(grace=2)
        if self.rest_server is not None:
            self.rest_server.shutdown()
        if self.flight_proxy is not None:
            try:
                self.flight_proxy.shutdown()
            except Exception:
                pass

    def wait(self) -> None:
        try:
            while not self._stopping.wait(1.0):
                pass
        except KeyboardInterrupt:
            self.shutdown()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="ballista_tpu scheduler daemon")
    ap.add_argument("--bind-host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=50050)
    ap.add_argument("--rest-port", type=int, default=50080)
    ap.add_argument("--flight-proxy-port", type=int, default=50051,
                    help="Flight result proxy port (-1 disables; 0 = ephemeral)")
    ap.add_argument("--job-state-dir", default=None,
                    help="persist job graphs here for fail-over recovery")
    ap.add_argument("--scheduler-id", default="scheduler-0")
    ap.add_argument("--shards", type=int, default=1,
                    help="event-loop shard count: jobs partition by crc32(job_id) mod N")
    ap.add_argument("--tls-cert", default=None, help="server certificate chain (PEM) — enables TLS")
    ap.add_argument("--tls-key", default=None, help="server private key (PEM)")
    ap.add_argument("--tls-client-ca", default=None,
                    help="CA to verify client certs (enables mTLS; also used to dial executors)")
    ap.add_argument("--force-recover", action="store_true",
                    help="adopt persisted jobs even if owned by another scheduler id "
                         "(standby takeover after the owner died)")
    ap.add_argument("--task-distribution", choices=("bias", "round-robin", "consistent-hash"),
                    default="bias")
    ap.add_argument("--executor-timeout-seconds", type=float, default=180.0)
    ap.add_argument("--quarantine-threshold", type=float, default=0.5,
                    help="decayed failure rate at which an executor stops receiving "
                         "offers (0 disables quarantine)")
    ap.add_argument("--quarantine-min-events", type=float, default=4.0,
                    help="minimum decayed task outcomes before the threshold applies")
    ap.add_argument("--health-half-life-seconds", type=float, default=60.0,
                    help="half-life of the decayed per-executor failure/success counters")
    ap.add_argument("--probe-backoff-seconds", type=float, default=10.0,
                    help="how long a quarantined executor waits before a probe task")
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument("--log-file", default=None, help="also log to this file (rotating)")
    ap.add_argument("--log-rotation", choices=("never", "minutely", "hourly", "daily"),
                    default="daily", help="rotation policy for --log-file")
    args = ap.parse_args(argv)
    from ballista_tpu.utils.log_util import init_logging

    init_logging(args.log_level, args.log_file, args.log_rotation)

    proc = SchedulerProcess(
        args.bind_host, args.port,
        args.task_distribution,
        args.executor_timeout_seconds, args.rest_port, args.flight_proxy_port,
        job_state_dir=args.job_state_dir, scheduler_id=args.scheduler_id,
        force_recover=args.force_recover,
        tls_cert=args.tls_cert, tls_key=args.tls_key, tls_client_ca=args.tls_client_ca,
        quarantine_threshold=args.quarantine_threshold,
        quarantine_min_events=args.quarantine_min_events,
        health_half_life_s=args.health_half_life_seconds,
        probe_backoff_s=args.probe_backoff_seconds,
        shards=args.shards,
    )
    signal.signal(signal.SIGTERM, lambda *_: proc.shutdown())
    proc.start()
    proc.wait()


if __name__ == "__main__":
    main()
