"""Distributed planner: split a physical plan into query stages.

Rebuild of DefaultDistributedPlanner::plan_query_stages
(scheduler/src/planner.rs:108): walk the plan, cut a stage at every
exchange —

- RepartitionExec(hash K)      → ShuffleWriterExec(K, keys) stage +
                                 UnresolvedShuffleExec leaf downstream
- CoalescePartitionsExec /     → passthrough ShuffleWriterExec stage (the
  SortPreservingMergeExec        downstream single task reads every map
                                 output partition)
- HashJoin/CrossJoin build side (collect_left) → broadcast stage
  (maybe_promote_to_broadcast, planner.rs:286): written once, read in full
  by every probe task via the reader's broadcast flag

The job's root plan gains a passthrough writer too: the final stage's
shuffle files ARE the query result the client fetches.

`remove_unresolved_shuffles` (planner.rs:568) swaps resolved readers in
when input stages complete — that lives in the ExecutionGraph here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    CrossJoinExec,
    ExecutionPlan,
    HashJoinExec,
    RepartitionExec,
    SortPreservingMergeExec,
)
from ballista_tpu.shuffle.reader import ShuffleReaderExec, UnresolvedShuffleExec
from ballista_tpu.shuffle.writer import ShuffleWriterExec


@dataclass
class QueryStage:
    stage_id: int
    plan: ShuffleWriterExec  # root is always a shuffle writer
    partitions: int  # number of map tasks (input partitions of the writer)
    output_partitions: int  # reduce-side partition count
    input_stage_ids: list[int] = field(default_factory=list)
    broadcast: bool = False  # consumed as a broadcast input
    # mesh-wide stage (merge_mesh_stages): the stage contains a
    # MeshExchangeExec and must ship as ONE task spanning every partition —
    # the exchange runs once, on-device, and serves all reduce buckets
    mesh: bool = False

    def display(self) -> str:
        mesh = " mesh" if self.mesh else ""
        return f"Stage {self.stage_id} [partitions={self.partitions} → {self.output_partitions}{mesh}]\n" + self.plan.display(1)


class DistributedPlanner:
    def __init__(self, job_id: str):
        self.job_id = job_id
        self.next_stage_id = 1
        self.stages: list[QueryStage] = []

    def plan_query_stages(self, plan: ExecutionPlan) -> list[QueryStage]:
        root, _ = self._walk(plan)
        # final stage: passthrough writer over the root
        final = ShuffleWriterExec(root, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False)
        self._add_stage(final, root.output_partition_count(), root.output_partition_count())
        return self.stages

    # ------------------------------------------------------------------

    def _add_stage(self, writer: ShuffleWriterExec, partitions: int, output_partitions: int,
                   broadcast: bool = False) -> QueryStage:
        stage = QueryStage(
            stage_id=self.next_stage_id,
            plan=writer,
            partitions=partitions,
            output_partitions=output_partitions,
            input_stage_ids=_find_input_stages(writer),
            broadcast=broadcast,
        )
        self.stages.append(stage)
        self.next_stage_id += 1
        return stage

    def _walk(self, node: ExecutionPlan) -> tuple[ExecutionPlan, bool]:
        """Returns (rewritten node, changed)."""
        if isinstance(node, RepartitionExec) and node.scheme == "hash":
            child, _ = self._walk(node.input)
            writer = ShuffleWriterExec(
                child, self.job_id, self.next_stage_id, node.n, node.keys, sort_shuffle=True
            )
            stage = self._add_stage(writer, child.output_partition_count(), node.n)
            return (
                UnresolvedShuffleExec(stage.stage_id, node.df_schema, node.n, broadcast=False),
                True,
            )
        if isinstance(node, (CoalescePartitionsExec, SortPreservingMergeExec)):
            child, _ = self._walk(node.children()[0])
            if child.output_partition_count() <= 1:
                return node.with_children([child]), True
            writer = ShuffleWriterExec(
                child, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False
            )
            stage = self._add_stage(writer, child.output_partition_count(), child.output_partition_count())
            reader_leaf = UnresolvedShuffleExec(
                stage.stage_id, child.df_schema, child.output_partition_count(), broadcast=False
            )
            return node.with_children([reader_leaf]), True
        if isinstance(node, (HashJoinExec, CrossJoinExec)) and getattr(node, "mode", "collect_left") == "collect_left":
            left, right = node.children()
            left, _ = self._walk(left)
            right, _ = self._walk(right)
            # broadcast promotion: build side materialized once (unless it is
            # already a single in-stage partition, e.g. a tiny dimension scan)
            if left.output_partition_count() > 1 or _contains_shuffle(left):
                writer = ShuffleWriterExec(
                    left, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False
                )
                stage = self._add_stage(
                    writer, left.output_partition_count(), left.output_partition_count(), broadcast=True
                )
                left = UnresolvedShuffleExec(
                    stage.stage_id, left.df_schema, left.output_partition_count(), broadcast=True
                )
            return node.with_children([left, right]), True
        kids = node.children()
        if not kids:
            return node, False
        new_kids = []
        changed = False
        for k in kids:
            nk, ch = self._walk(k)
            new_kids.append(nk)
            changed = changed or ch
        if changed:
            return node.with_children(new_kids), True
        return node, False


# -- mesh-wide stage merging (the tentpole of ISSUE 7) ------------------------
#
# A hash exchange between two stages of the SAME host round-trips through
# Arrow IPC files and Flight RPCs even though both sides run on chips of one
# device mesh. When the shape allows, the producer stage is merged INTO its
# consumer: the producer's ShuffleWriterExec(hash K) and the consumer's
# reader leaf collapse into a MeshExchangeExec, and the merged stage ships
# as one mesh-wide task whose repartition is an on-device all_to_all
# (ops/tpu/mesh_stage.py). Stages that don't fit the shape keep the file
# path — this is an optimization pass, never a correctness requirement.


def choose_mesh_mode(producer: QueryStage, consumers: list[tuple[QueryStage, list]],
                     config) -> tuple[bool, str]:
    """The planner's side of the mesh cost model: is this exchange edge
    mergeable at all? Returns (ok, reason); runtime demotion (capacity,
    devices, dtypes, AQE input-bytes) happens later with real data in hand.
    """
    if producer.broadcast:
        return False, "broadcast-producer"
    if not producer.plan.sort_shuffle or not producer.plan.keys:
        return False, "not-hash-exchange"
    if producer.output_partitions < 1:
        return False, "no-output-partitions"
    if producer.mesh:
        return False, "producer-already-mesh"
    if len(consumers) != 1:
        return False, f"consumers:{len(consumers)}"
    consumer, leaves = consumers[0]
    if len(leaves) != 1:
        return False, f"leaves:{len(leaves)}"
    if leaves[0].broadcast:
        return False, "broadcast-edge"
    if consumer.partitions != producer.output_partitions:
        # the merged stage's ONE task must cover exactly the reduce buckets
        # the exchange produces; a mismatched consumer keeps the file path
        return False, "partition-mismatch"
    return True, "mesh"


def merge_mesh_stages(stages: list[QueryStage], config) -> list[QueryStage]:
    """Fuse single-consumer hash-exchange edges into mesh-wide stages.

    Runs to a fixpoint so a chain of exchanges (partial agg → repartition →
    final agg → repartition → sort) can collapse into one mesh stage. Only
    active under `ballista.tpu.mesh.enabled` with the TPU executor engine —
    per-partition CPU tasks gain nothing from a collective exchange."""
    import logging

    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MESH_ENABLED

    log = logging.getLogger(__name__)
    if config is None or not bool(config.get(TPU_MESH_ENABLED)):
        return stages
    if str(config.get(EXECUTOR_ENGINE)) != "tpu":
        return stages

    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec

    def leaves_for(stage: QueryStage, producer_id: int):
        out = []

        def walk(n):
            if isinstance(n, UnresolvedShuffleExec) and n.stage_id == producer_id:
                out.append(n)
            for c in n.children():
                walk(c)

        walk(stage.plan)
        return out

    changed = True
    while changed:
        changed = False
        for producer in list(stages):
            consumers = []
            for s in stages:
                if s.stage_id == producer.stage_id:
                    continue
                leaves = leaves_for(s, producer.stage_id)
                if leaves:
                    consumers.append((s, leaves))
            if not consumers:
                continue  # the final stage: its files ARE the result
            ok, reason = choose_mesh_mode(producer, consumers, config)
            if not ok:
                log.debug("mesh merge skipped stage %d: %s", producer.stage_id, reason)
                continue
            consumer, (leaf,) = consumers[0]
            exchange = MeshExchangeExec(
                producer.plan.input, producer.plan.keys, producer.output_partitions
            )

            def swap(n):
                if n is leaf:
                    return exchange
                kids = n.children()
                if not kids:
                    return n
                new_kids = [swap(c) for c in kids]
                if all(a is b for a, b in zip(new_kids, kids)):
                    return n
                return n.with_children(new_kids)

            consumer.plan = swap(consumer.plan)
            consumer.mesh = True
            consumer.input_stage_ids = _find_input_stages(consumer.plan)
            stages = [s for s in stages if s.stage_id != producer.stage_id]
            log.info(
                "mesh merge: stage %d (hash exchange, %d buckets) fused into "
                "stage %d as an on-device all_to_all",
                producer.stage_id, producer.output_partitions, consumer.stage_id,
            )
            changed = True
            break
    return stages


def _find_input_stages(plan: ExecutionPlan) -> list[int]:
    out: list[int] = []

    def walk(n: ExecutionPlan):
        if isinstance(n, UnresolvedShuffleExec):
            out.append(n.stage_id)
        for c in n.children():
            walk(c)

    walk(plan)
    return sorted(set(out))


def _contains_shuffle(plan: ExecutionPlan) -> bool:
    if isinstance(plan, (UnresolvedShuffleExec, ShuffleReaderExec)):
        return True
    return any(_contains_shuffle(c) for c in plan.children())


def remove_unresolved_shuffles(plan: ExecutionPlan, resolved: dict[int, ShuffleReaderExec]) -> ExecutionPlan:
    """Swap UnresolvedShuffleExec leaves for concrete readers
    (reference: planner.rs:568)."""
    if isinstance(plan, UnresolvedShuffleExec):
        reader = resolved.get(plan.stage_id)
        if reader is None:
            raise RuntimeError(f"stage {plan.stage_id} not resolved yet")
        return reader
    kids = plan.children()
    if not kids:
        return plan
    return plan.with_children([remove_unresolved_shuffles(c, resolved) for c in kids])
