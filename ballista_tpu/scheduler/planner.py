"""Distributed planner: split a physical plan into query stages.

Rebuild of DefaultDistributedPlanner::plan_query_stages
(scheduler/src/planner.rs:108): walk the plan, cut a stage at every
exchange —

- RepartitionExec(hash K)      → ShuffleWriterExec(K, keys) stage +
                                 UnresolvedShuffleExec leaf downstream
- CoalescePartitionsExec /     → passthrough ShuffleWriterExec stage (the
  SortPreservingMergeExec        downstream single task reads every map
                                 output partition)
- HashJoin/CrossJoin build side (collect_left) → broadcast stage
  (maybe_promote_to_broadcast, planner.rs:286): written once, read in full
  by every probe task via the reader's broadcast flag

The job's root plan gains a passthrough writer too: the final stage's
shuffle files ARE the query result the client fetches.

`remove_unresolved_shuffles` (planner.rs:568) swaps resolved readers in
when input stages complete — that lives in the ExecutionGraph here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    CrossJoinExec,
    ExecutionPlan,
    HashJoinExec,
    RepartitionExec,
    SortPreservingMergeExec,
)
from ballista_tpu.shuffle.reader import ShuffleReaderExec, UnresolvedShuffleExec
from ballista_tpu.shuffle.writer import ShuffleWriterExec


@dataclass
class QueryStage:
    stage_id: int
    plan: ShuffleWriterExec  # root is always a shuffle writer
    partitions: int  # number of map tasks (input partitions of the writer)
    output_partitions: int  # reduce-side partition count
    input_stage_ids: list[int] = field(default_factory=list)
    broadcast: bool = False  # consumed as a broadcast input

    def display(self) -> str:
        return f"Stage {self.stage_id} [partitions={self.partitions} → {self.output_partitions}]\n" + self.plan.display(1)


class DistributedPlanner:
    def __init__(self, job_id: str):
        self.job_id = job_id
        self.next_stage_id = 1
        self.stages: list[QueryStage] = []

    def plan_query_stages(self, plan: ExecutionPlan) -> list[QueryStage]:
        root, _ = self._walk(plan)
        # final stage: passthrough writer over the root
        final = ShuffleWriterExec(root, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False)
        self._add_stage(final, root.output_partition_count(), root.output_partition_count())
        return self.stages

    # ------------------------------------------------------------------

    def _add_stage(self, writer: ShuffleWriterExec, partitions: int, output_partitions: int,
                   broadcast: bool = False) -> QueryStage:
        stage = QueryStage(
            stage_id=self.next_stage_id,
            plan=writer,
            partitions=partitions,
            output_partitions=output_partitions,
            input_stage_ids=_find_input_stages(writer),
            broadcast=broadcast,
        )
        self.stages.append(stage)
        self.next_stage_id += 1
        return stage

    def _walk(self, node: ExecutionPlan) -> tuple[ExecutionPlan, bool]:
        """Returns (rewritten node, changed)."""
        if isinstance(node, RepartitionExec) and node.scheme == "hash":
            child, _ = self._walk(node.input)
            writer = ShuffleWriterExec(
                child, self.job_id, self.next_stage_id, node.n, node.keys, sort_shuffle=True
            )
            stage = self._add_stage(writer, child.output_partition_count(), node.n)
            return (
                UnresolvedShuffleExec(stage.stage_id, node.df_schema, node.n, broadcast=False),
                True,
            )
        if isinstance(node, (CoalescePartitionsExec, SortPreservingMergeExec)):
            child, _ = self._walk(node.children()[0])
            if child.output_partition_count() <= 1:
                return node.with_children([child]), True
            writer = ShuffleWriterExec(
                child, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False
            )
            stage = self._add_stage(writer, child.output_partition_count(), child.output_partition_count())
            reader_leaf = UnresolvedShuffleExec(
                stage.stage_id, child.df_schema, child.output_partition_count(), broadcast=False
            )
            return node.with_children([reader_leaf]), True
        if isinstance(node, (HashJoinExec, CrossJoinExec)) and getattr(node, "mode", "collect_left") == "collect_left":
            left, right = node.children()
            left, _ = self._walk(left)
            right, _ = self._walk(right)
            # broadcast promotion: build side materialized once (unless it is
            # already a single in-stage partition, e.g. a tiny dimension scan)
            if left.output_partition_count() > 1 or _contains_shuffle(left):
                writer = ShuffleWriterExec(
                    left, self.job_id, self.next_stage_id, 0, [], sort_shuffle=False
                )
                stage = self._add_stage(
                    writer, left.output_partition_count(), left.output_partition_count(), broadcast=True
                )
                left = UnresolvedShuffleExec(
                    stage.stage_id, left.df_schema, left.output_partition_count(), broadcast=True
                )
            return node.with_children([left, right]), True
        kids = node.children()
        if not kids:
            return node, False
        new_kids = []
        changed = False
        for k in kids:
            nk, ch = self._walk(k)
            new_kids.append(nk)
            changed = changed or ch
        if changed:
            return node.with_children(new_kids), True
        return node, False


def _find_input_stages(plan: ExecutionPlan) -> list[int]:
    out: list[int] = []

    def walk(n: ExecutionPlan):
        if isinstance(n, UnresolvedShuffleExec):
            out.append(n.stage_id)
        for c in n.children():
            walk(c)

    walk(plan)
    return sorted(set(out))


def _contains_shuffle(plan: ExecutionPlan) -> bool:
    if isinstance(plan, (UnresolvedShuffleExec, ShuffleReaderExec)):
        return True
    return any(_contains_shuffle(c) for c in plan.children())


def remove_unresolved_shuffles(plan: ExecutionPlan, resolved: dict[int, ShuffleReaderExec]) -> ExecutionPlan:
    """Swap UnresolvedShuffleExec leaves for concrete readers
    (reference: planner.rs:568)."""
    if isinstance(plan, UnresolvedShuffleExec):
        reader = resolved.get(plan.stage_id)
        if reader is None:
            raise RuntimeError(f"stage {plan.stage_id} not resolved yet")
        return reader
    kids = plan.children()
    if not kids:
        return plan
    return plan.with_children([remove_unresolved_shuffles(c, resolved) for c in kids])
