"""Per-task plan restriction.

Rebuild of the reference's task-plan rewriter
(scheduler/src/state/task_builder.rs:18-64): every task of a stage shares
the stage plan, but a task executing partitions {p} only needs the scan
file-groups and shuffle-reader location lists of those partitions. Without
restriction, task protos grow as O(partitions × locations) — the
reference's own SF1000 baseline failed Q11/Q21/Q22 on a 16 MiB plan-size
ceiling even WITH restriction (BASELINE.md), so shipping full plans hits
that wall far sooner.

Restriction preserves GLOBAL partition indexing: non-task slots become
empty (no files / no locations), they are never removed, so `execute(p)`
addressing is unchanged.

Scoping (the task_builder.rs trap): leaves under a COLLAPSE — an operator
whose execute(k) consumes child partitions other than k — must keep full
input:
- collect_left HashJoin build sides (read in full by every task)
- CrossJoin left sides
- SortPreservingMerge / CoalescePartitions / Union / Repartition children
- broadcast shuffle readers (every partition reads everything)

Under `ballista.executor.engine = tpu`, Parquet scans are NOT restricted:
the executor's engine seam lifts scan-rooted chains into whole-table
device stages whose [P, N] device cache is keyed on the scan's file set —
per-task file subsets would defeat that cache (one device encode per task
instead of one per table). Reader location lists, which dominate plan
size, are still restricted.
"""

from __future__ import annotations

from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    CrossJoinExec,
    ExecutionPlan,
    HashJoinExec,
    ParquetScanExec,
    RepartitionExec,
    SortPreservingMergeExec,
    UnionExec,
)
from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec
from ballista_tpu.shuffle.reader import ShuffleReaderExec

_COLLAPSE_ALL_CHILDREN = (
    SortPreservingMergeExec,
    CoalescePartitionsExec,
    UnionExec,
    RepartitionExec,
)


def restrict_plan_to_partitions(plan: ExecutionPlan, partitions: list[int],
                                config: BallistaConfig | None = None) -> ExecutionPlan:
    keep = set(partitions)
    restrict_scans = True
    if config is not None and str(config.get(EXECUTOR_ENGINE)) == "tpu":
        restrict_scans = False

    def walk(node: ExecutionPlan, scoped: bool) -> ExecutionPlan:
        if isinstance(node, ShuffleReaderExec):
            if not scoped or node.broadcast:
                return node
            new_locs = [
                locs if i in keep else []
                for i, locs in enumerate(node.partition_locations)
            ]
            out = ShuffleReaderExec(node.df_schema, new_locs, node.broadcast)
            return out
        if isinstance(node, ParquetScanExec):
            if not scoped or not restrict_scans:
                return node
            new_parts = [
                p if i in keep else {"files": []}
                for i, p in enumerate(node.partitions)
            ]
            return ParquetScanExec(
                node.df_schema, new_parts, node.projection, node.filters, node.table_name
            )
        kids = node.children()
        if not kids:
            return node
        new_kids = []
        for idx, c in enumerate(kids):
            child_scoped = scoped
            if isinstance(node, _COLLAPSE_ALL_CHILDREN):
                child_scoped = False
            elif isinstance(node, MeshExchangeExec):
                # the fused exchange consumes EVERY producer partition in its
                # one device dispatch; scoping its input would starve the
                # all_to_all of rows
                child_scoped = False
            elif isinstance(node, HashJoinExec) and node.mode == "collect_left" and idx == 0:
                child_scoped = False
            elif isinstance(node, DynamicJoinSelectionExec):
                # the deferred decision may promote EITHER side to a
                # collected build at first-batch time — both children keep
                # full location lists (restriction is a size optimization,
                # never a correctness requirement)
                child_scoped = False
            elif isinstance(node, CrossJoinExec) and idx == 0:
                child_scoped = False
            new_kids.append(walk(c, child_scoped))
        if all(a is b for a, b in zip(new_kids, kids)):
            return node
        return node.with_children(new_kids)

    return walk(plan, True)
