from ballista_tpu.scheduler.process import main

main()
