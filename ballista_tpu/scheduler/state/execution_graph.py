"""Per-job execution graph: the stage DAG state machine.

Rebuild of ExecutionGraph / ExecutionStage
(scheduler/src/state/execution_graph.rs:103, execution_stage.rs):

stage lifecycle  UNRESOLVED → RESOLVED → RUNNING → SUCCESSFUL | FAILED
- a stage resolves when every input stage is successful: its
  UnresolvedShuffleExec leaves are swapped for ShuffleReaderExec carrying
  the input stages' partition locations (remove_unresolved_shuffles)
- tasks are handed out per partition SLICE (PendingPartitions::next_slice,
  max_partitions_per_task)
- failure handling: bounded per-stage retries with attempt counters and
  failure dedup (execution_stage.rs:142); executor loss rolls running
  stages back and reruns successful stages whose shuffle outputs were on
  the lost executor (reset_stages_on_lost_executor :180,
  rerun_successful_stage :216 — ResultLost recompute)
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ballista_tpu.config import MAX_PARTITIONS_PER_TASK, BallistaConfig
from ballista_tpu.scheduler.planner import QueryStage, remove_unresolved_shuffles
from ballista_tpu.shuffle.reader import ShuffleReaderExec
from ballista_tpu.shuffle.types import PartitionLocation

log = logging.getLogger(__name__)

MAX_STAGE_ATTEMPTS = 4
MAX_TASK_FAILURES = 4


class StageState(Enum):
    UNRESOLVED = "unresolved"
    RESOLVED = "resolved"
    RUNNING = "running"
    SUCCESSFUL = "successful"
    FAILED = "failed"


class JobState(Enum):
    QUEUED = "queued"
    RUNNING = "running"
    SUCCESSFUL = "successful"
    FAILED = "failed"
    CANCELLED = "cancelled"


@dataclass
class TaskDescription:
    job_id: str
    stage_id: int
    stage_attempt: int
    task_id: int
    partitions: list[int]
    plan: object  # ExecutionPlan (stage plan with resolved readers)
    session_id: str
    # 0 = original attempt; >0 = speculative duplicate of another task
    # covering the same partition slice
    task_attempt: int = 0
    # hard wall-clock budget (seconds, 0 = none); the executor aborts at
    # the deadline and reports a retryable timeout
    deadline_seconds: float = 0.0
    # serving tier: dispatched straight from the submit path (single-stage
    # plan, no execution graph); executors count these for heartbeat gauges
    fast_lane: bool = False


@dataclass
class RunningTask:
    task_id: int
    partitions: list[int]
    executor_id: str
    launched_at: float = field(default_factory=time.time)
    task_attempt: int = 0
    deadline_seconds: float = 0.0
    # the OTHER in-flight attempt of the same slice (original ↔ speculative);
    # first success wins and queues the rival for CancelTasks
    rival_task_id: int | None = None


class ExecutionStage:
    def __init__(self, stage: QueryStage):
        self.spec = stage
        self.stage_id = stage.stage_id
        self.state = StageState.UNRESOLVED if stage.input_stage_ids else StageState.RESOLVED
        self.attempt = 0
        self.resolved_plan = stage.plan if not stage.input_stage_ids else None
        self.pending: list[int] = list(range(stage.partitions))
        # may shrink via AQE coalescing or GROW via skew splitting
        self.effective_partitions = stage.partitions
        # SkewSplitReport when AQE split hot reduce partitions at this
        # stage's resolution; plan_check verifies the slice readers against
        # it (cover / no-overlap / order)
        self.skew_report = None
        self.running: dict[int, RunningTask] = {}
        # map_partition → locations published by the finished task
        self.completed: dict[int, list[PartitionLocation]] = {}
        self.failure_reasons: set[str] = set()
        self.task_failures = 0
        self.skipped = False  # completed by AQE pruning, never scheduled
        # wall-clock durations of this attempt's completed tasks — the
        # sample the speculation trigger and adaptive deadlines derive
        # their median from
        self.task_durations: list[float] = []
        # partition → failed/expired attempts so far: a relaunched slice
        # carries task_attempt = prior attempts, letting the executor side
        # distinguish a retry from a first run (chaos straggler mode only
        # delays attempt 0 — a retry must be able to escape the injected
        # fault, same as a speculative duplicate)
        self.retry_counts: dict[int, int] = {}

    @property
    def is_runnable(self) -> bool:
        return self.state in (StageState.RESOLVED, StageState.RUNNING) and bool(self.pending)

    def all_done(self) -> bool:
        return not self.pending and not self.running and len(self.completed) == self.effective_partitions

    def reset_for_retry(self) -> None:
        self.attempt += 1
        self.pending = list(range(self.spec.partitions))
        self.effective_partitions = self.spec.partitions
        self.skew_report = None
        self.running.clear()
        self.completed.clear()
        self.task_durations = []
        self.retry_counts = {}
        self.state = StageState.UNRESOLVED if self.spec.input_stage_ids else StageState.RESOLVED
        if not self.spec.input_stage_ids:
            self.resolved_plan = self.spec.plan

    def output_locations(self) -> list[PartitionLocation]:
        out: list[PartitionLocation] = []
        for locs in self.completed.values():
            out.extend(locs)
        return out


class ExecutionGraph:
    def __init__(self, job_id: str, job_name: str, session_id: str, stages: list[QueryStage],
                 config: BallistaConfig | None = None):
        self.job_id = job_id
        self.job_name = job_name
        self.session_id = session_id
        self.config = config or BallistaConfig()
        self.stages: dict[int, ExecutionStage] = {s.stage_id: ExecutionStage(s) for s in stages}
        self.final_stage_id = max(self.stages) if self.stages else 0
        self.status = JobState.RUNNING
        self.error: str = ""
        self.next_task_id = 0
        self.queued_at = time.time()
        self.ended_at: float | None = None
        self.output_links: dict[int, list[int]] = {sid: [] for sid in self.stages}
        for s in stages:
            for inp in s.input_stage_ids:
                self.output_links[inp].append(s.stage_id)
        self._lock = threading.RLock()
        # the adaptive replanning pipeline (reference: AdaptivePlanner,
        # state/aqe/planner.rs) — invoked after finalizations and at
        # resolution, always under self._lock
        from ballista_tpu.scheduler.aqe.replanner import AdaptiveReplanner

        self.replanner = AdaptiveReplanner()
        self.stage_metrics: dict[int, list] = {}
        # (executor_id, task_id, stage_id) of tasks obsoleted by incremental
        # replanning or job cancellation, awaiting a CancelTasks rpc
        # (drained by the scheduler server)
        self.cancelled_tasks: list[tuple[str, int, int]] = []

    def drain_cancelled_tasks(self) -> list[tuple[str, int, int]]:
        with self._lock:
            out = self.cancelled_tasks
            self.cancelled_tasks = []
            return out

    # ------------------------------------------------------------------

    def available_task_count(self) -> int:
        with self._lock:
            if self.status is not JobState.RUNNING:
                return 0
            return sum(len(s.pending) for s in self.stages.values() if s.is_runnable)

    def pop_next_task(self, executor_id: str) -> Optional[TaskDescription]:
        """Hand out one task (a slice of a runnable stage's partitions)."""
        with self._lock:
            if self.status is not JobState.RUNNING:
                return None
            slice_size = max(1, int(self.config.get(MAX_PARTITIONS_PER_TASK)))
            for stage in sorted(self.stages.values(), key=lambda s: s.stage_id):
                if not stage.is_runnable:
                    continue
                # a mesh stage's exchange runs ONCE and serves every reduce
                # bucket from one device dispatch — it must ship as a single
                # mesh-wide task, never be sliced across executors
                n = len(stage.pending) if stage.spec.mesh else slice_size
                parts = stage.pending[:n]
                stage.pending = stage.pending[n:]
                self.next_task_id += 1
                deadline = self._deadline_seconds(stage)
                attempt = max((stage.retry_counts.get(p, 0) for p in parts), default=0)
                task = TaskDescription(
                    job_id=self.job_id,
                    stage_id=stage.stage_id,
                    stage_attempt=stage.attempt,
                    task_id=self.next_task_id,
                    partitions=parts,
                    plan=stage.resolved_plan,
                    session_id=self.session_id,
                    task_attempt=attempt,
                    deadline_seconds=deadline,
                )
                stage.running[task.task_id] = RunningTask(
                    task.task_id, parts, executor_id, task_attempt=attempt,
                    deadline_seconds=deadline)
                stage.state = StageState.RUNNING
                return task
            return None

    @staticmethod
    def _median_duration(stage: ExecutionStage) -> float:
        durs = sorted(stage.task_durations)
        return durs[len(durs) // 2] if durs else 0.0

    def _deadline_seconds(self, stage: ExecutionStage) -> float:
        """Effective per-task deadline: the configured floor, raised by the
        adaptive multiplier × observed median once enough samples exist."""
        from ballista_tpu.config import TASK_DEADLINE_MULTIPLIER, TASK_DEADLINE_S

        floor = float(self.config.get(TASK_DEADLINE_S))
        mult = float(self.config.get(TASK_DEADLINE_MULTIPLIER))
        if mult > 0 and len(stage.task_durations) >= 3:
            adaptive = mult * self._median_duration(stage)
            return max(floor, adaptive) if adaptive > 0 else floor
        return floor

    def return_task(self, task: TaskDescription) -> None:
        """Un-pop a task (no executor could take it): partitions go back to
        pending, the running entry is dropped."""
        with self._lock:
            stage = self.stages.get(task.stage_id)
            if stage is None:
                return
            stage.running.pop(task.task_id, None)
            stage.pending = list(task.partitions) + stage.pending
            if not stage.running and stage.state is StageState.RUNNING:
                stage.state = StageState.RESOLVED

    def reassign_running(self, task_id: int, stage_id: int, executor_id: str) -> None:
        """Late-bind a popped task to the executor the distribution policy
        chose (consistent-hash binds after the pop)."""
        with self._lock:
            stage = self.stages.get(stage_id)
            if stage is not None and task_id in stage.running:
                stage.running[task_id].executor_id = executor_id

    # ------------------------------------------------------------------

    def update_task_status(self, task_id: int, stage_id: int, stage_attempt: int,
                           state: str, partitions: list[int],
                           locations: list[PartitionLocation],
                           error: str = "", retryable: bool = False,
                           metrics: list | None = None,
                           fetch_failed_executor_id: str = "",
                           fetch_failed_stage_id: int = 0,
                           timed_out: bool = False,
                           fetch_failed_cause: str = "") -> list[str]:
        """Ingest one task status; returns job-level events
        ('stage_completed', 'job_finished', 'job_failed')."""
        events: list[str] = []
        with self._lock:
            stage = self.stages.get(stage_id)
            if stage is None or self.status is not JobState.RUNNING:
                return events
            if stage_attempt != stage.attempt:
                return events  # stale attempt
            if stage.state in (StageState.SUCCESSFUL, StageState.FAILED):
                # finalized (normally, or skipped/cancelled by incremental
                # replanning): a doomed task racing the CancelTasks rpc must
                # not overwrite the finalized outputs or re-fire completion
                return events
            running = stage.running.pop(task_id, None)
            if state == "success":
                # FIRST ATTEMPT WINS: a duplicate (speculative) attempt
                # finishing second must not replace the winner's committed
                # locations — downstream readers may already hold them
                fresh = [p for p in partitions if p not in stage.completed]
                for p in fresh:
                    stage.completed[p] = [l for l in locations if l.map_partition == p]
                if running is not None:
                    stage.task_durations.append(max(0.0, time.time() - running.launched_at))
                    self._cancel_rival(stage, running)
                if metrics and fresh:
                    self.stage_metrics.setdefault(stage_id, []).extend(metrics)
                if stage.all_done():
                    stage.state = StageState.SUCCESSFUL
                    events.append("stage_completed")
                    self._on_stage_success(stage, events)
            elif state in ("failed", "cancelled"):
                if running is None and not fetch_failed_executor_id:
                    # unknown/already-settled attempt (cancelled speculation
                    # loser, deadline-swept task reporting late): its slice
                    # is covered elsewhere — don't burn retry budget on it
                    return events
                if running is not None:
                    self._unlink_rival(stage, running)
                    self._repend_uncovered(stage, running.partitions)
                if error:
                    stage.failure_reasons.add(error.splitlines()[0][:200])
                if fetch_failed_executor_id and fetch_failed_stage_id in self.stages:
                    # ResultLost: the UPSTREAM stage's shuffle output is gone —
                    # drop that executor's outputs and recompute the upstream
                    # stage (+ roll back its consumers) instead of burning
                    # this task's retry budget (execution_graph.rs:216)
                    up = self.stages[fetch_failed_stage_id]
                    up.completed = {
                        p: locs for p, locs in up.completed.items()
                        if not any(l.executor_id == fetch_failed_executor_id for l in locs)
                    }
                    self._rerun_stage_tree(fetch_failed_stage_id, cause=fetch_failed_cause)
                    if self.status is JobState.FAILED:
                        events.append("job_failed")
                    return events
                stage.task_failures += 1
                if state == "cancelled":
                    pass
                elif not retryable or stage.task_failures > MAX_TASK_FAILURES:
                    self._fail_job(f"stage {stage_id} failed: {error}")
                    events.append("job_failed")
            return events

    def _cancel_rival(self, stage: ExecutionStage, winner: RunningTask) -> None:
        """The other attempt of the winner's slice loses: drop it from
        running and queue a CancelTasks push."""
        if winner.rival_task_id is None:
            return
        rival = stage.running.pop(winner.rival_task_id, None)
        if rival is not None:
            log.info("task %d won over attempt %d of stage %d; cancelling the loser on %s",
                     winner.task_id, rival.task_id, stage.stage_id, rival.executor_id)
            self.cancelled_tasks.append((rival.executor_id, rival.task_id, stage.stage_id))

    @staticmethod
    def _unlink_rival(stage: ExecutionStage, task: RunningTask) -> None:
        """A failed/cancelled attempt leaves its rival as the sole owner of
        the slice (free to fail, finish, or be speculated again)."""
        if task.rival_task_id is not None:
            rival = stage.running.get(task.rival_task_id)
            if rival is not None:
                rival.rival_task_id = None

    @staticmethod
    def _repend_uncovered(stage: ExecutionStage, partitions: list[int]) -> None:
        """Re-queue only the partitions no completed output or other running
        attempt covers (a speculation rival may still be computing them)."""
        covered = set(stage.completed)
        for rt in stage.running.values():
            covered.update(rt.partitions)
        covered.update(stage.pending)
        fresh = [p for p in partitions if p not in covered]
        for p in fresh:
            stage.retry_counts[p] = stage.retry_counts.get(p, 0) + 1
        stage.pending.extend(fresh)

    # -- straggler defense (speculation + deadline sweep) ------------------

    def speculation_candidates(self, now: float) -> list[tuple[int, int, str]]:
        """Running tasks eligible for a speculative duplicate:
        [(stage_id, task_id, executor_id)]. A stage qualifies once ≥ the
        configured quantile of its partitions completed and it has no
        pending work; a task qualifies once it ran past
        max(min_runtime, multiplier × median completed duration) and has
        no duplicate in flight yet."""
        from ballista_tpu.config import (
            SPECULATION_ENABLED,
            SPECULATION_MIN_RUNTIME_S,
            SPECULATION_MULTIPLIER,
            SPECULATION_QUANTILE,
        )

        with self._lock:
            if self.status is not JobState.RUNNING:
                return []
            if not bool(self.config.get(SPECULATION_ENABLED)):
                return []
            quantile = float(self.config.get(SPECULATION_QUANTILE))
            mult = float(self.config.get(SPECULATION_MULTIPLIER))
            min_runtime = float(self.config.get(SPECULATION_MIN_RUNTIME_S))
            out: list[tuple[int, int, str]] = []
            for stage in self.stages.values():
                if stage.state is not StageState.RUNNING or not stage.running:
                    continue
                if stage.pending:
                    continue  # schedule fresh work before duplicating old
                done_frac = len(stage.completed) / max(1, stage.effective_partitions)
                if done_frac < quantile:
                    continue
                median = self._median_duration(stage)
                if median <= 0.0:
                    continue
                threshold = max(min_runtime, mult * median)
                for t in stage.running.values():
                    if t.rival_task_id is not None:
                        continue
                    if now - t.launched_at >= threshold:
                        out.append((stage.stage_id, t.task_id, t.executor_id))
            return out

    def register_speculative(self, stage_id: int, task_id: int,
                             executor_id: str) -> Optional[TaskDescription]:
        """Create the duplicate attempt of a running task on `executor_id`.
        Returns None if the original settled (or already has a rival) in
        the window since speculation_candidates picked it."""
        with self._lock:
            stage = self.stages.get(stage_id)
            if stage is None or self.status is not JobState.RUNNING:
                return None
            if stage.state is not StageState.RUNNING:
                return None
            orig = stage.running.get(task_id)
            if orig is None or orig.rival_task_id is not None:
                return None
            self.next_task_id += 1
            deadline = self._deadline_seconds(stage)
            task = TaskDescription(
                job_id=self.job_id,
                stage_id=stage_id,
                stage_attempt=stage.attempt,
                task_id=self.next_task_id,
                partitions=list(orig.partitions),
                plan=stage.resolved_plan,
                session_id=self.session_id,
                task_attempt=orig.task_attempt + 1,
                deadline_seconds=deadline,
            )
            dup = RunningTask(task.task_id, list(orig.partitions), executor_id,
                              task_attempt=orig.task_attempt + 1,
                              deadline_seconds=deadline,
                              rival_task_id=orig.task_id)
            orig.rival_task_id = task.task_id
            stage.running[task.task_id] = dup
            return task

    def expire_overdue_tasks(self, now: float, grace_s: float = 2.0) -> tuple[list[tuple[str, int, int]], bool]:
        """Scheduler-side deadline backstop: tasks past deadline + grace
        (executor unresponsive or ignoring its own enforcement) are dropped
        from running, queued for CancelTasks, and their uncovered partitions
        re-queued. Returns ([(executor_id, task_id, stage_id)], job_failed)."""
        expired: list[tuple[str, int, int]] = []
        job_failed = False
        with self._lock:
            if self.status is not JobState.RUNNING:
                return expired, job_failed
            for stage in self.stages.values():
                if stage.state is not StageState.RUNNING:
                    continue
                overdue = [
                    t for t in stage.running.values()
                    if t.deadline_seconds > 0
                    and now - t.launched_at > t.deadline_seconds + max(grace_s, 0.5 * t.deadline_seconds)
                ]
                for t in overdue:
                    stage.running.pop(t.task_id, None)
                    self._unlink_rival(stage, t)
                    self._repend_uncovered(stage, t.partitions)
                    stage.failure_reasons.add(
                        f"task {t.task_id} missed its {t.deadline_seconds:.1f}s deadline (swept)")
                    stage.task_failures += 1
                    self.cancelled_tasks.append((t.executor_id, t.task_id, stage.stage_id))
                    expired.append((t.executor_id, t.task_id, stage.stage_id))
                    if stage.task_failures > MAX_TASK_FAILURES:
                        self._fail_job(
                            f"stage {stage.stage_id} exceeded {MAX_TASK_FAILURES} task "
                            f"failures (deadline sweep)")
                        job_failed = True
                        return expired, job_failed
        return expired, job_failed

    def _on_stage_success(self, stage: ExecutionStage, events: list[str]) -> None:
        if stage.stage_id == self.final_stage_id:
            self.status = JobState.SUCCESSFUL
            self.ended_at = time.time()
            events.append("job_finished")
            return
        # the adaptive replanning pass over the remaining plan (empty
        # propagation → runtime join selection → obsolete-stage
        # cancellation); no-op unless ballista.planner.adaptive.enabled
        self.replanner.replan_after_finalize(self, stage, events)
        self._maybe_verify(f"replan after stage {stage.stage_id} finalized")
        if self.status is not JobState.RUNNING:
            return
        for out_id in self.output_links.get(stage.stage_id, []):
            consumer = self.stages.get(out_id)
            if consumer is None:
                continue
            self._try_resolve(consumer)

    def complete_stage_skipped(self, stage: ExecutionStage, events: list[str]) -> None:
        """Finalize a stage the replanner proved empty: it completes with
        zero-row outputs without ever scheduling a task."""
        stage.pending = []
        stage.completed = {p: [] for p in range(stage.effective_partitions)}
        stage.state = StageState.SUCCESSFUL
        stage.skipped = True
        events.append("stage_completed")
        self._on_stage_success(stage, events)

    def _rebuild_output_links(self) -> None:
        self.output_links = {sid: [] for sid in self.stages}
        for s in self.stages.values():
            for inp in s.spec.input_stage_ids:
                if inp in self.output_links:
                    self.output_links[inp].append(s.stage_id)

    def _cancel_obsolete_stages(self, events: list[str]) -> None:
        """A stage no consumer references (after join collapses rewired the
        graph) is dead weight: drop its pending work and queue its running
        tasks for a CancelTasks rpc."""
        referenced: set[int] = {self.final_stage_id}
        for s in self.stages.values():
            if s.state in (StageState.UNRESOLVED, StageState.RESOLVED, StageState.RUNNING):
                referenced.update(s.spec.input_stage_ids)
        for s in self.stages.values():
            if s.stage_id in referenced or s.state is StageState.SUCCESSFUL:
                continue
            if not s.pending and not s.running:
                continue
            log.info("incremental AQE: stage %d is no longer consumed — cancelled", s.stage_id)
            s.pending = []
            if s.running:
                self.cancelled_tasks.extend(
                    (t.executor_id, t.task_id, s.stage_id) for t in s.running.values()
                )
                s.running.clear()
            s.state = StageState.SUCCESSFUL
            s.skipped = True
            s.completed = {p: [] for p in range(s.effective_partitions)}
            events.append("stage_cancelled")

    def _try_resolve(self, stage: ExecutionStage) -> None:
        if stage.state is not StageState.UNRESOLVED:
            return
        inputs = [self.stages[i] for i in stage.spec.input_stage_ids]
        if not all(i.state is StageState.SUCCESSFUL for i in inputs):
            return
        # stage-alteration replanning (fan-out shrink) before readers build
        self.replanner.replan_at_resolution(self, stage, inputs)
        resolved: dict[int, ShuffleReaderExec] = {}
        for inp in inputs:
            resolved[inp.stage_id] = self._build_reader(inp)
        plan = remove_unresolved_shuffles(stage.spec.plan, resolved)

        # adaptive replanning with the inputs' ACTUAL statistics
        from ballista_tpu.scheduler.aqe.rules import InputStageStats, apply_aqe

        from ballista_tpu.utils.tdigest import TDigest

        stats: dict[int, InputStageStats] = {}
        for inp in inputs:
            locs = inp.output_locations()
            k = max(1, inp.spec.output_partitions)
            buckets = [0] * k
            for l in locs:
                if l.output_partition < k:
                    buckets[l.output_partition] += l.stats.num_bytes
            digest = TDigest()
            if buckets:
                import numpy as np

                digest.add_array(np.asarray(buckets, dtype=np.float64))
            stats[inp.stage_id] = InputStageStats(
                stage_id=inp.stage_id,
                total_rows=sum(l.stats.num_rows for l in locs),
                total_bytes=sum(l.stats.num_bytes for l in locs),
                bucket_bytes=buckets,
                broadcast=inp.spec.broadcast,
                bytes_digest=digest,
            )
        unconsumed = not self.output_links.get(stage.spec.stage_id)
        plan, new_parts, report = apply_aqe(
            plan, stats, self.config, stage.spec.partitions,
            stage_unconsumed=unconsumed,
        )
        stage.resolved_plan = plan
        stage.skew_report = report
        if new_parts is not None and new_parts != stage.spec.partitions:
            stage.pending = list(range(new_parts))
            stage.effective_partitions = new_parts
        stage.state = StageState.RESOLVED
        self._maybe_verify(f"stage {stage.stage_id} resolution")

    def _build_reader(self, inp: ExecutionStage) -> ShuffleReaderExec:
        # deterministic location order: completed.values() is task-ARRIVAL
        # order, which varies run to run (and between two evaluations of
        # the same subtree in one query, e.g. a CTE referenced twice).
        # Float aggregation is order-sensitive, so downstream merges must
        # see a stable order or q15-style self-equality comparisons break.
        locs = sorted(
            inp.output_locations(),
            key=lambda l: (l.output_partition, l.map_partition, l.path),
        )
        k = inp.spec.output_partitions
        by_output: list[list[PartitionLocation]] = [[] for _ in range(max(1, k))]
        for l in locs:
            by_output[l.output_partition].append(l)
        schema = inp.spec.plan.input.df_schema
        reader = ShuffleReaderExec(schema, by_output, broadcast=inp.spec.broadcast)
        reader.source_stage_id = inp.stage_id  # AQE stats lookup tag
        return reader

    def _maybe_verify(self, context: str) -> None:
        """Re-check DAG invariants after a rewrite, failing the job rather
        than executing a corrupt graph. Gated on ballista.debug.plan.verify."""
        from ballista_tpu.config import DEBUG_PLAN_VERIFY

        if not bool(self.config.get(DEBUG_PLAN_VERIFY)):
            return
        from ballista_tpu.analysis.plan_check import verify_graph

        violations = verify_graph(self)
        if violations:
            detail = "; ".join(x.render() for x in violations)
            log.error("plan verification failed after %s: %s", context, detail)
            self._fail_job(f"plan verification failed after {context}: {detail}")

    def _fail_job(self, error: str) -> None:
        self.status = JobState.FAILED
        self.error = error
        self.ended_at = time.time()

    def cancel(self) -> list[RunningTask]:
        with self._lock:
            self.status = JobState.CANCELLED
            self.ended_at = time.time()
            out = []
            for s in self.stages.values():
                self.cancelled_tasks.extend(
                    (t.executor_id, t.task_id, s.stage_id) for t in s.running.values()
                )
                out.extend(s.running.values())
                s.running.clear()
                s.pending.clear()
            return out

    # ------------------------------------------------------------------

    def reset_stages_on_lost_executor(self, executor_id: str) -> int:
        """Roll back running tasks on the executor and rerun successful
        stages whose shuffle outputs lived there (ResultLost recompute)."""
        with self._lock:
            if self.status is not JobState.RUNNING:
                return 0
            affected = 0
            lost_output_stages: set[int] = set()
            for stage in self.stages.values():
                # running tasks on the lost executor → back to pending
                dead = [t for t in stage.running.values() if t.executor_id == executor_id]
                for t in dead:
                    stage.running.pop(t.task_id, None)
                    self._unlink_rival(stage, t)
                    self._repend_uncovered(stage, t.partitions)
                    affected += 1
                # successful outputs on the lost executor → stage rerun
                if stage.state is StageState.SUCCESSFUL and any(
                    l.executor_id == executor_id for l in stage.output_locations()
                ):
                    lost_output_stages.add(stage.stage_id)
            for sid in lost_output_stages:
                self._rerun_stage_tree(sid)
                affected += 1
            return affected

    def _rerun_stage_tree(self, stage_id: int, cause: str = "") -> None:
        """Rerun a successful stage; downstream stages that already consumed
        it roll back to unresolved. MAX_STAGE_ATTEMPTS bounds the recompute
        loop; when the budget dies to corruption, the job failure says so —
        persistent checksum mismatches mean bad hardware (or a bad writer),
        and an unbounded rerun would never converge."""
        stage = self.stages[stage_id]
        if stage.attempt + 1 > MAX_STAGE_ATTEMPTS:
            if cause == "corruption":
                self._fail_job(
                    f"stage {stage_id} exceeded {MAX_STAGE_ATTEMPTS} attempts: "
                    "repeated shuffle data corruption (checksum mismatches "
                    "survived refetch and recompute — suspect failing disks "
                    "on the serving executors; see corruption strikes in "
                    "/api/executors)")
            else:
                self._fail_job(f"stage {stage_id} exceeded {MAX_STAGE_ATTEMPTS} attempts")
            return
        stage.reset_for_retry()
        # try re-resolving immediately (inputs may still be intact)
        self._try_resolve(stage)
        for out_id in self.output_links.get(stage_id, []):
            out = self.stages[out_id]
            if out.state in (StageState.RESOLVED, StageState.RUNNING, StageState.SUCCESSFUL):
                out.reset_for_retry()

    # ------------------------------------------------------------------

    def job_status(self) -> dict:
        with self._lock:
            final = self.stages.get(self.final_stage_id)
            done = sum(1 for s in self.stages.values() if s.state is StageState.SUCCESSFUL)
            out = {
                "job_id": self.job_id,
                "job_name": self.job_name,
                "state": self.status.value,
                "error": self.error,
                "completed_stages": done,
                "total_stages": len(self.stages),
                "queued_at": self.queued_at,
                "ended_at": self.ended_at,
            }
            if final is not None:
                out["schema"] = final.spec.plan.input.df_schema
            if self.status is JobState.SUCCESSFUL and final is not None:
                out["partitions"] = final.output_locations()
            if getattr(self, "inline_result", None) is not None:
                # an incremental render attached the served table — clients
                # take it over the raw stage partitions (accumulator state)
                out["inline_result"] = self.inline_result
                out["partitions"] = []
            return out

    def display(self) -> str:
        with self._lock:
            lines = [f"Job {self.job_id} [{self.status.value}]"]
            for sid in sorted(self.stages):
                s = self.stages[sid]
                lines.append(
                    f"  stage {sid}: {s.state.value} attempt={s.attempt} "
                    f"pending={len(s.pending)} running={len(s.running)} done={len(s.completed)}"
                )
            return "\n".join(lines)

    # -- externalization (reference: ExecutionGraph proto, ballista.proto:185;
    #    enables JobState persistence / scheduler fail-over) -----------------

    def to_proto(self):
        from ballista_tpu.proto import pb
        from ballista_tpu.serde import encode_location, encode_plan

        with self._lock:
            out = pb.ExecutionGraphProto(
                job_id=self.job_id, job_name=self.job_name,
                session_id=self.session_id, status=self.status.value,
            )
            for k, v in self.config.to_key_value_pairs():
                out.settings.add(key=k, value=v)
            for sid in sorted(self.stages):
                s = self.stages[sid]
                sp = out.stages.add()
                sp.stage_id = sid
                sp.state = s.state.value
                sp.partitions = s.spec.partitions
                sp.attempt = s.attempt
                sp.plan.CopyFrom(encode_plan(s.spec.plan))
                sp.output_links.extend(self.output_links.get(sid, []))
                for l in s.output_locations():
                    sp.completed.append(encode_location(l))
            return out

    @classmethod
    def from_proto(cls, proto, config: BallistaConfig | None = None) -> "ExecutionGraph":
        """Rebuild a graph from its externalized form. Successful stages keep
        their completed locations; anything mid-flight restarts (the durable
        unit is the materialized shuffle output, SURVEY.md §5)."""
        from ballista_tpu.scheduler.planner import QueryStage
        from ballista_tpu.serde import decode_location, decode_plan

        if config is None and proto.settings:
            # recovery must resume under the job's session settings, not
            # defaults (task slicing / AQE thresholds would silently change)
            config = BallistaConfig.from_key_value_pairs(
                [(kv.key, kv.value) for kv in proto.settings]
            )
        from ballista_tpu.ops.tpu.mesh_stage import contains_mesh_exchange
        from ballista_tpu.scheduler.planner import _find_input_stages
        from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

        plans: dict[int, object] = {sp.stage_id: decode_plan(sp.plan) for sp in proto.stages}
        # the proto has no per-stage flags; the plans themselves are the
        # durable record. A stage is a broadcast producer iff some consumer
        # reads it through a broadcast leaf — without this a recovered
        # broadcast stage would be read partition-wise and lose rows.
        broadcast_ids: set[int] = set()
        for plan in plans.values():
            def walk(n):
                if isinstance(n, UnresolvedShuffleExec) and n.broadcast:
                    broadcast_ids.add(n.stage_id)
                for c in n.children():
                    walk(c)
            walk(plan)
        stages = []
        links: dict[int, list[int]] = {}
        for sp in proto.stages:
            plan = plans[sp.stage_id]
            stages.append(
                QueryStage(
                    stage_id=sp.stage_id, plan=plan,
                    partitions=sp.partitions,
                    output_partitions=plan.output_partitions or sp.partitions,
                    input_stage_ids=_find_input_stages(plan),
                    broadcast=sp.stage_id in broadcast_ids,
                    # a recovered mesh stage must keep its single-task shape
                    mesh=contains_mesh_exchange(plan),
                )
            )
            links[sp.stage_id] = list(sp.output_links)
        g = cls(proto.job_id, proto.job_name, proto.session_id, stages, config)
        g.status = JobState(proto.status) if proto.status else JobState.RUNNING
        for sp in proto.stages:
            if sp.state == "successful":
                st = g.stages[sp.stage_id]
                for lp in sp.completed:
                    loc = decode_location(lp)
                    st.completed.setdefault(loc.map_partition, []).append(loc)
                st.pending = []
                st.state = StageState.SUCCESSFUL
                st.attempt = sp.attempt
        # re-resolve downstream stages from recovered outputs
        for st in g.stages.values():
            g._try_resolve(st)
        return g
