"""Pluggable job-state persistence + ownership (scheduler fail-over).

Rebuild of the `JobState` trait (reference: scheduler/src/cluster/mod.rs:283)
with the ownership events stubbed there made real: graphs are externalized
through `ExecutionGraph.to_proto` at every stage completion and terminal
transition, and a restarting (or standby) scheduler `recover()`s them —
successful stages keep their materialized shuffle outputs (the durable
unit, SURVEY.md §5), anything mid-flight recomputes.

`FileJobState` is the reference's memory-only backend taken one step
further: a directory of `{job_id}.graph` protos plus `{job_id}.owner`
ownership markers (JobAcquired/JobReleased, cluster/mod.rs:221). Every
ownership read-check-write runs under a per-job flock, so acquire, lease
takeover, and release are mutually atomic; a scheduler taking over a live
owner's jobs passes `force=True` (operator decision), expired leases are
adopted without it.
"""

from __future__ import annotations

import logging
import os
import threading

from ballista_tpu.config import BallistaConfig
from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

log = logging.getLogger(__name__)

# checkpoint framing: MAGIC + 4-byte little-endian CRC32 of the payload +
# the serialized graph proto. Files without the magic are legacy raw protos
# (still loadable); files WITH it get verified on recover, so a torn write
# or flipped bit is skipped with a WARN instead of adopted as truth.
GRAPH_MAGIC = b"BGR1"


def _frame_graph(payload: bytes) -> bytes:
    import struct
    import zlib

    return GRAPH_MAGIC + struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _unframe_graph(raw: bytes) -> bytes:
    """Payload of a framed checkpoint (verifying its CRC), or the input
    unchanged when it predates framing. Raises ValueError on a checksum
    mismatch or truncated header."""
    import struct
    import zlib

    if not raw.startswith(GRAPH_MAGIC):
        return raw
    if len(raw) < len(GRAPH_MAGIC) + 4:
        raise ValueError("truncated graph checkpoint header")
    (expected,) = struct.unpack_from("<I", raw, len(GRAPH_MAGIC))
    payload = raw[len(GRAPH_MAGIC) + 4:]
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != expected:
        raise ValueError(
            f"graph checkpoint CRC mismatch: {actual:08x} != {expected:08x}")
    return payload


class JobStateStore:
    """Trait: persist/recover job graphs and arbitrate ownership."""

    def save_graph(self, graph: ExecutionGraph) -> None:  # noqa: ARG002
        return

    def remove_job(self, job_id: str) -> None:  # noqa: ARG002
        return

    def list_jobs(self) -> list[str]:
        return []

    def load_graph(self, job_id: str, config: BallistaConfig | None = None):
        return None

    def acquire(self, job_id: str, scheduler_id: str, force: bool = False) -> bool:  # noqa: ARG002
        return True

    def release(self, job_id: str, scheduler_id: str) -> None:  # noqa: ARG002
        return


class InMemoryJobState(JobStateStore):
    """The reference's default: nothing survives the process."""


class FileJobState(JobStateStore):
    # a live owner refreshes its markers on every checkpoint; an owner file
    # untouched for longer than this is considered dead and may be adopted
    # without --force (the lease expiry the reference stubs)
    LEASE_S = 600.0

    def __init__(self, state_dir: str, lease_s: float | None = None,
                 fsync: bool = False):
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        # per-job locks, not one global one: checkpoints of DIFFERENT jobs
        # are independent files (mkstemp + os.replace is already safe across
        # jobs), and sharded scheduler event loops checkpoint concurrently —
        # a global lock would serialize every shard's file I/O again
        self._locks_guard = threading.Lock()
        self._job_locks: dict[str, threading.Lock] = {}
        self.lease_s = self.LEASE_S if lease_s is None else lease_s
        self.fsync = fsync

    def _job_lock(self, job_id: str) -> threading.Lock:
        with self._locks_guard:
            lock = self._job_locks.get(job_id)
            if lock is None:
                lock = self._job_locks[job_id] = threading.Lock()
            return lock

    def _graph_path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.graph")

    def _owner_path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.owner")

    def save_graph(self, graph: ExecutionGraph) -> None:
        import tempfile

        data = _frame_graph(graph.to_proto().SerializeToString())
        path = self._graph_path(graph.job_id)
        # refresh the ownership lease alongside the checkpoint
        try:
            os.utime(self._owner_path(graph.job_id))
        except OSError:
            pass
        with self._job_lock(graph.job_id):
            # unique tmp name: two scheduler PROCESSES (forced takeover with
            # a partitioned old owner) must never interleave into one file
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers never see a torn graph

    def remove_job(self, job_id: str) -> None:
        with self._job_lock(job_id):
            for p in (self._graph_path(job_id), self._owner_path(job_id)):
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass
        with self._locks_guard:
            self._job_locks.pop(job_id, None)

    def list_jobs(self) -> list[str]:
        try:
            return sorted(
                f[: -len(".graph")] for f in os.listdir(self.dir) if f.endswith(".graph")
            )
        except FileNotFoundError:
            return []

    def load_graph(self, job_id: str, config: BallistaConfig | None = None):
        from ballista_tpu.proto import pb

        path = self._graph_path(job_id)
        try:
            with open(path, "rb") as f:
                raw = f.read()
            # CRC check first: a torn/corrupt checkpoint that still parses
            # as SOME proto is the dangerous case — garbage adopted as truth
            proto = pb.ExecutionGraphProto.FromString(_unframe_graph(raw))
            return ExecutionGraph.from_proto(proto, config)
        except FileNotFoundError:
            return None
        except ValueError as e:
            log.warning("skipping torn/corrupt job checkpoint %s: %s", path, e)
            try:
                os.replace(path, path + ".bad")
            except OSError:
                pass
            return None
        except Exception as e:  # noqa: BLE001 — corrupt/skewed graph must
            # never make the scheduler unbootable: quarantine and continue
            log.warning("quarantining unreadable job graph %s: %s", path, e)
            try:
                os.replace(path, path + ".bad")
            except OSError:
                pass
            return None

    def _owner_lock(self, job_id: str):
        """Exclusive flock on a per-job sidecar file. EVERY ownership
        read-check-write (fresh acquire, takeover, release) runs under it —
        a takeover's os.replace must not clobber a concurrent fresh acquire,
        and two standbys adopting one expired lease must see each other."""
        import contextlib
        import fcntl

        lock_path = self._owner_path(job_id) + ".lock"

        @contextlib.contextmanager
        def held():
            with open(lock_path, "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)

        return held()

    def acquire(self, job_id: str, scheduler_id: str, force: bool = False) -> bool:
        import tempfile
        import time as _time

        path = self._owner_path(job_id)
        with self._owner_lock(job_id):
            try:
                with open(path) as f:
                    owner = f.read().strip()
                stale = (_time.time() - os.path.getmtime(path)) > self.lease_s
            except OSError:
                owner, stale = "", True
            if owner == scheduler_id:
                return True
            if owner and not stale and not force:
                return False
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".owner.tmp")
            with os.fdopen(fd, "w") as f:
                f.write(scheduler_id)
            os.replace(tmp, path)  # JobAcquired
            if owner:
                log.info(
                    "job %s ownership %s from %s to %s", job_id,
                    "forced" if force else "adopted (lease expired)", owner, scheduler_id,
                )
            return True

    def release(self, job_id: str, scheduler_id: str) -> None:
        path = self._owner_path(job_id)
        with self._owner_lock(job_id):
            try:
                with open(path) as f:
                    if f.read().strip() != scheduler_id:
                        return
                os.remove(path)  # JobReleased
            except FileNotFoundError:
                pass
