"""Executor registry: registration, heartbeats, slots, expiry.

Rebuild of ExecutorManager (scheduler/src/state/executor_manager.rs:62) +
the in-memory ClusterState slot accounting (cluster/memory.rs:54):
executors register with vcore counts (gated on wire-protocol version),
heartbeat on a cadence, get expired after `executor_timeout_seconds`
without one, and tasks bind against free slots under a distribution
policy (bias = fill one executor first; round-robin = spread).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ballista_tpu.errors import GeneralError
from ballista_tpu.executor.executor import ExecutorMetadata
from ballista_tpu.version import WIRE_PROTOCOL_VERSION

DEFAULT_EXECUTOR_TIMEOUT_S = 180


@dataclass
class ExecutorSlot:
    metadata: ExecutorMetadata
    total_slots: int
    free_slots: int
    last_seen: float = field(default_factory=time.time)
    terminating: bool = False


class ExecutorManager:
    def __init__(self, task_distribution: str = "bias", timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S):
        self.executors: dict[str, ExecutorSlot] = {}
        self.task_distribution = task_distribution
        self.timeout_s = timeout_s
        self._lock = threading.RLock()
        self._rr = 0

    def register(self, metadata: ExecutorMetadata) -> None:
        if metadata.wire_version != WIRE_PROTOCOL_VERSION:
            raise GeneralError(
                f"wire protocol mismatch: executor {metadata.wire_version!r} != "
                f"scheduler {WIRE_PROTOCOL_VERSION!r}"
            )
        with self._lock:
            self.executors[metadata.id] = ExecutorSlot(metadata, metadata.vcores, metadata.vcores)

    def heartbeat(self, executor_id: str) -> bool:
        """Returns False if the executor is unknown (must re-register)."""
        with self._lock:
            ex = self.executors.get(executor_id)
            if ex is None:
                return False
            ex.last_seen = time.time()
            return True

    def deregister(self, executor_id: str) -> None:
        with self._lock:
            self.executors.pop(executor_id, None)

    def get(self, executor_id: str) -> ExecutorSlot | None:
        with self._lock:
            return self.executors.get(executor_id)

    def alive_executors(self) -> list[ExecutorSlot]:
        with self._lock:
            return [e for e in self.executors.values() if not e.terminating]

    def expire_dead(self) -> list[str]:
        """Executors without a heartbeat for timeout_s (config.rs:310)."""
        now = time.time()
        with self._lock:
            dead = [eid for eid, e in self.executors.items() if now - e.last_seen > self.timeout_s]
            for eid in dead:
                del self.executors[eid]
            return dead

    # -- slot binding --------------------------------------------------------

    def reserve_slots(self, n: int) -> list[tuple[str, int]]:
        """Reserve up to n slots; returns [(executor_id, count)]."""
        with self._lock:
            avail = [e for e in self.executors.values() if e.free_slots > 0 and not e.terminating]
            if not avail:
                return []
            out: list[tuple[str, int]] = []
            if self.task_distribution == "bias":
                avail.sort(key=lambda e: -e.free_slots)
                for e in avail:
                    take = min(e.free_slots, n)
                    if take:
                        e.free_slots -= take
                        out.append((e.metadata.id, take))
                        n -= take
                    if n <= 0:
                        break
            else:  # round-robin
                i = self._rr
                while n > 0 and any(e.free_slots > 0 for e in avail):
                    e = avail[i % len(avail)]
                    if e.free_slots > 0:
                        e.free_slots -= 1
                        if out and out[-1][0] == e.metadata.id:
                            out[-1] = (e.metadata.id, out[-1][1] + 1)
                        else:
                            out.append((e.metadata.id, 1))
                        n -= 1
                    i += 1
                self._rr = i
            return out

    def free_slot(self, executor_id: str, n: int = 1) -> None:
        with self._lock:
            e = self.executors.get(executor_id)
            if e is not None:
                e.free_slots = min(e.total_slots, e.free_slots + n)

    def take_slots(self, executor_id: str, n: int) -> int:
        """Reserve up to n slots on ONE executor (pull-mode handout: the
        poller's self-reported free capacity must still debit the shared
        ledger, or a mixed push+pull cluster double-books)."""
        with self._lock:
            e = self.executors.get(executor_id)
            if e is None or e.terminating:
                return 0
            take = max(0, min(e.free_slots, n))
            e.free_slots -= take
            return take

    @staticmethod
    def _ring_point(s: str) -> int:
        import hashlib

        return int.from_bytes(hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")

    def _ring(self) -> tuple[list[int], list[str]]:
        """Sorted virtual-node ring, cached until executor membership
        changes (rebuilding + rehashing per pick would be O(tasks ×
        executors log executors) per offer)."""
        ids = tuple(sorted(
            e.metadata.id for e in self.executors.values() if not e.terminating
        ))
        cached = getattr(self, "_ring_cache", None)
        if cached is not None and cached[0] == ids:
            return cached[1], cached[2]
        ring: list[tuple[int, str]] = []
        for eid in ids:
            for v in range(8):  # virtual nodes smooth the distribution
                ring.append((self._ring_point(f"{eid}#{v}"), eid))
        ring.sort()
        points = [p for p, _ in ring]
        owners = [e for _, e in ring]
        self._ring_cache = (ids, points, owners)
        return points, owners

    def pick_consistent(self, key: str) -> str | None:
        """Consistent-hash task placement (reference: TaskDistributionPolicy
        consistent-hash, scheduler/src/config.rs:92 / cluster/mod.rs:626):
        the key (job/stage/partition identity) maps onto a ring of virtual
        executor nodes; the first ring node at-or-after the key's point
        with a free slot wins, so placement is sticky across offers (cache
        affinity) yet spills to neighbors under load."""
        import bisect

        with self._lock:
            points, owners = self._ring()
            if not points:
                return None
            i = bisect.bisect_left(points, self._ring_point(key)) % len(points)
            for off in range(len(points)):
                eid = owners[(i + off) % len(points)]
                e = self.executors.get(eid)
                if e is not None and not e.terminating and e.free_slots > 0:
                    e.free_slots -= 1
                    return eid
            return None
