"""Executor registry: registration, heartbeats, slots, expiry.

Rebuild of ExecutorManager (scheduler/src/state/executor_manager.rs:62) +
the in-memory ClusterState slot accounting (cluster/memory.rs:54):
executors register with vcore counts (gated on wire-protocol version),
heartbeat on a cadence, get expired after `executor_timeout_seconds`
without one, and tasks bind against free slots under a distribution
policy (bias = fill one executor first; round-robin = spread).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ballista_tpu.errors import GeneralError
from ballista_tpu.executor.executor import ExecutorMetadata
from ballista_tpu.version import WIRE_PROTOCOL_VERSION

DEFAULT_EXECUTOR_TIMEOUT_S = 180


@dataclass
class ExecutorSlot:
    metadata: ExecutorMetadata
    total_slots: int
    free_slots: int
    last_seen: float = field(default_factory=time.time)
    terminating: bool = False
    # -- health scoring / quarantine (decayed fail/success counters) -------
    health_state: str = "healthy"  # healthy | quarantined | probation
    health_fail: float = 0.0
    health_succ: float = 0.0
    health_updated: float = field(default_factory=time.time)
    quarantined_at: float = 0.0
    probe_inflight: bool = False
    # -- overload signals piggybacked on heartbeats -------------------------
    memory_pressure: float = 0.0  # 0..1+ fraction of pool capacity reserved
    pool_overcommitted_bytes: float = 0.0
    pressure_rejections: float = 0.0
    # -- shuffle integrity ---------------------------------------------------
    # strikes: times a READER escalated persistent corruption of bytes THIS
    # executor served (its disk is the suspect). Gauges below are the
    # executor's own heartbeat-reported reader-side counters.
    corruption_strikes: int = 0
    checksum_failures: float = 0.0
    corruption_retries: float = 0.0
    # -- direct-dispatch leases (heartbeat-reported gauges) ------------------
    active_leases: float = 0.0
    direct_dispatch_tasks: float = 0.0
    # -- out-of-core TPU execution (hbm.py demotion-ladder gauges) -----------
    tpu_hbm_budget_bytes: float = 0.0
    tpu_hbm_spill_bytes: float = 0.0
    tpu_hbm_spill_events: float = 0.0
    tpu_grace_splits: float = 0.0
    # -- lifecycle & storage (docs/lifecycle.md) -----------------------------
    lifecycle_state: str = "active"  # active | draining (drained = ledger)
    disk_used_bytes: float = 0.0
    disk_free_bytes: float = 0.0
    # executor self-reports it is past its high watermark: placement skips
    # it until the next heartbeat says otherwise
    disk_rejecting: float = 0.0
    disk_rejections: float = 0.0
    migrated_partitions: float = 0.0
    migrated_bytes: float = 0.0
    gc_reclaimed_bytes: float = 0.0
    orphans_reclaimed: float = 0.0

    @property
    def failure_rate(self) -> float:
        total = self.health_fail + self.health_succ
        return self.health_fail / total if total > 0 else 0.0

    @property
    def schedulable(self) -> bool:
        """Eligible for regular offers: quarantined/probation executors only
        receive work through the probe gate; a disk past its high watermark
        would reject the task at admission anyway, so placement skips it."""
        return (not self.terminating and self.health_state == "healthy"
                and self.disk_rejecting < 1.0)


class ExecutorManager:
    def __init__(self, task_distribution: str = "bias", timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S,
                 quarantine_threshold: float = 0.5, quarantine_min_events: float = 4.0,
                 health_half_life_s: float = 60.0, probe_backoff_s: float = 10.0):
        self.executors: dict[str, ExecutorSlot] = {}
        self.task_distribution = task_distribution
        self.timeout_s = timeout_s
        # flaky-executor quarantine knobs (cluster-scoped, not per-session):
        # an executor whose decayed failure rate crosses the threshold (with
        # at least min_events of decayed evidence) stops receiving offers
        # until a probe task succeeds. threshold <= 0 disables quarantine.
        self.quarantine_threshold = quarantine_threshold
        self.quarantine_min_events = quarantine_min_events
        self.health_half_life_s = max(1e-3, health_half_life_s)
        self.probe_backoff_s = probe_backoff_s
        self._lock = threading.RLock()
        self._rr = 0
        # terminal lifecycle ledger (docs/lifecycle.md): executors that
        # left THROUGH the drain state machine, with their handoff
        # counters — the quarantine/health ledger's "drained" terminal
        # reason. Bounded: a long-lived scheduler sees endless rolling
        # restarts.
        from ballista_tpu.utils.lru import LruDict

        self.drained = LruDict(max_entries=256)

    def register(self, metadata: ExecutorMetadata) -> None:
        if metadata.wire_version != WIRE_PROTOCOL_VERSION:
            raise GeneralError(
                f"wire protocol mismatch: executor {metadata.wire_version!r} != "
                f"scheduler {WIRE_PROTOCOL_VERSION!r}"
            )
        with self._lock:
            self.executors[metadata.id] = ExecutorSlot(metadata, metadata.vcores, metadata.vcores)

    def heartbeat(self, executor_id: str, metrics: dict[str, float] | None = None) -> bool:
        """Returns False if the executor is unknown (must re-register).
        `metrics` carries the overload signals piggybacked on
        HeartBeatParams.metrics (memory_pressure, pool_overcommitted_bytes,
        pressure_rejections — see proto/ballista.proto)."""
        with self._lock:
            ex = self.executors.get(executor_id)
            if ex is None:
                return False
            ex.last_seen = time.time()
            if metrics:
                ex.memory_pressure = float(metrics.get("memory_pressure", ex.memory_pressure))
                ex.pool_overcommitted_bytes = float(
                    metrics.get("pool_overcommitted_bytes", ex.pool_overcommitted_bytes))
                ex.pressure_rejections = float(
                    metrics.get("pressure_rejections", ex.pressure_rejections))
                ex.checksum_failures = float(
                    metrics.get("checksum_failures", ex.checksum_failures))
                ex.corruption_retries = float(
                    metrics.get("corruption_retries", ex.corruption_retries))
                ex.active_leases = float(
                    metrics.get("active_leases", ex.active_leases))
                ex.direct_dispatch_tasks = float(
                    metrics.get("direct_dispatch_tasks", ex.direct_dispatch_tasks))
                ex.tpu_hbm_budget_bytes = float(
                    metrics.get("tpu_hbm_budget_bytes", ex.tpu_hbm_budget_bytes))
                ex.tpu_hbm_spill_bytes = float(
                    metrics.get("tpu_hbm_spill_bytes", ex.tpu_hbm_spill_bytes))
                ex.tpu_hbm_spill_events = float(
                    metrics.get("tpu_hbm_spill_events", ex.tpu_hbm_spill_events))
                ex.tpu_grace_splits = float(
                    metrics.get("tpu_grace_splits", ex.tpu_grace_splits))
                ex.disk_used_bytes = float(
                    metrics.get("disk_used_bytes", ex.disk_used_bytes))
                ex.disk_free_bytes = float(
                    metrics.get("disk_free_bytes", ex.disk_free_bytes))
                ex.disk_rejecting = float(
                    metrics.get("disk_rejecting", ex.disk_rejecting))
                ex.disk_rejections = float(
                    metrics.get("disk_rejections", ex.disk_rejections))
                ex.migrated_partitions = float(
                    metrics.get("migrated_partitions", ex.migrated_partitions))
                ex.migrated_bytes = float(
                    metrics.get("migrated_bytes", ex.migrated_bytes))
                ex.gc_reclaimed_bytes = float(
                    metrics.get("gc_reclaimed_bytes", ex.gc_reclaimed_bytes))
                ex.orphans_reclaimed = float(
                    metrics.get("orphans_reclaimed", ex.orphans_reclaimed))
                if float(metrics.get("lifecycle_draining", 0.0)) >= 1.0:
                    # executor-initiated (SIGTERM) drain announcement; the
                    # scheduler's drain path notices and runs the handoff
                    if ex.lifecycle_state == "active":
                        ex.lifecycle_state = "draining"
            return True

    def aggregate_pressure(self) -> float:
        """Cluster-wide memory-pressure signal for the overload state
        machine: the mean of live executors' pool saturation (mean, not
        max — one hot executor is the quarantine/retry machinery's
        problem; admission control reacts to fleet-wide saturation)."""
        with self._lock:
            live = [e for e in self.executors.values() if not e.terminating]
            if not live:
                return 0.0
            return sum(e.memory_pressure for e in live) / len(live)

    def deregister(self, executor_id: str) -> None:
        with self._lock:
            self.executors.pop(executor_id, None)

    # -- lifecycle: drain state machine (docs/lifecycle.md) -------------------

    def begin_drain(self, executor_id: str) -> bool:
        """Move an executor into the draining state: no new offers bind to
        it (terminating), but it stays registered so in-flight tasks report
        and its map outputs stay addressable for the handoff. Returns False
        for an unknown executor, and idempotently True for one already
        draining."""
        with self._lock:
            e = self.executors.get(executor_id)
            if e is None:
                return False
            e.terminating = True
            e.lifecycle_state = "draining"
            return True

    def mark_drained(self, executor_id: str, migrated_partitions: int = 0,
                     migrated_bytes: int = 0, reason: str = "drained") -> None:
        """Terminal drain transition: deregister the executor and record it
        in the bounded drained ledger with its handoff counters."""
        with self._lock:
            self.executors.pop(executor_id, None)
            self.drained[executor_id] = {
                "state": "drained",
                "reason": reason,
                "at": time.time(),
                "migrated_partitions": int(migrated_partitions),
                "migrated_bytes": int(migrated_bytes),
            }

    def drained_snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {eid: dict(info) for eid, info in self.drained.items()}

    def get(self, executor_id: str) -> ExecutorSlot | None:
        with self._lock:
            return self.executors.get(executor_id)

    def alive_executors(self) -> list[ExecutorSlot]:
        with self._lock:
            return [e for e in self.executors.values() if not e.terminating]

    def expire_dead(self) -> list[str]:
        """Executors without a heartbeat for timeout_s (config.rs:310)."""
        now = time.time()
        with self._lock:
            dead = [eid for eid, e in self.executors.items() if now - e.last_seen > self.timeout_s]
            for eid in dead:
                del self.executors[eid]
            return dead

    # -- slot binding --------------------------------------------------------

    def reserve_slots(self, n: int) -> list[tuple[str, int]]:
        """Reserve up to n slots; returns [(executor_id, count)]."""
        with self._lock:
            avail = [e for e in self.executors.values() if e.free_slots > 0 and e.schedulable]
            if not avail:
                return []
            out: list[tuple[str, int]] = []
            if self.task_distribution == "bias":
                avail.sort(key=lambda e: -e.free_slots)
                for e in avail:
                    take = min(e.free_slots, n)
                    if take:
                        e.free_slots -= take
                        out.append((e.metadata.id, take))
                        n -= take
                    if n <= 0:
                        break
            else:  # round-robin
                i = self._rr
                while n > 0 and any(e.free_slots > 0 for e in avail):
                    e = avail[i % len(avail)]
                    if e.free_slots > 0:
                        e.free_slots -= 1
                        if out and out[-1][0] == e.metadata.id:
                            out[-1] = (e.metadata.id, out[-1][1] + 1)
                        else:
                            out.append((e.metadata.id, 1))
                        n -= 1
                    i += 1
                self._rr = i
            return out

    def free_slot(self, executor_id: str, n: int = 1) -> None:
        with self._lock:
            e = self.executors.get(executor_id)
            if e is not None:
                e.free_slots = min(e.total_slots, e.free_slots + n)

    def free_slot_count(self) -> int:
        """Fleet-wide schedulable free slots (cross-shard revive gate)."""
        with self._lock:
            return sum(e.free_slots for e in self.executors.values()
                       if e.schedulable and not e.terminating)

    def take_slots(self, executor_id: str, n: int) -> int:
        """Reserve up to n slots on ONE executor (pull-mode handout: the
        poller's self-reported free capacity must still debit the shared
        ledger, or a mixed push+pull cluster double-books)."""
        with self._lock:
            e = self.executors.get(executor_id)
            if e is None or e.terminating:
                return 0
            if e.health_state != "healthy":
                # pull-mode probe gate: a quarantined poller past its backoff
                # gets EXACTLY ONE task to prove itself with
                if (e.health_state == "quarantined" and not e.probe_inflight
                        and e.free_slots > 0
                        and time.time() - e.quarantined_at >= self.probe_backoff_s):
                    e.health_state = "probation"
                    e.probe_inflight = True
                    e.free_slots -= 1
                    return 1
                return 0
            take = max(0, min(e.free_slots, n))
            e.free_slots -= take
            return take

    @staticmethod
    def _ring_point(s: str) -> int:
        import hashlib

        return int.from_bytes(hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")

    def _ring(self) -> tuple[list[int], list[str]]:
        """Sorted virtual-node ring, cached until executor membership
        changes (rebuilding + rehashing per pick would be O(tasks ×
        executors log executors) per offer)."""
        ids = tuple(sorted(
            e.metadata.id for e in self.executors.values() if not e.terminating
        ))
        cached = getattr(self, "_ring_cache", None)
        if cached is not None and cached[0] == ids:
            return cached[1], cached[2]
        ring: list[tuple[int, str]] = []
        for eid in ids:
            for v in range(8):  # virtual nodes smooth the distribution
                ring.append((self._ring_point(f"{eid}#{v}"), eid))
        ring.sort()
        points = [p for p, _ in ring]
        owners = [e for _, e in ring]
        self._ring_cache = (ids, points, owners)
        return points, owners

    def pick_consistent(self, key: str) -> str | None:
        """Consistent-hash task placement (reference: TaskDistributionPolicy
        consistent-hash, scheduler/src/config.rs:92 / cluster/mod.rs:626):
        the key (job/stage/partition identity) maps onto a ring of virtual
        executor nodes; the first ring node at-or-after the key's point
        with a free slot wins, so placement is sticky across offers (cache
        affinity) yet spills to neighbors under load."""
        import bisect

        with self._lock:
            points, owners = self._ring()
            if not points:
                return None
            i = bisect.bisect_left(points, self._ring_point(key)) % len(points)
            for off in range(len(points)):
                eid = owners[(i + off) % len(points)]
                e = self.executors.get(eid)
                if e is not None and e.schedulable and e.free_slots > 0:
                    e.free_slots -= 1
                    return eid
            return None

    def reserve_one_avoiding(self, avoid: set[str]) -> str | None:
        """Reserve a single slot on any healthy executor NOT in `avoid` —
        speculative duplicates must land away from the straggling one."""
        with self._lock:
            cands = [e for e in self.executors.values()
                     if e.free_slots > 0 and e.schedulable and e.metadata.id not in avoid]
            if not cands:
                return None
            cands.sort(key=lambda e: -e.free_slots)
            cands[0].free_slots -= 1
            return cands[0].metadata.id

    # -- health scoring / quarantine ----------------------------------------

    def _decay_locked(self, e: ExecutorSlot, now: float) -> None:
        dt = now - e.health_updated
        if dt > 0:
            f = 0.5 ** (dt / self.health_half_life_s)
            e.health_fail *= f
            e.health_succ *= f
            e.health_updated = now

    def record_task_result(self, executor_id: str, ok: bool,
                           timed_out: bool = False) -> str | None:
        """Fold one task outcome into the executor's decayed health score.
        Returns a state transition ('quarantined' | 'readmitted' |
        'requarantined') when one happened, else None. Cancelled tasks
        should NOT be reported here (they say nothing about health)."""
        now = time.time()
        with self._lock:
            e = self.executors.get(executor_id)
            if e is None:
                return None
            self._decay_locked(e, now)
            if ok:
                e.health_succ += 1.0
            else:
                # timeouts weigh like failures: a straggling executor that
                # never fails outright is exactly what quarantine is for
                e.health_fail += 1.0
            if e.health_state == "probation":
                e.probe_inflight = False
                if ok:
                    e.health_state = "healthy"
                    # the probe clears the slate: old decayed failures must
                    # not instantly re-trip the threshold on the next miss
                    e.health_fail = 0.0
                    e.health_succ = 1.0
                    return "readmitted"
                e.health_state = "quarantined"
                e.quarantined_at = now
                return "requarantined"
            if e.health_state == "healthy" and not ok and self.quarantine_threshold > 0:
                total = e.health_fail + e.health_succ
                # epsilon: decay over the microseconds between back-to-back
                # events leaves N outcomes summing to N - ~1e-7, which must
                # still count as N against the min-events floor
                if total + 1e-6 >= self.quarantine_min_events and e.failure_rate >= self.quarantine_threshold:
                    e.health_state = "quarantined"
                    e.quarantined_at = now
                    return "quarantined"
            return None

    def record_corruption_strike(self, executor_id: str) -> str | None:
        """A reader escalated persistent corruption of bytes this executor
        SERVED: count the strike and fold it into the decayed health score
        as a failure — enough strikes quarantine the executor exactly like
        repeated task failures (its disk is suspect, not its compute, but
        either way its outputs can't be trusted). Returns the health-state
        transition when one happened."""
        with self._lock:
            e = self.executors.get(executor_id)
            if e is None:
                return None
            e.corruption_strikes += 1
            # RLock: safe to delegate the scoring under the held lock
            return self.record_task_result(executor_id, ok=False)

    def probe_reservations(self, now: float | None = None) -> list[tuple[str, int]]:
        """Quarantined executors past their backoff get one probation slot
        each; the caller must bind a real task to it (or cancel_probe)."""
        now = time.time() if now is None else now
        out: list[tuple[str, int]] = []
        with self._lock:
            for e in self.executors.values():
                if (e.health_state == "quarantined" and not e.terminating
                        and not e.probe_inflight and e.free_slots > 0
                        and now - e.quarantined_at >= self.probe_backoff_s):
                    e.health_state = "probation"
                    e.probe_inflight = True
                    e.free_slots -= 1
                    out.append((e.metadata.id, 1))
        return out

    def cancel_probe(self, executor_id: str) -> None:
        """No task could be bound to the probe slot: put the executor back
        in quarantine (same quarantined_at, so the next offer retries)."""
        with self._lock:
            e = self.executors.get(executor_id)
            if e is not None and e.health_state == "probation" and e.probe_inflight:
                e.health_state = "quarantined"
                e.probe_inflight = False
                e.free_slots = min(e.total_slots, e.free_slots + 1)

    def probes_due(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        with self._lock:
            return any(
                e.health_state == "quarantined" and not e.probe_inflight
                and now - e.quarantined_at >= self.probe_backoff_s
                for e in self.executors.values()
            )

    def quarantined_count(self) -> int:
        with self._lock:
            return sum(1 for e in self.executors.values()
                       if e.health_state in ("quarantined", "probation"))

    def health_snapshot(self) -> dict[str, dict]:
        now = time.time()
        with self._lock:
            out = {}
            for eid, e in self.executors.items():
                self._decay_locked(e, now)
                out[eid] = {
                    "state": e.health_state,
                    "failure_rate": round(e.failure_rate, 4),
                    "decayed_failures": round(e.health_fail, 3),
                    "decayed_successes": round(e.health_succ, 3),
                    "memory_pressure": round(e.memory_pressure, 4),
                    "pool_overcommitted_bytes": int(e.pool_overcommitted_bytes),
                    "pressure_rejections": int(e.pressure_rejections),
                    "corruption_strikes": e.corruption_strikes,
                    "active_leases": int(e.active_leases),
                    "direct_dispatch_tasks": int(e.direct_dispatch_tasks),
                    "checksum_failures": int(e.checksum_failures),
                    "corruption_retries": int(e.corruption_retries),
                    "hbm_budget_bytes": int(e.tpu_hbm_budget_bytes),
                    "hbm_spill_bytes": int(e.tpu_hbm_spill_bytes),
                    "hbm_spill_events": int(e.tpu_hbm_spill_events),
                    "grace_splits": int(e.tpu_grace_splits),
                    "lifecycle_state": e.lifecycle_state,
                    "disk_used_bytes": int(e.disk_used_bytes),
                    "disk_free_bytes": int(e.disk_free_bytes),
                    "disk_rejecting": bool(e.disk_rejecting >= 1.0),
                    "disk_rejections": int(e.disk_rejections),
                    "migrated_partitions": int(e.migrated_partitions),
                    "migrated_bytes": int(e.migrated_bytes),
                    "gc_reclaimed_bytes": int(e.gc_reclaimed_bytes),
                    "orphans_reclaimed": int(e.orphans_reclaimed),
                }
            return out
