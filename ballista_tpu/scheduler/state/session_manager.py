"""Session registry: session id → config → planning context.

Rebuild of SessionManager (scheduler/src/state/session_manager.rs:29).
Table registrations travel inside the session config as
`ballista.catalog.table.<name> = <parquet path>` key/value pairs (the
reference ships ListingTable definitions inside the logical-plan proto;
same information, different envelope).
"""

from __future__ import annotations

import threading

from ballista_tpu.config import BallistaConfig
from ballista_tpu.ids import SessionId, new_session_id

CATALOG_PREFIX = "ballista.catalog.table."


class SessionManager:
    def __init__(self):
        self.sessions: dict[str, BallistaConfig] = {}
        self._lock = threading.Lock()
        # serving tier hook: called with the table name whenever a session
        # update registers a table or points an existing one at a new path
        # (bumps the table-version vector → cached results stop matching)
        self.on_catalog_change = None

    def create_or_update(self, settings: list[tuple[str, str]], session_id: str = "") -> str:
        cfg = BallistaConfig.from_key_value_pairs(settings, scrub_restricted=True)
        sid = session_id or str(new_session_id())
        with self._lock:
            old = self.sessions.get(sid)
            self.sessions[sid] = cfg
        if self.on_catalog_change is not None:
            for k, v in cfg.to_key_value_pairs():
                if k.startswith(CATALOG_PREFIX) and (old is None or old.get(k) != v):
                    self.on_catalog_change(k[len(CATALOG_PREFIX):])
        return sid

    def get(self, session_id: str) -> BallistaConfig | None:
        with self._lock:
            return self.sessions.get(session_id)

    def remove(self, session_id: str) -> None:
        with self._lock:
            self.sessions.pop(session_id, None)

    def create_planning_context(self, session_id: str):
        """SessionContext (local mode) wired with the session's config and
        catalog registrations (create_datafusion_context analog)."""
        from ballista_tpu.client.context import SessionContext

        cfg = self.get(session_id) or BallistaConfig()
        ctx = SessionContext(cfg.copy(), mode="local")
        for k, v in cfg.to_key_value_pairs():
            if k.startswith(CATALOG_PREFIX):
                ctx.register_parquet(k[len(CATALOG_PREFIX):], v)
        return ctx
