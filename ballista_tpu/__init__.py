"""ballista_tpu — a TPU-native distributed SQL query execution engine.

A ground-up rebuild of the capabilities of Apache DataFusion Ballista
(reference: /root/reference, surveyed in SURVEY.md) designed TPU-first:

- The control plane (scheduler, execution-graph state machine, task manager,
  cluster state) mirrors the reference's architecture
  (ballista/scheduler/src/*) because that shape is forced by the problem:
  stages split at shuffle boundaries, one task per partition (slice),
  materialized shuffle outputs as the durable retry unit.
- The data plane exchanges Arrow IPC partitions over Arrow Flight
  (reference: ballista/executor/src/flight_service.rs), with local
  fast-path reads and an 8 MiB raw-block transport action.
- The per-partition operator engine — the seam the reference exposes as
  `ExecutionEngine` (ballista/executor/src/execution_engine.rs:51) — has two
  implementations selected by `ballista.executor.engine`:
    * "cpu":  Arrow-native operators over pyarrow.compute (the parity
              baseline, standing in for the reference's DataFusion engine).
    * "tpu":  query stages compiled to XLA via JAX — columns are
              dictionary/int64-encoded into fixed shape-bucketed device
              tensors, and filter/project/hash-aggregate/hash-join/hash-
              repartition run as jitted kernels on the MXU/VPU, with
              per-subtree fallback to the cpu engine.

Nothing in this package is a translation of the reference's Rust; the
reference defines WHAT (features, wire behavior, test strategy), this
package decides HOW for TPU hardware.
"""

from ballista_tpu.version import BALLISTA_VERSION, WIRE_PROTOCOL_VERSION

__version__ = BALLISTA_VERSION

__all__ = ["BALLISTA_VERSION", "WIRE_PROTOCOL_VERSION"]
