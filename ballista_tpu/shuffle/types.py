"""Shuffle partition identity and location types.

Mirrors the reference's PartitionId / PartitionLocation / PartitionStats
(ballista/core/src/serde/scheduler/mod.rs): a completed map task publishes
one location per output partition; downstream ShuffleReaderExec leaves
consume lists of these.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class PartitionId:
    job_id: str
    stage_id: int
    partition_id: int


@dataclass
class PartitionStats:
    num_rows: int = 0
    num_batches: int = 0
    num_bytes: int = 0


@dataclass
class PartitionLocation:
    """Where one (stage, output_partition) shuffle result lives."""

    map_partition: int
    job_id: str
    stage_id: int
    output_partition: int
    executor_id: str = ""
    host: str = ""
    flight_port: int = 0
    path: str = ""  # data file path on the executor
    layout: str = "hash"  # hash | sort
    stats: PartitionStats = field(default_factory=PartitionStats)

    @property
    def addr(self) -> str:
        """Data-plane dial address of the owning executor — the coalescing
        key: locations sharing an addr can ship in one fetch RPC."""
        return f"{self.host}:{self.flight_port}"
