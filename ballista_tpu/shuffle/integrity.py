"""Shuffle integrity primitives: block checksums + corruption accounting.

The shuffle contract carries a per-partition checksum from the writer all
the way to the reader (SURVEY.md §5: the materialized shuffle output is
the durable unit, so IT is what must be verifiable): the writer records a
checksum over each output partition's stored byte range as it writes, the
Flight servers ship the recorded value in their per-location headers, and
clients/local readers recompute it over the received bytes BEFORE handing
them to the Arrow decoder. A flipped bit therefore surfaces as a typed
DataCorrupted instead of an opaque decoder crash — or, silently worse,
wrong query results.

Checksum values are small self-describing strings, `"<algo>:<8 hex>"`:

- ``c32`` — CRC32C (Castagnoli), used when an accelerated implementation
  is importable (the `crc32c`/`google_crc32c` wheels);
- ``z32`` — CRC-32 (ISO-HDLC) via zlib, the always-available C-speed
  fallback.

The algo travels WITH the value, so a verifier always recomputes with the
writer's algorithm — mixed fleets never turn an algo skew into a false
corruption signal. A pure-Python CRC32C exists only to verify `c32:`
values written by a host that had the accelerated wheel; writers never
pick an algorithm they'd compute slowly.
"""

from __future__ import annotations

import threading
import zlib

# -- algorithm selection -----------------------------------------------------

try:  # accelerated CRC32C if the wheel is present (never a hard dep)
    import crc32c as _crc32c_mod  # type: ignore

    def _crc32c(data, crc: int = 0) -> int:
        return _crc32c_mod.crc32c(data, crc)

    _HAVE_FAST_C32 = True
except ImportError:
    try:
        import google_crc32c as _gcrc32c_mod  # type: ignore

        def _crc32c(data, crc: int = 0) -> int:
            return _gcrc32c_mod.extend(crc, bytes(data))

        _HAVE_FAST_C32 = True
    except ImportError:
        _HAVE_FAST_C32 = False
        _C32_TABLE: list[int] | None = None

        def _c32_table() -> list[int]:
            global _C32_TABLE
            if _C32_TABLE is None:
                poly = 0x82F63B78  # Castagnoli, reflected
                tbl = []
                for i in range(256):
                    c = i
                    for _ in range(8):
                        c = (c >> 1) ^ poly if c & 1 else c >> 1
                    tbl.append(c)
                _C32_TABLE = tbl
            return _C32_TABLE

        def _crc32c(data, crc: int = 0) -> int:
            # pure-Python verification fallback only — writers on hosts
            # without the accelerated wheel emit z32 (zlib, C speed) instead
            tbl = _c32_table()
            c = crc ^ 0xFFFFFFFF
            for b in memoryview(data).cast("B"):
                c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
            return c ^ 0xFFFFFFFF


DEFAULT_ALGO = "c32" if _HAVE_FAST_C32 else "z32"

_UPDATERS = {
    "c32": _crc32c,
    "z32": lambda data, crc=0: zlib.crc32(data, crc) & 0xFFFFFFFF,
}


class Checksum:
    """Incremental checksum with a self-describing string digest."""

    def __init__(self, algo: str | None = None):
        self.algo = algo or DEFAULT_ALGO
        self._update = _UPDATERS[self.algo]
        self._crc = 0

    def update(self, data) -> None:
        if len(data):
            self._crc = self._update(data, self._crc)

    def reset(self) -> None:
        self._crc = 0

    def digest(self) -> str:
        return f"{self.algo}:{self._crc & 0xFFFFFFFF:08x}"


def checksum_bytes(data, algo: str | None = None) -> str:
    c = Checksum(algo)
    c.update(data)
    return c.digest()


def algo_of(value: str) -> str | None:
    """Algo tag of a stored checksum string; None when unparseable (a
    malformed stored value must read as 'no checksum', not crash serving)."""
    algo, _, rest = value.partition(":")
    return algo if algo in _UPDATERS and rest else None


def verify_or_raise(blocks, expected: str | None, where: str) -> None:
    """Recompute `expected`'s algorithm over the received blocks and raise
    DataCorrupted (with both digests) on mismatch. None or unknown-algo
    expected → unchecked, returns silently."""
    if not expected:
        return
    algo = algo_of(expected)
    if algo is None:
        return
    c = Checksum(algo)
    for b in blocks:
        c.update(memoryview(b))
    actual = c.digest()
    if actual != expected:
        from ballista_tpu.errors import DataCorrupted

        raise DataCorrupted(where, expected, actual)


def verify_blocks(blocks, expected: str) -> bool:
    """Recompute `expected`'s algorithm over a sequence of buffer-protocol
    blocks (pyarrow Buffers, memoryviews, bytes) and compare. An expected
    value with an unknown algo verifies as True — a newer writer's format
    must degrade to 'unchecked', never to a false corruption signal."""
    algo = algo_of(expected)
    if algo is None:
        return True
    c = Checksum(algo)
    for b in blocks:
        c.update(memoryview(b))
    return c.digest() == expected


class ChecksumSink:
    """File-object wrapper that checksums bytes AS THEY ARE WRITTEN
    (per-range: `start_range()` resets the running value so one physical
    file yields one digest per output-partition byte range). Implements
    just enough of the binary-file protocol for pyarrow's IPC writer."""

    closed = False

    def __init__(self, f, enabled: bool = True):
        self._f = f
        self._cs = Checksum() if enabled else None

    def write(self, data) -> int:
        if self._cs is not None:
            self._cs.update(data)
        return self._f.write(data)

    def tell(self) -> int:
        return self._f.tell()

    def flush(self) -> None:
        self._f.flush()

    def writable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return False

    def readable(self) -> bool:
        return False

    def start_range(self) -> None:
        if self._cs is not None:
            self._cs.reset()

    def digest(self) -> str | None:
        return None if self._cs is None else self._cs.digest()


# -- executor-wide corruption accounting -------------------------------------


class IntegrityCounters:
    """Process-wide integrity counters, heartbeat-piggybacked to the
    scheduler (same no-proto-change pattern as the overload gauges) and
    exposed on the executor's /health endpoint."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data = {"checksum_failures": 0, "corruption_retries": 0}

    def add(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._data[key] += n

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._data)

    def reset(self) -> None:
        with self._lock:
            for k in self._data:
                self._data[k] = 0


INTEGRITY = IntegrityCounters()
