"""Shuffle file layout.

hash layout (reference: execution_plans/mod.rs:78):
    {work_dir}/{job_id}/{stage_id}/{output_partition}/data-{task_id}.arrow
    one complete Arrow IPC stream per (map task, output partition)

sort layout (reference: sort_shuffle/index.rs — 2×M files instead of N×M):
    {work_dir}/{job_id}/{stage_id}/data-{map_partition}-{task_id}.arrow
        (K buckets, each byte range a complete IPC stream, sorted by
        partition id; task_id makes the name attempt-unique so speculative
        duplicates never clobber each other)
    {work_dir}/{job_id}/{stage_id}/data-{map_partition}-{task_id}.idx
        (json index: output_partition → [offset, length, rows, bytes]
         + an optional 5th element: the range's checksum string — readers
         that predate it only index [0] and [1], so old and new binaries
         interoperate in both directions)

integrity sidecars: a hash-layout data file's whole-file checksum lives in
`{data}.arrow.crc` (the sort layout stores per-range checksums inside the
index instead). Both are optional — their absence means "unchecked", never
an error, so files written with `ballista.shuffle.checksum.enabled=false`
round-trip unchanged.
"""

from __future__ import annotations

import os


def hash_partition_dir(work_dir: str, job_id: str, stage_id: int, output_partition: int) -> str:
    return os.path.join(work_dir, job_id, str(stage_id), str(output_partition))


def hash_data_path(work_dir: str, job_id: str, stage_id: int, output_partition: int, task_id) -> str:
    return os.path.join(hash_partition_dir(work_dir, job_id, stage_id, output_partition), f"data-{task_id}.arrow")


def sort_data_path(work_dir: str, job_id: str, stage_id: int, map_partition: int,
                   task_id=None) -> str:
    """With task_id, the name is ATTEMPT-unique: concurrent attempts of the
    same map partition (speculation, deadline retries) write disjoint files
    and the winner's paths are the only ones the scheduler commits. The
    reader derives the index name from whatever path it is handed."""
    name = f"data-{map_partition}.arrow" if task_id is None else f"data-{map_partition}-{task_id}.arrow"
    return os.path.join(work_dir, job_id, str(stage_id), name)


def index_path(data_path: str) -> str:
    return data_path[: -len(".arrow")] + ".idx" if data_path.endswith(".arrow") else data_path + ".idx"


def crc_path(data_path: str) -> str:
    """Sidecar holding a hash-layout file's whole-file checksum string."""
    return data_path + ".crc"


def checksum_for(path: str, layout: str, output_partition: int) -> str | None:
    """The stored checksum of one output partition's byte range, or None
    when it was never recorded (pre-checksum writer, knob disabled, or the
    partition is absent). Sort layout: 5th element of the index entry;
    hash layout: the `.crc` sidecar. Never raises — a serving path must
    treat an unreadable checksum as 'unchecked', not as an error."""
    try:
        if is_sort_layout(layout):
            import json

            with open(index_path(path)) as f:
                index = json.load(f)
            entry = index.get(str(output_partition))
            if entry is None or len(entry) < 5 or not isinstance(entry[4], str):
                return None
            return entry[4] or None
        with open(crc_path(path)) as f:
            return f.read().strip() or None
    except (OSError, ValueError):
        return None


def is_sort_layout(layout: str) -> bool:
    return layout == "sort"


def range_for(path: str, layout: str, output_partition: int) -> tuple[int, int] | None:
    """(offset, length) of one output partition's bytes inside `path`, or
    None when the partition is absent from a sort index (empty = contract).
    Hash layout is always the whole file."""
    if not is_sort_layout(layout):
        return 0, os.path.getsize(path)
    import json

    with open(index_path(path)) as f:
        index = json.load(f)
    entry = index.get(str(output_partition))
    if entry is None:
        return None
    return entry[0], entry[1]


def open_range_buffer(path: str, layout: str, output_partition: int,
                      use_mmap: bool = True):
    """One partition's stored IPC bytes as a pyarrow Buffer.

    With mmap (the default) the buffer is a zero-copy slice of a memory
    map — the page cache backs it and the kernel faults pages in as the
    consumer streams, so neither the Flight server nor a local reader ever
    materializes the partition in anonymous memory. The buffer holds a
    reference to the mapping, which stays alive until the last slice drops.
    Returns None for a partition absent from a sort index."""
    import pyarrow as pa

    r = range_for(path, layout, output_partition)
    if r is None:
        return None
    offset, length = r
    size = os.path.getsize(path)
    if offset + length > size:
        # torn write / truncated disk / stale index: a short mmap slice
        # would silently end the IPC stream early — refuse with a typed,
        # retryable error instead (the Flight server surfaces it as
        # unavailable; a local reader's retry ladder escalates it)
        from ballista_tpu.errors import ShortRead

        raise ShortRead(path, offset, length, size)
    if use_mmap:
        mm = pa.memory_map(path)
        mm.seek(offset)
        return mm.read_buffer(length)
    with open(path, "rb") as f:
        f.seek(offset)
        return pa.py_buffer(f.read(length))


def job_dir(work_dir: str, job_id: str) -> str:
    return os.path.join(work_dir, job_id)


def validate_job_id(job_id: str) -> str:
    """Reject job ids that could escape the work dir when joined into a
    filesystem path (data-plane actions take the id from the wire)."""
    if not job_id or job_id in (".", "..") or "/" in job_id or "\\" in job_id or "\x00" in job_id:
        raise ValueError(f"invalid job id {job_id!r}")
    return job_id


def contained_path(work_dir: str, path: str) -> str:
    """Resolve `path` and require it to live under `work_dir`.

    The Flight data plane receives file paths inside tickets (they are the
    location fields a PartitionLocation carries); the server must not trust
    them to stay inside its own shuffle directory — the reference builds
    paths server-side from structured fields for the same reason
    (executor/src/flight_service.rs). Raises PermissionError on escape.
    """
    root = os.path.realpath(work_dir)
    resolved = os.path.realpath(path)
    if resolved != root and not resolved.startswith(root + os.sep):
        raise PermissionError(f"path {path!r} escapes work dir {work_dir!r}")
    return resolved
