"""Shuffle file layout.

hash layout (reference: execution_plans/mod.rs:78):
    {work_dir}/{job_id}/{stage_id}/{output_partition}/data-{task_id}.arrow
    one complete Arrow IPC stream per (map task, output partition)

sort layout (reference: sort_shuffle/index.rs — 2×M files instead of N×M):
    {work_dir}/{job_id}/{stage_id}/data-{map_partition}.arrow   (K buckets,
        each byte range a complete IPC stream, sorted by partition id)
    {work_dir}/{job_id}/{stage_id}/data-{map_partition}.idx     (json index:
        output_partition → [offset, length, rows, bytes])
"""

from __future__ import annotations

import os


def hash_partition_dir(work_dir: str, job_id: str, stage_id: int, output_partition: int) -> str:
    return os.path.join(work_dir, job_id, str(stage_id), str(output_partition))


def hash_data_path(work_dir: str, job_id: str, stage_id: int, output_partition: int, task_id) -> str:
    return os.path.join(hash_partition_dir(work_dir, job_id, stage_id, output_partition), f"data-{task_id}.arrow")


def sort_data_path(work_dir: str, job_id: str, stage_id: int, map_partition: int) -> str:
    return os.path.join(work_dir, job_id, str(stage_id), f"data-{map_partition}.arrow")


def index_path(data_path: str) -> str:
    return data_path[: -len(".arrow")] + ".idx" if data_path.endswith(".arrow") else data_path + ".idx"


def is_sort_layout(layout: str) -> bool:
    return layout == "sort"


def job_dir(work_dir: str, job_id: str) -> str:
    return os.path.join(work_dir, job_id)
