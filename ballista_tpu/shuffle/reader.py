"""Shuffle reader: the leaf of every downstream stage.

Rebuilds ShuffleReaderExec (core/src/execution_plans/shuffle_reader.rs:100):

- local fast path (:818): when the data file is on this host, read it
  directly (sort layout: byte-range via the index file);
- remote fetch (:762): Arrow Flight do_get against the owning executor,
  governed by a semaphore trio — max in-flight requests, max per address,
  in-flight byte budget — with bounded retries; a failed fetch raises
  FetchFailed carrying the map identity so the scheduler can recompute the
  upstream stage (ResultLost);
- broadcast mode (:110): every execute(p) reads ALL upstream partitions
  (build side of a broadcast join).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterator

import pyarrow as pa
import pyarrow.ipc as ipc

from ballista_tpu.config import (
    IO_RETRIES,
    IO_RETRY_WAIT_MS,
    SHUFFLE_BLOCK_TRANSPORT,
    SHUFFLE_CHECKSUM_ENABLED,
    SHUFFLE_FETCH_COALESCE,
    SHUFFLE_MMAP,
    SHUFFLE_READER_FORCE_REMOTE,
    SHUFFLE_READER_MAX_PER_ADDR,
    SHUFFLE_READER_MAX_REQUESTS,
)
from ballista_tpu.errors import DataCorrupted, FetchFailed
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext, _empty_batch
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.shuffle import paths
from ballista_tpu.shuffle.integrity import INTEGRITY, verify_or_raise
from ballista_tpu.shuffle.types import PartitionLocation
from ballista_tpu.utils.lru import LruDict


class ShuffleReaderExec(ExecutionPlan):
    def __init__(self, df_schema: DFSchema, partition_locations: list[list[PartitionLocation]],
                 broadcast: bool = False):
        super().__init__(df_schema)
        self.partition_locations = partition_locations
        self.broadcast = broadcast

    def children(self):
        return []

    def with_children(self, c):
        assert not c
        return self

    def output_partition_count(self) -> int:
        if self.broadcast:
            # every partition reads everything; expose ONE so consumers
            # (CollectLeft builds) pull the full input exactly once
            return 1
        return max(1, len(self.partition_locations))

    def node_str(self) -> str:
        n = sum(len(l) for l in self.partition_locations)
        b = " broadcast" if self.broadcast else ""
        return f"ShuffleReaderExec: partitions={len(self.partition_locations)} locations={n}{b}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(self._run(partition, ctx))

    def _run(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.broadcast:
            locs = [l for part in self.partition_locations for l in part]
        else:
            locs = self.partition_locations[partition] if partition < len(self.partition_locations) else []
        force_remote = bool(ctx.config.get(SHUFFLE_READER_FORCE_REMOTE))
        produced = False
        gov = _governor(ctx)
        ctr = _FetchCounters()
        t0 = time.perf_counter_ns()
        if len(locs) > 1:
            stream = _stream_locations(locs, ctx, force_remote, gov, counters=ctr)
        else:
            stream = (b for loc in locs for b in fetch_partition(
                loc, ctx, force_remote=force_remote, governor=gov, counters=ctr))
        try:
            for b in stream:
                if b.num_rows:
                    if not produced:
                        self.metrics.extra["time_to_first_batch_ns"] = (
                            time.perf_counter_ns() - t0)
                    produced = True
                    yield b
        finally:
            # data-plane accounting for EXPLAIN ANALYZE / the scheduler's
            # task metrics: RPCs issued and bytes moved by provenance
            self.metrics.extra.update(ctr.snapshot())
        if not produced:
            yield _empty_batch(self.schema())


def split_location_ranges(locs: list[PartitionLocation], k: int) -> list[list[PartitionLocation]]:
    """Split one reduce partition's location list into k contiguous,
    byte-balanced sub-ranges — the unit AQE's skew defense hands to each
    partition-slice task.

    Contiguity over the scheduler's canonical (map_partition, path) order
    is the whole point: each slice reads a distinct sub-range of the hot
    partition's map outputs, so concatenating the slices in range order
    reproduces the unsplit read byte-for-byte (cover, no overlap, order —
    the postconditions plan_check's skew rule verifies). The greedy
    boundary walk balances bytes without ever reordering; k is clamped to
    the location count because a single map output is never subdivided."""
    k = max(1, min(int(k), len(locs)))
    if k <= 1:
        return [list(locs)]
    total = sum(max(0, l.stats.num_bytes) for l in locs)
    out: list[list[PartitionLocation]] = []
    cur: list[PartitionLocation] = []
    cur_bytes = 0
    done_bytes = 0
    for i, l in enumerate(locs):
        cur.append(l)
        cur_bytes += max(0, l.stats.num_bytes)
        locs_left = len(locs) - i - 1
        slices_after = k - len(out) - 1  # slices still owed after closing cur
        if slices_after <= 0:
            continue
        ideal = (total - done_bytes) / (slices_after + 1)
        if cur_bytes >= ideal or locs_left == slices_after:
            out.append(cur)
            done_bytes += cur_bytes
            cur, cur_bytes = [], 0
    if cur:
        out.append(cur)
    return out


class UnresolvedShuffleExec(ExecutionPlan):
    """Placeholder leaf: 'stage N's output, not yet materialized'
    (reference: unresolved_shuffle.rs:35). The scheduler swaps it for a
    ShuffleReaderExec when the upstream stage completes."""

    def __init__(self, stage_id: int, df_schema: DFSchema, output_partitions: int,
                 broadcast: bool = False):
        super().__init__(df_schema)
        self.stage_id = stage_id
        self.output_partitions = output_partitions
        self.broadcast = broadcast

    def children(self):
        return []

    def with_children(self, c):
        assert not c
        return self

    def output_partition_count(self) -> int:
        if self.broadcast:
            return 1
        return max(1, self.output_partitions)

    def node_str(self) -> str:
        b = " broadcast" if self.broadcast else ""
        return f"UnresolvedShuffleExec: stage={self.stage_id} out={self.output_partitions}{b}"

    def execute(self, partition: int, ctx: TaskContext):
        raise RuntimeError(f"UnresolvedShuffleExec(stage={self.stage_id}) is not executable")


# -- fetch machinery ---------------------------------------------------------


def _note_corruption(counters: "_FetchCounters | None", retried: bool) -> None:
    """Account one checksum failure (and, when it triggers an in-place
    refetch, one corruption retry) in both the per-execute counters and
    the process-wide INTEGRITY gauges the heartbeat ships."""
    INTEGRITY.add("checksum_failures")
    if counters:
        counters.add("checksum_failures")
    if retried:
        INTEGRITY.add("corruption_retries")
        if counters:
            counters.add("corruption_retries")


class _FetchCounters:
    """Per-execute data-plane accounting, mutated from fetch threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data = {"fetch_rpcs": 0, "bytes_fetched_remote": 0, "bytes_read_local": 0,
                      "checksum_failures": 0, "corruption_retries": 0}

    def add(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._data[key] += n

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._data)


class FetchGovernor:
    """Reduce-side flow control (reference's 3-semaphore governor,
    shuffle_reader.rs:778): total request slots + per-address slots + an
    in-flight byte budget (fetches declare their expected size from the
    partition stats; oversized singletons are admitted alone rather than
    deadlocked)."""

    def __init__(self, max_requests: int, max_per_addr: int, max_bytes: int = 256 * 1024 * 1024):
        self.total = threading.Semaphore(max_requests)
        self.per_addr: dict[str, threading.Semaphore] = {}
        self.max_per_addr = max_per_addr
        self.max_bytes = max_bytes
        self.inflight_bytes = 0
        self._lock = threading.Lock()
        self._bytes_free = threading.Condition(self._lock)

    def acquire(self, addr: str, nbytes: int = 0):
        with self._lock:
            sem = self.per_addr.setdefault(addr, threading.Semaphore(self.max_per_addr))
        self.total.acquire()
        sem.acquire()
        nbytes = min(nbytes, self.max_bytes)  # oversized fetches admit alone
        with self._bytes_free:
            # strict notify-driven accounting: every release() notifies under
            # the lock (and runs in a finally), so no timed re-poll is needed
            while self.inflight_bytes > 0 and self.inflight_bytes + nbytes > self.max_bytes:
                self._bytes_free.wait()
            self.inflight_bytes += nbytes
        return (sem, nbytes)

    def release(self, addr: str, token):
        sem, nbytes = token
        with self._bytes_free:
            self.inflight_bytes -= nbytes
            self._bytes_free.notify_all()
        sem.release()
        self.total.release()


_GOV_CACHE = LruDict(max_entries=64)


def _governor(ctx: TaskContext) -> FetchGovernor:
    from ballista_tpu.config import SHUFFLE_READER_MAX_BYTES

    # limits-derived key (id() aliases recycled addresses across configs)
    key = (
        int(ctx.config.get(SHUFFLE_READER_MAX_REQUESTS)),
        int(ctx.config.get(SHUFFLE_READER_MAX_PER_ADDR)),
        int(ctx.config.get(SHUFFLE_READER_MAX_BYTES)),
    )
    g = _GOV_CACHE.get(key)
    if g is not None:
        return g
    # setdefault is atomic: concurrent reduce tasks with the same limits
    # must share one governor or the global budgets mean nothing
    return _GOV_CACHE.setdefault(key, FetchGovernor(*key))


def _fetch_units(locs: list[PartitionLocation], remote: list[int],
                 budget: int, coalesce: bool) -> list[list[int]]:
    """Group remote location indices into fetch units: with coalescing, one
    unit per executor address (split so a unit's byte estimate stays under
    the reader budget) — a reduce task then issues ≈one RPC per executor
    instead of one per map output. Units are ordered by their first location
    index so the scheduler's prefix matches consumption order."""
    if not coalesce:
        return [[i] for i in remote]
    by_addr: dict[str, list[list[int]]] = {}
    for i in remote:
        addr = locs[i].addr
        chunks = by_addr.setdefault(addr, [[]])
        est = min(locs[i].stats.num_bytes, budget)
        cur_est = sum(min(locs[j].stats.num_bytes, budget) for j in chunks[-1])
        if chunks[-1] and cur_est + est > budget:
            chunks.append([])
        chunks[-1].append(i)
    units = [c for chunks in by_addr.values() for c in chunks]
    units.sort(key=lambda u: u[0])
    return units


def _stream_locations(locs: list[PartitionLocation], ctx: TaskContext,
                      force_remote: bool, gov: "FetchGovernor | None",
                      counters: "_FetchCounters | None" = None):
    """Bounded multi-location streaming merge (the reference's concurrent
    reduce-side reader, sort_shuffle/multi_stream_reader.rs).

    Remote locations prefetch concurrently in UNITS — with coalescing on,
    all of one executor's map outputs fetch in a single coalesced RPC —
    while LOCAL locations stream lazily inline when their turn comes (no
    buffering at all). Yield order stays location order, so order-sensitive
    float merges are deterministic. Fetched-but-unconsumed bytes are capped
    by the reader byte budget: a unit's result counts against the window
    until the CONSUMER drains it, and new units are only admitted under the
    cap — except that the unit holding the location the consumer is about
    to block on is always admitted (by-address grouping interleaves units
    with consumption order, so a hard cap could park the needed unit behind
    buffered bytes that can never drain; the budget is a soft bound there,
    like the oversized-singleton admission). Per-location buffering is
    retained — a retry around a half-yielded Flight stream would duplicate
    rows (shuffle_reader.rs:975)."""
    import concurrent.futures as fut
    from ballista_tpu.config import SHUFFLE_READER_MAX_BYTES

    budget = int(ctx.config.get(SHUFFLE_READER_MAX_BYTES))
    remote = [
        i for i, loc in enumerate(locs)
        if force_remote or not (loc.path and os.path.exists(loc.path))
    ]
    remote_set = set(remote)
    if not remote:
        for loc in locs:
            yield from fetch_partition(loc, ctx, force_remote=force_remote,
                                       governor=gov, counters=counters)
        return

    coalesce = (bool(ctx.config.get(SHUFFLE_FETCH_COALESCE))
                and bool(ctx.config.get(SHUFFLE_BLOCK_TRANSPORT)))
    units = _fetch_units(locs, remote, budget, coalesce)
    unit_of = {i: u for u, unit in enumerate(units) for i in unit}

    def est_loc(i: int) -> int:
        return min(locs[i].stats.num_bytes, budget)

    cond = threading.Condition()
    results: dict[int, list | Exception] = {}
    state = {"buffered": 0, "next": 0}

    def publish(i: int, out) -> None:
        with cond:
            results[i] = out
            if not isinstance(out, Exception):
                # replace the stats estimate with actual bytes
                state["buffered"] += sum(b.nbytes for b in out) - est_loc(i)
            cond.notify_all()

    def fetch(i: int) -> None:
        try:
            out: list | Exception = list(
                fetch_partition(locs[i], ctx, force_remote=force_remote,
                                governor=gov, counters=counters))
        except Exception as e:  # noqa: BLE001 — surfaced at the consumer in order
            out = e
        publish(i, out)

    def fetch_unit(unit: list[int]) -> None:
        if len(unit) == 1:
            fetch(unit[0])
            return
        fallback = _fetch_unit_coalesced(unit, locs, ctx, gov, publish, counters)
        for i in fallback:
            fetch(i)

    pool = fut.ThreadPoolExecutor(
        max_workers=min(len(units), int(ctx.config.get(SHUFFLE_READER_MAX_REQUESTS))),
        thread_name_prefix="shuffle-fetch",
    )

    def submit_next_locked() -> None:
        u = state["next"]
        state["buffered"] += sum(est_loc(i) for i in units[u])
        state["next"] += 1
        pool.submit(fetch_unit, units[u])

    def top_up_locked() -> None:
        while state["next"] < len(units):
            est = sum(est_loc(i) for i in units[state["next"]])
            if state["buffered"] > 0 and state["buffered"] + est > budget:
                break
            submit_next_locked()

    try:
        with cond:
            top_up_locked()
        for i, loc in enumerate(locs):
            if i in remote_set:
                with cond:
                    # progress guarantee: the unit this wait depends on (and
                    # every unit before it) must be in flight
                    while state["next"] <= unit_of[i]:
                        submit_next_locked()
                    while i not in results:
                        cond.wait()
                    batches = results.pop(i)
                if isinstance(batches, Exception):
                    raise batches
                yield from batches
                with cond:
                    state["buffered"] -= sum(b.nbytes for b in batches)
                    top_up_locked()
            else:
                # local: stream straight off disk, nothing buffered
                yield from fetch_partition(loc, ctx, force_remote=False,
                                           governor=gov, counters=counters)
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def _fetch_unit_coalesced(unit: list[int], locs: list[PartitionLocation],
                          ctx: TaskContext, gov: "FetchGovernor | None",
                          publish, counters: "_FetchCounters | None") -> list[int]:
    """Fetch one executor's map outputs in a single coalesced RPC,
    publishing each location's batches as it completes. Retries re-request
    only the incomplete tail (completed locations were already published —
    exactly-once per location). After retries the FetchFailed carries the
    identity of the map output the last stream died on. Returns the indices
    to fall back on per-location (server without the coalesced action)."""
    from ballista_tpu.flight.client import (
        CoalesceUnsupported,
        FetchStreamError,
        fetch_partitions_flight,
    )

    retries = int(ctx.config.get(IO_RETRIES))
    wait_ms = int(ctx.config.get(IO_RETRY_WAIT_MS))
    addr = locs[unit[0]].addr
    remaining = list(unit)
    failed = remaining[0]
    last: BaseException | None = None
    # locations that already burned their one free corruption refetch:
    # a second checksum failure on the same map output is persistent
    # (bad stored bytes), so escalate instead of spinning
    corrupted: set[int] = set()
    attempt = 0
    while attempt <= retries:
        sub = list(remaining)
        token = gov.acquire(addr, sum(locs[i].stats.num_bytes for i in sub)) if gov else None
        corrupt_retry = False
        try:
            if counters:
                counters.add("fetch_rpcs")
            try:
                for j, batches, nbytes in fetch_partitions_flight(
                        [locs[i] for i in sub], ctx):
                    if counters:
                        counters.add("bytes_fetched_remote", sum(b.nbytes for b in batches))
                    publish(sub[j], batches)
                    remaining.remove(sub[j])
                return []
            except CoalesceUnsupported:
                return remaining
            except FetchStreamError as e:
                failed = sub[min(e.loc_index, len(sub) - 1)]
                last = e.cause
                if isinstance(e.cause, DataCorrupted):
                    first = failed not in corrupted
                    _note_corruption(counters, retried=first)
                    if not first:
                        break  # persistent corruption: escalate now
                    corrupted.add(failed)
                    corrupt_retry = True
        finally:
            if gov:
                gov.release(addr, token)
        if corrupt_retry:
            # retry ONCE in place, immediately and without consuming the
            # generic IO budget — in-transit corruption heals on refetch
            continue
        time.sleep(wait_ms * (attempt + 1) / 1000.0)
        attempt += 1
    floc = locs[failed]
    cause = "corruption" if isinstance(last, DataCorrupted) else ""
    err = FetchFailed(floc.executor_id, floc.job_id, floc.stage_id,
                      floc.map_partition, str(last), cause=cause)
    for i in remaining:
        publish(i, err)
    return []


def fetch_partition(loc: PartitionLocation, ctx: TaskContext, force_remote: bool = False,
                    governor: FetchGovernor | None = None,
                    counters: _FetchCounters | None = None) -> Iterator[pa.RecordBatch]:
    local = not force_remote and loc.path and os.path.exists(loc.path)
    if local:
        verify = bool(ctx.config.get(SHUFFLE_CHECKSUM_ENABLED))
        corrupt_seen = False
        while True:
            try:
                served = 0
                for b in read_local_partition(
                        loc, use_mmap=bool(ctx.config.get(SHUFFLE_MMAP)), verify=verify):
                    served += b.nbytes
                    yield b
                if counters:
                    counters.add("bytes_read_local", served)
                return
            except DataCorrupted as e:
                # verification happens BEFORE the first batch decodes, so a
                # retry here cannot duplicate rows. One free re-read (a torn
                # page-cache read can heal); a second failure means the
                # stored bytes are bad — same escalation as a remote fetch,
                # blaming this executor's own disk
                first = not corrupt_seen
                _note_corruption(counters, retried=first)
                if not first:
                    raise FetchFailed(loc.executor_id, loc.job_id, loc.stage_id,
                                      loc.map_partition, str(e), cause="corruption") from e
                corrupt_seen = True
    retries = int(ctx.config.get(IO_RETRIES))
    wait_ms = int(ctx.config.get(IO_RETRY_WAIT_MS))
    addr = loc.addr
    last: Exception | None = None
    corrupt_seen = False
    attempt = 0
    while attempt <= retries:
        token = governor.acquire(addr, loc.stats.num_bytes) if governor else None
        try:
            from ballista_tpu.flight.client import fetch_partition_flight

            if counters:
                counters.add("fetch_rpcs")
            # buffer the WHOLE partition before yielding anything: in
            # decoded (do_get) mode the flight client streams batches
            # incrementally, so a retry around a half-yielded stream would
            # duplicate the first attempt's rows downstream (the
            # reference's fetch_partition_buffered, shuffle_reader.rs:975)
            batches = list(fetch_partition_flight(loc, ctx))
        except DataCorrupted as e:
            last = e
            first = not corrupt_seen
            _note_corruption(counters, retried=first)
            if not first:
                break  # persistent corruption: escalate with blame
            corrupt_seen = True
            continue  # retry ONCE in place — no IO-budget charge, no sleep
        except Exception as e:  # noqa: BLE001 — retried, then surfaced as FetchFailed
            last = e
            time.sleep(wait_ms * (attempt + 1) / 1000.0)
            attempt += 1
            continue
        finally:
            if governor:
                governor.release(addr, token)
        if counters:
            counters.add("bytes_fetched_remote", sum(b.nbytes for b in batches))
        yield from batches
        return
    cause = "corruption" if isinstance(last, DataCorrupted) else ""
    raise FetchFailed(loc.executor_id, loc.job_id, loc.stage_id, loc.map_partition,
                      str(last), cause=cause)


def read_local_partition(loc: PartitionLocation, use_mmap: bool = True,
                         verify: bool = False) -> Iterator[pa.RecordBatch]:
    if verify:
        expected = paths.checksum_for(loc.path, loc.layout, loc.output_partition)
        if expected is not None:
            # buffered (NOT mmap) read: the verified copy is byte-for-byte
            # the copy the decoder consumes — with a live mapping the kernel
            # could re-fault a page from a bad disk between verify and
            # decode. Verification completes BEFORE the first yield, so the
            # caller's retry-once cannot duplicate rows.
            buf = paths.open_range_buffer(loc.path, loc.layout, loc.output_partition,
                                          use_mmap=False)
            if buf is None or buf.size == 0:
                return
            verify_or_raise([buf], expected, f"{loc.path}#p{loc.output_partition}")
            yield from ipc.open_stream(pa.BufferReader(buf))
            return
    if not use_mmap and not paths.is_sort_layout(loc.layout):
        # hash layout without mmap: stream straight off the open file
        with open(loc.path, "rb") as f:
            yield from ipc.open_stream(f)
        return
    # zero-copy: batches decode directly out of the page cache; the buffer
    # keeps the mapping alive for exactly as long as any batch references it
    buf = paths.open_range_buffer(loc.path, loc.layout, loc.output_partition,
                                  use_mmap=use_mmap)
    if buf is None or buf.size == 0:
        return
    yield from ipc.open_stream(pa.BufferReader(buf))
