"""Shuffle reader: the leaf of every downstream stage.

Rebuilds ShuffleReaderExec (core/src/execution_plans/shuffle_reader.rs:100):

- local fast path (:818): when the data file is on this host, read it
  directly (sort layout: byte-range via the index file);
- remote fetch (:762): Arrow Flight do_get against the owning executor,
  governed by a semaphore trio — max in-flight requests, max per address,
  in-flight byte budget — with bounded retries; a failed fetch raises
  FetchFailed carrying the map identity so the scheduler can recompute the
  upstream stage (ResultLost);
- broadcast mode (:110): every execute(p) reads ALL upstream partitions
  (build side of a broadcast join).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.ipc as ipc

from ballista_tpu.config import (
    IO_RETRIES,
    IO_RETRY_WAIT_MS,
    SHUFFLE_READER_FORCE_REMOTE,
    SHUFFLE_READER_MAX_PER_ADDR,
    SHUFFLE_READER_MAX_REQUESTS,
)
from ballista_tpu.errors import FetchFailed
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext, _empty_batch
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.shuffle import paths
from ballista_tpu.shuffle.types import PartitionLocation


class ShuffleReaderExec(ExecutionPlan):
    def __init__(self, df_schema: DFSchema, partition_locations: list[list[PartitionLocation]],
                 broadcast: bool = False):
        super().__init__(df_schema)
        self.partition_locations = partition_locations
        self.broadcast = broadcast

    def children(self):
        return []

    def with_children(self, c):
        assert not c
        return self

    def output_partition_count(self) -> int:
        if self.broadcast:
            # every partition reads everything; expose ONE so consumers
            # (CollectLeft builds) pull the full input exactly once
            return 1
        return max(1, len(self.partition_locations))

    def node_str(self) -> str:
        n = sum(len(l) for l in self.partition_locations)
        b = " broadcast" if self.broadcast else ""
        return f"ShuffleReaderExec: partitions={len(self.partition_locations)} locations={n}{b}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(self._run(partition, ctx))

    def _run(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.broadcast:
            locs = [l for part in self.partition_locations for l in part]
        else:
            locs = self.partition_locations[partition] if partition < len(self.partition_locations) else []
        force_remote = bool(ctx.config.get(SHUFFLE_READER_FORCE_REMOTE))
        produced = False
        gov = _governor(ctx)
        if len(locs) > 1:
            for b in _stream_locations(locs, ctx, force_remote, gov):
                if b.num_rows:
                    produced = True
                    yield b
        else:
            for loc in locs:
                for b in fetch_partition(loc, ctx, force_remote=force_remote, governor=gov):
                    if b.num_rows:
                        produced = True
                        yield b
        if not produced:
            yield _empty_batch(self.schema())


class UnresolvedShuffleExec(ExecutionPlan):
    """Placeholder leaf: 'stage N's output, not yet materialized'
    (reference: unresolved_shuffle.rs:35). The scheduler swaps it for a
    ShuffleReaderExec when the upstream stage completes."""

    def __init__(self, stage_id: int, df_schema: DFSchema, output_partitions: int,
                 broadcast: bool = False):
        super().__init__(df_schema)
        self.stage_id = stage_id
        self.output_partitions = output_partitions
        self.broadcast = broadcast

    def children(self):
        return []

    def with_children(self, c):
        assert not c
        return self

    def output_partition_count(self) -> int:
        if self.broadcast:
            return 1
        return max(1, self.output_partitions)

    def node_str(self) -> str:
        b = " broadcast" if self.broadcast else ""
        return f"UnresolvedShuffleExec: stage={self.stage_id} out={self.output_partitions}{b}"

    def execute(self, partition: int, ctx: TaskContext):
        raise RuntimeError(f"UnresolvedShuffleExec(stage={self.stage_id}) is not executable")


# -- fetch machinery ---------------------------------------------------------


class FetchGovernor:
    """Reduce-side flow control (reference's 3-semaphore governor,
    shuffle_reader.rs:778): total request slots + per-address slots + an
    in-flight byte budget (fetches declare their expected size from the
    partition stats; oversized singletons are admitted alone rather than
    deadlocked)."""

    def __init__(self, max_requests: int, max_per_addr: int, max_bytes: int = 256 * 1024 * 1024):
        self.total = threading.Semaphore(max_requests)
        self.per_addr: dict[str, threading.Semaphore] = {}
        self.max_per_addr = max_per_addr
        self.max_bytes = max_bytes
        self.inflight_bytes = 0
        self._lock = threading.Lock()
        self._bytes_free = threading.Condition(self._lock)

    def acquire(self, addr: str, nbytes: int = 0):
        with self._lock:
            sem = self.per_addr.setdefault(addr, threading.Semaphore(self.max_per_addr))
        self.total.acquire()
        sem.acquire()
        nbytes = min(nbytes, self.max_bytes)  # oversized fetches admit alone
        with self._bytes_free:
            # strict notify-driven accounting: every release() notifies under
            # the lock (and runs in a finally), so no timed re-poll is needed
            while self.inflight_bytes > 0 and self.inflight_bytes + nbytes > self.max_bytes:
                self._bytes_free.wait()
            self.inflight_bytes += nbytes
        return (sem, nbytes)

    def release(self, addr: str, token):
        sem, nbytes = token
        with self._bytes_free:
            self.inflight_bytes -= nbytes
            self._bytes_free.notify_all()
        sem.release()
        self.total.release()


_GOV_CACHE: dict[tuple, FetchGovernor] = {}
_GOV_LOCK = threading.Lock()


def _governor(ctx: TaskContext) -> FetchGovernor:
    from ballista_tpu.config import SHUFFLE_READER_MAX_BYTES

    # limits-derived key (id() aliases recycled addresses across configs)
    key = (
        int(ctx.config.get(SHUFFLE_READER_MAX_REQUESTS)),
        int(ctx.config.get(SHUFFLE_READER_MAX_PER_ADDR)),
        int(ctx.config.get(SHUFFLE_READER_MAX_BYTES)),
    )
    with _GOV_LOCK:
        g = _GOV_CACHE.get(key)
        if g is None:
            g = FetchGovernor(
                int(ctx.config.get(SHUFFLE_READER_MAX_REQUESTS)),
                int(ctx.config.get(SHUFFLE_READER_MAX_PER_ADDR)),
                int(ctx.config.get(SHUFFLE_READER_MAX_BYTES)),
            )
            _GOV_CACHE[key] = g
        return g


def _stream_locations(locs: list[PartitionLocation], ctx: TaskContext,
                      force_remote: bool, gov: "FetchGovernor | None"):
    """Bounded multi-location streaming merge (the reference's concurrent
    reduce-side reader, sort_shuffle/multi_stream_reader.rs).

    Remote locations prefetch concurrently; LOCAL locations stream lazily
    inline when their turn comes (no buffering at all). Yield order stays
    location order, so order-sensitive float merges are deterministic.
    Unlike the old fetch-everything-then-drain shape, fetched-but-unconsumed
    bytes are capped by the reader byte budget: a fetch's result counts
    against the window until the CONSUMER drains it, and new fetches are
    only admitted under the cap (one is always admitted when the window is
    empty, so an oversized partition streams alone instead of deadlocking).
    Per-location buffering is retained — a retry around a half-yielded
    Flight stream would duplicate rows (shuffle_reader.rs:975)."""
    import concurrent.futures as fut
    from ballista_tpu.config import SHUFFLE_READER_MAX_BYTES

    budget = int(ctx.config.get(SHUFFLE_READER_MAX_BYTES))
    remote = [
        i for i, loc in enumerate(locs)
        if force_remote or not (loc.path and os.path.exists(loc.path))
    ]
    remote_set = set(remote)
    if not remote:
        for loc in locs:
            yield from fetch_partition(loc, ctx, force_remote=force_remote, governor=gov)
        return

    cond = threading.Condition()
    results: dict[int, list | Exception] = {}
    state = {"buffered": 0, "next": 0}

    def fetch(i: int) -> None:
        try:
            out: list | Exception = list(
                fetch_partition(locs[i], ctx, force_remote=force_remote, governor=gov))
        except Exception as e:  # noqa: BLE001 — surfaced at the consumer in order
            out = e
        with cond:
            results[i] = out
            if not isinstance(out, Exception):
                got = sum(b.nbytes for b in out)
                # replace the stats estimate with actual bytes
                state["buffered"] += got - min(locs[i].stats.num_bytes, budget)
            cond.notify_all()

    pool = fut.ThreadPoolExecutor(
        max_workers=min(len(remote), int(ctx.config.get(SHUFFLE_READER_MAX_REQUESTS))),
        thread_name_prefix="shuffle-fetch",
    )

    def top_up_locked() -> None:
        while state["next"] < len(remote):
            est = min(locs[remote[state["next"]]].stats.num_bytes, budget)
            if state["buffered"] > 0 and state["buffered"] + est > budget:
                break
            state["buffered"] += est
            pool.submit(fetch, remote[state["next"]])
            state["next"] += 1

    try:
        with cond:
            top_up_locked()
        for i, loc in enumerate(locs):
            if i in remote_set:
                with cond:
                    while i not in results:
                        cond.wait()
                    batches = results.pop(i)
                if isinstance(batches, Exception):
                    raise batches
                yield from batches
                with cond:
                    state["buffered"] -= sum(b.nbytes for b in batches)
                    top_up_locked()
            else:
                # local: stream straight off disk, nothing buffered
                yield from fetch_partition(loc, ctx, force_remote=False, governor=gov)
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def fetch_partition(loc: PartitionLocation, ctx: TaskContext, force_remote: bool = False,
                    governor: FetchGovernor | None = None) -> Iterator[pa.RecordBatch]:
    local = not force_remote and loc.path and os.path.exists(loc.path)
    if local:
        yield from read_local_partition(loc)
        return
    retries = int(ctx.config.get(IO_RETRIES))
    wait_ms = int(ctx.config.get(IO_RETRY_WAIT_MS))
    addr = f"{loc.host}:{loc.flight_port}"
    last: Exception | None = None
    for attempt in range(retries + 1):
        token = governor.acquire(addr, loc.stats.num_bytes) if governor else None
        try:
            from ballista_tpu.flight.client import fetch_partition_flight

            # buffer the WHOLE partition before yielding anything: in
            # decoded (do_get) mode the flight client streams batches
            # incrementally, so a retry around a half-yielded stream would
            # duplicate the first attempt's rows downstream (the
            # reference's fetch_partition_buffered, shuffle_reader.rs:975)
            batches = list(fetch_partition_flight(loc, ctx))
        except Exception as e:  # noqa: BLE001 — retried, then surfaced as FetchFailed
            last = e
            time.sleep(wait_ms * (attempt + 1) / 1000.0)
            continue
        finally:
            if governor:
                governor.release(addr, token)
        yield from batches
        return
    raise FetchFailed(loc.executor_id, loc.job_id, loc.stage_id, loc.map_partition, str(last))


def read_local_partition(loc: PartitionLocation) -> Iterator[pa.RecordBatch]:
    if paths.is_sort_layout(loc.layout):
        with open(paths.index_path(loc.path)) as f:
            index = json.load(f)
        entry = index.get(str(loc.output_partition))
        if entry is None:
            return
        offset, length = entry[0], entry[1]
        with open(loc.path, "rb") as f:
            f.seek(offset)
            buf = f.read(length)
        reader = ipc.open_stream(pa.BufferReader(buf))
        yield from reader
    else:
        with open(loc.path, "rb") as f:
            reader = ipc.open_stream(f)
            yield from reader
