"""Shuffle writers: the root operator of every intermediate stage.

ShuffleWriterExec rebuilds the reference's two writers behind one node:

- hash layout (ShuffleWriterExec, shuffle_writer.rs:305): rows routed by
  the engine-wide key hash into K output files per map task — used for
  passthrough/collapse stages (K=0 → one output mirroring the input
  partition) and small fan-outs.
- sort layout (SortShuffleWriterExec, sort_shuffle/writer.rs:179): one
  consolidated data file per map task containing K buckets sorted by
  output partition + an index file; buffered per-bucket batches spill to
  disk when `ballista.shuffle.sort.memory.limit` is exceeded and are
  merged at finish (2×M files instead of N×M).

execute(map_partition) drives the child and yields ONE metadata batch
(output_partition, path, rows, bytes, layout) — the same
results-as-metadata-batches contract the reference uses to report
ShuffleWritePartition summaries (execution_engine.rs:304).

On-device partitioning: when the child pipeline ran on the TPU engine the
hash is computed with the jax twin of ops/hashing.py; host and device
partitions are bit-identical so readers never care who wrote a file.
"""

from __future__ import annotations

import io
import os
import json
import uuid
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.ipc as ipc

from ballista_tpu.config import (
    SHUFFLE_CHECKSUM_ENABLED,
    SHUFFLE_COMPRESSION_CODEC,
    SORT_SHUFFLE_MEMORY_LIMIT,
)
from ballista_tpu.errors import ExecutionError
from ballista_tpu.executor import disk
from ballista_tpu.executor.chaos import maybe_disk_full
from ballista_tpu.shuffle.integrity import ChecksumSink
from ballista_tpu.ops.hashing import partition_indices
from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.plan.expressions import Expr
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext, _empty_batch
from ballista_tpu.plan.schema import DFField, DFSchema
from ballista_tpu.shuffle import paths
from ballista_tpu.shuffle.types import PartitionStats


METADATA_SCHEMA = DFSchema(
    [
        DFField("output_partition", pa.int32(), False),
        DFField("path", pa.string(), False),
        DFField("num_rows", pa.int64(), False),
        DFField("num_batches", pa.int64(), False),
        DFField("num_bytes", pa.int64(), False),
        DFField("layout", pa.string(), False),
    ]
)


def _unlink_quiet(*ps: str) -> None:
    for p in ps:
        try:
            os.remove(p)
        except OSError:
            pass


def _checksum_on(ctx: TaskContext) -> bool:
    return bool(ctx.config.get(SHUFFLE_CHECKSUM_ENABLED))


def _write_crc_sidecar(data_path: str, digest: str | None) -> None:
    """Commit a hash-layout file's checksum sidecar (tmp + atomic rename,
    same discipline as the data file it describes). A None digest (knob
    off) writes nothing — absence means 'unchecked' to every reader."""
    if not digest:
        return
    cp = paths.crc_path(data_path)
    try:
        with open(cp + ".tmp", "w") as f:
            f.write(digest)
    except BaseException:
        _unlink_quiet(cp + ".tmp")
        raise
    os.replace(cp + ".tmp", cp)


def _codec(ctx: TaskContext) -> Optional[str]:
    c = str(ctx.config.get(SHUFFLE_COMPRESSION_CODEC))
    return None if c == "none" else c


def _ipc_options(ctx: TaskContext) -> ipc.IpcWriteOptions:
    return ipc.IpcWriteOptions(compression=_codec(ctx))


def write_ipc_stream(batches: list[pa.RecordBatch], schema: pa.Schema, sink, ctx: TaskContext) -> tuple[int, int]:
    """Write batches as one IPC stream; returns (rows, bytes_written)."""
    start = sink.tell()
    rows = 0
    with ipc.new_stream(sink, schema, options=_ipc_options(ctx)) as w:
        for b in batches:
            if b.num_rows:
                w.write_batch(b)
                rows += b.num_rows
    return rows, sink.tell() - start


class ShuffleWriterExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, job_id: str, stage_id: int,
                 output_partitions: int, keys: list[Expr] | None,
                 sort_shuffle: bool = True):
        super().__init__(METADATA_SCHEMA)
        self.input = input
        self.job_id = job_id
        self.stage_id = stage_id
        self.output_partitions = output_partitions  # 0 = passthrough
        self.keys = keys or []
        self.sort_shuffle = sort_shuffle and output_partitions > 0

    def children(self):
        return [self.input]

    def with_children(self, c):
        return ShuffleWriterExec(
            c[0], self.job_id, self.stage_id, self.output_partitions, self.keys, self.sort_shuffle
        )

    def output_partition_count(self) -> int:
        return self.input.output_partition_count()

    def input_schema(self) -> pa.Schema:
        return self.input.schema()

    def node_str(self) -> str:
        k = f" keys=[{', '.join(str(e) for e in self.keys)}]" if self.keys else ""
        mode = "sort" if self.sort_shuffle else "hash"
        return (
            f"ShuffleWriterExec: {self.job_id}/{self.stage_id} "
            f"out={self.output_partitions or 'passthrough'} layout={mode}{k}"
        )

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter([self._write(partition, ctx)]))

    # ------------------------------------------------------------------

    def _write(self, map_partition: int, ctx: TaskContext) -> pa.RecordBatch:
        if not ctx.work_dir:
            raise ExecutionError("shuffle writer needs a work_dir in TaskContext")
        task_id = ctx.task_id or f"{map_partition}-{uuid.uuid4().hex[:6]}"
        schema = self.input.schema()

        if self.output_partitions <= 0:
            # passthrough: stage collapse / preserved partitioning.
            # tmp + atomic rename: a task killed mid-write (deadline, cancel,
            # crash) must never leave a truncated file under the final name
            path = paths.hash_data_path(ctx.work_dir, self.job_id, self.stage_id, map_partition, task_id)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            maybe_disk_full(ctx.config, self.job_id, self.stage_id, map_partition,
                            ctx.task_attempt, "shuffle passthrough write")
            try:
                with open(path + ".tmp", "wb") as f:
                    sink = ChecksumSink(f, enabled=_checksum_on(ctx))
                    rows = 0
                    batches = 0
                    with ipc.new_stream(sink, schema, options=_ipc_options(ctx)) as w:
                        for b in self.input.execute(map_partition, ctx):
                            if b.num_rows:
                                w.write_batch(b)
                                rows += b.num_rows
                                batches += 1
                    nbytes = f.tell()
            except OSError as e:
                _unlink_quiet(path + ".tmp")
                typed = disk.wrap_enospc(e, f"shuffle write {self.job_id}/{self.stage_id}/{map_partition}")
                if typed is not None:
                    raise typed from e
                raise
            except BaseException:
                # an attempt killed mid-write (cancel, deadline, crash) must
                # not leave its .tmp around — it will never be renamed
                _unlink_quiet(path + ".tmp")
                raise
            _write_crc_sidecar(path, sink.digest())
            os.replace(path + ".tmp", path)
            return self._meta([(map_partition, path, rows, batches, nbytes, "hash")])

        bound = [bind_expr(k, self.input.df_schema) for k in self.keys]
        K = self.output_partitions
        buckets: list[list[pa.RecordBatch]] = [[] for _ in range(K)]
        bucket_rows = [0] * K
        bucket_batches = [0] * K
        buffered = 0
        spills: list[list[str]] = [[] for _ in range(K)]
        limit = int(ctx.config.get(SORT_SHUFFLE_MEMORY_LIMIT)) if self.sort_shuffle else 0
        # session-shared pool (try_grow semantics): when present, buffering
        # reserves against the SESSION's budget — concurrent tasks share it,
        # so idle tasks lend headroom to a heavy sort and a refusal means
        # "spill first" (the reference's per-session RuntimeEnv MemoryPool,
        # runtime_cache.rs:59)
        pool = ctx.memory_pool if self.sort_shuffle else None
        pool_held = 0

        def spill_largest() -> bool:
            nonlocal buffered, pool_held
            # low-watermark shed: spills are the OPTIONAL disk writes, so
            # they stop first under disk pressure. Returning False pushes
            # the caller onto the memory-overcommit ladder (grow_wait)
            # instead of filling the last of the disk.
            if not disk.spill_allowed(ctx.config, ctx.work_dir):
                return False
            k = max(range(K), key=lambda i: sum(b.nbytes for b in buckets[i]))
            if not buckets[k]:
                return False
            maybe_disk_full(ctx.config, self.job_id, self.stage_id, map_partition,
                            ctx.task_attempt, "sort-shuffle spill")
            sp = paths.sort_data_path(ctx.work_dir, self.job_id, self.stage_id, map_partition, task_id) + f".spill{len(spills[k])}.{k}"
            os.makedirs(os.path.dirname(sp), exist_ok=True)
            try:
                with open(sp, "wb") as f:
                    _, sp_bytes = write_ipc_stream(buckets[k], schema, f, ctx)
            except OSError as e:
                _unlink_quiet(sp)
                typed = disk.wrap_enospc(e, f"sort-shuffle spill {self.job_id}/{self.stage_id}/{map_partition}")
                if typed is not None:
                    raise typed from e
                raise
            spills[k].append(sp)
            freed = sum(b.nbytes for b in buckets[k])
            buffered -= freed
            # SpillManager-style accounting (sort_shuffle/spill.rs:46,110):
            # cumulative spilled volume surfaces in EXPLAIN ANALYZE metrics
            self.metrics.extra["spilled_bytes"] = (
                self.metrics.extra.get("spilled_bytes", 0) + sp_bytes)
            self.metrics.extra["spill_count"] = self.metrics.extra.get("spill_count", 0) + 1
            if pool is not None:
                pool.shrink(min(freed, pool_held))
                pool_held -= min(freed, pool_held)
            buckets[k] = []
            return True

        def reserve(nbytes: int) -> None:
            nonlocal pool_held
            if pool is None:
                return
            while not pool.try_grow(nbytes):
                if not spill_largest():
                    # nothing of ours left to spill: BLOCK with a deadline
                    # for peer tasks of this session to shrink (their next
                    # refusal makes them spill); only a deadline pass takes
                    # the headroom unaccounted — bounded liveness instead of
                    # the old unconditional grow()
                    from ballista_tpu.config import SORT_SHUFFLE_POOL_WAIT_S

                    wait_s = float(ctx.config.get(SORT_SHUFFLE_POOL_WAIT_S))
                    if not pool.grow_wait(nbytes, timeout_s=wait_s):
                        import logging

                        logging.getLogger(__name__).warning(
                            "memory pool overcommitted by %d bytes after %.1fs "
                            "wait (session under real pressure)", nbytes, wait_s)
                    break
            pool_held += nbytes

        from ballista_tpu.executor.chaos import skew_params, skew_remap_pids
        from ballista_tpu.ops.hashing import hash_arrays, split_batch_by_partition

        skew = skew_params(ctx.config)
        try:
            for b in self.input.execute(map_partition, ctx):
                if b.num_rows == 0:
                    continue
                pids = None
                if getattr(self, "device_routed", False) and "__pid" in b.schema.names:
                    if skew is not None and bound:
                        # chaos skew reroutes by the row's KEY HASH, but the
                        # device only shipped final partition ids. Recompute
                        # the keys on the host (the jax hash is a bit-exact
                        # twin) so every writer of this exchange — host- or
                        # device-hashed — remaps the same rows.
                        key_arrays = [evaluate_to_array(kb, b) for kb in bound]
                        b = b.select([n for n in b.schema.names if n != "__pid"])
                    else:
                        # device-side routing: the TPU stage already hashed
                        # rows to partitions (bit-exact twin); consume and
                        # drop the column. Gated on the engine-set flag so a
                        # user column named __pid is never misinterpreted.
                        i = b.schema.get_field_index("__pid")
                        pids = b.column(i).to_numpy(zero_copy_only=False).astype(np.uint64)
                        b = b.select([n for n in b.schema.names if n != "__pid"])
                        key_arrays = []
                else:
                    key_arrays = [evaluate_to_array(kb, b) for kb in bound]
                if skew is not None and key_arrays:
                    pids = skew_remap_pids(hash_arrays(key_arrays), K, *skew)
                for k, part in split_batch_by_partition(b, key_arrays, K, precomputed_pids=pids):
                    reserve(part.nbytes)
                    buckets[k].append(part)
                    bucket_rows[k] += part.num_rows
                    bucket_batches[k] += 1
                    buffered += part.nbytes
                while limit and buffered > limit:
                    if not spill_largest():
                        break

            if self.sort_shuffle:
                return self._finish_sort(map_partition, task_id, schema, buckets, spills, bucket_rows, bucket_batches, ctx)
            return self._finish_hash(map_partition, task_id, schema, buckets, bucket_rows, bucket_batches, ctx)
        except BaseException:
            # consolidation removes spills as it streams them; an aborted
            # attempt has to sweep up whatever it spilled itself
            _unlink_quiet(*(sp for ks in spills for sp in ks))
            raise
        finally:
            if pool is not None and pool_held:
                pool.shrink(pool_held)

    def _finish_hash(self, map_partition, task_id, schema, buckets, rows, batches, ctx):
        """Drain the K bucket files CONCURRENTLY (the reference's K
        concurrent per-output drain tasks, shuffle_writer.rs:214-303):
        Arrow's IPC write releases the GIL for compression + IO, so the
        drains genuinely overlap."""
        import concurrent.futures as fut

        live = [k for k in range(len(buckets)) if rows[k]]
        if not live:
            return self._meta([])
        maybe_disk_full(ctx.config, self.job_id, self.stage_id, map_partition,
                        ctx.task_attempt, "hash-shuffle commit")

        def drain(k: int):
            path = paths.hash_data_path(ctx.work_dir, self.job_id, self.stage_id, k, task_id)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                with open(path + ".tmp", "wb") as f:
                    sink = ChecksumSink(f, enabled=_checksum_on(ctx))
                    _, nbytes = write_ipc_stream(buckets[k], schema, sink, ctx)
            except OSError as e:
                _unlink_quiet(path + ".tmp")
                typed = disk.wrap_enospc(e, f"shuffle write {self.job_id}/{self.stage_id}/{k}")
                if typed is not None:
                    raise typed from e
                raise
            except BaseException:
                _unlink_quiet(path + ".tmp")
                raise
            _write_crc_sidecar(path, sink.digest())
            os.replace(path + ".tmp", path)
            return (k, path, rows[k], batches[k], nbytes, "hash")

        if len(live) == 1:
            return self._meta([drain(live[0])])
        with fut.ThreadPoolExecutor(max_workers=min(len(live), 8),
                                    thread_name_prefix="shuffle-drain") as pool:
            out = list(pool.map(drain, live))
        return self._meta(out)

    @staticmethod
    def _iter_bucket_batches(in_memory: list, spill_files: list[str]):
        """Stream a bucket's batches: in-memory first, then each spill file
        decoded ONE BATCH AT A TIME. Consolidation must never rebuffer what
        it spilled — that would peak at exactly the memory the spill
        existed to avoid (sort_shuffle/spill.rs:46 streams the same way)."""
        for b in in_memory:
            yield b
        for sp in spill_files:
            with open(sp, "rb") as sf:
                yield from ipc.open_stream(sf)
            os.remove(sp)

    def _finish_sort(self, map_partition, task_id, schema, buckets, spills, rows, batches, ctx):
        """Consolidate buckets (memory + spills) into one data file + index.

        The data file name is attempt-unique (task_id baked in) and both
        files commit via tmp + atomic rename, data BEFORE index: duplicate
        attempts of the same map partition (speculation) each produce a
        complete private file set, and whichever status reaches the
        scheduler first decides which set readers ever see."""
        data_path = paths.sort_data_path(ctx.work_dir, self.job_id, self.stage_id, map_partition, task_id)
        os.makedirs(os.path.dirname(data_path), exist_ok=True)
        maybe_disk_full(ctx.config, self.job_id, self.stage_id, map_partition,
                        ctx.task_attempt, "sort-shuffle commit")
        index: dict[str, list] = {}
        out = []
        idx_path = paths.index_path(data_path)
        try:
            with open(data_path + ".tmp", "wb") as f:
                sink = ChecksumSink(f, enabled=_checksum_on(ctx))
                for k in range(len(buckets)):
                    if not rows[k]:
                        continue
                    start = f.tell()
                    nrows = 0
                    # per-RANGE checksum: each bucket's byte range is the unit
                    # readers fetch and verify, so the digest resets here
                    sink.start_range()
                    with ipc.new_stream(sink, schema, options=_ipc_options(ctx)) as w:
                        for b in self._iter_bucket_batches(buckets[k], spills[k]):
                            if b.num_rows:
                                w.write_batch(b)
                                nrows += b.num_rows
                    length = f.tell() - start
                    crc = sink.digest()
                    entry: list = [start, length, nrows, length]
                    if crc:
                        entry.append(crc)
                    index[str(k)] = entry
                    out.append((k, data_path, nrows, batches[k], length, "sort"))
            os.replace(data_path + ".tmp", data_path)
            with open(idx_path + ".tmp", "w") as f:
                json.dump(index, f)
        except OSError as e:
            _unlink_quiet(data_path + ".tmp", idx_path + ".tmp")
            typed = disk.wrap_enospc(e, f"sort-shuffle commit {self.job_id}/{self.stage_id}/{map_partition}")
            if typed is not None:
                raise typed from e
            raise
        except BaseException:
            _unlink_quiet(data_path + ".tmp", idx_path + ".tmp")
            raise
        os.replace(idx_path + ".tmp", idx_path)
        return self._meta(out)

    def _meta(self, rows: list[tuple]) -> pa.RecordBatch:
        schema = self.schema()
        if not rows:
            return _empty_batch(schema)
        cols = list(zip(*rows))
        arrays = [
            pa.array(cols[0], pa.int32()),
            pa.array(cols[1], pa.string()),
            pa.array([int(x) for x in cols[2]], pa.int64()),
            pa.array([int(x) for x in cols[3]], pa.int64()),
            pa.array([int(x) for x in cols[4]], pa.int64()),
            pa.array(cols[5], pa.string()),
        ]
        return pa.RecordBatch.from_arrays(arrays, schema=schema)


def metadata_to_locations(batch: pa.RecordBatch, job_id: str, stage_id: int,
                          map_partition: int, executor_id: str, host: str, flight_port: int):
    """Convert a writer metadata batch into PartitionLocations
    (reference: drive_shuffle_writer_stage → ShuffleWritePartition,
    execution_engine.rs:304; zero-byte sentinels dropped :336)."""
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    out = []
    for i in range(batch.num_rows):
        if batch.column(2)[i].as_py() == 0:
            continue
        out.append(
            PartitionLocation(
                map_partition=map_partition,
                job_id=job_id,
                stage_id=stage_id,
                output_partition=batch.column(0)[i].as_py(),
                executor_id=executor_id,
                host=host,
                flight_port=flight_port,
                path=batch.column(1)[i].as_py(),
                layout=batch.column(5)[i].as_py(),
                stats=PartitionStats(
                    num_rows=batch.column(2)[i].as_py(),
                    num_batches=batch.column(3)[i].as_py(),
                    num_bytes=batch.column(4)[i].as_py(),
                ),
            )
        )
    return out
