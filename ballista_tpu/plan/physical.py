"""Physical execution plan: Volcano-style over Arrow RecordBatches.

Each node implements `execute(partition, ctx) -> Iterator[RecordBatch]`.
This is the CPU engine — the parity baseline standing in for the
reference's DataFusion operator set (SURVEY.md §1 "engine under it all").
The TPU engine (engine/tpu_engine.py) compiles supported subtrees of THIS
plan to XLA and falls back here per-subtree.

Partitioning model mirrors the reference: a node has N output partitions;
`RepartitionExec` is the in-process exchange that the distributed planner
replaces with shuffle writer/reader pairs at stage boundaries
(reference: scheduler/src/planner.rs:108).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from ballista_tpu.config import BATCH_SIZE, BallistaConfig
from ballista_tpu.errors import ExecutionError
from ballista_tpu.ops.cpu.join_kernel import match_pairs
from ballista_tpu.ops.hashing import partition_indices
from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.plan.expressions import Expr, SortKey
from ballista_tpu.plan.schema import DFSchema

log = logging.getLogger(__name__)


class Metrics:
    def __init__(self):
        self.output_rows = 0
        self.output_batches = 0
        self.elapsed_ns = 0
        # operator-specific counters (spilled_bytes, spill_count, ...) —
        # the reference's labeled MetricsSet values beyond the core trio
        self.extra: dict[str, int] = {}

    def as_dict(self) -> dict:
        return {
            "output_rows": self.output_rows,
            "output_batches": self.output_batches,
            "elapsed_ns": self.elapsed_ns,
            **self.extra,
        }


class TaskContext:
    def __init__(self, config: BallistaConfig | None = None, task_id: str = "", work_dir: str = ""):
        self.config = config or BallistaConfig()
        self.task_id = task_id
        self.work_dir = work_dir
        self.batch_size = int(self.config.get(BATCH_SIZE))
        # session-shared MemoryPool (try_grow semantics) when running under
        # an executor; None = static per-task limits only
        self.memory_pool = None
        # per-chip pinning: jax device ordinal this task must dispatch to
        # (-1 = unpinned); set by Executor.execute_task from its metadata
        self.device_ordinal = -1
        # straggler-defense plumbing, set by Executor.execute_task:
        # which attempt of the task this is (speculative duplicates > 0),
        # a callable polled by long-running operators to honor preemptive
        # cancels, and the absolute wall-clock deadline (0.0 = none)
        self.task_attempt = 0
        self.cancel_check = None
        self.deadline_at = 0.0


class ExecutionPlan:
    """Base physical operator."""

    def __init__(self, df_schema: DFSchema):
        self.df_schema = df_schema
        self.metrics = Metrics()

    def schema(self) -> pa.Schema:
        return self.df_schema.to_arrow()

    def children(self) -> list["ExecutionPlan"]:
        return []

    def with_children(self, children: list["ExecutionPlan"]) -> "ExecutionPlan":
        raise NotImplementedError(type(self).__name__)

    def output_partition_count(self) -> int:
        return self.children()[0].output_partition_count()

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def node_str(self) -> str:
        return type(self).__name__

    def display(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [pad + self.node_str()]
        for c in self.children():
            lines.append(c.display(indent + 1))
        return "\n".join(lines)

    def _timed(self, it: Iterator[pa.RecordBatch]) -> Iterator[pa.RecordBatch]:
        m = self.metrics
        while True:
            t0 = time.perf_counter_ns()
            try:
                b = next(it)
            except StopIteration:
                m.elapsed_ns += time.perf_counter_ns() - t0
                return
            m.elapsed_ns += time.perf_counter_ns() - t0
            m.output_rows += b.num_rows
            m.output_batches += 1
            yield b


def collect_metrics(plan: ExecutionPlan, out: list | None = None, depth: int = 0) -> list:
    """Recursive metrics harvest (reference: utils.rs collect_plan_metrics)."""
    if out is None:
        out = []
    out.append((depth, plan.node_str(), plan.metrics.as_dict()))
    for c in plan.children():
        collect_metrics(c, out, depth + 1)
    return out


def _empty_batch(schema: pa.Schema) -> pa.RecordBatch:
    return pa.RecordBatch.from_arrays([pa.array([], f.type) for f in schema], schema=schema)


def _concat(batches: list[pa.RecordBatch], schema: pa.Schema) -> pa.Table:
    if not batches:
        return pa.table({f.name: pa.array([], f.type) for f in schema}, schema=schema)
    return pa.Table.from_batches(batches, schema=schema)


# ---------------------------------------------------------------------------
# scans
# ---------------------------------------------------------------------------


class ParquetScanExec(ExecutionPlan):
    """Parquet scan over (file, row-group) partitions with exact filter
    application post-read and row-group pruning via parquet min/max stats."""

    def __init__(self, df_schema: DFSchema, partitions: list[dict], projection: list[str],
                 filters: list[Expr], table_name: str = ""):
        super().__init__(df_schema)
        self.partitions = partitions
        self.projection = projection
        self.filters = filters
        self.table_name = table_name

    def output_partition_count(self) -> int:
        return max(1, len(self.partitions))

    def with_children(self, c):
        assert not c
        return self

    def node_str(self) -> str:
        f = f" filters={[str(x) for x in self.filters]}" if self.filters else ""
        return (
            f"ParquetScanExec: {self.table_name} partitions={len(self.partitions)} "
            f"projection={self.projection}{f}"
        )

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(self._run(partition, ctx))

    def _run(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if not self.partitions:
            yield _empty_batch(self.schema())
            return
        part = self.partitions[partition]
        preds = [bind_expr(f, self.df_schema) for f in self.filters]
        out_schema = self.schema()
        produced = False
        for fdesc in part.get("files", []):
            fpath = fdesc["file"]
            if fpath.startswith("s3://"):
                from ballista_tpu.plan.object_store import resolve_filesystem

                fs, inner = resolve_filesystem(fpath)
                pf = pq.ParquetFile(inner, filesystem=fs)
            else:
                pf = pq.ParquetFile(fpath)
            rgs = fdesc.get("row_groups")
            if rgs is None:
                rgs = list(range(pf.metadata.num_row_groups))
            rgs = [rg for rg in rgs if not self._prunable(pf.metadata, rg)]
            if not rgs:
                continue
            for batch in pf.iter_batches(batch_size=ctx.batch_size, row_groups=rgs, columns=self.projection):
                batch = _align_batch(batch, out_schema)
                for p in preds:
                    mask = evaluate_to_array(p, batch)
                    batch = batch.filter(pc.fill_null(mask, False))
                    if batch.num_rows == 0:
                        break
                if batch.num_rows:
                    produced = True
                    yield batch
        if not produced:
            yield _empty_batch(out_schema)

    def _prunable(self, md, rg_idx: int) -> bool:
        """True if min/max stats prove no row in this group can pass."""
        if not self.filters:
            return False
        from ballista_tpu.plan.expressions import Between, BinaryExpr, Column, Literal

        rg = md.row_group(rg_idx)
        col_stats = {}
        for ci in range(rg.num_columns):
            col = rg.column(ci)
            st = col.statistics
            if st is not None and st.has_min_max:
                col_stats[col.path_in_schema] = (st.min, st.max)
        for f in self.filters:
            name, op, val = None, None, None
            if isinstance(f, BinaryExpr) and isinstance(f.left, Column) and isinstance(f.right, Literal):
                name, op, val = f.left.name, f.op, f.right.value
            elif isinstance(f, BinaryExpr) and isinstance(f.right, Column) and isinstance(f.left, Literal):
                flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<=", "=": "=", "<>": "<>"}
                name, op, val = f.right.name, flip[f.op], f.left.value
            elif isinstance(f, Between) and isinstance(f.expr, Column) and not f.negated \
                    and isinstance(f.low, Literal) and isinstance(f.high, Literal):
                if f.expr.name in col_stats:
                    mn, mx = col_stats[f.expr.name]
                    lo, hi = _stat_val(f.low.value), _stat_val(f.high.value)
                    try:
                        if _stat_val(mx) < lo or _stat_val(mn) > hi:
                            return True
                    except TypeError:
                        pass
                continue
            if name is None or name not in col_stats or val is None:
                continue
            mn, mx = _stat_val(col_stats[name][0]), _stat_val(col_stats[name][1])
            v = _stat_val(val)
            try:
                if op == "=" and (v < mn or v > mx):
                    return True
                if op in ("<", "<=") and mn > v:
                    return True
                if op in (">", ">=") and mx < v:
                    return True
            except TypeError:
                continue
        return False


def _stat_val(v):
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        return v.date()
    return v


def _align_batch(batch: pa.RecordBatch, schema: pa.Schema) -> pa.RecordBatch:
    """Reorder/cast columns read from parquet to the node's output schema."""
    cols = []
    for f in schema:
        arr = batch.column(batch.schema.get_field_index(f.name))
        if arr.type != f.type:
            arr = arr.cast(f.type)
        cols.append(arr)
    return pa.RecordBatch.from_arrays(cols, schema=schema)


_MEM_SCAN_COUNTER = iter(range(1, 1 << 62))


class MemoryScanExec(ExecutionPlan):
    def __init__(self, df_schema: DFSchema, batches: list[pa.RecordBatch], partitions: int = 1):
        super().__init__(df_schema)
        self.batches = batches
        self.partitions = max(1, partitions)
        # collision-free cache identity (id() recycles addresses)
        self.mem_token = next(_MEM_SCAN_COUNTER)

    def output_partition_count(self) -> int:
        return self.partitions

    def with_children(self, c):
        return self

    def execute(self, partition: int, ctx: TaskContext):
        sel = [b for i, b in enumerate(self.batches) if i % self.partitions == partition]
        schema = self.schema()
        sel = [_align_batch(b, schema) for b in sel]
        if not sel:
            sel = [_empty_batch(schema)]
        return self._timed(iter(sel))

    def node_str(self) -> str:
        rows = sum(b.num_rows for b in self.batches)
        return f"MemoryScanExec: rows={rows} partitions={self.partitions}"


class EmptyExec(ExecutionPlan):
    def __init__(self, df_schema: DFSchema, produce_one_row: bool = False):
        super().__init__(df_schema)
        self.produce_one_row = produce_one_row

    def output_partition_count(self) -> int:
        return 1

    def with_children(self, c):
        return self

    def execute(self, partition: int, ctx: TaskContext):
        schema = self.schema()
        if self.produce_one_row:
            if len(schema) == 0:
                # a 1-row batch needs at least one column in Arrow; SELECTs
                # without FROM project literals over this placeholder
                schema = pa.schema([pa.field("__placeholder", pa.null())])
            arrays = [pa.nulls(1, f.type) for f in schema]
            return iter([pa.RecordBatch.from_arrays(arrays, schema=schema)])
        return iter([_empty_batch(schema)])


# ---------------------------------------------------------------------------
# row pipeline operators
# ---------------------------------------------------------------------------


class FilterExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, predicate: Expr):
        super().__init__(input.df_schema)
        self.input = input
        self.predicate = predicate

    def children(self):
        return [self.input]

    def with_children(self, c):
        return FilterExec(c[0], self.predicate)

    def node_str(self) -> str:
        return f"FilterExec: {self.predicate}"

    def execute(self, partition: int, ctx: TaskContext):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        pred = bind_expr(self.predicate, self.df_schema)
        for batch in self.input.execute(partition, ctx):
            mask = evaluate_to_array(pred, batch)
            out = batch.filter(pc.fill_null(mask, False))
            if out.num_rows:
                yield out


class ProjectionExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, exprs: list[Expr], df_schema: DFSchema):
        super().__init__(df_schema)
        self.input = input
        self.exprs = exprs

    def children(self):
        return [self.input]

    def with_children(self, c):
        return ProjectionExec(c[0], self.exprs, self.df_schema)

    def node_str(self) -> str:
        return f"ProjectionExec: {', '.join(str(e) for e in self.exprs)}"

    def execute(self, partition: int, ctx: TaskContext):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        bound = [bind_expr(e, self.input.df_schema) for e in self.exprs]
        schema = self.schema()
        for batch in self.input.execute(partition, ctx):
            arrays = []
            for pe, f in zip(bound, schema):
                arr = evaluate_to_array(pe, batch)
                if arr.type != f.type:
                    arr = arr.cast(f.type)
                arrays.append(arr)
            yield pa.RecordBatch.from_arrays(arrays, schema=schema)


class CoalesceBatchesExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, target_rows: int = 64 * 1024):
        super().__init__(input.df_schema)
        self.input = input
        self.target_rows = target_rows

    def children(self):
        return [self.input]

    def with_children(self, c):
        return CoalesceBatchesExec(c[0], self.target_rows)

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        buf: list[pa.RecordBatch] = []
        rows = 0
        schema = self.schema()
        for b in self.input.execute(partition, ctx):
            if b.num_rows == 0:
                continue
            buf.append(b)
            rows += b.num_rows
            if rows >= self.target_rows:
                yield _concat(buf, schema).combine_chunks().to_batches()[0]
                buf, rows = [], 0
        if buf:
            yield _concat(buf, schema).combine_chunks().to_batches()[0]
        elif rows == 0:
            yield _empty_batch(schema)


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


def _as_py_scalar(v):
    return v.as_py() if isinstance(v, pa.Scalar) else v


def _welford_merge_lists(n_lists, mean_lists, m2_lists):
    """Merge per-group lists of Welford partials (one element per upstream
    partial row) with the mean-centered formula:

        N = Σn_i;  mean = Σ n_i·mean_i / N
        M2 = Σ M2_i + Σ n_i·(mean_i − mean)²

    Centering before squaring keeps intermediates at data scale — this is
    why the decomposition survives large-magnitude columns where the naive
    q − s²/n form catastrophically cancels. Vectorized over groups via
    flattened values + reduceat (list lengths are identical across the three
    columns: each upstream partial row contributes one slot to each list).
    """
    def _la(col):
        col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        return col

    n_la, mean_la, m2_la = _la(n_lists), _la(mean_lists), _la(m2_lists)
    off = n_la.offsets.to_numpy()
    starts = off[:-1]
    lens = np.diff(off)
    n_flat = n_la.flatten().to_numpy(zero_copy_only=False).astype(np.float64)
    mean_flat = mean_la.flatten().to_numpy(zero_copy_only=False)
    m2_flat = m2_la.flatten().to_numpy(zero_copy_only=False)
    # partials are null only when n==0 (zero contribution); with n>0 a NaN is
    # genuine data NaN and must propagate, matching single-partition results
    mean_flat = np.where(n_flat > 0, mean_flat, 0.0)
    m2_flat = np.where(n_flat > 0, m2_flat, 0.0)
    n_groups = len(lens)
    if len(n_flat) == 0:
        empty = pa.nulls(n_groups, pa.float64())
        return empty, empty
    N = np.add.reduceat(n_flat, starts)
    wsum = np.add.reduceat(n_flat * mean_flat, starts)
    with np.errstate(invalid="ignore", divide="ignore"):
        mean_g = wsum / N
    mean_rep = np.repeat(np.nan_to_num(mean_g), lens)
    centered = n_flat * (mean_flat - mean_rep) ** 2
    M2 = np.add.reduceat(m2_flat + centered, starts)
    valid = N > 0
    mean_arr = pa.array(np.where(valid, mean_g, 0.0), pa.float64(), mask=~valid)
    m2_arr = pa.array(np.where(valid, M2, 0.0), pa.float64(), mask=~valid)
    return mean_arr, m2_arr


@dataclass
class AggDesc:
    func: str  # sum | min | max | count | count_all
    expr: Optional[Expr]  # None for count_all
    name: str  # output column name


class HashAggregateExec(ExecutionPlan):
    """Two-phase hash aggregation.

    partial: groups within one input partition, emits accumulator columns.
    final:   merges accumulator columns (after a hash repartition on keys).
    single:  both at once (single-partition plans).
    """

    def __init__(self, input: ExecutionPlan, group_exprs: list[Expr], aggs: list[AggDesc],
                 mode: str, df_schema: DFSchema):
        super().__init__(df_schema)
        self.input = input
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.mode = mode  # partial | final | single

    def children(self):
        return [self.input]

    def with_children(self, c):
        return HashAggregateExec(c[0], self.group_exprs, self.aggs, self.mode, self.df_schema)

    def node_str(self) -> str:
        g = ", ".join(str(e) for e in self.group_exprs)
        a = ", ".join(f"{d.func}({d.expr if d.expr is not None else '*'})" for d in self.aggs)
        return f"HashAggregateExec: mode={self.mode}, gby=[{g}], aggr=[{a}]"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        schema = self.schema()
        in_schema = self.input.df_schema
        batches = [b for b in self.input.execute(partition, ctx) if b.num_rows]
        n_group = len(self.group_exprs)

        if self.mode in ("partial", "single"):
            group_bound = [bind_expr(e, in_schema) for e in self.group_exprs]
            agg_bound = [bind_expr(d.expr, in_schema) if d.expr is not None else None for d in self.aggs]
            gcols: dict[str, list] = {f"__g{i}": [] for i in range(n_group)}
            acols: dict[str, list] = {f"__a{i}": [] for i in range(len(self.aggs))}
            ones_needed = any(d.func == "count_all" for d in self.aggs)
            for b in batches:
                for i, ge in enumerate(group_bound):
                    gcols[f"__g{i}"].append(evaluate_to_array(ge, b))
                for i, (d, ab) in enumerate(zip(self.aggs, agg_bound)):
                    if d.func == "count_all":
                        acols[f"__a{i}"].append(pa.array(np.ones(b.num_rows, dtype=np.int64)))
                    else:
                        acols[f"__a{i}"].append(evaluate_to_array(ab, b))
            if not batches:
                tbl = None
            else:
                cols = {k: pa.chunked_array(v) for k, v in {**gcols, **acols}.items()}
                tbl = pa.table(cols)
            pairs = []
            for i, d in enumerate(self.aggs):
                fn = {"sum": "sum", "min": "min", "max": "max", "count": "count",
                      "count_all": "sum", "welford_mean": "mean",
                      "welford_m2": "variance"}[d.func]
                pairs.append((f"__a{i}", fn))
        else:  # final: input columns are [groups..., accumulators...]
            tbl = _concat(batches, self.input.schema()) if batches else None
            if tbl is not None:
                names = [f"__g{i}" for i in range(n_group)] + [f"__a{i}" for i in range(len(self.aggs))]
                tbl = tbl.rename_columns(names)
            pairs = []
            for i, d in enumerate(self.aggs):
                # welford partials merge as a (cnt, mean, m2) unit: list-collect
                # the per-partition values, merged below with the mean-centered
                # formula (numerically stable — no sum-of-squares cancellation)
                fn = {"sum": "sum", "min": "min", "max": "max", "count": "sum",
                      "count_all": "sum", "welford_mean": "list",
                      "welford_m2": "list"}[d.func]
                pairs.append((f"__a{i}", fn))

        if tbl is None or tbl.num_rows == 0:
            if n_group == 0:
                yield self._empty_global_row(schema)
            else:
                yield _empty_batch(schema)
            return

        if n_group == 0:
            arrays = []
            welford_global: dict[int, tuple] = {}  # mean-desc idx → (mean, m2)
            for i, ((cname, fn), d, f) in enumerate(zip(pairs, self.aggs, schema)):
                col = tbl.column(cname)
                if d.func == "welford_mean" and self.mode == "final":
                    welford_global[i] = self._welford_merge_global(tbl, i - 1)
                    v = welford_global[i][0]
                elif d.func == "welford_m2" and self.mode == "final":
                    v = welford_global[i - 1][1]
                elif d.func == "welford_mean":
                    v = pc.mean(col)
                elif d.func == "welford_m2":
                    n = len(col) - col.null_count
                    var = pc.variance(col, ddof=0).as_py() if n else None
                    v = pa.scalar(None if var is None else var * n, pa.float64())
                elif fn == "sum":
                    v = pc.sum(col)
                elif fn == "min":
                    v = pc.min(col)
                elif fn == "max":
                    v = pc.max(col)
                elif fn == "count":
                    v = pa.scalar(len(col) - col.null_count, pa.int64())
                arr = pa.array([_as_py_scalar(v)], f.type)
                arrays.append(arr)
            yield pa.RecordBatch.from_arrays(arrays, schema=schema)
            return

        keys = [f"__g{i}" for i in range(n_group)]
        agg_calls: list = []
        for (cname, fn), d in zip(pairs, self.aggs):
            if fn == "variance":
                agg_calls.append((cname, "variance", pc.VarianceOptions(ddof=0)))
                agg_calls.append((cname, "count"))  # for m2 = var_pop * n
            else:
                agg_calls.append((cname, fn))
        for i, d in enumerate(self.aggs):
            if self.mode == "final" and d.func == "welford_mean":
                agg_calls.append((f"__a{i - 1}", "list"))  # the triple's counts
        grouped = tbl.group_by(keys, use_threads=False).aggregate(agg_calls)
        # grouped columns: [agg outputs named __aI_fn ..., keys...] (pyarrow puts
        # aggregates first or keys first depending on version) — map by name.
        out_arrays = []
        for i in range(n_group):
            out_arrays.append(grouped.column(f"__g{i}"))
        welford_cache: dict[int, tuple] = {}  # mean-desc idx → (mean_arr, m2_arr)
        for i, ((cname, fn), d) in enumerate(zip(pairs, self.aggs)):
            if fn == "variance":  # partial welford_m2: m2 = var_pop * n
                var = pc.cast(grouped.column(f"{cname}_variance"), pa.float64())
                n = pc.cast(grouped.column(f"{cname}_count"), pa.float64())
                out_arrays.append(pc.multiply(var, n))
            elif fn == "list" and d.func == "welford_mean":
                merged = _welford_merge_lists(
                    grouped.column(f"__a{i - 1}_list"),
                    grouped.column(f"__a{i}_list"),
                    grouped.column(f"__a{i + 1}_list"),
                )
                welford_cache[i] = merged
                out_arrays.append(merged[0])
            elif fn == "list" and d.func == "welford_m2":
                out_arrays.append(welford_cache[i - 1][1])
            else:
                out_arrays.append(grouped.column(f"{cname}_{fn}"))
        casted = []
        for arr, f in zip(out_arrays, schema):
            a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
            if a.type != f.type:
                a = a.cast(f.type)
            casted.append(a)
        yield pa.RecordBatch.from_arrays(casted, schema=schema)

    def _welford_merge_global(self, tbl: pa.Table, cnt_idx: int):
        """Merge all partial (count, mean, m2) rows into one global pair.
        Columns __a{cnt_idx}, __a{cnt_idx+1}, __a{cnt_idx+2} hold the triple."""
        n = tbl.column(f"__a{cnt_idx}").to_numpy(zero_copy_only=False).astype(np.float64)
        mean = tbl.column(f"__a{cnt_idx + 1}").to_numpy(zero_copy_only=False)
        m2 = tbl.column(f"__a{cnt_idx + 2}").to_numpy(zero_copy_only=False)
        # null partials ⟺ n==0; NaN with n>0 is data NaN and must propagate
        mean = np.where(n > 0, mean, 0.0)
        m2 = np.where(n > 0, m2, 0.0)
        total = n.sum()
        if total <= 0:
            return None, None
        g_mean = float((n * mean).sum() / total)
        g_m2 = float(m2.sum() + (n * (mean - g_mean) ** 2).sum())
        return g_mean, g_m2

    def _empty_global_row(self, schema: pa.Schema) -> pa.RecordBatch:
        arrays = []
        for d, f in zip(self.aggs, schema):
            if d.func in ("count", "count_all"):
                arrays.append(pa.array([0], f.type))
            else:
                arrays.append(pa.nulls(1, f.type))
        return pa.RecordBatch.from_arrays(arrays, schema=schema)


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------


class HashJoinExec(ExecutionPlan):
    """Hash equi-join; builds LEFT side, probes RIGHT side.

    mode='collect_left' broadcasts the whole left input to every probe
    partition (reference: CollectLeft); mode='partitioned' assumes both
    sides are co-hash-partitioned on the join keys.
    """

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 on: list[tuple[Expr, Expr]], join_type: str, filter: Optional[Expr],
                 mode: str, df_schema: DFSchema):
        super().__init__(df_schema)
        self.left = left
        self.right = right
        self.on = on
        self.join_type = join_type
        self.filter = filter
        self.mode = mode
        self._build_cache: dict[int, pa.Table] = {}
        self._lock = threading.Lock()
        # collect_left + build-side-emitting join types (left/full/semi/anti)
        # need matched-bitmap coordination across probe partitions: every
        # partition sees the SAME build table, so tail emission must happen
        # exactly once, after the LAST probe partition drains (the reference
        # relies on DataFusion's shared bitmap for CollectLeft likewise).
        self._shared_matched: np.ndarray | None = None
        self._done_partitions = 0

    def children(self):
        return [self.left, self.right]

    def with_children(self, c):
        return HashJoinExec(c[0], c[1], self.on, self.join_type, self.filter, self.mode, self.df_schema)

    def output_partition_count(self) -> int:
        return self.right.output_partition_count()

    def node_str(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        f = f", filter={self.filter}" if self.filter is not None else ""
        return f"HashJoinExec: mode={self.mode}, type={self.join_type}, on=[{on}]{f}"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _build_table(self, partition: int, ctx: TaskContext) -> pa.Table:
        key = -1 if self.mode == "collect_left" else partition
        with self._lock:
            if key in self._build_cache:
                return self._build_cache[key]
        if self.mode == "collect_left":
            batches = []
            for p in range(self.left.output_partition_count()):
                batches.extend(b for b in self.left.execute(p, ctx) if b.num_rows)
        else:
            batches = [b for b in self.left.execute(partition, ctx) if b.num_rows]
        tbl = _concat(batches, self.left.schema()).combine_chunks()
        if self.mode == "collect_left":
            # a collect_left planned under the tpu engine's HBM-scaled
            # threshold can land here when the device stage is declined —
            # EVERY probe task then collects this table into host memory.
            # The cliff is survivable but must not be silent.
            from ballista_tpu.config import BROADCAST_JOIN_ROWS_THRESHOLD
            cpu_threshold = int(ctx.config.get(BROADCAST_JOIN_ROWS_THRESHOLD))
            if tbl.num_rows > cpu_threshold:
                log.warning(
                    "collect_left join build side has %d rows, exceeding the CPU "
                    "broadcast threshold of %d (%s); this join was likely planned "
                    "for a device stage that fell back to host execution — every "
                    "probe task materializes the full build table in host memory",
                    tbl.num_rows, cpu_threshold, BROADCAST_JOIN_ROWS_THRESHOLD)
        with self._lock:
            self._build_cache[key] = tbl
        return tbl

    def _run(self, partition, ctx):
        build = self._build_table(partition, ctx)
        lschema, rschema = self.left.df_schema, self.right.df_schema
        lkeys = [bind_expr(l, lschema) for l, _ in self.on]
        rkeys = [bind_expr(r, rschema) for _, r in self.on]
        combined_schema = lschema.merge(rschema)
        filt = bind_expr(self.filter, combined_schema) if self.filter is not None else None
        out_schema = self.schema()

        build_batch = (
            build.to_batches()[0] if build.num_rows else _empty_batch(self.left.schema())
        )
        if build.num_rows:
            build_batch = build.combine_chunks().to_batches()[0]
        build_key_arrays = [evaluate_to_array(k, build_batch) for k in lkeys]

        # prepare the build ONCE per execution: dictionary-encode + sort the
        # build keys a single time, then map every probe batch into that id
        # space (re-encoding a large build per batch dominated join time).
        # Both sides cast to a common key type first so the shared id space
        # is lossless.
        from ballista_tpu.ops.cpu.join_kernel import PreparedBuild, _common_type

        key_types: list = []
        if build.num_rows:
            probe_schema = self.right.schema()
            prep_cols = []
            for k_expr, arr in zip(rkeys, build_key_arrays):
                a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
                try:
                    p_type = evaluate_to_array(
                        k_expr, _empty_batch(self.right.df_schema)
                    ).type
                except Exception:  # noqa: BLE001 — fall back to the build type
                    p_type = a.type
                common = _common_type(a.type, p_type)
                key_types.append(common)
                prep_cols.append(a.cast(common) if a.type != common else a)
            prepared = PreparedBuild(prep_cols)
        else:
            prepared = None

        jt = self.join_type
        build_emitting = jt in ("left", "full", "left_semi", "left_anti")
        shared = self.mode == "collect_left" and build_emitting and self.right.output_partition_count() > 1
        if shared:
            with self._lock:
                if self._shared_matched is None:
                    self._shared_matched = np.zeros(build.num_rows, dtype=bool)
            matched_build = np.zeros(build.num_rows, dtype=bool)
        else:
            matched_build = np.zeros(build.num_rows, dtype=bool)
        produced = False

        for probe in self.right.execute(partition, ctx):
            if probe.num_rows == 0:
                continue
            probe_keys = [evaluate_to_array(k, probe) for k in rkeys]
            if prepared is not None:
                cast_keys = [
                    (a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a)
                    for a in probe_keys
                ]
                cast_keys = [
                    a.cast(ty) if a.type != ty else a
                    for a, ty in zip(cast_keys, key_types)
                ]
                bi, pi = prepared.match(cast_keys)
            else:
                bi = pi = np.zeros(0, dtype=np.int64)
            if filt is not None and len(bi):
                pair_batch = _pair_batch(build_batch, bi, probe, pi, combined_schema)
                mask = evaluate_to_array(filt, pair_batch)
                keep = pc.fill_null(mask, False).to_numpy(zero_copy_only=False)
                bi, pi = bi[keep], pi[keep]
            if len(bi):
                matched_build[bi] = True
            if jt == "inner":
                if len(bi):
                    produced = True
                    yield _emit_pairs(build_batch, bi, probe, pi, out_schema)
            elif jt in ("right", "full"):
                pm = np.zeros(probe.num_rows, dtype=bool)
                if len(pi):
                    pm[pi] = True
                out = []
                if len(bi):
                    out.append(_emit_pairs(build_batch, bi, probe, pi, out_schema))
                un = np.nonzero(~pm)[0]
                if len(un):
                    out.append(_emit_null_left(build_batch.schema, probe, un, out_schema))
                for b in out:
                    produced = True
                    yield b
            elif jt == "left":
                if len(bi):
                    produced = True
                    yield _emit_pairs(build_batch, bi, probe, pi, out_schema)
            elif jt == "right_semi":
                pm = np.zeros(probe.num_rows, dtype=bool)
                if len(pi):
                    pm[pi] = True
                sel = np.nonzero(pm)[0]
                if len(sel):
                    produced = True
                    yield _take_batch(probe, sel, out_schema)
            elif jt == "right_anti":
                pm = np.zeros(probe.num_rows, dtype=bool)
                if len(pi):
                    pm[pi] = True
                sel = np.nonzero(~pm)[0]
                if len(sel):
                    produced = True
                    yield _take_batch(probe, sel, out_schema)
            elif jt in ("left_semi", "left_anti"):
                pass  # emitted at end from matched_build
            else:
                raise ExecutionError(f"join type {jt} not supported")

        # end-of-probe emissions from the build side
        emit_tail = build_emitting
        if shared:
            with self._lock:
                self._shared_matched |= matched_build
                self._done_partitions += 1
                emit_tail = self._done_partitions == self.right.output_partition_count()
                if emit_tail:
                    matched_build = self._shared_matched
        if emit_tail and jt in ("left", "full"):
            un = np.nonzero(~matched_build)[0]
            if len(un):
                produced = True
                yield _emit_null_right(build_batch, un, self.right.schema(), out_schema)
        elif emit_tail and jt == "left_semi":
            sel = np.nonzero(matched_build)[0]
            if len(sel):
                produced = True
                yield _take_batch(build_batch, sel, out_schema)
        elif emit_tail and jt == "left_anti":
            sel = np.nonzero(~matched_build)[0]
            if len(sel):
                produced = True
                yield _take_batch(build_batch, sel, out_schema)
        if not produced:
            yield _empty_batch(out_schema)


def _take_batch(batch: pa.RecordBatch, idx: np.ndarray, out_schema: pa.Schema) -> pa.RecordBatch:
    t = batch.take(pa.array(idx))
    return pa.RecordBatch.from_arrays([c for c in t.columns], schema=out_schema)


def _pair_batch(build: pa.RecordBatch, bi, probe: pa.RecordBatch, pi, combined: DFSchema) -> pa.RecordBatch:
    bcols = build.take(pa.array(bi)).columns
    pcols = probe.take(pa.array(pi)).columns
    return pa.RecordBatch.from_arrays(list(bcols) + list(pcols), schema=combined.to_arrow())


def _emit_pairs(build, bi, probe, pi, out_schema) -> pa.RecordBatch:
    bcols = build.take(pa.array(bi)).columns
    pcols = probe.take(pa.array(pi)).columns
    return pa.RecordBatch.from_arrays(list(bcols) + list(pcols), schema=out_schema)


def _emit_null_left(build_schema: pa.Schema, probe, idx, out_schema) -> pa.RecordBatch:
    n = len(idx)
    bcols = [pa.nulls(n, f.type) for f in build_schema]
    pcols = probe.take(pa.array(idx)).columns
    return pa.RecordBatch.from_arrays(bcols + list(pcols), schema=out_schema)


def _emit_null_right(build, idx, right_schema: pa.Schema, out_schema) -> pa.RecordBatch:
    bcols = build.take(pa.array(idx)).columns
    n = len(idx)
    pcols = [pa.nulls(n, f.type) for f in right_schema]
    return pa.RecordBatch.from_arrays(list(bcols) + pcols, schema=out_schema)


class CrossJoinExec(ExecutionPlan):
    def __init__(self, left: ExecutionPlan, right: ExecutionPlan, df_schema: DFSchema):
        super().__init__(df_schema)
        self.left = left
        self.right = right
        self._cache: pa.Table | None = None
        self._lock = threading.Lock()

    def children(self):
        return [self.left, self.right]

    def with_children(self, c):
        return CrossJoinExec(c[0], c[1], self.df_schema)

    def output_partition_count(self) -> int:
        return self.right.output_partition_count()

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        with self._lock:
            if self._cache is None:
                batches = []
                for p in range(self.left.output_partition_count()):
                    batches.extend(b for b in self.left.execute(p, ctx) if b.num_rows)
                self._cache = _concat(batches, self.left.schema()).combine_chunks()
        build = self._cache
        out_schema = self.schema()
        produced = False
        nb = build.num_rows
        if nb == 0:
            yield _empty_batch(out_schema)
            return
        build_batch = build.to_batches()[0]
        for probe in self.right.execute(partition, ctx):
            if probe.num_rows == 0:
                continue
            npr = probe.num_rows
            bi = np.repeat(np.arange(nb, dtype=np.int64), npr)
            pi = np.tile(np.arange(npr, dtype=np.int64), nb)
            produced = True
            yield _emit_pairs(build_batch, bi, probe, pi, out_schema)
        if not produced:
            yield _empty_batch(out_schema)


# ---------------------------------------------------------------------------
# sort / limit / exchange
# ---------------------------------------------------------------------------


def _sort_table(tbl: pa.Table, df_schema: DFSchema, keys: list[SortKey]) -> pa.Table:
    if tbl.num_rows == 0:
        return tbl
    sort_cols = []
    aux = {}
    batch = tbl.combine_chunks().to_batches()[0]
    for i, k in enumerate(keys):
        pe = bind_expr(k.expr, df_schema)
        arr = evaluate_to_array(pe, batch)
        if arr.null_count:
            # null placement without the SortOptions kwarg: pyarrow ≥25
            # deprecates the global null_placement (the FutureWarning that
            # flooded the multichip dryrun tail) and older releases have no
            # per-key form — a leading is-null flag column expresses the
            # same order on every version, and honors nulls_first PER KEY
            # instead of only key 0's setting
            aux[f"__n{i}"] = pc.is_null(arr)
            sort_cols.append((f"__n{i}", "descending" if k.nulls_first else "ascending"))
        aux[f"__s{i}"] = arr
        sort_cols.append((f"__s{i}", "ascending" if k.ascending else "descending"))
    aux_tbl = pa.table(aux)
    idx = pc.sort_indices(aux_tbl, sort_keys=sort_cols)
    return tbl.take(idx)


class WindowExec(ExecutionPlan):
    """Computes window expressions, appending __win{i} columns.

    Contract: rows sharing a window PARTITION BY key never span physical
    partitions (the planner hash-repartitions on those keys, or coalesces
    to one partition when there are none), so partitions are independent.
    """

    def __init__(self, input: ExecutionPlan, window_exprs: list, df_schema: DFSchema):
        super().__init__(df_schema)
        self.input = input
        self.window_exprs = window_exprs

    def children(self):
        return [self.input]

    def with_children(self, c):
        return WindowExec(c[0], self.window_exprs, self.df_schema)

    def output_partition_count(self) -> int:
        return self.input.output_partition_count()

    def node_str(self) -> str:
        return f"WindowExec: [{', '.join(map(str, self.window_exprs))}]"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        from ballista_tpu.ops.cpu.window import compute_windows

        batches = [b for b in self.input.execute(partition, ctx) if b.num_rows]
        if not batches:
            yield _empty_batch(self.schema())
            return
        tbl = _concat(batches, self.input.schema())
        batch = tbl.combine_chunks().to_batches()[0] if tbl.num_rows else None
        if batch is None:
            yield _empty_batch(self.schema())
            return
        wins = compute_windows(batch, self.window_exprs, self.input.df_schema)
        arrays = [batch.column(i) for i in range(batch.num_columns)] + wins
        out = pa.RecordBatch.from_arrays(arrays, schema=self.schema())
        n = out.num_rows
        for off in range(0, n, ctx.batch_size):
            yield out.slice(off, min(ctx.batch_size, n - off))


class SortExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, keys: list[SortKey], fetch: Optional[int] = None):
        super().__init__(input.df_schema)
        self.input = input
        self.keys = keys
        self.fetch = fetch

    def children(self):
        return [self.input]

    def with_children(self, c):
        return SortExec(c[0], self.keys, self.fetch)

    def node_str(self) -> str:
        k = ", ".join(str(x) for x in self.keys)
        f = f", fetch={self.fetch}" if self.fetch is not None else ""
        return f"SortExec: [{k}]{f}"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        batches = [b for b in self.input.execute(partition, ctx) if b.num_rows]
        tbl = _concat(batches, self.schema())
        tbl = _sort_table(tbl, self.df_schema, self.keys)
        if self.fetch is not None:
            tbl = tbl.slice(0, self.fetch)
        if tbl.num_rows == 0:
            yield _empty_batch(self.schema())
            return
        for b in tbl.combine_chunks().to_batches(max_chunksize=ctx.batch_size):
            yield b


class SortPreservingMergeExec(ExecutionPlan):
    """N sorted partitions → 1 sorted partition. Implemented as gather +
    re-sort: simpler than a streaming k-way merge and equivalent because
    every input partition is already fully materialized by SortExec."""

    def __init__(self, input: ExecutionPlan, keys: list[SortKey], fetch: Optional[int] = None):
        super().__init__(input.df_schema)
        self.input = input
        self.keys = keys
        self.fetch = fetch

    def children(self):
        return [self.input]

    def with_children(self, c):
        return SortPreservingMergeExec(c[0], self.keys, self.fetch)

    def output_partition_count(self) -> int:
        return 1

    def node_str(self) -> str:
        return f"SortPreservingMergeExec: [{', '.join(str(k) for k in self.keys)}]"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        batches = []
        for p in range(self.input.output_partition_count()):
            batches.extend(b for b in self.input.execute(p, ctx) if b.num_rows)
        tbl = _sort_table(_concat(batches, self.schema()), self.df_schema, self.keys)
        if self.fetch is not None:
            tbl = tbl.slice(0, self.fetch)
        if tbl.num_rows == 0:
            yield _empty_batch(self.schema())
            return
        for b in tbl.combine_chunks().to_batches(max_chunksize=ctx.batch_size):
            yield b


class CoalescePartitionsExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan):
        super().__init__(input.df_schema)
        self.input = input

    def children(self):
        return [self.input]

    def with_children(self, c):
        return CoalescePartitionsExec(c[0])

    def output_partition_count(self) -> int:
        return 1

    def execute(self, partition, ctx):
        return self._timed(self._run(ctx))

    def _run(self, ctx):
        for p in range(self.input.output_partition_count()):
            yield from self.input.execute(p, ctx)


class LocalLimitExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, fetch: int):
        super().__init__(input.df_schema)
        self.input = input
        self.fetch = fetch

    def children(self):
        return [self.input]

    def with_children(self, c):
        return LocalLimitExec(c[0], self.fetch)

    def node_str(self) -> str:
        return f"LocalLimitExec: fetch={self.fetch}"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        left = self.fetch
        for b in self.input.execute(partition, ctx):
            if left <= 0:
                return
            if b.num_rows > left:
                yield b.slice(0, left)
                return
            left -= b.num_rows
            yield b


class GlobalLimitExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, fetch: Optional[int], skip: int = 0):
        super().__init__(input.df_schema)
        self.input = input
        self.fetch = fetch
        self.skip = skip

    def children(self):
        return [self.input]

    def with_children(self, c):
        return GlobalLimitExec(c[0], self.fetch, self.skip)

    def output_partition_count(self) -> int:
        return 1

    def node_str(self) -> str:
        return f"GlobalLimitExec: fetch={self.fetch}, skip={self.skip}"

    def execute(self, partition, ctx):
        return self._timed(self._run(ctx))

    def _run(self, ctx):
        skip = self.skip
        left = self.fetch if self.fetch is not None else None
        assert self.input.output_partition_count() == 1
        for b in self.input.execute(0, ctx):
            if skip:
                if b.num_rows <= skip:
                    skip -= b.num_rows
                    continue
                b = b.slice(skip)
                skip = 0
            if left is None:
                yield b
                continue
            if left <= 0:
                return
            if b.num_rows > left:
                yield b.slice(0, left)
                return
            left -= b.num_rows
            yield b


class RepartitionExec(ExecutionPlan):
    """In-process exchange. scheme='hash' routes rows by the shared
    deterministic key hash (ops/hashing.py); 'round_robin' balances batches.
    The distributed planner replaces these with shuffle boundaries."""

    def __init__(self, input: ExecutionPlan, scheme: str, n: int, keys: list[Expr] | None = None):
        super().__init__(input.df_schema)
        self.input = input
        self.scheme = scheme
        self.n = n
        self.keys = keys or []
        self._cache: list[list[pa.RecordBatch]] | None = None
        self._lock = threading.Lock()

    def children(self):
        return [self.input]

    def with_children(self, c):
        return RepartitionExec(c[0], self.scheme, self.n, self.keys)

    def output_partition_count(self) -> int:
        return self.n

    def node_str(self) -> str:
        k = f"({', '.join(str(e) for e in self.keys)})" if self.keys else ""
        return f"RepartitionExec: {self.scheme}{k}, n={self.n}"

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _materialize(self, ctx) -> list[list[pa.RecordBatch]]:
        with self._lock:
            if self._cache is not None:
                return self._cache
            outs: list[list[pa.RecordBatch]] = [[] for _ in range(self.n)]
            bound = [bind_expr(k, self.input.df_schema) for k in self.keys]
            rr = 0
            for p in range(self.input.output_partition_count()):
                for b in self.input.execute(p, ctx):
                    if b.num_rows == 0:
                        continue
                    if self.scheme == "round_robin":
                        outs[rr % self.n].append(b)
                        rr += 1
                    else:
                        from ballista_tpu.ops.hashing import split_batch_by_partition

                        key_arrays = [evaluate_to_array(k, b) for k in bound]
                        for k, part in split_batch_by_partition(b, key_arrays, self.n):
                            outs[k].append(part)
            self._cache = outs
            return outs

    def _run(self, partition, ctx):
        outs = self._materialize(ctx)
        batches = outs[partition]
        if not batches:
            yield _empty_batch(self.schema())
            return
        yield from batches


class UnionExec(ExecutionPlan):
    def __init__(self, inputs: list[ExecutionPlan], df_schema: DFSchema):
        super().__init__(df_schema)
        self.inputs = inputs

    def children(self):
        return list(self.inputs)

    def with_children(self, c):
        return UnionExec(c, self.df_schema)

    def output_partition_count(self) -> int:
        return sum(c.output_partition_count() for c in self.inputs)

    def execute(self, partition, ctx):
        off = partition
        for c in self.inputs:
            n = c.output_partition_count()
            if off < n:
                schema = self.schema()
                return self._timed(
                    (_align_batch(b, schema) for b in c.execute(off, ctx))
                )
            off -= n
        raise ExecutionError("bad union partition")
