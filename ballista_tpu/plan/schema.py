"""Schema model for logical/physical plans.

A thin qualified-name layer over `pyarrow.Schema`: each field may carry a
relation qualifier (`lineitem.l_orderkey`). The reference gets this from
DataFusion's DFSchema; we rebuild just the parts planning needs — qualified
lookup, ambiguity detection, merge for joins.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import pyarrow as pa

from ballista_tpu.errors import SchemaError


@dataclass(frozen=True)
class DFField:
    name: str
    dtype: pa.DataType
    nullable: bool = True
    qualifier: str | None = None

    @property
    def qualified_name(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name

    def to_arrow(self) -> pa.Field:
        return pa.field(self.name, self.dtype, self.nullable)

    def __repr__(self) -> str:
        return f"{self.qualified_name}:{self.dtype}"


class DFSchema:
    def __init__(self, fields: list[DFField]):
        self.fields = list(fields)
        self._by_name: dict[str, list[int]] = {}
        for i, f in enumerate(self.fields):
            self._by_name.setdefault(f.name, []).append(i)

    @classmethod
    def from_arrow(cls, schema: pa.Schema, qualifier: str | None = None) -> "DFSchema":
        return cls([DFField(f.name, f.type, f.nullable, qualifier) for f in schema])

    def to_arrow(self) -> pa.Schema:
        return pa.schema([f.to_arrow() for f in self.fields])

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self) -> Iterator[DFField]:
        return iter(self.fields)

    def field(self, i: int) -> DFField:
        return self.fields[i]

    def index_of(self, name: str, qualifier: str | None = None) -> int:
        """Resolve a possibly-qualified column reference to a field index."""
        if qualifier is not None:
            matches = [
                i
                for i in self._by_name.get(name, [])
                if self.fields[i].qualifier == qualifier
            ]
            if not matches:
                raise SchemaError(f"no column {qualifier}.{name} in schema {self}")
            if len(matches) > 1:
                raise SchemaError(f"ambiguous column {qualifier}.{name}")
            return matches[0]
        matches = self._by_name.get(name, [])
        if not matches:
            raise SchemaError(f"no column {name} in schema {self}")
        if len(matches) > 1:
            quals = {self.fields[i].qualifier for i in matches}
            if len(quals) > 1:
                raise SchemaError(
                    f"ambiguous column {name}: qualify with one of {sorted(q or '?' for q in quals)}"
                )
        return matches[0]

    def maybe_index_of(self, name: str, qualifier: str | None = None) -> int | None:
        try:
            return self.index_of(name, qualifier)
        except SchemaError:
            return None

    def merge(self, other: "DFSchema") -> "DFSchema":
        return DFSchema(self.fields + other.fields)

    def strip_qualifiers(self) -> "DFSchema":
        return DFSchema([DFField(f.name, f.dtype, f.nullable, None) for f in self.fields])

    def with_qualifier(self, qualifier: str) -> "DFSchema":
        return DFSchema([DFField(f.name, f.dtype, f.nullable, qualifier) for f in self.fields])

    def __repr__(self) -> str:
        return "[" + ", ".join(repr(f) for f in self.fields) + "]"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DFSchema) and [
            (f.name, f.dtype, f.qualifier) for f in self.fields
        ] == [(f.name, f.dtype, f.qualifier) for f in other.fields]


EMPTY_SCHEMA = DFSchema([])
