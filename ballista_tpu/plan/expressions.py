"""Logical expression IR.

The expression vocabulary is scoped to what the TPC-H/TPC-DS query classes
need (the reference outsources this to DataFusion; see SURVEY.md §1 "engine
under it all"): column refs, literals, arithmetic/comparison/boolean ops,
CASE, casts, LIKE, IN, BETWEEN, scalar functions (date EXTRACT/substr/...),
aggregate functions, and subquery placeholders that the optimizer
decorrelates into joins before execution.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _decimal
from dataclasses import dataclass, field, replace
from typing import Any, Sequence

import pyarrow as pa

from ballista_tpu.errors import PlanningError, SchemaError
from ballista_tpu.plan.schema import DFField, DFSchema


class Expr:
    """Base logical expression."""

    def children(self) -> list["Expr"]:
        return []

    def with_children(self, children: list["Expr"]) -> "Expr":
        assert not children
        return self

    def data_type(self, schema: DFSchema) -> pa.DataType:
        raise NotImplementedError(type(self).__name__)

    def nullable(self, schema: DFSchema) -> bool:
        return True

    def output_name(self) -> str:
        return str(self)

    # -- convenience builders (DataFrame API surface) -----------------------
    def __add__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "+", lit(other))

    def __sub__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "-", lit(other))

    def __mul__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "*", lit(other))

    def __truediv__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "/", lit(other))

    def __gt__(self, other: Any) -> "Expr":
        return BinaryExpr(self, ">", lit(other))

    def __ge__(self, other: Any) -> "Expr":
        return BinaryExpr(self, ">=", lit(other))

    def __lt__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "<", lit(other))

    def __le__(self, other: Any) -> "Expr":
        return BinaryExpr(self, "<=", lit(other))

    def eq(self, other: Any) -> "Expr":
        return BinaryExpr(self, "=", lit(other))

    def neq(self, other: Any) -> "Expr":
        return BinaryExpr(self, "<>", lit(other))

    def alias(self, name: str) -> "Expr":
        return Alias(self, name)

    def is_null(self) -> "Expr":
        return IsNull(self)

    def sort(self, ascending: bool = True, nulls_first: bool | None = None) -> "SortKey":
        return SortKey(self, ascending, nulls_first if nulls_first is not None else not ascending)


def lit(v: Any) -> "Expr":
    if isinstance(v, Expr):
        return v
    return Literal(v)


@dataclass(frozen=True)
class Column(Expr):
    name: str
    qualifier: str | None = None

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return schema.field(schema.index_of(self.name, self.qualifier)).dtype

    def nullable(self, schema: DFSchema) -> bool:
        return schema.field(schema.index_of(self.name, self.qualifier)).nullable

    def output_name(self) -> str:
        return self.name

    def __str__(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


def col(name: str) -> Column:
    if "." in name:
        q, n = name.rsplit(".", 1)
        return Column(n, q)
    return Column(name)


@dataclass(frozen=True)
class Literal(Expr):
    value: Any
    # parameter slot assigned by the serving tier's plan normalizer
    # (serving/normalize.py). Excluded from equality/repr so tagged plans
    # stay indistinguishable from untagged ones everywhere else.
    param: int | None = field(default=None, compare=False, repr=False)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return literal_type(self.value)

    def nullable(self, schema: DFSchema) -> bool:
        return self.value is None

    def __str__(self) -> str:
        if isinstance(self.value, str):
            return f"'{self.value}'"
        if isinstance(self.value, _dt.date):
            return f"DATE '{self.value.isoformat()}'"
        return str(self.value)


def literal_type(v: Any) -> pa.DataType:
    if v is None:
        return pa.null()
    if isinstance(v, bool):
        return pa.bool_()
    if isinstance(v, int):
        return pa.int64()
    if isinstance(v, float):
        return pa.float64()
    if isinstance(v, _decimal.Decimal):
        # minimal precision/scale from the digits (pa.scalar's own typing):
        # tight literal types keep decimal arithmetic chains under the
        # precision caps — the lynchpin of the exact-decimal policy
        return pa.scalar(v).type
    if isinstance(v, str):
        return pa.string()
    if isinstance(v, _dt.date):
        return pa.date32()
    raise PlanningError(f"unsupported literal {v!r}")


_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}
_BOOL_OPS = {"and", "or"}
_ARITH_OPS = {"+", "-", "*", "/", "%"}


@dataclass(frozen=True)
class BinaryExpr(Expr):
    left: Expr
    op: str  # one of _CMP_OPS | _BOOL_OPS | _ARITH_OPS
    right: Expr

    def children(self) -> list[Expr]:
        return [self.left, self.right]

    def with_children(self, children: list[Expr]) -> "Expr":
        return BinaryExpr(children[0], self.op, children[1])

    def data_type(self, schema: DFSchema) -> pa.DataType:
        if self.op in _CMP_OPS or self.op in _BOOL_OPS:
            return pa.bool_()
        lt, rt = self.left.data_type(schema), self.right.data_type(schema)
        if pa.types.is_decimal(lt) or pa.types.is_decimal(rt):
            return decimal_arith_type(self.left, self.right, lt, rt, self.op)
        return arith_result_type(lt, rt, self.op)

    def __str__(self) -> str:
        op = self.op.upper() if self.op in _BOOL_OPS else self.op
        return f"({self.left} {op} {self.right})"


def arith_result_type(lt: pa.DataType, rt: pa.DataType, op: str) -> pa.DataType:
    # date +/- interval days → date
    if pa.types.is_date(lt):
        return lt
    if pa.types.is_date(rt):
        return rt
    if pa.types.is_floating(lt) or pa.types.is_floating(rt) or op == "/":
        return pa.float64()
    if pa.types.is_decimal(lt) or pa.types.is_decimal(rt):
        # value-blind fallback (callers without the exprs); BinaryExpr uses
        # the value-aware decimal_arith_type instead
        return decimal_arith_type(None, None, lt, rt, op)
    return pa.int64()


def _effective_decimal(expr: "Expr | None", t: pa.DataType):
    """(precision, scale) a side contributes to Arrow's decimal arithmetic.
    Integer LITERALS get minimal digits — matching the evaluator, which
    re-types them as tight decimal scalars (ops/phys_expr.py) so chains like
    price*(1-disc)*(1+tax) stay inside the 38/76 precision caps. Non-literal
    integers take Arrow's own widths (int64→(19,0) etc.)."""
    if pa.types.is_decimal(t):
        return t.precision, t.scale
    if expr is not None and isinstance(expr, Literal) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return max(1, len(str(abs(expr.value)))), 0
    if pa.types.is_integer(t):
        return {1: 3, 2: 5, 4: 10, 8: 19}.get(t.bit_width // 8, 19), 0
    return None  # float/other: not a decimal operand


def sum_result_type(t: pa.DataType) -> pa.DataType:
    """SUM output typing, shared by aggregates, window functions and the
    physical planner's accumulator schema (one rule — they must agree).
    Exact decimal sums widen to max precision (DataFusion's rule; keeps
    billion-row money sums from overflowing the input type)."""
    if pa.types.is_integer(t):
        return pa.int64()
    if pa.types.is_decimal128(t):
        return pa.decimal128(38, t.scale)
    if pa.types.is_decimal256(t):
        return pa.decimal256(76, t.scale)
    return pa.float64()


def decimal_arith_type(le: "Expr | None", re: "Expr | None",
                       lt: pa.DataType, rt: pa.DataType, op: str) -> pa.DataType:
    """Arrow's decimal result-type rules (exact decimal policy — reference
    behavior: DataFusion decimal128 exactness, SURVEY §7 hard-part #2).
    Division and chains past decimal256's cap degrade to float64; the
    evaluator mirrors every branch (ops/phys_expr.py::_decimal_binop)."""
    if op in ("/", "%"):
        return pa.float64()
    l = _effective_decimal(le, lt)
    r = _effective_decimal(re, rt)
    if l is None or r is None:  # mixed with float → float64 (Arrow promotes)
        return pa.float64()
    (lp, ls), (rp, rs) = l, r
    if op == "*":
        p, s = lp + rp + 1, ls + rs
    else:  # + -
        s = max(ls, rs)
        p = max(lp - ls, rp - rs) + s + 1
    if p <= 38 and not pa.types.is_decimal256(lt) and not pa.types.is_decimal256(rt):
        return pa.decimal128(p, s)
    if p <= 76:
        return pa.decimal256(min(p, 76), s)
    return pa.float64()


def and_(*exprs: Expr) -> Expr:
    exprs = [e for e in exprs if e is not None]
    if not exprs:
        raise PlanningError("and_ of nothing")
    out = exprs[0]
    for e in exprs[1:]:
        out = BinaryExpr(out, "and", e)
    return out


def split_conjunction(e: Expr) -> list[Expr]:
    if isinstance(e, BinaryExpr) and e.op == "and":
        return split_conjunction(e.left) + split_conjunction(e.right)
    return [e]


@dataclass(frozen=True)
class Not(Expr):
    expr: Expr

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Not(c[0])

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        return f"NOT {self.expr}"


@dataclass(frozen=True)
class Negative(Expr):
    expr: Expr

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Negative(c[0])

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return self.expr.data_type(schema)

    def __str__(self) -> str:
        return f"(- {self.expr})"


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return IsNull(c[0])

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def nullable(self, schema: DFSchema) -> bool:
        return False

    def __str__(self) -> str:
        return f"{self.expr} IS NULL"


@dataclass(frozen=True)
class IsNotNull(Expr):
    expr: Expr

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return IsNotNull(c[0])

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def nullable(self, schema: DFSchema) -> bool:
        return False

    def __str__(self) -> str:
        return f"{self.expr} IS NOT NULL"


@dataclass(frozen=True)
class Alias(Expr):
    expr: Expr
    name: str

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Alias(c[0], self.name)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return self.expr.data_type(schema)

    def nullable(self, schema: DFSchema) -> bool:
        return self.expr.nullable(schema)

    def output_name(self) -> str:
        return self.name

    def __str__(self) -> str:
        return f"{self.expr} AS {self.name}"


@dataclass(frozen=True)
class Cast(Expr):
    expr: Expr
    to: pa.DataType

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Cast(c[0], self.to)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return self.to

    def nullable(self, schema: DFSchema) -> bool:
        return self.expr.nullable(schema)

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.to})"


@dataclass(frozen=True)
class Like(Expr):
    expr: Expr
    pattern: str  # SQL LIKE pattern with % and _
    negated: bool = False

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Like(c[0], self.pattern, self.negated)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        n = " NOT" if self.negated else ""
        return f"{self.expr}{n} LIKE '{self.pattern}'"


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    values: tuple[Any, ...]  # python scalars
    negated: bool = False

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return InList(c[0], self.values, self.negated)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        n = " NOT" if self.negated else ""
        vals = ", ".join(repr(v) if not isinstance(v, str) else f"'{v}'" for v in self.values)
        return f"{self.expr}{n} IN ({vals})"


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False

    def children(self) -> list[Expr]:
        return [self.expr, self.low, self.high]

    def with_children(self, c: list[Expr]) -> "Expr":
        return Between(c[0], c[1], c[2], self.negated)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        n = " NOT" if self.negated else ""
        return f"{self.expr}{n} BETWEEN {self.low} AND {self.high}"


@dataclass(frozen=True)
class Case(Expr):
    """CASE [expr] WHEN .. THEN .. ELSE .. END (searched form only after binding)."""

    branches: tuple[tuple[Expr, Expr], ...]  # (when_predicate, then_value)
    else_expr: Expr | None = None

    def children(self) -> list[Expr]:
        out: list[Expr] = []
        for w, t in self.branches:
            out.extend((w, t))
        if self.else_expr is not None:
            out.append(self.else_expr)
        return out

    def with_children(self, c: list[Expr]) -> "Expr":
        n = len(self.branches)
        branches = tuple((c[2 * i], c[2 * i + 1]) for i in range(n))
        els = c[2 * n] if self.else_expr is not None else None
        return Case(branches, els)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        t = self.branches[0][1].data_type(schema)
        if pa.types.is_null(t) and self.else_expr is not None:
            return self.else_expr.data_type(schema)
        # numeric widening across branches
        for _, then in self.branches[1:]:
            t = _widen(t, then.data_type(schema))
        if self.else_expr is not None:
            t = _widen(t, self.else_expr.data_type(schema))
        return t

    def __str__(self) -> str:
        parts = ["CASE"]
        for w, t in self.branches:
            parts.append(f"WHEN {w} THEN {t}")
        if self.else_expr is not None:
            parts.append(f"ELSE {self.else_expr}")
        parts.append("END")
        return " ".join(parts)


def _widen(a: pa.DataType, b: pa.DataType) -> pa.DataType:
    if a == b:
        return a
    if pa.types.is_null(a):
        return b
    if pa.types.is_null(b):
        return a
    if pa.types.is_decimal(a) or pa.types.is_decimal(b):
        # CASE branches mixing decimal with numerics: two decimals widen to
        # cover both (integer digits and scale); decimal+int grows integer
        # digits by Arrow's int width; decimal+float falls to float64
        def dims(t):
            if pa.types.is_decimal(t):
                return t.precision - t.scale, t.scale
            if pa.types.is_integer(t):
                return {8: 3, 16: 5, 32: 10, 64: 19}.get(t.bit_width, 19), 0
            return None
        da, db = dims(a), dims(b)
        if da is None or db is None:
            return pa.float64()
        ints, scale = max(da[0], db[0]), max(da[1], db[1])
        p = ints + scale
        if p <= 38 and not pa.types.is_decimal256(a) and not pa.types.is_decimal256(b):
            return pa.decimal128(p, scale)
        if p <= 76:
            return pa.decimal256(p, scale)
        return pa.float64()
    if (pa.types.is_integer(a) or pa.types.is_floating(a)) and (
        pa.types.is_integer(b) or pa.types.is_floating(b)
    ):
        if pa.types.is_floating(a) or pa.types.is_floating(b):
            return pa.float64()
        return pa.int64()
    return a


@dataclass(frozen=True)
class ScalarFunction(Expr):
    """Named scalar function; the registry in ops/ defines evaluation."""

    name: str  # extract_year, substr, strpos, length, abs, round, coalesce, date_part...
    args: tuple[Expr, ...]

    def children(self) -> list[Expr]:
        return list(self.args)

    def with_children(self, c: list[Expr]) -> "Expr":
        return ScalarFunction(self.name, tuple(c))

    def data_type(self, schema: DFSchema) -> pa.DataType:
        n = self.name
        if n in ("extract_year", "extract_month", "extract_day", "strpos", "length"):
            return pa.int64()
        if n in ("substr", "upper", "lower", "trim", "concat"):
            return pa.string()
        if n in ("abs", "round", "ceil", "floor"):
            return self.args[0].data_type(schema)
        if n == "sqrt":
            return pa.float64()
        if n == "coalesce":
            for a in self.args:
                t = a.data_type(schema)
                if not pa.types.is_null(t):
                    return t
            return pa.null()
        if n == "date_trunc":
            return pa.date32()
        from ballista_tpu import udf

        u = udf.resolve(n)
        if u is not None:
            return u.return_type
        raise PlanningError(f"unknown scalar function {n}")

    def __str__(self) -> str:
        return f"{self.name}({', '.join(map(str, self.args))})"


WINDOW_FUNCS = ("row_number", "rank", "dense_rank", "lag", "lead",
                "sum", "avg", "min", "max", "count")


@dataclass(frozen=True)
class WindowFunction(Expr):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [frame]).

    Default frame follows SQL: with ORDER BY, aggregates run RANGE
    UNBOUNDED PRECEDING..CURRENT ROW (peers share); without, the whole
    partition. `frame` = ("rows", start, end) for explicit ROWS frames:
    offsets relative to the current row (negative = preceding, None =
    unbounded in that direction)."""

    func: str  # one of WINDOW_FUNCS
    args: tuple  # aggregates: (expr,) or (); lag/lead: (expr[, offset[, default]])
    partition_by: tuple = ()
    order_by: tuple = ()  # SortKey tuple
    frame: tuple | None = None  # ("rows", start|None, end|None)

    def children(self) -> list["Expr"]:
        return list(self.args) + list(self.partition_by) + [k.expr for k in self.order_by]

    def with_children(self, c: list["Expr"]) -> "Expr":
        na = len(self.args)
        np_ = len(self.partition_by)
        keys = tuple(
            SortKey(e, k.ascending, k.nulls_first)
            for e, k in zip(c[na + np_:], self.order_by)
        )
        return WindowFunction(
            self.func, tuple(c[:na]), tuple(c[na:na + np_]), keys, self.frame
        )

    def data_type(self, schema: DFSchema) -> pa.DataType:
        if self.func in ("row_number", "rank", "dense_rank", "count"):
            return pa.int64()
        if self.func == "avg":
            return pa.float64()
        t = self.args[0].data_type(schema)
        if self.func == "sum":
            return sum_result_type(t)
        return t

    def __str__(self) -> str:
        a = ", ".join(map(str, self.args))
        parts = []
        if self.partition_by:
            parts.append("PARTITION BY " + ", ".join(map(str, self.partition_by)))
        if self.order_by:
            parts.append("ORDER BY " + ", ".join(map(str, self.order_by)))
        if self.frame is not None:
            def b(v, side):
                if v is None:
                    return f"UNBOUNDED {side}"
                if v == 0:
                    return "CURRENT ROW"
                return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"

            parts.append(
                f"ROWS BETWEEN {b(self.frame[1], 'PRECEDING')} AND {b(self.frame[2], 'FOLLOWING')}"
            )
        return f"{self.func}({a}) OVER ({' '.join(parts)})"


AGG_FUNCS = ("sum", "avg", "min", "max", "count", "count_distinct",
             "stddev_samp", "stddev_pop", "var_samp", "var_pop")

# aggregates whose result is always float64 (decomposed into Welford
# (count, mean, M2) partials by the physical planner — see _plan_aggregate)
VARIANCE_FUNCS = ("stddev_samp", "stddev_pop", "var_samp", "var_pop")


@dataclass(frozen=True)
class AggregateFunction(Expr):
    func: str  # one of AGG_FUNCS
    arg: Expr | None  # None for count(*)
    distinct: bool = False

    def children(self) -> list[Expr]:
        return [self.arg] if self.arg is not None else []

    def with_children(self, c: list[Expr]) -> "Expr":
        return AggregateFunction(self.func, c[0] if c else None, self.distinct)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        if self.func in ("count", "count_distinct"):
            return pa.int64()
        if self.func == "avg" or self.func in VARIANCE_FUNCS:
            return pa.float64()
        assert self.arg is not None
        t = self.arg.data_type(schema)
        if self.func == "sum":
            return sum_result_type(t)
        return t

    def output_name(self) -> str:
        return str(self)

    def __str__(self) -> str:
        if self.arg is None:
            return "count(*)"
        d = "DISTINCT " if self.distinct or self.func == "count_distinct" else ""
        f = "count" if self.func == "count_distinct" else self.func
        return f"{f}({d}{self.arg})"


@dataclass(frozen=True)
class SortKey:
    """Not an Expr — ordering spec used by Sort nodes."""

    expr: Expr
    ascending: bool = True
    nulls_first: bool = False

    def __str__(self) -> str:
        d = "ASC" if self.ascending else "DESC"
        n = " NULLS FIRST" if self.nulls_first else ""
        return f"{self.expr} {d}{n}"


# -- subquery placeholders (removed by the decorrelation optimizer) ---------


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    plan: Any  # LogicalPlan; Any to avoid circular import

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return self.plan.schema.field(0).dtype

    def __str__(self) -> str:
        return "(<scalar subquery>)"


@dataclass(frozen=True)
class InSubquery(Expr):
    expr: Expr
    plan: Any
    negated: bool = False

    def children(self) -> list[Expr]:
        return [self.expr]

    def with_children(self, c: list[Expr]) -> "Expr":
        return InSubquery(c[0], self.plan, self.negated)

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        n = " NOT" if self.negated else ""
        return f"{self.expr}{n} IN (<subquery>)"


@dataclass(frozen=True)
class Exists(Expr):
    plan: Any
    negated: bool = False

    def data_type(self, schema: DFSchema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        n = "NOT " if self.negated else ""
        return f"{n}EXISTS (<subquery>)"


# -- tree utilities ---------------------------------------------------------


def transform_expr(e: Expr, fn) -> Expr:
    """Bottom-up rewrite."""
    kids = e.children()
    if kids:
        new_kids = [transform_expr(k, fn) for k in kids]
        # identity, not equality: rewrites may swap in nodes that compare
        # equal to the originals (e.g. Literal carries non-compared metadata)
        if any(a is not b for a, b in zip(new_kids, kids)):
            e = e.with_children(new_kids)
    return fn(e)


def expr_any(e: Expr, pred) -> bool:
    if pred(e):
        return True
    return any(expr_any(c, pred) for c in e.children())


def collect_columns(e: Expr, out: set | None = None) -> set:
    if out is None:
        out = set()
    if isinstance(e, Column):
        out.add(e)
    for c in e.children():
        collect_columns(c, out)
    # subquery plans keep their own columns; outer refs handled by decorrelator
    return out


def to_field(e: Expr, schema: DFSchema) -> DFField:
    # Plain column references keep their qualifier through projections so
    # self-join disambiguation (e.g. lineitem l1 vs l2) survives SELECT *.
    qualifier = e.qualifier if isinstance(e, Column) else None
    return DFField(e.output_name(), e.data_type(schema), e.nullable(schema), qualifier)
