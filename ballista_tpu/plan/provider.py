"""Table providers: how scans get their data.

The reference delegates to DataFusion's TableProvider/ObjectStore stack
(listing tables over parquet on local disk or S3). We provide:

- ParquetTable: a directory (or list) of parquet files; file-level
  partitioning, column projection + predicate pushdown into the reader,
  row-group pruning via parquet statistics.
- MemoryTable: in-memory record batches (used by tests / VALUES / caches).

Statistics (row counts, byte sizes, per-column min/max) feed the physical
optimizer's broadcast-join decisions, matching the reference's
JoinSelection-by-stats (scheduler/src/physical_optimizer/join_selection.rs).
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Any, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ballista_tpu.plan.schema import DFSchema


@dataclass
class ColumnStats:
    min_value: Any = None
    max_value: Any = None
    null_count: int | None = None
    distinct_count: int | None = None


@dataclass
class TableStats:
    num_rows: int | None = None
    total_bytes: int | None = None
    columns: dict[str, ColumnStats] | None = None

    @property
    def exact(self) -> bool:
        return self.num_rows is not None


class TableProvider:
    def arrow_schema(self) -> pa.Schema:
        raise NotImplementedError

    def df_schema(self) -> DFSchema:
        return DFSchema.from_arrow(self.arrow_schema())

    def statistics(self) -> TableStats:
        return TableStats()

    def scan_partitions(self, target_partitions: int) -> list[dict]:
        """Split the table into partition descriptors (serializable dicts).

        Each descriptor is what one scan task reads; the scheduler's per-task
        plan restriction slices this list (reference: task_builder.rs).
        """
        raise NotImplementedError


class ParquetTable(TableProvider):
    def __init__(self, path: str, collect_statistics: bool = True):
        self.path = path
        if path.startswith("s3://"):
            from ballista_tpu.plan.object_store import resolve_filesystem
            import pyarrow.fs as pafs

            fs, inner = resolve_filesystem(path)
            infos = fs.get_file_info(pafs.FileSelector(inner.rstrip("/"), recursive=True))
            self.files = sorted(
                "s3://" + i.path for i in infos if i.path.endswith(".parquet")
            ) or [path]
        elif os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "**", "*.parquet"), recursive=True))
        elif "*" in path:
            self.files = sorted(glob.glob(path))
        else:
            self.files = [path]
        if not self.files:
            raise FileNotFoundError(f"no parquet files under {path}")
        self._schema = _normalize_schema(_read_schema(self.files[0]))
        self._stats: TableStats | None = None
        if collect_statistics:
            self._collect_stats()

    def arrow_schema(self) -> pa.Schema:
        return self._schema

    def _collect_stats(self) -> None:
        rows = 0
        tbytes = 0
        for f in self.files:
            md = _read_metadata(f)
            rows += md.num_rows
            tbytes += sum(
                md.row_group(i).total_byte_size for i in range(md.num_row_groups)
            )
        self._stats = TableStats(num_rows=rows, total_bytes=tbytes)

    def statistics(self) -> TableStats:
        return self._stats or TableStats()

    def scan_partitions(self, target_partitions: int) -> list[dict]:
        """One partition per (file, row-group range), rebalanced to roughly
        `target_partitions` groups by byte size."""
        units: list[tuple[str, int, int]] = []  # (file, rg_index, bytes)
        for f in self.files:
            md = _read_metadata(f)
            for rg in range(md.num_row_groups):
                units.append((f, rg, md.row_group(rg).total_byte_size))
        if not units:
            return [{"file": f, "row_groups": None} for f in self.files]
        target = max(1, min(target_partitions, len(units)))
        # greedy LPT bin packing by bytes
        bins: list[list[tuple[str, int]]] = [[] for _ in range(target)]
        sizes = [0] * target
        for f, rg, sz in sorted(units, key=lambda u: -u[2]):
            i = sizes.index(min(sizes))
            bins[i].append((f, rg))
            sizes[i] += sz
        parts = []
        for b in bins:
            if not b:
                continue
            by_file: dict[str, list[int]] = {}
            for f, rg in b:
                by_file.setdefault(f, []).append(rg)
            parts.append(
                {"files": [{"file": f, "row_groups": sorted(rgs)} for f, rgs in sorted(by_file.items())]}
            )
        return parts


def _normalize_schema(schema: pa.Schema) -> pa.Schema:
    """Exact decimal policy: decimal128 columns keep their type end-to-end —
    parser literals carry minimal precision, arithmetic follows Arrow's
    decimal rules with decimal256 widening (plan/expressions.py::
    decimal_arith_type), and sums aggregate at max precision. This replaces
    the round-4 float64 coercion policy (the reference gets the same
    exactness from DataFusion decimal128; SURVEY §7 hard-part #2). Only
    decimals beyond 256-bit range — which parquet cannot produce — would
    need normalization, so this is now the identity."""
    return schema


def _read_schema(path: str) -> pa.Schema:
    if path.startswith("s3://"):
        from ballista_tpu.plan.object_store import resolve_filesystem

        fs, inner = resolve_filesystem(path)
        return pq.read_schema(inner, filesystem=fs)
    return pq.read_schema(path)


def _read_metadata(path: str):
    if path.startswith("s3://"):
        from ballista_tpu.plan.object_store import resolve_filesystem

        fs, inner = resolve_filesystem(path)
        return pq.read_metadata(inner, filesystem=fs)
    return pq.read_metadata(path)


class MemoryTable(TableProvider):
    def __init__(self, batches: list[pa.RecordBatch], schema: pa.Schema | None = None, partitions: int = 1):
        raw = schema or (batches[0].schema if batches else pa.schema([]))
        self._schema = _normalize_schema(raw)
        if self._schema is not raw and batches:
            tbl = pa.Table.from_batches(batches, raw).cast(self._schema)
            batches = tbl.to_batches()
        self.batches = batches
        self.partitions = max(1, partitions)

    @classmethod
    def from_table(cls, table: pa.Table, partitions: int = 1) -> "MemoryTable":
        return cls(table.to_batches(), table.schema, partitions)

    def arrow_schema(self) -> pa.Schema:
        return self._schema

    def statistics(self) -> TableStats:
        rows = sum(b.num_rows for b in self.batches)
        nbytes = sum(b.nbytes for b in self.batches)
        return TableStats(num_rows=rows, total_bytes=nbytes)

    def scan_partitions(self, target_partitions: int) -> list[dict]:
        n = min(self.partitions, max(1, len(self.batches))) if self.batches else 1
        return [{"memory_partition": i, "of": n} for i in range(n)]


class AppendedTable(TableProvider):
    """A base provider overlaid with appended in-memory batches — local
    mode's mirror of the scheduler's ingest DeltaRegistry. `ctx.append`
    wraps the registered provider once and extends the overlay on each
    call; the planner unions the base scan with a memory scan of the
    overlay (engine/physical_planner.py::_plan_scan), so reads always see
    base + appends without rewriting files."""

    def __init__(self, base: TableProvider):
        self.base = base
        self.batches: list[pa.RecordBatch] = []
        self.version = 0

    def append(self, batches: list[pa.RecordBatch]) -> int:
        self.batches.extend(batches)
        self.version += 1
        return self.version

    def arrow_schema(self) -> pa.Schema:
        return self.base.arrow_schema()

    def statistics(self) -> TableStats:
        base = self.base.statistics()
        if base.num_rows is None:
            return TableStats()
        rows = sum(b.num_rows for b in self.batches)
        nbytes = sum(b.nbytes for b in self.batches)
        return TableStats(base.num_rows + rows, (base.total_bytes or 0) + nbytes,
                          base.columns)

    def scan_partitions(self, target_partitions: int) -> list[dict]:
        return self.base.scan_partitions(target_partitions)


class Catalog:
    """Session table registry (names → providers)."""

    def __init__(self):
        self.tables: dict[str, TableProvider] = {}

    def register(self, name: str, provider: TableProvider) -> None:
        self.tables[name.lower()] = provider

    def get(self, name: str) -> TableProvider | None:
        return self.tables.get(name.lower())

    def deregister(self, name: str) -> None:
        self.tables.pop(name.lower(), None)

    def names(self) -> list[str]:
        return sorted(self.tables)
