"""Logical plan nodes.

The relational algebra the SQL frontend lowers into and the optimizer
rewrites. Scoped to the reference's exercised surface (TPC-H/TPC-DS class):
scan/filter/project/aggregate/join/sort/limit/distinct/union/values plus
subquery alias. Each node knows its output schema; display() produces the
indented tree used by golden-plan tests (reference: tpch_plan_stability).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import pyarrow as pa

from ballista_tpu.errors import PlanningError, SchemaError
from ballista_tpu.plan.expressions import (
    AggregateFunction,
    Expr,
    SortKey,
    to_field,
)
from ballista_tpu.plan.schema import DFField, DFSchema


class LogicalPlan:
    schema: DFSchema

    def children(self) -> list["LogicalPlan"]:
        return []

    def with_children(self, children: list["LogicalPlan"]) -> "LogicalPlan":
        assert not children
        return self

    def node_str(self) -> str:
        return type(self).__name__

    def display(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [pad + self.node_str()]
        for c in self.children():
            lines.append(c.display(indent + 1))
        return "\n".join(lines)


@dataclass
class TableScan(LogicalPlan):
    table_name: str
    provider: Any  # TableProvider
    projection: Optional[list[int]] = None  # pushed-down column indices
    filters: list[Expr] = field(default_factory=list)  # pushed-down predicates
    alias: Optional[str] = None

    def __post_init__(self):
        qualifier = self.alias or self.table_name
        full = self.provider.df_schema().with_qualifier(qualifier)
        if self.projection is None:
            self.schema = full
        else:
            self.schema = DFSchema([full.field(i) for i in self.projection])

    def node_str(self) -> str:
        proj = ""
        if self.projection is not None:
            proj = f" projection=[{', '.join(f.name for f in self.schema)}]"
        filt = f" filters=[{', '.join(map(str, self.filters))}]" if self.filters else ""
        al = f" AS {self.alias}" if self.alias and self.alias != self.table_name else ""
        return f"TableScan: {self.table_name}{al}{proj}{filt}"


@dataclass
class Projection(LogicalPlan):
    input: LogicalPlan
    exprs: list[Expr]

    def __post_init__(self):
        self.schema = DFSchema([to_field(e, self.input.schema) for e in self.exprs])

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Projection(c[0], self.exprs)

    def node_str(self) -> str:
        return f"Projection: {', '.join(map(str, self.exprs))}"


@dataclass
class Filter(LogicalPlan):
    input: LogicalPlan
    predicate: Expr

    def __post_init__(self):
        self.schema = self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Filter(c[0], self.predicate)

    def node_str(self) -> str:
        return f"Filter: {self.predicate}"


@dataclass
class Aggregate(LogicalPlan):
    input: LogicalPlan
    group_exprs: list[Expr]
    agg_exprs: list[Expr]  # AggregateFunction possibly wrapped in Alias

    def __post_init__(self):
        fields = [to_field(e, self.input.schema) for e in self.group_exprs]
        fields += [to_field(e, self.input.schema) for e in self.agg_exprs]
        self.schema = DFSchema(fields)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Aggregate(c[0], self.group_exprs, self.agg_exprs)

    def node_str(self) -> str:
        g = ", ".join(map(str, self.group_exprs))
        a = ", ".join(map(str, self.agg_exprs))
        return f"Aggregate: groupBy=[{g}], aggr=[{a}]"


JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti", "right_semi", "right_anti")


@dataclass
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    on: list[tuple[Expr, Expr]]  # equi-join key pairs (left expr, right expr)
    join_type: str = "inner"
    filter: Optional[Expr] = None  # non-equi residual predicate

    def __post_init__(self):
        if self.join_type not in JOIN_TYPES:
            raise PlanningError(f"bad join type {self.join_type}")
        if self.join_type in ("left_semi", "left_anti"):
            self.schema = self.left.schema
        elif self.join_type in ("right_semi", "right_anti"):
            self.schema = self.right.schema
        else:
            self.schema = self.left.schema.merge(self.right.schema)

    def children(self) -> list[LogicalPlan]:
        return [self.left, self.right]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Join(c[0], c[1], self.on, self.join_type, self.filter)

    def node_str(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        f = f", filter={self.filter}" if self.filter is not None else ""
        return f"Join: type={self.join_type}, on=[{on}]{f}"


@dataclass
class CrossJoin(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan

    def __post_init__(self):
        self.schema = self.left.schema.merge(self.right.schema)

    def children(self) -> list[LogicalPlan]:
        return [self.left, self.right]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return CrossJoin(c[0], c[1])

    def node_str(self) -> str:
        return "CrossJoin"


@dataclass
class Window(LogicalPlan):
    """Appends one column per window expression (named __win{i}) to the
    input; the projection above references them by name."""

    input: LogicalPlan
    window_exprs: list[Expr]  # WindowFunction nodes

    def __post_init__(self):
        from ballista_tpu.plan.schema import DFField

        fields = list(self.input.schema.fields)
        for i, e in enumerate(self.window_exprs):
            fields.append(DFField(f"__win{i}", e.data_type(self.input.schema)))
        self.schema = DFSchema(fields)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Window(c[0], self.window_exprs)

    def node_str(self) -> str:
        return f"Window: {', '.join(map(str, self.window_exprs))}"


@dataclass
class Sort(LogicalPlan):
    input: LogicalPlan
    keys: list[SortKey]
    fetch: Optional[int] = None  # top-k pushdown

    def __post_init__(self):
        self.schema = self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Sort(c[0], self.keys, self.fetch)

    def node_str(self) -> str:
        k = ", ".join(map(str, self.keys))
        f = f", fetch={self.fetch}" if self.fetch is not None else ""
        return f"Sort: {k}{f}"


@dataclass
class Limit(LogicalPlan):
    input: LogicalPlan
    fetch: Optional[int]
    skip: int = 0

    def __post_init__(self):
        self.schema = self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Limit(c[0], self.fetch, self.skip)

    def node_str(self) -> str:
        return f"Limit: fetch={self.fetch}, skip={self.skip}"


@dataclass
class Distinct(LogicalPlan):
    input: LogicalPlan

    def __post_init__(self):
        self.schema = self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Distinct(c[0])

    def node_str(self) -> str:
        return "Distinct"


@dataclass
class SubqueryAlias(LogicalPlan):
    input: LogicalPlan
    alias: str

    def __post_init__(self):
        self.schema = self.input.schema.strip_qualifiers().with_qualifier(self.alias)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return SubqueryAlias(c[0], self.alias)

    def node_str(self) -> str:
        return f"SubqueryAlias: {self.alias}"


@dataclass
class Union(LogicalPlan):
    inputs: list[LogicalPlan]
    all: bool = True

    def __post_init__(self):
        self.schema = self.inputs[0].schema.strip_qualifiers()

    def children(self) -> list[LogicalPlan]:
        return list(self.inputs)

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Union(c, self.all)

    def node_str(self) -> str:
        return "Union" + ("" if self.all else " Distinct")


@dataclass
class Values(LogicalPlan):
    rows: list[list[Any]]
    schema: DFSchema = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.schema is None:
            from ballista_tpu.plan.expressions import literal_type

            fields = []
            for i, v in enumerate(self.rows[0]):
                fields.append(DFField(f"column{i + 1}", literal_type(v), True, None))
            self.schema = DFSchema(fields)

    def node_str(self) -> str:
        return f"Values: {len(self.rows)} rows"


@dataclass
class EmptyRelation(LogicalPlan):
    produce_one_row: bool = False
    schema: DFSchema = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.schema is None:
            self.schema = DFSchema([])

    def node_str(self) -> str:
        return f"EmptyRelation: produce_one_row={self.produce_one_row}"


@dataclass
class Explain(LogicalPlan):
    input: LogicalPlan
    analyze: bool = False
    verbose: bool = False

    def __post_init__(self):
        self.schema = DFSchema(
            [DFField("plan_type", pa.string(), False), DFField("plan", pa.string(), False)]
        )

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def with_children(self, c: list[LogicalPlan]) -> "LogicalPlan":
        return Explain(c[0], self.analyze, self.verbose)

    def node_str(self) -> str:
        return "Explain" + (" Analyze" if self.analyze else "")


def transform_plan_up(plan: LogicalPlan, fn) -> LogicalPlan:
    kids = plan.children()
    if kids:
        new = [transform_plan_up(k, fn) for k in kids]
        plan = plan.with_children(new)
    return fn(plan)
