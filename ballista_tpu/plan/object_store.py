"""Object store glue (reference: core/src/object_store.rs — S3/MinIO glue).

Resolves table locations to pyarrow filesystems:
- local paths → LocalFileSystem
- s3://bucket/key → pyarrow.fs.S3FileSystem, configured from the standard
  AWS env vars (AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY / AWS_ENDPOINT_URL
  / AWS_REGION / AWS_ALLOW_HTTP — the same knobs the reference's S3Options
  reads). Build environments without network reach fail at FIRST READ with
  a clear error, not at registration.
"""

from __future__ import annotations

import os

import pyarrow.fs as pafs

from ballista_tpu.errors import ConfigurationError


def resolve_filesystem(path: str):
    """Returns (filesystem, path_within_fs)."""
    if path.startswith("s3://"):
        kwargs = {}
        if os.environ.get("AWS_REGION"):
            kwargs["region"] = os.environ["AWS_REGION"]
        if os.environ.get("AWS_ENDPOINT_URL"):
            kwargs["endpoint_override"] = os.environ["AWS_ENDPOINT_URL"]
        if os.environ.get("AWS_ACCESS_KEY_ID"):
            kwargs["access_key"] = os.environ["AWS_ACCESS_KEY_ID"]
            kwargs["secret_key"] = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        if os.environ.get("AWS_ALLOW_HTTP", "").lower() in ("1", "true"):
            kwargs["scheme"] = "http"
        try:
            fs = pafs.S3FileSystem(**kwargs)
        except Exception as e:  # noqa: BLE001
            raise ConfigurationError(f"cannot initialize S3 filesystem: {e}") from None
        return fs, path[len("s3://"):]
    if path.startswith("file://"):
        return pafs.LocalFileSystem(), path[len("file://"):]
    return pafs.LocalFileSystem(), path
