select i_manufact_id, sum_sales, avg_quarterly_sales
from (select i_manufact_id, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manufact_id)
               avg_quarterly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205, 1206, 1207,
                            1208, 1209, 1210, 1211)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('class#1', 'class#2', 'class#3'))
             or (i_category in ('Women', 'Music', 'Men')
                 and i_class in ('class#4', 'class#5', 'class#6')))
      group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
