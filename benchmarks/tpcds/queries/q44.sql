select asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
from (select item_sk, rnk
      from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col,
                   rank() over (order by avg(ss_net_profit) asc) rnk
            from store_sales ss1
            where ss_store_sk = 4
            group by ss_item_sk
            having avg(ss_net_profit) > 0.9 * (select avg(ss_net_profit) rank_col
                                               from store_sales
                                               where ss_store_sk = 4
                                                 and ss_addr_sk is null
                                               group by ss_store_sk)) v1
      where rnk < 11) asceding,
     (select item_sk, rnk
      from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col,
                   rank() over (order by avg(ss_net_profit) desc) rnk
            from store_sales ss1
            where ss_store_sk = 4
            group by ss_item_sk
            having avg(ss_net_profit) > 0.9 * (select avg(ss_net_profit) rank_col
                                               from store_sales
                                               where ss_store_sk = 4
                                                 and ss_addr_sk is null
                                               group by ss_store_sk)) v2
      where rnk < 11) descending,
     item i1, item i2
where asceding.rnk = descending.rnk
  and i1.i_item_sk = asceding.item_sk
  and i2.i_item_sk = descending.item_sk
order by asceding.rnk
limit 100
